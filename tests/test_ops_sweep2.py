"""Systematic op sweep, part 2: optimizer update rules, metrics, RNN cells,
detection ops, 3-D conv/pool, sequence-structure ops, collectives, tensor
arrays, SelectedRows host ops — plus the registry-completeness check that
asserts EVERY registered op has a test (here, part 1, or a named dedicated
test file).

Reference parity: op_test.py-driven unittests plus the per-family tests
(test_adam_op.py, test_bipartite_match_op.py, test_edit_distance_op.py, ...).
"""

import glob
import os
import re

import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import check_output, check_grad, run_op


def _r(*shape, lo=0.0, hi=1.0, seed=0, dtype=np.float32):
    rng = np.random.RandomState(abs(hash((shape, lo, hi, seed))) % (2**31))
    return (rng.uniform(lo, hi, size=shape)).astype(dtype)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# --------------------------------------------------------------------------
# optimizer update rules (operators/{sgd,momentum,adam,...}_op.cc)
def _opt_base(seed=0):
    p = _r(3, 4, lo=-1, hi=1, seed=seed)
    g = _r(3, 4, lo=-1, hi=1, seed=seed + 1)
    lr = np.array([0.1], np.float32)
    return p, g, lr


def test_sgd_op():
    p, g, lr = _opt_base(120)
    check_output("sgd", {"Param": p, "Grad": g, "LearningRate": lr}, {},
                 {"ParamOut": p - lr * g}, rtol=1e-5)


def test_momentum_op():
    p, g, lr = _opt_base(121)
    v = _r(3, 4, seed=122)
    mu = 0.9
    vn = mu * v + g
    check_output("momentum",
                 {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr},
                 {"mu": mu},
                 {"ParamOut": p - lr * vn, "VelocityOut": vn}, rtol=1e-5)
    # nesterov variant
    check_output("momentum",
                 {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr},
                 {"mu": mu, "use_nesterov": True},
                 {"ParamOut": p - (g + mu * vn) * lr}, rtol=1e-5)


def test_adagrad_op():
    p, g, lr = _opt_base(123)
    m = _r(3, 4, lo=0, hi=1, seed=124)
    eps = 1e-6
    mn = m + g * g
    check_output("adagrad",
                 {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
                 {"epsilon": eps},
                 {"ParamOut": p - lr * g / (np.sqrt(mn) + eps),
                  "MomentOut": mn}, rtol=1e-5)


def test_adam_op():
    p, g, lr = _opt_base(125)
    m1, m2 = _r(3, 4, seed=126), _r(3, 4, lo=0, hi=1, seed=127)
    b1, b2, eps = 0.9, 0.999, 1e-8
    b1p = np.array([b1 ** 3], np.float32)
    b2p = np.array([b2 ** 3], np.float32)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
    check_output("adam",
                 {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
                  "LearningRate": lr, "Beta1Pow": b1p, "Beta2Pow": b2p},
                 {"beta1": b1, "beta2": b2, "epsilon": eps,
                  "update_beta_pow": True},
                 {"ParamOut": p - lr_t * m1n / (np.sqrt(m2n) + eps),
                  "Moment1Out": m1n, "Moment2Out": m2n,
                  "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2},
                 rtol=1e-5)


def test_adamax_op():
    p, g, lr = _opt_base(128)
    m = _r(3, 4, seed=129)
    inf = _r(3, 4, lo=0.1, hi=1, seed=130)
    b1, b2, eps = 0.9, 0.999, 1e-8
    b1p = np.array([b1 ** 2], np.float32)
    mn = b1 * m + (1 - b1) * g
    infn = np.maximum(b2 * inf, np.abs(g) + eps)
    check_output("adamax",
                 {"Param": p, "Grad": g, "Moment": m, "InfNorm": inf,
                  "LearningRate": lr, "Beta1Pow": b1p},
                 {"beta1": b1, "beta2": b2, "epsilon": eps},
                 {"ParamOut": p - (lr / (1 - b1p)) * mn / infn,
                  "MomentOut": mn, "InfNormOut": infn}, rtol=1e-5)


def test_decayed_adagrad_op():
    p, g, lr = _opt_base(131)
    m = _r(3, 4, lo=0, hi=1, seed=132)
    decay, eps = 0.95, 1e-6
    mn = decay * m + (1 - decay) * g * g
    check_output("decayed_adagrad",
                 {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
                 {"decay": decay, "epsilon": eps},
                 {"ParamOut": p - lr * g / (np.sqrt(mn) + eps),
                  "MomentOut": mn}, rtol=1e-5)


def test_adadelta_op():
    p, g, _ = _opt_base(133)
    asg = _r(3, 4, lo=0, hi=1, seed=134)
    asu = _r(3, 4, lo=0, hi=1, seed=135)
    rho, eps = 0.95, 1e-6
    asgn = rho * asg + (1 - rho) * g * g
    upd = -np.sqrt((asu + eps) / (asgn + eps)) * g
    asun = rho * asu + (1 - rho) * upd * upd
    check_output("adadelta",
                 {"Param": p, "Grad": g, "AvgSquaredGrad": asg,
                  "AvgSquaredUpdate": asu},
                 {"rho": rho, "epsilon": eps},
                 {"ParamOut": p + upd, "AvgSquaredGradOut": asgn,
                  "AvgSquaredUpdateOut": asun}, rtol=1e-5)


def test_rmsprop_op():
    p, g, lr = _opt_base(136)
    ms = _r(3, 4, lo=0.1, hi=1, seed=137)
    mom = _r(3, 4, seed=138)
    rho, eps, momentum = 0.9, 1e-10, 0.5
    msn = rho * ms + (1 - rho) * g * g
    momn = momentum * mom + lr * g / np.sqrt(msn + eps)
    check_output("rmsprop",
                 {"Param": p, "Grad": g, "MeanSquare": ms, "Moment": mom,
                  "LearningRate": lr},
                 {"decay": rho, "epsilon": eps, "momentum": momentum},
                 {"ParamOut": p - momn, "MeanSquareOut": msn,
                  "MomentOut": momn}, rtol=1e-5)


def test_ftrl_op():
    p, g, lr = _opt_base(139)
    sq = _r(3, 4, lo=0.1, hi=1, seed=140)
    lin = _r(3, 4, seed=141)
    l1, l2, power = 0.1, 0.2, -0.5
    sqn = sq + g * g
    sigma = (sqn ** 0.5 - sq ** 0.5) / lr
    linn = lin + g - sigma * p
    x = l1 * np.sign(linn) - linn
    y = sqn ** 0.5 / lr + 2 * l2
    pn = np.where(np.abs(linn) > l1, x / y, 0.0)
    check_output("ftrl",
                 {"Param": p, "Grad": g, "SquaredAccumulator": sq,
                  "LinearAccumulator": lin, "LearningRate": lr},
                 {"l1": l1, "l2": l2, "lr_power": power},
                 {"ParamOut": pn, "SquaredAccumOut": sqn,
                  "LinearAccumOut": linn}, rtol=1e-4)


def test_proximal_gd_op():
    p, g, lr = _opt_base(142)
    l1, l2 = 0.05, 0.1
    prox = p - lr * g
    pn = np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0) \
        / (1 + lr * l2)
    check_output("proximal_gd",
                 {"Param": p, "Grad": g, "LearningRate": lr},
                 {"l1": l1, "l2": l2}, {"ParamOut": pn}, rtol=1e-5)


def test_proximal_adagrad_op():
    p, g, lr = _opt_base(143)
    m = _r(3, 4, lo=0.1, hi=1, seed=144)
    l1, l2 = 0.05, 0.1
    mn = m + g * g
    lr_t = lr / np.sqrt(mn)
    prox = p - lr_t * g
    pn = np.sign(prox) * np.maximum(np.abs(prox) - lr_t * l1, 0) \
        / (1 + lr_t * l2)
    check_output("proximal_adagrad",
                 {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
                 {"l1": l1, "l2": l2},
                 {"ParamOut": pn, "MomentOut": mn}, rtol=1e-5)


def test_average_accumulates_op():
    p = _r(3, 4, seed=145)
    s1, s2, s3 = (_r(3, 4, seed=s) for s in (146, 147, 148))
    num_acc = np.array([1], np.int64)
    old_num = np.array([0], np.int64)
    num_upd = np.array([1], np.int64)
    # window = clip(avg_window*num_upd, min_w, max_w) = 100 -> no rollover
    got = run_op("average_accumulates",
                 {"param": p, "in_sum_1": s1, "in_sum_2": s2, "in_sum_3": s3,
                  "in_num_accumulates": num_acc,
                  "in_old_num_accumulates": old_num,
                  "in_num_updates": num_upd},
                 {"average_window": 10.0, "max_average_window": 100,
                  "min_average_window": 100},
                 ["out_sum_1", "out_num_accumulates"])
    np.testing.assert_allclose(np.asarray(got["out_sum_1"]), s1 + p,
                               rtol=1e-5)
    assert int(np.asarray(got["out_num_accumulates"])) == 2


# --------------------------------------------------------------------------
# metrics (operators/{accuracy,edit_distance,precision_recall}_op.cc)
def test_accuracy_op():
    # top-k membership semantics (accuracy_op.cc): a row counts as correct
    # if the label appears anywhere in its top-k indices
    indices = np.array([[1, 0], [2, 3], [0, 2], [1, 2]], np.int64)
    label = np.array([[1], [1], [0], [2]], np.int64)
    got = run_op("accuracy", {"Indices": indices, "Label": label}, {},
                 ["Accuracy", "Correct", "Total"])
    np.testing.assert_allclose(float(np.asarray(got["Accuracy"])), 0.75)
    assert int(np.asarray(got["Correct"])) == 3
    assert int(np.asarray(got["Total"])) == 4


def _levenshtein(a, b):
    m, n = len(a), len(b)
    d = np.zeros((m + 1, n + 1))
    d[:, 0] = np.arange(m + 1)
    d[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + cost)
    return d[m, n]


def test_edit_distance_op():
    hyp = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int64)
    ref = np.array([[1, 3, 3, 9], [5, 6, 8, 8]], np.int64)
    want = np.array([[_levenshtein(hyp[i], ref[i])] for i in range(2)],
                    np.float32)
    check_output("edit_distance", {"Hyps": hyp, "Refs": ref},
                 {"normalized": False}, {"Out": want})
    check_output("edit_distance", {"Hyps": hyp, "Refs": ref},
                 {"normalized": True}, {"Out": want / 4.0}, rtol=1e-5)


def test_precision_recall_shapes():
    indices = np.array([[0], [1], [2], [1]], np.int64)
    labels = np.array([[0], [1], [1], [2]], np.int64)
    got = run_op("precision_recall",
                 {"Indices": indices, "Labels": labels},
                 {"class_number": 3}, ["BatchMetrics"])
    bm = np.asarray(got["BatchMetrics"])
    assert bm.shape == (6,)
    assert np.all(bm >= 0) and np.all(bm <= 1.0 + 1e-6)


# --------------------------------------------------------------------------
# RNN cells (operators/{lstm_unit,gru_unit}_op.cc)
def test_lstm_unit_op():
    b, d = 3, 4
    x = _r(b, 4 * d, lo=-1, hi=1, seed=150)
    c_prev = _r(b, d, lo=-1, hi=1, seed=151)
    fb = 0.5
    gi, gf, gc, go = np.split(x, 4, axis=-1)
    c = _sigmoid(gf + fb) * c_prev + _sigmoid(gi) * np.tanh(gc)
    h = _sigmoid(go) * np.tanh(c)
    check_output("lstm_unit", {"X": x, "C_prev": c_prev},
                 {"forget_bias": fb}, {"C": c, "H": h}, rtol=1e-4)
    check_grad("lstm_unit", {"X": _r(2, 8, lo=-1, hi=1, seed=152),
                             "C_prev": _r(2, 2, lo=-1, hi=1, seed=153)},
               {"forget_bias": fb}, wrt=["X", "C_prev"], out="H",
               out_slots=["C", "H"])


def test_gru_unit_op():
    b, d = 3, 4
    x = _r(b, 3 * d, lo=-1, hi=1, seed=154)
    h_prev = _r(b, d, lo=-1, hi=1, seed=155)
    w = _r(d, 3 * d, lo=-0.5, hi=0.5, seed=156)
    xu, xr, xc = x[:, :d], x[:, d:2 * d], x[:, 2 * d:]
    gh = h_prev @ w[:, :2 * d]
    u = _sigmoid(xu + gh[:, :d])
    r = _sigmoid(xr + gh[:, d:])
    c = np.tanh(xc + (r * h_prev) @ w[:, 2 * d:])
    h = u * c + (1 - u) * h_prev
    check_output("gru_unit",
                 {"Input": x, "HiddenPrev": h_prev, "Weight": w}, {},
                 {"Hidden": h, "ResetHiddenPrev": r * h_prev}, rtol=1e-4)


# --------------------------------------------------------------------------
# detection (operators/detection/*.cc)
def test_iou_similarity_op():
    x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    y = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)

    def iou(a, b):
        ix = max(0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = (a[2] - a[0]) * (a[3] - a[1]) + \
             (b[2] - b[0]) * (b[3] - b[1]) - inter
        return inter / ua if ua > 0 else 0.0

    want = np.array([[iou(a, b) for b in y] for a in x], np.float32)
    check_output("iou_similarity", {"X": x, "Y": y}, {}, {"Out": want},
                 rtol=1e-5)


def test_box_coder_decode():
    prior = np.array([[0, 0, 4, 4], [2, 2, 6, 8]], np.float32)
    var = np.ones((2, 4), np.float32) * 0.5
    deltas = _r(3, 2, 4, lo=-0.3, hi=0.3, seed=160)
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    dcx = deltas[..., 0] * var[None, :, 0] * pw[None] + pcx[None]
    dcy = deltas[..., 1] * var[None, :, 1] * ph[None] + pcy[None]
    dw = np.exp(deltas[..., 2] * var[None, :, 2]) * pw[None]
    dh = np.exp(deltas[..., 3] * var[None, :, 3]) * ph[None]
    want = np.stack([dcx - dw / 2, dcy - dh / 2,
                     dcx + dw / 2, dcy + dh / 2], axis=-1)
    check_output("box_coder",
                 {"PriorBox": prior, "PriorBoxVar": var, "TargetBox": deltas},
                 {"code_type": "decode_center_size"},
                 {"OutputBox": want}, rtol=1e-4)


def test_bipartite_match_op():
    dist = np.array([[0.1, 0.9, 0.3],
                     [0.8, 0.2, 0.7]], np.float32)
    # greedy global: (0,1)=0.9 then (1,0)=0.8; col 2 unmatched
    got = run_op("bipartite_match", {"DistMat": dist}, {},
                 ["ColToRowMatchIndices", "ColToRowMatchDist"])
    np.testing.assert_array_equal(
        np.asarray(got["ColToRowMatchIndices"]), [[1, 0, -1]])
    np.testing.assert_allclose(
        np.asarray(got["ColToRowMatchDist"]), [[0.8, 0.9, 0.0]], rtol=1e-6)


def test_target_assign_op():
    x = _r(3, 5, seed=161)          # N_gt=3, K=5
    match = np.array([[0, -1, 2, 1]], np.int32)
    got = run_op("target_assign", {"X": x, "MatchIndices": match},
                 {"mismatch_value": 0.0}, ["Out", "OutWeight"])
    out = np.asarray(got["Out"])[0]
    wt = np.asarray(got["OutWeight"])[0, :, 0]
    np.testing.assert_allclose(out[0], x[0], rtol=1e-6)
    np.testing.assert_allclose(out[2], x[2], rtol=1e-6)
    np.testing.assert_allclose(out[3], x[1], rtol=1e-6)
    np.testing.assert_array_equal(wt, [1, 0, 1, 1])


def test_mine_hard_examples_shapes():
    cls_loss = _r(2, 6, seed=162)
    match = np.array([[0, -1, -1, 1, -1, -1],
                      [-1, 0, -1, -1, -1, 1]], np.int32)
    got = run_op("mine_hard_examples",
                 {"ClsLoss": cls_loss, "MatchIndices": match},
                 {"neg_pos_ratio": 1.0, "mining_type": "max_negative"},
                 ["NegIndices", "UpdatedMatchIndices"])
    assert np.asarray(got["UpdatedMatchIndices"]).shape == (2, 6)


def test_prior_box_shapes():
    feat = _r(1, 8, 4, 4, seed=163)
    img = _r(1, 3, 32, 32, seed=164)
    got = run_op("prior_box", {"Input": feat, "Image": img},
                 {"min_sizes": [4.0], "max_sizes": [8.0],
                  "aspect_ratios": [1.0], "variances": [0.1, 0.1, 0.2, 0.2]},
                 ["Boxes", "Variances"])
    boxes = np.asarray(got["Boxes"])
    assert boxes.shape[-1] == 4 and boxes.shape[0] == 4  # H,W,priors,4
    assert np.asarray(got["Variances"]).shape == boxes.shape


def test_detection_map_shapes():
    det = np.array([[0, 0.9, 0, 0, 2, 2], [1, 0.8, 1, 1, 3, 3]], np.float32)
    gt = np.array([[0, 0, 0, 2, 2, 0], [1, 1, 1, 3, 3, 0]], np.float32)
    got = run_op("detection_map", {"DetectRes": det, "Label": gt}, {},
                 ["MAP"])
    v = float(np.asarray(got["MAP"]))
    assert 0.0 <= v <= 1.0


# --------------------------------------------------------------------------
# 3-D conv/pool + pyramid/row/sequence-image ops (torch-referenced where a
# closed-form numpy ref would re-implement the kernel)
def test_conv3d_vs_torch():
    import torch
    import torch.nn.functional as F
    x = _r(1, 2, 4, 5, 5, lo=-1, hi=1, seed=165)
    w = _r(3, 2, 2, 3, 3, lo=-1, hi=1, seed=166)
    want = F.conv3d(torch.tensor(x), torch.tensor(w),
                    stride=(1, 2, 2), padding=(0, 1, 1)).numpy()
    check_output("conv3d", {"Input": x, "Filter": w},
                 {"strides": [1, 2, 2], "paddings": [0, 1, 1]},
                 {"Output": want}, rtol=1e-3, atol=1e-4)


def test_conv3d_transpose_vs_torch():
    import torch
    import torch.nn.functional as F
    x = _r(1, 3, 3, 4, 4, lo=-1, hi=1, seed=167)
    w = _r(3, 2, 2, 3, 3, lo=-1, hi=1, seed=168)   # [Cin, Cout, kd, kh, kw]
    want = F.conv_transpose3d(torch.tensor(x), torch.tensor(w),
                              stride=(1, 2, 2)).numpy()
    check_output("conv3d_transpose", {"Input": x, "Filter": w},
                 {"strides": [1, 2, 2], "paddings": [0, 0, 0]},
                 {"Output": want}, rtol=1e-3, atol=1e-4)


def test_pool3d_vs_torch():
    import torch
    import torch.nn.functional as F
    x = _r(1, 2, 4, 6, 6, lo=-1, hi=1, seed=169)
    t = torch.tensor(x)
    want_max = F.max_pool3d(t, kernel_size=2, stride=2).numpy()
    check_output("pool3d", {"X": x},
                 {"pooling_type": "max", "ksize": [2, 2, 2],
                  "strides": [2, 2, 2]},
                 {"Out": want_max}, rtol=1e-5)
    want_avg = F.avg_pool3d(t, kernel_size=2, stride=2).numpy()
    check_output("pool3d", {"X": x},
                 {"pooling_type": "avg", "ksize": [2, 2, 2],
                  "strides": [2, 2, 2]},
                 {"Out": want_avg}, rtol=1e-4)


def test_spp_op():
    # pyramid_height=2 -> level 0: global pool (1 bin), level 1: 2x2 bins
    x = _r(2, 3, 4, 4, lo=-1, hi=1, seed=170)
    lvl0 = x.max(axis=(2, 3)).reshape(2, -1)
    lvl1 = np.stack([x[:, :, :2, :2].max(axis=(2, 3)),
                     x[:, :, :2, 2:].max(axis=(2, 3)),
                     x[:, :, 2:, :2].max(axis=(2, 3)),
                     x[:, :, 2:, 2:].max(axis=(2, 3))], axis=2)
    lvl1 = lvl1.reshape(2, -1)
    # reference layout per level: [N, C*bins] with bins fastest — build via
    # reshape of [N, C, bins]
    want = np.concatenate([lvl0, lvl1], axis=1)
    got = run_op("spp", {"X": x}, {"pyramid_height": 2,
                                   "pooling_type": "max"}, ["Out"])
    g = np.asarray(got["Out"])
    assert g.shape == (2, 3 + 12)
    np.testing.assert_allclose(g[:, :3], lvl0, rtol=1e-5)
    np.testing.assert_allclose(np.sort(g[:, 3:], 1), np.sort(lvl1, 1),
                               rtol=1e-5)


def test_row_conv_op():
    t, d, k = 6, 3, 3
    x = _r(t, d, lo=-1, hi=1, seed=171)
    w = _r(k, d, lo=-1, hi=1, seed=172)
    xp = np.pad(x, ((0, k - 1), (0, 0)))
    want = sum(xp[i:i + t] * w[i] for i in range(k))
    check_output("row_conv", {"X": x, "Filter": w}, {}, {"Out": want},
                 rtol=1e-4)


def test_im2sequence_op():
    x = _r(1, 2, 4, 4, lo=-1, hi=1, seed=173)
    got = run_op("im2sequence", {"X": x},
                 {"kernels": [2, 2], "strides": [2, 2],
                  "paddings": [0, 0, 0, 0]}, ["Out"])
    out = np.asarray(got["Out"])
    # 2x2 windows over 4x4 stride 2 -> 4 windows, each C*kh*kw = 8 wide
    assert out.shape == (4, 8)
    # first window must contain x[0,:, :2, :2]
    np.testing.assert_allclose(np.sort(out[0]),
                               np.sort(x[0, :, :2, :2].reshape(-1)),
                               rtol=1e-5)


# --------------------------------------------------------------------------
# collectives — identity semantics outside a mesh (documented contract;
# in-mesh semantics are covered by tests/test_parallel.py)
@pytest.mark.parametrize("op", ["c_allreduce_sum", "c_allreduce_max",
                                "c_allgather", "c_reducescatter",
                                "c_broadcast", "all_to_all"])
def test_collective_identity_outside_mesh(op):
    x = _r(4, 3, seed=174)
    check_output(op, {"X": x}, {"ring_id": 0}, {"Out": x})


def test_c_sync_comm_stream():
    x = _r(2, 2, seed=175)
    check_output("c_sync_comm_stream", {"X": x}, {}, {"Out": x})


# --------------------------------------------------------------------------
# LoDTensorArray ops + rank-table ops (tensor_array_read_write.cc,
# lod_rank_table_op.cc, max_sequence_len_op.cc, shrink_rnn_memory_op.cc)
def test_tensor_array_write_read_length():
    prog = fluid.Program()
    blk = prog.global_block()
    for nm, arr in (("x0", np.ones((2, 3), np.float32)),
                    ("x1", 2 * np.ones((2, 3), np.float32))):
        blk.create_var(name=nm, shape=(2, 3), dtype="float32", is_data=True)
    blk.create_var(name="i0")
    blk.append_op("fill_constant", {}, {"Out": ["i0"]},
                  {"shape": [1], "value": 0.0, "dtype": "int64"})
    blk.create_var(name="i1")
    blk.append_op("fill_constant", {}, {"Out": ["i1"]},
                  {"shape": [1], "value": 1.0, "dtype": "int64"})
    blk.create_var(name="arr")
    blk.append_op("write_to_array", {"X": ["x0"], "I": ["i0"]},
                  {"Out": ["arr"]}, {})
    blk.append_op("write_to_array", {"X": ["x1"], "I": ["i1"]},
                  {"Out": ["arr"]}, {})
    blk.create_var(name="read1")
    blk.append_op("read_from_array", {"X": ["arr"], "I": ["i1"]},
                  {"Out": ["read1"]}, {})
    blk.create_var(name="alen")
    blk.append_op("lod_array_length", {"X": ["arr"]}, {"Out": ["alen"]}, {})
    exe = fluid.Executor(fluid.CPUPlace())
    # TensorArray indices must be trace-time constants: the array ops are
    # host-tier, so the PUBLIC run() path routes this program through the
    # interpreter (index-producing segments still compile)
    with fluid.scope_guard(fluid.Scope()):
        r, n = exe.run(
            prog,
            feed={"x0": np.ones((2, 3), np.float32),
                  "x1": 2 * np.ones((2, 3), np.float32)},
            fetch_list=["read1", "alen"])
    np.testing.assert_allclose(np.asarray(r), 2.0)
    assert int(np.asarray(n)[0]) == 2


def test_rank_table_and_max_sequence_len():
    prog = fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=(7, 2), dtype="float32", is_data=True,
                   lod_level=1)
    blk.create_var(name="table")
    blk.append_op("lod_rank_table", {"X": ["x"]}, {"Out": ["table"]}, {})
    blk.create_var(name="maxlen")
    blk.append_op("max_sequence_len", {"RankTable": ["table"]},
                  {"Out": ["maxlen"]}, {})
    blk.create_var(name="shrunk")
    blk.append_op("shrink_rnn_memory", {"X": ["x"], "RankTable": ["table"],
                                        "I": ["maxlen"]},
                  {"Out": ["shrunk"]}, {})
    x = np.arange(14, dtype=np.float32).reshape(7, 2)
    lod = fluid.LoDTensor(x)
    lod.set_recursive_sequence_lengths([[3, 4]])
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        ml, sh = exe.run(prog, feed={"x": lod},
                         fetch_list=["maxlen", "shrunk"])
    assert int(np.asarray(ml)) == 4
    np.testing.assert_allclose(np.asarray(sh), x)


def test_select_rows_by_mask_op():
    mask = np.array([1, 0, 1], np.float32)
    t = _r(3, 2, seed=176)
    f = _r(3, 2, seed=177)
    want = np.where(mask[:, None] > 0, t, f)
    check_output("select_rows_by_mask",
                 {"Mask": mask, "TrueOut": t, "FalseOut": f}, {},
                 {"Out": want})


# --------------------------------------------------------------------------
# SelectedRows host ops (split/merge/lookup — operators/
# {split_selected_rows,merge_selected_rows,lookup_sparse_table}_op.cc).
# These are host ops: the program runs in the eager interpreter with
# SelectedRows values living in the scope.
def _sr(rows, value, height):
    from paddle_tpu.core.selected_rows import SelectedRows
    return SelectedRows(np.asarray(rows, np.int64),
                        np.asarray(value, np.float32), height)


def test_split_and_merge_selected_rows_ops():
    from paddle_tpu.core.selected_rows import SelectedRows
    prog = fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="sr_in", persistable=True,
                   type=fluid.core.program.VarType.SELECTED_ROWS)
    for nm in ("part0", "part1", "merged"):
        blk.create_var(name=nm)
    blk.append_op("split_selected_rows", {"X": ["sr_in"]},
                  {"Out": ["part0", "part1"]}, {"height_sections": [4, 4]})
    blk.append_op("merge_selected_rows", {"X": ["dup"]},
                  {"Out": ["merged"]}, {})
    blk.create_var(name="dup", persistable=True,
                   type=fluid.core.program.VarType.SELECTED_ROWS)
    # make the program a host-op program by construction (split/merge are
    # host ops), run through the scope
    scope = fluid.Scope()
    scope.set("sr_in", _sr([1, 5, 6], np.arange(6).reshape(3, 2), 8))
    scope.set("dup", _sr([2, 2, 3], [[1, 1], [2, 2], [5, 5]], 8))
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        p0, p1, merged = exe.run(prog, feed={},
                                 fetch_list=["part0", "part1", "merged"],
                                 return_numpy=False)
    assert isinstance(p0, SelectedRows)
    np.testing.assert_array_equal(p0.rows, [1])
    np.testing.assert_array_equal(p1.rows, [1, 2])  # 5-4, 6-4
    np.testing.assert_array_equal(merged.rows, [2, 3])
    np.testing.assert_allclose(merged.value, [[3, 3], [5, 5]])


def test_lookup_sparse_table_op():
    prog = fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="w", persistable=True,
                   type=fluid.core.program.VarType.SELECTED_ROWS)
    blk.create_var(name="ids", shape=(3, 1), dtype="int64", is_data=True)
    blk.create_var(name="out")
    blk.append_op("lookup_sparse_table", {"W": ["w"], "Ids": ["ids"]},
                  {"Out": ["out"]}, {})
    scope = fluid.Scope()
    scope.set("w", _sr([3, 7], [[1, 2], [3, 4]], 10))
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        out, = exe.run(prog,
                       feed={"ids": np.array([[7], [3], [9]], np.int64)},
                       fetch_list=["out"])
    np.testing.assert_allclose(np.asarray(out),
                               [[3, 4], [1, 2], [0, 0]])


def test_lstmp_op():
    # LSTM with recurrent projection (lstmp_op.cc), no peepholes, no bias:
    # numpy step-by-step reference over one sequence
    t, d, p = 5, 3, 2
    x = _r(t, 4 * d, lo=-0.5, hi=0.5, seed=180)
    w = _r(p, 4 * d, lo=-0.5, hi=0.5, seed=181)
    w_proj = _r(d, p, lo=-0.5, hi=0.5, seed=182)
    r = np.zeros(p, np.float32)
    c = np.zeros(d, np.float32)
    want = np.zeros((t, p), np.float32)
    for i in range(t):
        gates = x[i] + r @ w
        gi, gf, gc, go = np.split(gates, 4)
        cn = _sigmoid(gf) * c + _sigmoid(gi) * np.tanh(gc)
        h = _sigmoid(go) * np.tanh(cn)
        r = np.tanh(h @ w_proj)
        c = cn
        want[i] = r
    check_output("lstmp",
                 {"Input": (x, [t]), "Weight": w, "ProjWeight": w_proj},
                 {"use_peepholes": False}, {"Projection": want}, rtol=1e-4,
                 atol=1e-5)


def test_conditional_block_op():
    prog = fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=(2, 3), dtype="float32", is_data=True)
    blk.create_var(name="c", shape=(1,), dtype="bool", is_data=True)
    blk.create_var(name="y")
    blk.append_op("fill_constant", {}, {"Out": ["y"]},
                  {"shape": [2, 3], "value": 0.0})
    sub = prog.create_block(parent_idx=0)
    sub.append_op("scale", {"X": ["x"]}, {"Out": ["y"]}, {"scale": 2.0})
    blk.append_op("conditional_block", {"Condition": ["c"]},
                  {"Out": ["y"]},
                  {"sub_block": sub, "written_names": ["y"]})
    x = _r(2, 3, seed=183)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        yt, = exe.run(prog, feed={"x": x, "c": np.array([True])},
                      fetch_list=["y"])
        yf, = exe.run(prog, feed={"x": x, "c": np.array([False])},
                      fetch_list=["y"])
    np.testing.assert_allclose(np.asarray(yt), 2 * x, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(yf), 0.0)


def test_sequence_concat_op():
    # LoD path: sequences interleave — seq i of every input, inputs in order
    a = np.arange(6, dtype=np.float32).reshape(3, 2)        # lens [1, 2]
    b = 10 + np.arange(8, dtype=np.float32).reshape(4, 2)   # lens [2, 2]
    want = np.concatenate([a[:1], b[:2], a[1:], b[2:]], axis=0)
    check_output("sequence_concat", {"X": [(a, [1, 2]), (b, [2, 2])]}, {},
                 {"Out": want})


def test_sequence_scatter_op():
    x = _r(5, 2, seed=184)
    ids = np.array([0, 3, 1], np.int64)
    upd = _r(3, 2, seed=185)
    want = x.copy()
    for i, u in zip(ids, upd):
        want[i] += u
    check_output("sequence_scatter", {"X": x, "Ids": ids, "Updates": upd},
                 {}, {"Out": want}, rtol=1e-5)


def test_lod_reset_op():
    # rebind [2,4] lengths to [3,3] via target_lod OFFSETS, then pool:
    # the downstream sequence op must see the NEW segmentation
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        blk = prog.global_block()
        for nm in ("x_in", "reset", "pooled"):
            blk.create_var(name=nm)
        blk.append_op("lod_reset", {"X": ["x_in"]}, {"Out": ["reset"]},
                      {"target_lod": [0, 3, 6]})
        blk.append_op("sequence_pool", {"X": ["reset"]},
                      {"Out": ["pooled"]}, {"pooltype": "SUM"})
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            pooled, = exe.run(
                prog, feed={"x_in": fluid.LoDTensor(x, [[0, 2, 6]])},
                fetch_list=["pooled"])
    want = np.stack([x[:3].sum(0), x[3:].sum(0)])
    np.testing.assert_allclose(np.asarray(pooled), want)

    # Y-input form: Out must ADOPT Y's LoD — prove it via a chained pool
    def pooled_after_reset(y_val, y_feed_key, feed_extra):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            blk = prog.global_block()
            for nm in ("x_in2", y_feed_key, "reset2", "pooled2"):
                blk.create_var(name=nm)
            blk.append_op("lod_reset",
                          {"X": ["x_in2"], "Y": [y_feed_key]},
                          {"Out": ["reset2"]}, {})
            blk.append_op("sequence_pool", {"X": ["reset2"]},
                          {"Out": ["pooled2"]}, {"pooltype": "SUM"})
            exe = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(fluid.Scope()):
                out, = exe.run(
                    prog,
                    feed={"x_in2": fluid.LoDTensor(x, [[0, 2, 6]]),
                          y_feed_key: y_val, **feed_extra},
                    fetch_list=["pooled2"])
        return np.asarray(out)

    # (a) Y carries a LoD: lengths [1, 5] replace x's [2, 4]
    y = fluid.LoDTensor(np.zeros((6, 1), np.float32), [[0, 1, 6]])
    got = pooled_after_reset(y, "y_lod", {})
    np.testing.assert_allclose(got, np.stack([x[:1].sum(0), x[1:].sum(0)]))

    # (b) Y without LoD: its VALUES are level-0 offsets
    y_off = np.array([0, 4, 6], np.int32)
    got = pooled_after_reset(y_off, "y_off", {})
    np.testing.assert_allclose(got, np.stack([x[:4].sum(0), x[4:].sum(0)]))


def test_sequence_slice_op():
    # per-sequence sub-slices: seq0 = rows 0-2 (take offset 1 len 2),
    # seq1 = rows 3-6 (take offset 0 len 1)
    x = np.arange(14, dtype=np.float32).reshape(7, 2)
    offset = np.array([[1], [0]], np.int64)
    length = np.array([[2], [1]], np.int64)
    want = np.concatenate([x[1:3], x[3:4]], axis=0)
    got = run_op("sequence_slice",
                 {"X": (x, [3, 4]), "Offset": offset, "Length": length},
                 {}, ["Out"])
    # kept rows first; the executor trims to sum(Length) via the output's
    # propagated @LOD lengths
    np.testing.assert_allclose(np.asarray(got["Out"]), want)


# --------------------------------------------------------------------------
# finite-difference gradient checks for the hand-built scans — the analytic
# side is jax.value_and_grad through lax.scan, which per-op numpy refs do
# not exercise (reference: test_linear_chain_crf_op.py check_grad,
# test_warpctc_op.py check_grad, test_lstm_op.py reverse-direction grads)
def test_linear_chain_crf_grad():
    d = 3
    emission = _r(5, d, lo=-0.5, hi=0.5, seed=190)
    label = np.array([[0], [2], [1], [1], [0]], np.int64)
    trans = _r(d + 2, d, lo=-0.5, hi=0.5, seed=191)
    check_grad("linear_chain_crf",
               {"Emission": (emission, [2, 3]), "Label": (label, [2, 3]),
                "Transition": trans},
               {}, wrt=["Emission", "Transition"], out="LogLikelihood",
               out_slots=["LogLikelihood", "Alpha", "EmissionExps",
                          "TransitionExps"],
               delta=1e-2, rtol=5e-2, atol=1e-3)


def test_warpctc_grad():
    c = 4  # classes incl. blank 0
    logits = _r(6, c, lo=-1, hi=1, seed=192)
    label = np.array([[1], [2], [3]], np.int64)
    check_grad("warpctc",
               {"Logits": (logits, [3, 3]), "Label": (label, [2, 1])},
               {"blank": 0}, wrt=["Logits"], out="Loss",
               out_slots=["Loss", "WarpCTCGrad"],
               delta=1e-2, rtol=5e-2, atol=1e-3)


def test_fused_lstm_reverse_grad():
    d = 2
    x = _r(5, 4 * d, lo=-0.5, hi=0.5, seed=193)
    w = _r(d, 4 * d, lo=-0.5, hi=0.5, seed=194)
    check_grad("lstm", {"Input": (x, [2, 3]), "Weight": w},
               {"use_peepholes": False, "is_reverse": True},
               wrt=["Input", "Weight"], out="Hidden",
               delta=1e-2, rtol=5e-2, atol=1e-3)


def test_fused_gru_reverse_grad():
    d = 2
    x = _r(5, 3 * d, lo=-0.5, hi=0.5, seed=195)
    w = _r(d, 3 * d, lo=-0.5, hi=0.5, seed=196)
    check_grad("gru", {"Input": (x, [2, 3]), "Weight": w},
               {"is_reverse": True}, wrt=["Input", "Weight"], out="Hidden",
               delta=1e-2, rtol=5e-2, atol=1e-3)


# --------------------------------------------------------------------------
# registry completeness: every registered op must be tested somewhere —
# in the two sweep files or in a named dedicated test file (verified to
# actually mention the op). New ops without tests fail here.
COVERED_ELSEWHERE = {
    # conv/pool/vision — torch-referenced in tests/test_conv_ops.py
    "conv2d": "test_conv_ops.py", "conv2d_transpose": "test_conv_ops.py",
    "depthwise_conv2d": "test_conv_ops.py", "pool2d": "test_conv_ops.py",
    "max_pool2d_with_index": "test_conv_ops.py",
    "unpool": "test_conv_ops.py", "roi_pool": "test_conv_ops.py",
    # sequence family — LoD semantics in tests/test_sequence_ops.py
    "sequence_pool": "test_sequence_ops.py",
    "sequence_first_step": "test_sequence_ops.py",
    "sequence_last_step": "test_sequence_ops.py",
    "sequence_expand": "test_sequence_ops.py",
    "sequence_reshape": "test_sequence_ops.py",
    "sequence_erase": "test_sequence_ops.py",
    "sequence_conv": "test_sequence_ops.py",
    "sequence_pad": "test_sequence_ops.py",
    "sequence_unpad": "test_sequence_ops.py",
    "sequence_softmax": "test_sequence_ops.py",
    # CRF / CTC / detection e2e — tests/test_detection_crf_ctc.py
    "linear_chain_crf": "test_detection_crf_ctc.py",
    "crf_decoding": "test_detection_crf_ctc.py",
    "warpctc": "test_detection_crf_ctc.py",
    "ctc_align": "test_detection_crf_ctc.py",
    "multiclass_nms": "test_detection_crf_ctc.py",
    "chunk_eval": "test_detection_crf_ctc.py",
    "auc": "test_io_and_m2.py",
    # recurrent/control flow — tests/test_control_flow_rnn.py
    "lstm": "test_control_flow_rnn.py", "gru": "test_control_flow_rnn.py",
    "recurrent": "test_control_flow_rnn.py",
    "while": "test_control_flow_rnn.py",
    # beam search — tests/test_beam_search.py
    "beam_search": "test_beam_search.py",
    "beam_search_decode": "test_beam_search.py",
    # rematerialization regions — tests/test_recompute.py
    "recompute_block": "test_recompute.py",
    # parallel/distributed subsystems — dedicated suites
    "sp_attention": "test_parallel_integration.py",
    "moe_ffn": "test_pipeline_moe.py",
    "send": "test_distributed.py", "recv": "test_distributed.py",
    "listen_and_serv": "test_distributed.py",
    "prefetch": "test_distributed.py",
    "split_ids": "test_distributed.py",
    "send_sparse": "test_dist_lookup_table.py",
    "ssd_loss": "test_ssd.py",
    # fused ops (ISSUE 15) — only ever emitted by transform/fusion.py;
    # their lowerings delegate to the component ops covered above, and
    # the fusion tier pins golden rewrites + bitwise execution identity
    "fused_matmul_bias_act": "test_specialize.py",
    "fused_scale_cast": "test_specialize.py",
}

# ops with no one-op test by design; each entry documents why
EXEMPT = {
    "print": "side-effect op (jax.debug.print); smoke-run only",
    "delete_var": "env mutation only; exercised by While-loop cleanup",
    "range": "requires static (trace-time constant) Start/End/Step; "
             "exercised via layers that emit constant inputs",
    "send_barrier": "emitted by DistributeTranspiler; exercised end-to-end "
                    "by test_distributed.py pserver-mode parity tests",
    "pipeline_stack": "emitted by transformer_lm_parallel(pp>1); exercised "
                      "end-to-end by test_parallel_integration.py "
                      "test_flagship_pp_parity",
}


def test_registry_completeness():
    from paddle_tpu.core import registry
    here = os.path.dirname(os.path.abspath(__file__))
    sweep_text = open(os.path.join(here, "test_ops_sweep.py")).read() + \
        open(os.path.join(here, "test_ops_sweep2.py")).read()
    missing, stale = [], []
    for op in sorted(registry.registered_ops()):
        if op in EXEMPT:
            continue
        if op in COVERED_ELSEWHERE:
            path = os.path.join(here, COVERED_ELSEWHERE[op])
            text = open(path).read()
            # substring, not word-boundary: op names legitimately appear
            # inside test identifiers (test_sp_attention_...)
            if op not in text:
                stale.append("%s -> %s" % (op, COVERED_ELSEWHERE[op]))
            continue
        if not re.search(r'"%s"' % re.escape(op), sweep_text):
            missing.append(op)
    assert not stale, "COVERED_ELSEWHERE entries not found in file: %s" % stale
    assert not missing, (
        "registered ops with no test coverage (add a sweep case or a "
        "COVERED_ELSEWHERE/EXEMPT entry): %s" % missing)


def test_print_op_smoke():
    from op_test import on_tpu_place
    if on_tpu_place():
        # axon PJRT transport has no host send/recv callbacks, which
        # jax.debug.print needs (EXEMPT_TPU in tests_tpu/run_sweep.py)
        pytest.skip("axon transport lacks host callbacks")
    x = _r(2, 2, seed=178)
    got = run_op("print", {"In": x}, {"message": "sweep"}, ["Out"])
    np.testing.assert_allclose(np.asarray(got["Out"]), x)


def test_positive_negative_pair_op():
    # query 0: labels 2,1 scores 0.9,0.4 -> positive; query 1: labels
    # (2,1),(2,0),(1,0): one wrong order -> 2 pos 1 neg
    score = np.array([[0.9], [0.4], [0.3], [0.7], [0.5]], np.float32)
    label = np.array([[2], [1], [2], [1], [0]], np.float32)
    qid = np.array([[0], [0], [1], [1], [1]], np.int64)
    got = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": qid}, {},
                 ["PositivePair", "NegativePair", "NeutralPair"])
    assert float(np.asarray(got["PositivePair"])[0]) == 2.0
    assert float(np.asarray(got["NegativePair"])[0]) == 2.0
    assert float(np.asarray(got["NeutralPair"])[0]) == 0.0


def test_reorder_lod_tensor_by_rank_op():
    prog = fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="x", shape=(6, 2), dtype="float32", is_data=True,
                   lod_level=1)
    blk.create_var(name="table")
    blk.append_op("lod_rank_table", {"X": ["x"]}, {"Out": ["table"]}, {})
    blk.create_var(name="out")
    blk.append_op("reorder_lod_tensor_by_rank",
                  {"X": ["x"], "RankTable": ["table"]}, {"Out": ["out"]},
                  {})
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    t = fluid.LoDTensor(x)
    t.set_recursive_sequence_lengths([[2, 4]])
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        out, = exe.run(prog, feed={"x": t}, fetch_list=["out"])
    # longer sequence (rows 2..5) first, then rows 0..1
    want = np.concatenate([x[2:], x[:2]])
    np.testing.assert_allclose(np.asarray(out), want)


def test_positive_negative_pair_weighted():
    score = np.array([[0.9], [0.4]], np.float32)
    label = np.array([[2], [1]], np.float32)
    qid = np.array([[0], [0]], np.int64)
    weight = np.array([[3.0], [1.0]], np.float32)
    got = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": qid,
                  "Weight": weight}, {}, ["PositivePair", "NegativePair"])
    # one correctly-ordered pair with weight (3+1)/2
    assert float(np.asarray(got["PositivePair"])[0]) == 2.0
    assert float(np.asarray(got["NegativePair"])[0]) == 0.0


def test_reorder_lod_tensor_by_rank_rowwise():
    # LoD-less X: rows reorder by the rank table's decreasing-length order
    prog = fluid.Program()
    blk = prog.global_block()
    blk.create_var(name="seq", shape=(5, 1), dtype="float32", is_data=True,
                   lod_level=1)
    blk.create_var(name="x", shape=(2, 3), dtype="float32", is_data=True)
    blk.create_var(name="table")
    blk.append_op("lod_rank_table", {"X": ["seq"]}, {"Out": ["table"]}, {})
    blk.create_var(name="out")
    blk.append_op("reorder_lod_tensor_by_rank",
                  {"X": ["x"], "RankTable": ["table"]}, {"Out": ["out"]},
                  {})
    seq = fluid.LoDTensor(np.zeros((5, 1), np.float32))
    seq.set_recursive_sequence_lengths([[2, 3]])
    x = np.array([[1, 1, 1], [2, 2, 2]], np.float32)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        out, = exe.run(prog, feed={"seq": seq, "x": x},
                       fetch_list=["out"])
    np.testing.assert_allclose(np.asarray(out), x[[1, 0]])


def test_positive_negative_pair_chunked_matches_direct():
    # >2048 rows exercises the chunked [chunk, N] path; counts must match
    # the direct computation
    rng = np.random.RandomState(7)
    n = 2500
    score = rng.rand(n, 1).astype(np.float32)
    label = rng.randint(0, 3, (n, 1)).astype(np.float32)
    qid = rng.randint(0, 50, (n, 1)).astype(np.int64)
    got = run_op("positive_negative_pair",
                 {"Score": score, "Label": label, "QueryID": qid}, {},
                 ["PositivePair", "NegativePair", "NeutralPair"])
    s, l, q = score.ravel(), label.ravel(), qid.ravel()
    pos = neg = 0
    for i in range(n):
        same = (q == q[i]) & (l[i] > l)
        pos += int(np.sum(same & (s[i] > s)))
        neg += int(np.sum(same & (s[i] < s)))
    assert float(np.asarray(got["PositivePair"])[0]) == pos
    assert float(np.asarray(got["NegativePair"])[0]) == neg
