"""Book test: understand_sentiment through the paddle.v2 API, written
in the canonical v2 script shape (reference capability: the v2 book
chapter's stacked-LSTM and sequence-conv networks over imdb —
integer_value_sequence data, embedding, lstmemory with activation
objects, pooling with paddle.pooling.Max(), attr.Param regularization,
networks.sequence_conv_pool).

L9 closure (round-4 directive #6) — second of the two near-verbatim v2
book scripts backing COVERAGE's L9 row."""

import numpy as np

import paddle_tpu.v2 as paddle


def stacked_lstm_net(data, class_dim=2, emb_dim=32, hid_dim=32,
                     stacked_num=3):
    assert stacked_num % 2 == 1
    fc_para_attr = paddle.attr.Param(learning_rate=1.0)
    lstm_para_attr = paddle.attr.Param(initial_std=0.0, learning_rate=1.0)
    relu = paddle.activation.Relu()
    linear = paddle.activation.Linear()

    emb = paddle.layer.embedding(input=data, size=emb_dim)
    fc1 = paddle.layer.fc(input=emb, size=hid_dim, act=linear,
                          param_attr=fc_para_attr)
    lstm1 = paddle.layer.lstmemory(input=fc1, act=relu)

    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = paddle.layer.fc(input=inputs, size=hid_dim, act=linear,
                             param_attr=fc_para_attr)
        lstm = paddle.layer.lstmemory(
            input=fc, reverse=(i % 2) == 0, act=relu)
        inputs = [fc, lstm]

    fc_last = paddle.layer.pooling(input=inputs[0],
                                   pooling_type=paddle.pooling.Max())
    lstm_last = paddle.layer.pooling(input=inputs[1],
                                     pooling_type=paddle.pooling.Max())
    output = paddle.layer.fc(input=[fc_last, lstm_last], size=class_dim,
                             act=paddle.activation.Softmax(),
                             param_attr=fc_para_attr)
    return output


def convolution_net(data, class_dim=2, emb_dim=32, hid_dim=32):
    emb = paddle.layer.embedding(input=data, size=emb_dim)
    conv_3 = paddle.networks.sequence_conv_pool(
        input=emb, context_len=3, hidden_size=hid_dim)
    conv_4 = paddle.networks.sequence_conv_pool(
        input=emb, context_len=4, hidden_size=hid_dim)
    output = paddle.layer.fc(input=[conv_3, conv_4], size=class_dim,
                             act=paddle.activation.Softmax())
    return output


def _train(net_fn, passes=4):
    import paddle_tpu as fluid
    fluid.switch_main_program(fluid.Program())
    fluid.switch_startup_program(fluid.Program())

    paddle.init(use_gpu=False, trainer_count=1)
    word_dict = paddle.dataset.imdb.word_dict()
    data = paddle.layer.data(
        name="word",
        type=paddle.data_type.integer_value_sequence(len(word_dict)))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    output = net_fn(data)
    cost = paddle.layer.classification_cost(input=output, label=label)

    parameters = paddle.parameters.create(cost)
    adam_optimizer = paddle.optimizer.Adam(
        learning_rate=2e-3,
        regularization=paddle.optimizer.L2Regularization(rate=8e-4))
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=adam_optimizer)

    costs = []

    def event_handler(event):
        if isinstance(event, paddle.event.EndPass):
            costs.append(event.cost)

    trainer.train(
        reader=paddle.batch(
            paddle.reader.shuffle(
                paddle.dataset.imdb.train(word_dict, n=256),
                buf_size=256),
            batch_size=32),
        num_passes=passes, event_handler=event_handler)
    assert costs[-1] < costs[0], costs

    result = trainer.test(
        reader=paddle.batch(paddle.dataset.imdb.test(word_dict, n=64),
                            batch_size=32))
    assert np.isfinite(result.cost)
    return costs


def test_v2_understand_sentiment_stacked_lstm():
    _train(stacked_lstm_net)


def test_v2_understand_sentiment_conv():
    _train(convolution_net)
