"""Optimizing IR passes over core/program.py's Program.

The reference stack's multi-device SSA graph builder (SURVEY layer 4)
was a *transform* tier: it rewrote ProgramDesc graphs before execution.
paddle_tpu.analysis is read-only — it lints and prices programs without
ever rewriting one. This module is where analysis grows hands: a
``Pass`` rewrites a Program clone, a ``PassManager`` drives passes to a
fixed point, and every shipped pass is semantics-preserving by
construction — ``verify_bitwise`` re-executes the transformed program
and asserts fetch outputs are BITWISE-identical to the untransformed
one (the tier-1 contract, tests/test_transform.py).

Why these rewrites matter when XLA optimizes anyway: the Executor
*traces* every op in the block before XLA sees anything — dead chains
and duplicate subgraphs cost trace time on every compile, bloat the
jaxpr the analyzer and the cost model walk, and on the eager/host-op
path they execute for real. Shrinking the IR shrinks all three.

Purity model (what a pass may touch):
  * RNG ops (``registry.OpInfo.stateful_rng``) are pinned in place:
    each draws from the trace-order fold_in stream, so removing or
    deduplicating one would shift every later op's stream position and
    break bitwise identity (dropout masks, sampled negatives).
  * host (IO) ops, grad markers, ``print``, ops with sub-block attrs
    (control flow), in-place updaters (an output name that is also an
    input name) and writers of persistable vars are side-effecting
    roots: never removed, never deduplicated, never folded.
  * everything else is a pure function of its inputs + attrs.
"""

import collections
import time

import numpy as np

from ..core import registry
from ..core.program import Block, Operator, Parameter

# ops that are side-effecting regardless of registry info
_SIDE_EFFECT_TYPES = frozenset({
    "feed", "fetch", "print",
    "backward_marker", "calc_gradient_marker",
})

# grad markers name their dataflow in attrs, not input slots
_MARKER_ATTR_INPUTS = {
    "backward_marker": ("param_names", "loss_name"),
    "calc_gradient_marker": ("input_names", "target_names"),
}


class _Opaque(Exception):
    """Raised while canonicalizing attrs we refuse to reason about."""


def _attr_key(v):
    """Hashable canonical form of one attr value (CSE key material)."""
    if isinstance(v, Block):
        raise _Opaque(v)
    if isinstance(v, np.ndarray):
        return ("nd", v.dtype.str, v.shape, v.tobytes())
    if isinstance(v, (list, tuple)):
        return ("seq",) + tuple(_attr_key(x) for x in v)
    if isinstance(v, dict):
        return ("map", tuple(sorted((k, _attr_key(x))
                                    for k, x in v.items())))
    return (type(v).__name__, v)


def _has_subblock(op):
    return any(isinstance(v, Block) for v in op.attrs.values())


def _marker_input_names(op):
    names = []
    for attr in _MARKER_ATTR_INPUTS.get(op.type, ()):
        v = op.attr(attr)
        if isinstance(v, str):
            names.append(v)
        elif v:
            names.extend(str(n) for n in v)
    return names


def op_inputs(op):
    """All names an op reads, including grad-marker attr references."""
    return op.input_names + _marker_input_names(op)


def is_rng_op(op):
    info = registry.lookup(op.type)
    # unknown op type: assume the worst (it may draw from the stream)
    return info is None or info.stateful_rng


def is_side_effecting(op, persistable):
    """True when an op must stay, in place, regardless of use: it does
    IO, draws RNG (stream position!), owns control flow, updates state
    in place, or writes a persistable var (the step's lasting effect)."""
    if op.type in _SIDE_EFFECT_TYPES or _has_subblock(op):
        return True
    if registry.is_host_op(op.type) or is_rng_op(op):
        return True
    outs = set(op.output_names)
    if outs & set(op.input_names):      # in-place update
        return True
    return bool(outs & persistable)


def _subblock_needed(program):
    """Names referenced from any sub-block: control-flow bodies read
    parent-block vars by name, invisibly to the global op list."""
    needed = set()
    for blk in program.blocks[1:]:
        for op in blk.ops:
            needed.update(op_inputs(op))
            needed.update(op.output_names)
    return needed


def _def_counts(block):
    c = collections.Counter()
    for op in block.ops:
        for n in op.output_names:
            c[n] += 1
    return c


class Pass:
    """One rewrite over a Program's global block.

    Contract: ``rewrite(program, keep)`` mutates ``program`` in place
    (the PassManager hands it a clone) and returns the number of ops it
    removed or replaced. ``keep`` is the set of var names whose values
    must survive (fetch targets); persistable vars are always kept.
    Every pass must be semantics-preserving: the verify phase
    (``verify_bitwise``) re-executes and compares fetches bitwise."""

    name = "?"
    doc = ""

    def rewrite(self, program, keep):
        raise NotImplementedError


class DeadOpEliminationPass(Pass):
    """Remove ops whose outputs no fetch, persistable write or
    side-effecting op (transitively) consumes.

    Beyond ``Program.prune()``: prune backward-slices to explicit
    targets and is meant for carving inference graphs (it drops
    optimizer ops!); this is a liveness pass — roots are the keep set
    PLUS every side-effecting op, so training semantics survive while
    dead chains (including chains that feed only other dead ops, which
    prune's target-walk keeps when any link shares a var with a live
    chain's input set) are dropped."""

    name = "dead_op"
    doc = "liveness-rooted dead-op elimination (beyond prune())"

    def rewrite(self, program, keep):
        gb = program.global_block()
        persistable = {v.name for v in gb.vars.values() if v.persistable}
        needed = set(keep) | _subblock_needed(program)
        live = []
        for op in reversed(gb.ops):
            if is_side_effecting(op, persistable) \
                    or set(op.output_names) & needed:
                live.append(op)
                needed.update(op_inputs(op))
        if len(live) == len(gb.ops):
            return 0
        removed = len(gb.ops) - len(live)
        live.reverse()
        gb.ops = live
        program._bump_version()
        return removed


class CSEPass(Pass):
    """Common-subexpression elimination: two pure ops with the same
    type, attrs and (version-tracked) input values compute the same
    thing — the later one is dropped and its output names rewritten to
    the earlier one's.

    Safety: only ops whose outputs are written EXACTLY once in the
    block participate (the IR is not SSA; a name redefined later would
    let a rewritten consumer read the wrong generation), and outputs in
    the keep/persistable set are never dropped (their name must hold a
    value at fetch/commit time)."""

    name = "cse"
    doc = "common-subexpression elimination over pure ops"

    def rewrite(self, program, keep):
        gb = program.global_block()
        persistable = {v.name for v in gb.vars.values() if v.persistable}
        protected = set(keep) | persistable | _subblock_needed(program)
        # grad markers reference their dataflow through ATTRS, which the
        # rename map never rewrites — a producer of a marker-referenced
        # name must survive under its own name
        for op in gb.ops:
            protected.update(_marker_input_names(op))
        defs = _def_counts(gb)
        version = collections.Counter()
        rename = {}
        seen = {}
        new_ops = []
        removed = 0
        for op in gb.ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [rename.get(n, n) for n in names]
            key = None
            if not is_side_effecting(op, persistable):
                try:
                    key = (
                        op.type,
                        tuple(sorted(
                            (slot,
                             tuple((n, version[n]) for n in names))
                            for slot, names in op.inputs.items())),
                        tuple(sorted((k, _attr_key(v))
                                     for k, v in op.attrs.items())),
                        tuple(sorted((slot, len(names))
                                     for slot, names in
                                     op.outputs.items())),
                    )
                except _Opaque:
                    key = None
            prev = seen.get(key) if key is not None else None
            eliminable = (
                prev is not None
                and all(defs[n] == 1 for n in op.output_names)
                and all(defs[n] == 1
                        for names in prev.values() for n in names)
                and not (set(op.output_names) & protected))
            if eliminable:
                for slot, names in op.outputs.items():
                    for mine, theirs in zip(names, prev[slot]):
                        if mine != theirs:
                            rename[mine] = theirs
                removed += 1
                continue
            if key is not None and key not in seen:
                seen[key] = {slot: list(names)
                             for slot, names in op.outputs.items()}
            for n in op.output_names:
                version[n] += 1
            new_ops.append(op)
        if removed:
            gb.ops = new_ops
            program._bump_version()
        return removed


class ConstantFoldPass(Pass):
    """Evaluate pure ops whose inputs are all compile-time constants
    and fold the result into an initialized var: the op is replaced by
    an ``assign_value`` op carrying the computed array (the IR's
    "initialized var" form — serializable, and its lowering
    materializes exactly the bits computed here, on the same backend).

    Constant sources are ``fill_constant`` / ``assign_value`` ops
    (evaluated but left in place — they are already minimal; a source
    made dead by folding its consumer is removed by the dead-op pass in
    the same fixed-point loop). Folding caps the materialized size
    (``max_elements``) so it never bakes a recompile-hazard-sized
    constant into the graph."""

    name = "constant_fold"
    doc = "evaluate all-constant pure ops into assign_value ops"

    _SOURCES = frozenset({"fill_constant", "assign_value"})

    def __init__(self, max_elements=65536):
        self.max_elements = int(max_elements)

    def _evaluate(self, op, const_env):
        """Run the op's real lowering on the concrete constant inputs;
        None when anything about it resists folding."""
        import jax.numpy as jnp
        info = registry.lookup(op.type)
        if info is None:
            return None

        def no_rng():
            raise _Opaque(op)   # a pure op must not draw

        env = {n: jnp.asarray(const_env[n]) for n in op.input_names}
        ctx = registry.LowerContext(env, no_rng, block=op.block)
        try:
            info.lower(ctx, op)
        except Exception:
            return None
        # lowerings may publish SIDECAR env entries beyond the declared
        # outputs (e.g. sequence ops write "<out>@LOD"); an assign_value
        # replacement cannot reproduce those, so such ops do not fold
        declared = set(op.input_names) | set(op.output_names)
        if any(k not in declared for k in env):
            return None
        outs = {}
        for n in op.output_names:
            v = env.get(n)
            if v is None or not hasattr(v, "shape"):
                return None
            arr = np.asarray(v)
            if arr.size > self.max_elements:
                return None
            outs[n] = arr
        return outs

    def rewrite(self, program, keep):
        gb = program.global_block()
        persistable = {v.name for v in gb.vars.values() if v.persistable}
        defs = _def_counts(gb)
        const_env = {}
        folded = 0
        for i, op in enumerate(list(gb.ops)):
            if is_side_effecting(op, persistable) or _has_subblock(op):
                # a redefinition kills constness of the name
                for n in op.output_names:
                    const_env.pop(n, None)
                continue
            inputs_const = all(n in const_env for n in op.input_names)
            single_def = all(defs[n] == 1 for n in op.output_names)
            if not (inputs_const and single_def):
                for n in op.output_names:
                    const_env.pop(n, None)
                continue
            if op.type in self._SOURCES:
                outs = self._evaluate(op, const_env)
                if outs:
                    const_env.update(outs)
                continue
            outs = self._evaluate(op, const_env)
            if not outs or len(outs) != 1:
                continue
            (name, arr), = outs.items()
            gb.ops[i] = Operator(
                gb, "assign_value", None, {"Out": [name]},
                {"shape": list(arr.shape), "dtype": str(arr.dtype),
                 "values": np.ascontiguousarray(arr)})
            const_env[name] = arr
            folded += 1
        if folded:
            program._bump_version()
        return folded


def default_passes():
    """The shipped pipeline, in application order: fold constants so
    duplicate results unify, dedup, drop what fell dead, then fuse the
    surviving chains (fusion last: dedup first means one fused op per
    unique chain, and fusion output never grows the CSE search
    space)."""
    from .fusion import FusionPass
    return [ConstantFoldPass(), CSEPass(), DeadOpEliminationPass(),
            FusionPass()]


def passes_by_name():
    """Name -> instance for every selectable pass: the default pipeline
    plus the opt-in passes 'all' deliberately excludes (bf16_cast is
    rtol-gated, not bitwise — see transform/infer.py)."""
    from .infer import Bf16CastPass
    table = {p.name: p for p in default_passes()}
    table["bf16_cast"] = Bf16CastPass()
    return table


def resolve_passes(spec):
    """'all' / 'none' / comma list -> ordered Pass instances (the
    transform_passes flag grammar, shared by the CLI and the armed
    executor path)."""
    spec = (spec or "all").strip().lower()
    if spec in ("", "none", "0"):
        return []
    if spec in ("all", "1", "true"):
        return default_passes()
    table = passes_by_name()
    out = []
    for name in spec.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in table:
            raise ValueError(
                "unknown transform pass %r (have: %s)"
                % (name, ", ".join(sorted(table))))
        out.append(table[name])
    return out


class TransformResult:
    """PassManager output: the transformed clone + per-pass accounting
    (``stats[pass_name]`` = ops removed or rewritten by that pass),
    per-PATTERN fusion hits (``patterns``), plus the op counts
    before/after for the one-line story."""

    def __init__(self, program, stats, ops_before, ops_after, rounds,
                 patterns=None):
        self.program = program
        self.stats = stats            # OrderedDict pass -> changes
        self.ops_before = ops_before
        self.ops_after = ops_after
        self.rounds = rounds
        self.patterns = dict(patterns or {})   # pattern -> hits

    @property
    def ops_removed(self):
        return self.ops_before - self.ops_after

    def to_dict(self):
        return {"ops_before": self.ops_before,
                "ops_after": self.ops_after,
                "ops_removed": self.ops_removed,
                "rounds": self.rounds,
                "passes": dict(self.stats),
                "patterns": dict(self.patterns)}


class PassManager:
    """Drives passes to a fixed point over a CLONE of the input program
    (the caller's program is never mutated). The transformed clone
    carries ``_transform_meta`` — parent version, new version, pass
    stats — so the monitor's recompile classifier can attribute a
    post-transform compile to the transform instead of counting a
    mystery new program (see monitor/runtime.on_compile)."""

    def __init__(self, passes=None, max_rounds=8):
        self.passes = list(passes if passes is not None
                           else default_passes())
        self.max_rounds = int(max_rounds)

    def run(self, program, keep=()):
        from .. import monitor as _mon
        clone = program.clone()
        keep = tuple(str(k) for k in keep)
        stats = collections.OrderedDict((p.name, 0) for p in self.passes)
        patterns = collections.OrderedDict()
        ops_before = len(clone.global_block().ops)
        rounds = 0
        for _ in range(self.max_rounds):
            rounds += 1
            changed = 0
            for p in self.passes:
                before = len(clone.global_block().ops)
                t0 = time.perf_counter()
                n = p.rewrite(clone, keep)
                dt = time.perf_counter() - t0
                after = len(clone.global_block().ops)
                stats[p.name] += n
                changed += n
                hits = getattr(p, "last_patterns", None)
                if hits:
                    for pat, c in hits.items():
                        patterns[pat] = patterns.get(pat, 0) + c
                _mon.on_transform(clone, p.name, before, after, dt,
                                  changes=n, patterns=hits)
            if not changed:
                break
        ops_after = len(clone.global_block().ops)
        clone._bump_version()
        clone._transform_meta = {
            "parent_version": program._version,
            "version": clone._version,
            "passes": dict(stats),
            "patterns": {k: v for k, v in patterns.items() if v},
            "ops_removed": ops_before - ops_after,
        }
        return TransformResult(clone, stats, ops_before, ops_after,
                               rounds, patterns=patterns)


def maybe_transform_for_build(program, fetch_names):
    """Armed-executor hook (PADDLE_TPU_TRANSFORM=1): called by
    Executor._build on every compile-cache MISS, so a transformed
    program compiles while the cache key — original program + version +
    signature — stays the caller's. Off (the default), one flag check.

    The transformed clone is MEMOIZED on the original program per
    (version, pass list, keep set): a feed-signature churn that misses
    the compile cache repeatedly does not re-run the pipeline (constant
    folding executes real lowerings). The latest clone's meta is also
    mirrored onto the original as ``_transform_applied`` so the
    monitor's compile classifier — which sees the CALLER's program —
    can attribute the compile to the transform.

    Host-op programs pass through untouched (they run on the eager
    path, where op identity is the execution order), as do programs
    already carrying a transform meta (idempotence)."""
    from .. import flags
    if not flags.get_flag("transform"):
        # drop any stale mirror: a disarmed compile builds the REAL
        # program, and must not keep classifying as transformed
        program.__dict__.pop("_transform_applied", None)
        return program
    if getattr(program, "_transform_meta", None) is not None:
        return program
    if any(registry.is_host_op(o.type)
           for o in program.global_block().ops):
        program.__dict__.pop("_transform_applied", None)
        return program
    passes = resolve_passes(flags.get_flag("transform_passes"))
    if not passes:
        program.__dict__.pop("_transform_applied", None)
        return program
    key = (program._version,
           tuple(p.name for p in passes),
           tuple(sorted(str(k) for k in fetch_names)))
    memo = program.__dict__.setdefault("_transform_builds", {})
    clone = memo.get(key)
    if clone is None:
        clone = PassManager(passes).run(program, keep=fetch_names).program
        if len(memo) >= 4:    # bound: each clone pins a whole program
            memo.clear()
        memo[key] = clone
    program._transform_applied = clone._transform_meta
    return clone


# --------------------------------------------------------------------------
# verify phase: the semantics-preservation contract, checked for real
# --------------------------------------------------------------------------

def _bitwise_equal(a, b):
    from ..core.lod import LoDTensor
    if isinstance(a, LoDTensor) or isinstance(b, LoDTensor):
        if not (isinstance(a, LoDTensor) and isinstance(b, LoDTensor)):
            return False
        return a.lod == b.lod and _bitwise_equal(
            np.asarray(a.data), np.asarray(b.data))
    a, b = np.asarray(a), np.asarray(b)
    return (a.dtype == b.dtype and a.shape == b.shape
            and a.tobytes() == b.tobytes())


def verify_bitwise(main, startup, feed_fn, fetch_names, transformed,
                   steps=2, seed=0):
    """Execute ``main`` and ``transformed`` from identical initial
    state and feeds for ``steps`` real Executor steps; every fetch of
    every step must be BITWISE-identical (dtype, shape, bytes).

    Both runs use fresh Executors (RNG counters at 0) over copies of
    one startup-initialized scope, so the only degree of freedom is the
    transform itself. Returns (ok, detail_str)."""
    import paddle_tpu as fluid

    base = fluid.Scope()
    exe0 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(base):
        exe0.run(startup)
    rng = np.random.RandomState(seed)
    feeds = [feed_fn(rng) for _ in range(steps)]
    names = [v.name for v in main.global_block().vars.values()
             if v.persistable]

    def fork():
        sc = fluid.Scope()
        for n in names:
            v = base.find_var(n)
            if v is not None:
                sc.set(n, np.array(np.asarray(v)))
        return sc

    runs = []
    for prog in (main, transformed):
        exe = fluid.Executor(fluid.CPUPlace())
        sc = fork()
        with fluid.scope_guard(sc):
            runs.append([exe.run(prog, feed=f,
                                 fetch_list=list(fetch_names))
                         for f in feeds])
    for step, (ref, got) in enumerate(zip(*runs)):
        for name, a, b in zip(fetch_names, ref, got):
            if not _bitwise_equal(a, b):
                return False, (
                    "fetch %r diverged at step %d: %r vs %r"
                    % (name, step, np.asarray(a), np.asarray(b)))
    return True, "ok"
