"""fluid.average.WeightedAverage + fluid.evaluator façade parity
(reference python/paddle/fluid/average.py, evaluator.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def test_weighted_average():
    wa = fluid.average.WeightedAverage()
    wa.add(2.0, 1)
    wa.add(4.0, 3)
    assert abs(wa.eval() - 3.5) < 1e-9
    wa.reset()
    with pytest.raises(ValueError):
        wa.eval()
    with pytest.raises(ValueError):
        wa.add("nope", 1)


def test_evaluator_aliases_are_metrics():
    assert fluid.evaluator.ChunkEvaluator is fluid.metrics.ChunkEvaluator
    assert fluid.evaluator.EditDistance is fluid.metrics.EditDistance


def test_detection_map_rejects_unknown_ap_version():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        with pytest.raises(ValueError, match="ap_version"):
            fluid.evaluator.DetectionMAP(det, gt, ap_version="7point")


def test_detection_map_integral_and_difficult():
    """Round-4 closures: integral AP and VOC-style difficult-GT
    exclusion. One TP at rank 1 + one FP at rank 2 over 2 easy GT:
    integral AP = (1/1)·(1/2) = 0.5; marking the missed GT difficult
    makes the TP cover ALL easy GT -> AP 1.0."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        diff = fluid.layers.data("diff", [1])
        m_int = fluid.evaluator.DetectionMAP(det, gt,
                                             ap_version="integral")
        m_nd = fluid.evaluator.DetectionMAP(det, gt, gt_difficult=diff,
                                            evaluate_difficult=False,
                                            ap_version="integral")
        exe = fluid.Executor(fluid.CPUPlace())
        dv = np.array([[0, 0.9, 0, 0, 10, 10],
                       [0, 0.8, 50, 50, 60, 60]], np.float32)
        gv = np.array([[0, 0, 0, 10, 10],
                       [0, 20, 20, 30, 30]], np.float32)
        difficult = np.array([[0.0], [1.0]], np.float32)
        a, b = exe.run(main, feed={"det": dv, "gt": gv,
                                   "diff": difficult},
                       fetch_list=[m_int.metrics[0], m_nd.metrics[0]])
        np.testing.assert_allclose(np.asarray(a), [0.5], rtol=1e-5)
        np.testing.assert_allclose(np.asarray(b), [1.0], rtol=1e-5)


def test_detection_map_evaluator():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        m = fluid.evaluator.DetectionMAP(det, gt)
        exe = fluid.Executor(fluid.CPUPlace())
        # two perfect detections -> mAP 1.0
        dv = np.array([[0, 0.9, 0, 0, 10, 10],
                       [1, 0.8, 20, 20, 30, 30]], np.float32)
        gv = np.array([[0, 0, 0, 10, 10],
                       [1, 20, 20, 30, 30]], np.float32)
        for _ in range(3):
            mv, = exe.run(main, feed={"det": dv, "gt": gv},
                          fetch_list=m.metrics)
            m.update(mv)
        out = m.eval()
    np.testing.assert_allclose(out, [1.0], rtol=1e-5)
    m.reset()
    with pytest.raises(ValueError):
        m.eval()


def test_detection_map_duplicates_are_false_positives():
    """One-to-one GT assignment (VOC visited flags): a duplicate
    detection of an already-claimed GT is a false positive, so AP stays
    in [0, 1] — two boxes on one GT give integral AP 1.0 (the TP covers
    the single GT) with the duplicate only hurting precision, never
    adding recall."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        m = fluid.layers.detection_map(det, gt, ap_version="integral")
        exe = fluid.Executor(fluid.CPUPlace())
        dv = np.array([[0, 0.9, 0, 0, 10, 10],
                       [0, 0.8, 0, 0, 10, 10]], np.float32)
        gv = np.array([[0, 0, 0, 10, 10]], np.float32)
        mv, = exe.run(main, feed={"det": dv, "gt": gv}, fetch_list=[m])
        np.testing.assert_allclose(np.asarray(mv), [1.0], rtol=1e-5)
        # three GT, two dups on the first: integral AP = (1/1)/3 = 1/3
        gv3 = np.array([[0, 0, 0, 10, 10], [0, 20, 20, 30, 30],
                        [0, 40, 40, 50, 50]], np.float32)
        mv3, = exe.run(main, feed={"det": dv, "gt": gv3},
                       fetch_list=[m])
        np.testing.assert_allclose(np.asarray(mv3), [1.0 / 3], rtol=1e-5)


def test_layers_detection_map_validates_knobs():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        with pytest.raises(ValueError, match="ap_version"):
            fluid.layers.detection_map(det, gt, ap_version="7point")
        with pytest.raises(ValueError, match="difficult"):
            fluid.layers.detection_map(det, gt,
                                       evaluate_difficult=False)


def test_detection_map_per_class_average():
    """class_num > 0 -> true mAP (detection_map_op.h): per-class AP
    averaged over classes with GT. Crafted so pooled != per-class:
    class 2's lone TP ranks above class 1's FP+TP.
      per-class integral: AP(c2)=1, AP(c1)=1/2 -> mAP 0.75
      pooled ranked list: (1/1 + 2/3)/2 = 0.8333
    """
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        m_cls = fluid.layers.detection_map(det, gt, class_num=3,
                                           ap_version="integral")
        m_pool = fluid.layers.detection_map(det, gt,
                                            ap_version="integral")
        exe = fluid.Executor(fluid.CPUPlace())
        dv = np.array([[2, 0.9, 20, 20, 30, 30],     # TP class 2
                       [1, 0.8, 50, 50, 60, 60],     # FP class 1
                       [1, 0.7, 0, 0, 10, 10]],      # TP class 1
                      np.float32)
        gv = np.array([[1, 0, 0, 10, 10],
                       [2, 20, 20, 30, 30]], np.float32)
        a, b = exe.run(main, feed={"det": dv, "gt": gv},
                       fetch_list=[m_cls, m_pool])
        np.testing.assert_allclose(np.asarray(a), [0.75], rtol=1e-5)
        np.testing.assert_allclose(np.asarray(b), [5.0 / 6], rtol=1e-5)


def test_detection_map_skips_undetected_classes():
    """CalcMAP parity (detection_map_op.h GetMAP): a class with ground
    truth but ZERO detections has empty true_pos/false_pos maps and the
    reference `continue`s past it — it must not enter the mAP
    denominator as AP=0. One perfect TP for class 1 + undetected class
    2 GT -> mAP = AP(c1) = 1.0 (not 0.5)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        m = fluid.layers.detection_map(det, gt, class_num=3,
                                       ap_version="integral")
        exe = fluid.Executor(fluid.CPUPlace())
        dv = np.array([[1, 0.9, 10, 10, 20, 20]], np.float32)
        gv = np.array([[1, 10, 10, 20, 20],
                       [2, 50, 50, 60, 60]], np.float32)
        mv, = exe.run(main, feed={"det": dv, "gt": gv}, fetch_list=[m])
        np.testing.assert_allclose(np.asarray(mv), [1.0], rtol=1e-5)


def test_detection_map_evaluator_requires_difficult_input():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        det = fluid.layers.data("det", [6])
        gt = fluid.layers.data("gt", [5])
        with pytest.raises(ValueError, match="difficult"):
            fluid.evaluator.DetectionMAP(det, gt,
                                         evaluate_difficult=False)
