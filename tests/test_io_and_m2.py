"""M2 training-completeness tests: save/load, inference model round-trip,
atomic checkpointing, LR schedules, nets composites, conv+bn inference
fusion, metrics, profiler."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid


def _mlp(img_dim=16, classes=4):
    x = fluid.layers.data("x", [img_dim])
    label = fluid.layers.data("label", [1], dtype="int64")
    pred = fluid.layers.fc(x, classes, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    return x, label, pred, loss


def test_save_load_params_roundtrip(tmp_path):
    x, label, pred, loss = _mlp()
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    d = np.random.rand(8, 16).astype(np.float32)
    y = np.random.randint(0, 4, (8, 1)).astype(np.int64)
    exe.run(feed={"x": d, "label": y}, fetch_list=[loss])
    # eval on a pruned program: the full program would also run the
    # optimizer ops (whole-program semantics, like the reference)
    eval_prog = fluid.default_main_program().prune([pred])
    before, = exe.run(eval_prog, feed={"x": d}, fetch_list=[pred])

    fluid.io.save_params(exe, str(tmp_path / "model"))
    # clobber params, then restore
    scope = fluid.global_scope()
    for p in fluid.default_main_program().all_parameters():
        scope.set(p.name, np.zeros_like(np.asarray(scope.find_var(p.name))))
    fluid.io.load_params(exe, str(tmp_path / "model"))
    after, = exe.run(eval_prog, feed={"x": d}, fetch_list=[pred])
    np.testing.assert_allclose(before, after, rtol=1e-6)


def test_save_load_combined_file(tmp_path):
    x, label, pred, loss = _mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_persistables(exe, str(tmp_path), filename="all_params")
    scope = fluid.global_scope()
    names = [p.name for p in fluid.default_main_program().all_parameters()]
    orig = {n: np.asarray(scope.find_var(n)).copy() for n in names}
    for n in names:
        scope.set(n, np.zeros_like(orig[n]))
    fluid.io.load_persistables(exe, str(tmp_path), filename="all_params")
    for n in names:
        np.testing.assert_allclose(np.asarray(scope.find_var(n)), orig[n])


def test_inference_model_roundtrip(tmp_path):
    x, label, pred, loss = _mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    d = np.random.rand(4, 16).astype(np.float32)
    eval_prog = fluid.default_main_program().prune([pred])
    want, = exe.run(eval_prog, feed={"x": d}, fetch_list=[pred])
    fluid.io.save_inference_model(str(tmp_path / "infer"), ["x"], [pred],
                                  exe)
    # fresh scope + program, as a separate serving process would have
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = fluid.io.load_inference_model(
            str(tmp_path / "infer"), exe)
        assert feeds == ["x"]
        got, = exe.run(prog, feed={"x": d}, fetch_list=fetches,
                       scope=scope2)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_checkpoint_atomic_and_corrupt_recovery(tmp_path):
    x, label, pred, loss = _mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    ck = str(tmp_path / "ckpt")
    fluid.io.save_checkpoint(ck, step=1)
    scope = fluid.global_scope()
    names = [p.name for p in fluid.default_main_program().all_parameters()]
    vals1 = {n: np.asarray(scope.find_var(n)).copy() for n in names}
    # step 2 checkpoint, then corrupt it — loader must fall back to step 1
    scope.set(names[0], vals1[names[0]] + 1.0)
    fluid.io.save_checkpoint(ck, step=2)
    with open(os.path.join(ck, "ckpt-2.npz"), "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad\xbe\xef")
    for n in names:
        scope.set(n, np.zeros_like(vals1[n]))
    step = fluid.io.load_checkpoint(ck)
    assert step == 1
    np.testing.assert_allclose(np.asarray(scope.find_var(names[0])),
                               vals1[names[0]])


@pytest.mark.parametrize("decay_fn,kwargs,expect", [
    ("exponential_decay", dict(learning_rate=1.0, decay_steps=2,
                               decay_rate=0.5), [1.0, 0.7071, 0.5]),
    ("natural_exp_decay", dict(learning_rate=1.0, decay_steps=1,
                               decay_rate=0.5),
     [1.0, np.exp(-0.5), np.exp(-1.0)]),
    ("inverse_time_decay", dict(learning_rate=1.0, decay_steps=1,
                                decay_rate=1.0), [1.0, 0.5, 1 / 3]),
    ("piecewise_decay", dict(boundaries=[1, 2], values=[1.0, 0.5, 0.1]),
     [1.0, 0.5, 0.1]),
])
def test_lr_schedules(decay_fn, kwargs, expect):
    lr = getattr(fluid.layers, decay_fn)(**kwargs)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got = []
    for _ in range(len(expect)):
        v, = exe.run(feed={}, fetch_list=[lr])
        got.append(float(np.asarray(v).reshape(-1)[0]))
    np.testing.assert_allclose(got, expect, rtol=1e-3)


def test_noam_decay_peaks_at_warmup():
    lr = fluid.layers.noam_decay(d_model=64, warmup_steps=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    vals = [float(np.asarray(exe.run(feed={}, fetch_list=[lr])[0]).reshape(-1)[0])
            for _ in range(6)]
    assert np.argmax(vals) == 2          # peak at step == warmup_steps
    assert vals[3] > vals[4] > vals[5]   # then decays


def test_scaled_dot_product_attention_runs():
    q = fluid.layers.data("q", [6, 16])
    k = fluid.layers.data("k", [6, 16])
    v = fluid.layers.data("v", [6, 16])
    ctx = fluid.nets.scaled_dot_product_attention(q, k, v, num_heads=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    out, = exe.run(feed={"q": rng.rand(2, 6, 16).astype(np.float32),
                         "k": rng.rand(2, 6, 16).astype(np.float32),
                         "v": rng.rand(2, 6, 16).astype(np.float32)},
                   fetch_list=[ctx])
    assert out.shape == (2, 6, 16)
    # attention over softmax weights keeps values in the convex hull
    assert out.min() >= 0.0 and out.max() <= 1.0


def test_glu():
    x = fluid.layers.data("x", [8])
    out = fluid.nets.glu(x, dim=-1)
    exe = fluid.Executor(fluid.CPUPlace())
    d = np.random.rand(3, 8).astype(np.float32)
    got, = exe.run(feed={"x": d}, fetch_list=[out])
    a, b = d[:, :4], d[:, 4:]
    np.testing.assert_allclose(got, a / (1 + np.exp(-b)), rtol=1e-5)


def test_inference_transpiler_fuses_conv_bn():
    img = fluid.layers.data("img", [3, 8, 8])
    conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                               bias_attr=False)
    bn = fluid.layers.batch_norm(conv, act=None)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    # give BN non-trivial statistics
    scope = fluid.global_scope()
    prog = fluid.default_main_program()
    bn_op = [op for op in prog.global_block().ops
             if op.type == "batch_norm"][0]
    rng = np.random.RandomState(3)
    scope.set(bn_op.input("Mean")[0], rng.rand(4).astype(np.float32))
    scope.set(bn_op.input("Variance")[0],
              (0.5 + rng.rand(4)).astype(np.float32))
    d = rng.rand(2, 3, 8, 8).astype(np.float32)
    infer_prog = prog.prune([bn]).clone(for_test=True)
    want, = exe.run(infer_prog, feed={"img": d}, fetch_list=[bn.name])

    t = fluid.InferenceTranspiler()
    t.transpile(infer_prog)
    types = [op.type for op in infer_prog.global_block().ops]
    assert "batch_norm" not in types
    got, = exe.run(infer_prog, feed={"img": d}, fetch_list=[bn.name])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_metrics_accumulators():
    m = fluid.metrics.Accuracy()
    m.update(0.5, 10)
    m.update(1.0, 10)
    assert abs(m.eval() - 0.75) < 1e-9
    p = fluid.metrics.Precision()
    p.update(np.array([1, 1, 0, 1]), np.array([1, 0, 0, 1]))
    assert abs(p.eval() - 2 / 3) < 1e-9
    auc = fluid.metrics.Auc(num_thresholds=200)
    preds = np.array([0.1, 0.4, 0.35, 0.8])
    labels = np.array([0, 0, 1, 1])
    auc.update(preds, labels)
    assert 0.6 < auc.eval() < 0.9


def test_profiler_summary(capsys):
    with fluid.profiler.profiler("CPU", "total", "/tmp/ptpu_prof"):
        with fluid.profiler.RecordEvent("stepA"):
            pass
    outp = capsys.readouterr().out
    assert "stepA" in outp


def test_profiler_memory_column(capsys):
    """FLAGS profile_memory surfaces live/peak device bytes per event
    (operator.cc:576-578 FLAGS_benchmark parity): the summary table grows
    Live/Peak columns and a compiled step records nonzero usage."""
    import numpy as np
    from paddle_tpu import flags, profiler

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    flags.set_flag("profile_memory", True)
    profiler.reset_profiler()
    try:
        with fluid.program_guard(main, startup), fluid.scope_guard(scope):
            x = fluid.layers.data("x", [64])
            h = fluid.layers.fc(x, 128)
            loss = fluid.layers.mean(h)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            with fluid.profiler.profiler("CPU", "total",
                                         "/tmp/ptpu_prof_mem"):
                exe.run(main,
                        feed={"x": np.ones((8, 64), np.float32)},
                        fetch_list=[loss])
    finally:
        flags.set_flag("profile_memory", None)
    outp = capsys.readouterr().out
    assert "PeakHBM(MB)" in outp and "Live(MB)" in outp
    assert "exe.run(compiled)" in outp
    # the recorded peak is nonzero (params + activations live on device)
    row = [ln for ln in outp.splitlines()
           if ln.startswith("exe.run(compiled)")][0]
    assert float(row.split()[-1]) > 0
