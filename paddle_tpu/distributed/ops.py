"""Distributed host ops: send / recv / prefetch / listen_and_serv /
split_ids / split_selected_rows / merge_selected_rows / sum-over-rows.

Reference parity: operators/{send,send_vars,send_barrier,recv,prefetch,
listen_and_serv,split_ids,split_selected_rows}_op.cc. These are HOST ops —
they do IO, so the Executor runs programs containing them in eager
(op-interpreter) mode instead of whole-program XLA (core/executor.py
_run_eager), exactly where the reference also left compiled-graph land.
"""

import numpy as np

from ..core.registry import register
from ..core.selected_rows import SelectedRows
from ..resilience.retry import default_policy
from .rpc import RPCClient, StaleIncarnationError


import threading
import weakref

# Per-THREAD connection cache: RPCClient is a plain socket with no
# framing lock, so two in-process trainers (threads) must not share one
# — each thread keeps its own connections, like two trainer processes
# would. The registry holds WEAK references: when a trainer thread
# dies, its cache is collected (closing the sockets via refcount)
# instead of pinning file descriptors forever. reset_clients() closes
# every live thread's connections.


class _Cache(dict):
    """dict subclass so the registry can hold weak references."""

    # dict disables hashing (value equality); the registry needs
    # identity hashing to hold caches in a WeakSet
    __hash__ = object.__hash__


_TLS = threading.local()
_ALL_CACHES = weakref.WeakSet()
_ALL_LOCK = threading.Lock()


def _client(ep):
    cache = getattr(_TLS, "clients", None)
    if cache is None:
        cache = _TLS.clients = _Cache()
        with _ALL_LOCK:
            _ALL_CACHES.add(cache)
    cli = cache.get(ep)
    if cli is None:
        # flag-gated transparent reconnect/retry (rpc_retry, default
        # on): the executor's tagged round sends are exactly-once
        # server-side, so a broken socket is re-issued instead of
        # killing the step — rpc.py documents which verbs qualify
        cli = cache[ep] = RPCClient(ep, retry=default_policy())
    return cli


def reset_clients():
    # close + clear every live thread's connections; threads reconnect
    # lazily on next use
    with _ALL_LOCK:
        caches = list(_ALL_CACHES)
    for cache in caches:
        for cli in cache.values():
            cli.close()
        cache.clear()


def _round_tag(ctx, op):
    """Idempotency tag for this trainer's current round:
    t<trainer>:i<incarnation>:s<seq>. The server replaces a retried
    (name, tag) send, drops sends/barriers of already-applied rounds,
    and evicts pending grads of a dead incarnation (rpc.py SEND/BARR).
    None when the executor doesn't track rounds."""
    seq = getattr(ctx, "run_seq", None)
    if seq is None:
        return None
    return "t%s:i%s:s%d" % (op.attr("trainer_id", 0),
                            getattr(ctx, "incarnation", "0"), seq)


def _bump_incarnation(ctx, exc):
    """A server judged this trainer's incarnation stale (clock skew
    after an elastic reschedule can make a LIVE replacement look like a
    dead straggler — rpc.StaleIncarnationError). Re-incarnate the
    executor past the server's max epoch and rebuild ctx.incarnation,
    preserving the per-program nonce suffix."""
    ex = getattr(ctx, "executor", None)
    old = getattr(ctx, "incarnation", "")
    exec_inc = getattr(ex, "_incarnation", "")
    if not exec_inc or not old.startswith(exec_inc):
        raise exc          # no executor-owned incarnation to renew
    program_nonce = old[len(exec_inc):]
    ctx.incarnation = ex._reincarnate(exc.max_epoch) + program_nonce


def _retrying_round(ctx, op, body):
    """Run `body(tag)` with stale-incarnation recovery.

    Re-incarnating changes the round tag, and the server's idempotency
    bookkeeping is keyed by it — so a retry must (a) replay EVERY
    tagged send body of this round, not just the failing op (an earlier
    op's pending grads under the old tag are evicted by the first
    new-tag message and would otherwise be silently lost), and (b) skip
    endpoints whose round barrier already completed (their round closed
    WITH our old-tag grads applied; a new-tag resend would bypass the
    seq dedup and double-apply). Bodies honor (b) via
    ``ctx.round_closed_eps``. Both records live on ctx, which is fresh
    per Executor.run, i.e. per round. Bounded attempts: several servers
    may each hold a higher max epoch, needing one bump per offender."""
    journal = getattr(ctx, "_round_journal", None)
    if journal is None:
        journal = ctx._round_journal = []
        ctx.round_closed_eps = set()
    journal.append(body)
    replay_from = len(journal) - 1       # first attempt: just this op
    for _ in range(5):
        try:
            tag = _round_tag(ctx, op)
            for b in journal[replay_from:]:
                b(tag)
            return
        except StaleIncarnationError as exc:
            _bump_incarnation(ctx, exc)
            replay_from = 0              # new tag: replay the full round
    raise RuntimeError(
        "send round still judged stale after 5 re-incarnations")


@register("send", host=True)
def _send(ctx, op):
    """Push each input var to its endpoint (send_op.cc / send_vars)."""
    eps = op.attr("epmap") or op.attr("endpoints") or []
    names = op.input("X")

    def round_body(tag):
        closed = getattr(ctx, "round_closed_eps", set())
        for i, name in enumerate(names):
            ep = eps[i % len(eps)]
            if ep in closed:
                continue    # that server's round already applied these
            val = ctx.get(name)
            if not isinstance(val, SelectedRows):
                val = np.asarray(val)
            _client(ep).send_var(op.attr("send_names", names)[i]
                                 if op.attr("send_names") else name, val,
                                 tag=tag)
        # barrier EVERY transpiled endpoint, not just the ones that
        # received a dense grad: a server owning only a sparse-table
        # shard still needs this trainer's round signal
        # (listen_and_serv fan_in semantics)
        if op.attr("sync", True):
            for ep in set(op.attr("endpoints") or eps):
                if ep in closed:
                    continue
                _client(ep).barrier(tag=tag)
                closed.add(ep)

    _retrying_round(ctx, op, round_body)


@register("send_barrier", host=True)
def _send_barrier(ctx, op):
    for ep in (op.attr("endpoints") or []):
        _client(ep).barrier()


@register("send_sparse", host=True)
def _send_sparse(ctx, op):
    """Route a distributed embedding-table gradient to its shards: pair
    each prefetch's ids with the grad of its output rows, sum duplicate
    ids, split by ``id % num_shards`` and SEND each part as SelectedRows
    with GLOBAL row ids under ``grad_name`` (the reference trainer's
    split_ids + send-of-SelectedRows, distribute_transpiler.py:201-255).
    No barrier here — the program's trailing send_barrier closes the
    round for every endpoint."""
    eps = op.attr("epmap") or op.attr("endpoints") or []
    grad_name = op.attr("grad_name")
    height = int(op.attr("height"))
    id_names = op.input("Ids")
    grad_names = op.input("Grads")
    all_ids = []
    all_rows = []
    for idn, gn in zip(id_names, grad_names):
        ids = np.asarray(ctx.env[idn]).reshape(-1).astype(np.int64)
        if ids.size == 0:
            continue
        g = np.asarray(ctx.env[gn])
        g = g.reshape(ids.size, -1)
        all_ids.append(ids)
        all_rows.append(g)
    if not all_ids:
        return            # an empty batch sends nothing this round
    ids = np.concatenate(all_ids)
    rows = np.concatenate(all_rows)
    # sum duplicate ids (a batch repeats hot ids; the update must see one
    # accumulated row per id, lookup_table_grad SelectedRows semantics)
    uniq, inv = np.unique(ids, return_inverse=True)
    acc = np.zeros((len(uniq), rows.shape[1]), rows.dtype)
    np.add.at(acc, inv, rows)
    n = max(1, len(eps))

    def round_body(tag):
        closed = getattr(ctx, "round_closed_eps", set())
        for i, ep in enumerate(eps):
            mask = (uniq % n) == i
            if not mask.any() or ep in closed:
                continue
            _client(ep).send_var(
                grad_name, SelectedRows(uniq[mask], acc[mask], height),
                tag=tag)

    _retrying_round(ctx, op, round_body)


@register("recv", host=True)
def _recv(ctx, op):
    eps = op.attr("epmap") or op.attr("endpoints") or []
    outs = op.output("Out")
    fetch_names = op.attr("recv_names") or outs
    for i, out in enumerate(outs):
        ep = eps[i % len(eps)]
        ctx.env[out] = _client(ep).get_var(fetch_names[i])


@register("prefetch", host=True)
def _prefetch(ctx, op):
    """Fetch embedding rows by id from the sharded table
    (prefetch_op.cc + distributed lookup table)."""
    eps = op.attr("epmap") or op.attr("endpoints") or []
    table = op.attr("table_name")
    ids_arr = np.asarray(ctx.in1(op, "X"))
    ids = ids_arr.reshape(-1).astype(np.int64)
    # shard ids across endpoints like split_ids (round robin by id % n);
    # UNIQUE ids per shard — a batch repeats hot ids, and SelectedRows
    # merge would sum duplicate returned rows (it is a grad-accumulate)
    n = len(eps)
    parts = [np.unique(ids[ids % n == i]) for i in range(n)]
    merged = None
    for ep, part in zip(eps, parts):
        if len(part) == 0:
            continue
        sr = _client(ep).prefetch(table, part)
        merged = sr if merged is None else merged.merge(sr)
    if merged is None:
        merged = SelectedRows(height=0)
    # return rows aligned with the request order
    width = merged.value.shape[1] if merged.value.ndim > 1 else 1
    lut = {int(r): i for i, r in enumerate(merged.rows)}
    out = np.stack([merged.value[lut[int(i)]] for i in ids]) \
        if len(ids) else np.zeros((0, width), np.float32)
    # embedding-layer output shape: ids shape (trailing 1 stripped) + [D]
    lead = ids_arr.shape
    if lead and lead[-1] == 1:
        lead = lead[:-1]
    ctx.set_out(op, "Out", out.reshape(tuple(lead) + (width,)))


@register("listen_and_serv", host=True)
def _listen_and_serv(ctx, op):
    """Run the parameter-server loop until shutdown
    (listen_and_serv_op.cc:76-239). The optimize step per round runs the
    op's sub-block through the eager interpreter with merged grads bound."""
    from .rpc import VariableServer
    from ..core.executor import _lower_op
    from ..core.registry import LowerContext

    fan_in = int(op.attr("Fanin", op.attr("fan_in", 1)))
    sync_mode = bool(op.attr("sync_mode", True))
    endpoint = op.attr("endpoint", "127.0.0.1:0")
    port_file = op.attr("port_file")
    param_names = op.attr("param_names") or []
    grad_names = op.attr("grad_names") or []
    sparse_tables = dict(op.attr("sparse_tables") or {})
    sparse_grad_of = {t + "@GRAD": t for t in sparse_tables}
    blocks = op.attr("optimize_blocks") or []
    if not isinstance(blocks, (list, tuple)):
        blocks = [blocks]
    # every var the optimize blocks read or write, minus the per-round
    # gradients: params AND optimizer state (moments, beta pows, lr).
    # All of it must live in the server store ACROSS rounds — resetting
    # adam moments every round would silently break stateful optimizers
    # (ParameterServer2 keeps momentum buffers server-side the same way).
    state_names = set(param_names)
    for blk in blocks:
        for op2 in blk.ops:
            for coll in (op2.inputs, op2.outputs):
                for ns in coll.values():
                    state_names.update(ns)
    state_names -= set(grad_names)
    state_names -= {g for g in state_names if g.endswith("@GRAD")}

    def optimize_fn(store, merged_grads):
        env = dict(ctx.env)
        env.update(store)
        for g, val in merged_grads.items():
            tbl = sparse_grad_of.get(g)
            if tbl is not None and isinstance(val, SelectedRows):
                # sharded-table grad: global row ids → this shard's
                # compact local indices (g // n); KEEP SelectedRows so
                # the optimizer applies a sparse row update, never a
                # dense [V, D] materialization
                meta = sparse_tables[tbl]
                n = int(meta["num_shards"])
                local_h = int(np.asarray(store[tbl]).shape[0]) \
                    if tbl in store else -(-int(meta["height"]) // n)
                env[g] = SelectedRows(np.asarray(val.rows) // n,
                                      val.value, local_h)
                continue
            env[g] = val if not isinstance(val, SelectedRows) \
                else val.to_dense()
        sctx = LowerContext(env, ctx._rng_fn, executor=ctx.executor)
        # async mode delivers one grad at a time: skip optimize ops whose
        # grad input didn't arrive, and propagate the skip transitively so
        # consumers of a skipped op's outputs (e.g. clip → sgd chains)
        # don't run against missing/stale values
        tainted = {g for g in grad_names if g not in merged_grads}
        for blk in blocks:
            for op2 in blk.ops:
                refs = [n for ns in op2.inputs.values() for n in ns]
                if any(n in tainted for n in refs):
                    tainted.update(n for ns in op2.outputs.values()
                                   for n in ns)
                    continue
                _lower_op(sctx, op2)
        for p in state_names:
            if p in env and p not in tainted:
                store[p] = np.asarray(env[p])

    host, port = endpoint.rsplit(":", 1)
    server = VariableServer(host=host, port=int(port), fan_in=fan_in,
                            optimize_fn=optimize_fn, port_file=port_file,
                            sync=sync_mode, sparse_tables=sparse_tables)
    # publish initial params + optimizer state from the scope/env
    for p in state_names:
        if p in ctx.env:
            server.store[p] = np.asarray(ctx.env[p])
    server.start()
    ctx.env["@PSERVER@"] = server
    if op.attr("blocking", True):
        server._shutdown.wait()
    # commit updated params + state back
    for p in state_names:
        if p in server.store:
            ctx.env[p] = server.store[p]


@register("split_ids", host=True)
def _split_ids(ctx, op):
    ids = np.asarray(ctx.in1(op, "Ids")).reshape(-1).astype(np.int64)
    outs = op.output("Out")
    n = len(outs)
    for i, out in enumerate(outs):
        ctx.env[out] = ids[ids % n == i].reshape(-1, 1)


@register("split_selected_rows", host=True)
def _split_selected_rows(ctx, op):
    sr = ctx.in1(op, "X")
    outs = op.output("Out")
    height_sections = op.attr("height_sections") or []
    n = len(outs)
    bounds = np.cumsum([0] + list(height_sections)) if height_sections \
        else np.linspace(0, sr.height, n + 1).astype(np.int64)
    for i, out in enumerate(outs):
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        mask = (sr.rows >= lo) & (sr.rows < hi)
        ctx.env[out] = SelectedRows(sr.rows[mask] - lo, sr.value[mask],
                                    hi - lo)


@register("merge_selected_rows", host=True)
def _merge_selected_rows(ctx, op):
    sr = ctx.in1(op, "X")
    if isinstance(sr, SelectedRows):
        uniq, inv = np.unique(sr.rows, return_inverse=True)
        out = np.zeros((len(uniq),) + sr.value.shape[1:], sr.value.dtype)
        np.add.at(out, inv, sr.value)
        ctx.set_out(op, "Out", SelectedRows(uniq, out, sr.height))
    else:
        ctx.set_out(op, "Out", sr)


@register("lookup_sparse_table", host=True)
def _lookup_sparse_table(ctx, op):
    """Local sparse-table lookup over a SelectedRows-stored table."""
    w = ctx.in1(op, "W")
    ids = np.asarray(ctx.in1(op, "Ids")).reshape(-1).astype(np.int64)
    if isinstance(w, SelectedRows):
        lut = {int(r): i for i, r in enumerate(w.rows)}
        rows = np.stack([w.value[lut[int(i)]] if int(i) in lut
                         else np.zeros(w.value.shape[1], w.value.dtype)
                         for i in ids])
    else:
        rows = np.asarray(w)[np.clip(ids, 0, len(w) - 1)]
    ctx.set_out(op, "Out", rows)
