"""MNIST MLP benchmark — parity with reference benchmark/fluid/mnist.py
(timing protocol: skip first N batches, report avg samples/sec,
mnist.py:38-50)."""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid


def parse_args():
    p = argparse.ArgumentParser("mnist benchmark")
    p.add_argument("--batch_size", type=int, default=128)
    p.add_argument("--iterations", type=int, default=100)
    p.add_argument("--skip_batch_num", type=int, default=5)
    p.add_argument("--pass_num", type=int, default=1)
    p.add_argument("--device", type=str, default="TPU",
                   choices=["CPU", "TPU", "GPU"])
    p.add_argument("--monitor_log", type=str, default="",
                   help="arm paddle_tpu.monitor with this flight-recorder"
                        " JSONL path and print the telemetry summary")
    return p.parse_args()


def build():
    img = fluid.layers.data("img", [784])
    label = fluid.layers.data("label", [1], dtype="int64")
    hidden = fluid.layers.fc(img, 128, act="relu")
    hidden = fluid.layers.fc(hidden, 64, act="relu")
    prediction = fluid.layers.fc(hidden, 10, act="softmax")
    cost = fluid.layers.cross_entropy(prediction, label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=0.001).minimize(avg_cost)
    return img, label, avg_cost


def main():
    args = parse_args()
    import contextlib
    with contextlib.ExitStack() as stack:
        mon_sess = None
        if getattr(args, "monitor_log", ""):
            from paddle_tpu import monitor
            # session(): reuses an env-armed ambient config untouched,
            # arms a fresh recorder only when the monitor is off, and
            # reports THIS run's counts as deltas either way; the
            # ExitStack disarms it even when a step raises
            mon_sess = stack.enter_context(
                monitor.session(log_path=args.monitor_log))
            stack.callback(
                lambda: print("monitor: %s" % mon_sess.summary()))
        img, label, avg_cost = build()
        place = fluid.CPUPlace() if args.device == "CPU" \
            else fluid.TPUPlace(0)
        exe = fluid.Executor(place)
        exe.run(fluid.default_startup_program())

        rng = np.random.RandomState(0)
        xs = rng.rand(args.batch_size, 784).astype(np.float32)
        ys = rng.randint(0, 10, (args.batch_size, 1)).astype(np.int64)

        times = []
        for i in range(args.iterations + args.skip_batch_num):
            t0 = time.time()
            loss, = exe.run(feed={"img": xs, "label": ys},
                            fetch_list=[avg_cost])
            _ = float(np.asarray(loss))   # sync
            if i >= args.skip_batch_num:
                times.append(time.time() - t0)
        ips = args.batch_size / np.mean(times)
        print("avg %.4f ms/batch, %.1f imgs/sec" %
              (1000 * np.mean(times), ips))
    return ips


if __name__ == "__main__":
    main()
