"""Kill-and-resume for MESH-mode training (complements test_elastic.py's
pserver/master tier): a trainer process checkpoints every step
(io.save_checkpoint: atomic npz + CRC meta), is SIGKILLed mid-run, and a
fresh process resumes from the newest valid checkpoint — final weights
must exactly match an uninterrupted run, proving optimizer accumulators
(Adam moments) round-trip through the checkpoint too.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np

_WORKER = r"""
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.core import unique_name

ckdir, steps, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]

with unique_name.guard("mr_"):
    x = fluid.layers.data("x", [6])
    y = fluid.layers.data("y", [1])
    h = fluid.layers.fc(x, 12, act="tanh")
    pred = fluid.layers.fc(h, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

main = fluid.default_main_program()
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
start = 0
resumed = fluid.io.load_checkpoint(ckdir, main_program=main)
if resumed is not None:
    start = resumed + 1
    print("resumed from step", resumed, flush=True)
pexe = fluid.ParallelExecutor(loss_name=loss.name, main_program=main,
                              mesh=parallel.make_mesh({"dp": 4}))
rng = np.random.RandomState(7)
batches = [(rng.rand(8, 6).astype(np.float32),
            rng.rand(8, 1).astype(np.float32)) for _ in range(steps)]
for i in range(start, steps):
    xv, yv = batches[i]
    l, = pexe.run([loss], feed={"x": xv, "y": yv})
    fluid.io.save_checkpoint(ckdir, i, main_program=main)
    print("step %%d loss %%.6f" %% (i, float(np.asarray(l))), flush=True)
    if mode == "crash" and i == 2:
        import time
        time.sleep(600)   # parent SIGKILLs us here, mid-run
ws = {v.name: np.asarray(fluid.global_scope().find_var(v.name))
      for v in main.global_block().vars.values()
      if v.persistable and fluid.global_scope().find_var(v.name)
      is not None}
np.savez(ckdir + "/final_%%s.npz" %% mode, **ws)
print("DONE", flush=True)
"""


def _spawn(script, ckdir, steps, mode):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    return subprocess.Popen(
        [sys.executable, str(script), str(ckdir), str(steps), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def test_mesh_training_kill_and_resume(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % {"repo": repo})
    steps = 6

    # uninterrupted baseline
    base_dir = tmp_path / "base"
    base_dir.mkdir()
    p = _spawn(script, base_dir, steps, "plain")
    out, _ = p.communicate(timeout=240)
    assert p.returncode == 0 and "DONE" in out, out[-2000:]

    # crashing run: SIGKILL while the worker sleeps after step 2
    crash_dir = tmp_path / "crash"
    crash_dir.mkdir()
    p = _spawn(script, crash_dir, steps, "crash")
    deadline = time.time() + 180
    while time.time() < deadline:
        if (crash_dir / "meta-2.json").exists():
            break
        if p.poll() is not None:     # died early: surface its traceback
            out, _ = p.communicate(timeout=10)
            raise AssertionError(
                "crash worker exited rc=%s before step 2:\n%s"
                % (p.returncode, out[-2000:]))
        time.sleep(0.5)
    else:
        p.kill()
        out, _ = p.communicate(timeout=10)
        raise AssertionError(
            "crash worker never reached step 2:\n%s" % out[-2000:])
    time.sleep(0.5)
    p.send_signal(signal.SIGKILL)
    p.wait(timeout=30)

    # resume in a FRESH process; must pick up from step 3
    p = _spawn(script, crash_dir, steps, "resume")
    out, _ = p.communicate(timeout=240)
    assert p.returncode == 0 and "DONE" in out, out[-2000:]
    assert "resumed from step 2" in out, out[-2000:]

    base = np.load(base_dir / "final_plain.npz")
    res = np.load(crash_dir / "final_resume.npz")
    assert sorted(base.files) == sorted(res.files)
    for n in base.files:
        # bitwise: the same jitted step on identical float32 inputs is
        # deterministic, so resume must reproduce the baseline exactly
        np.testing.assert_array_equal(res[n], base[n], err_msg=n)
