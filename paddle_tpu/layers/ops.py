"""Auto-generated unary layer wrappers.

Reference parity: python/paddle/fluid/layers/ops.py + layer_function_generator
— one python function per registered activation/elementwise op, generated
from the op registry instead of OpProto introspection.
"""

from .layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink", "sqrt",
    "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round", "reciprocal",
    "log", "square", "softplus", "softsign", "sign", "gelu", "erf",
    "brelu", "leaky_relu", "soft_relu", "elu", "relu6", "pow", "stanh",
    "hard_shrink", "softshrink", "thresholded_relu", "hard_sigmoid", "swish",
    "mish", "silu", "cumsum",
]

_ATTR_NAMES = {
    "brelu": ("t_min", "t_max"),
    "leaky_relu": ("alpha",),
    "soft_relu": ("threshold",),
    "elu": ("alpha",),
    "relu6": ("threshold",),
    "pow": ("factor",),
    "stanh": ("scale_a", "scale_b"),
    "hard_shrink": ("threshold",),
    "softshrink": ("lambda",),
    "thresholded_relu": ("threshold",),
    "hard_sigmoid": ("slope", "offset"),
    "swish": ("beta",),
    "gelu": ("approximate",),
    "cumsum": ("axis", "exclusive", "reverse"),
}


def _make_layer(op_type):
    allowed = _ATTR_NAMES.get(op_type, ())

    def layer(x, name=None, **kwargs):
        attrs = {k: v for k, v in kwargs.items() if k in allowed}
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype,
                                                        shape=x.shape)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    layer.__doc__ = "Elementwise %s (auto-generated wrapper)." % op_type
    return layer


_g = globals()
for _op in _UNARY_OPS:
    _g[_op] = _make_layer(_op)

__all__ = list(_UNARY_OPS)


def elementwise_layer(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        from .math_ops import _broadcast_shape
        helper = LayerHelper(op_type, act=act, name=name)
        out = helper.create_variable_for_type_inference(
            x.dtype, shape=_broadcast_shape(x.shape, y.shape))
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        return helper.append_activation(out)
    layer.__name__ = op_type
    return layer


for _op in ["elementwise_add", "elementwise_sub", "elementwise_mul",
            "elementwise_div", "elementwise_max", "elementwise_min",
            "elementwise_pow"]:
    _g[_op] = elementwise_layer(_op)
    __all__.append(_op)


def _compare_layer(op_type):
    def layer(x, y, cond=None, **ignored):
        helper = LayerHelper(op_type)
        if cond is None:
            cond = helper.create_variable_for_type_inference(
                "bool", shape=x.shape)
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [cond]}, attrs={"axis": -1})
        return cond
    layer.__name__ = op_type
    return layer


for _op in ["less_than", "less_equal", "greater_than", "greater_equal",
            "equal", "not_equal"]:
    _g[_op] = _compare_layer(_op)
    __all__.append(_op)


def logical_op_layer(op_type, binary=True):
    def layer(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference(
                "bool", shape=x.shape)
        inputs = {"X": [x]}
        if binary:
            inputs["Y"] = [y]
        helper.append_op(type=op_type, inputs=inputs, outputs={"Out": [out]})
        return out
    layer.__name__ = op_type
    return layer


for _op in ["logical_and", "logical_or", "logical_xor"]:
    _g[_op] = logical_op_layer(_op)
    __all__.append(_op)
_g["logical_not"] = logical_op_layer("logical_not", binary=False)
__all__.append("logical_not")
