"""paddle_tpu.transform — optimizing IR passes + automatic parallelism.

The write half of the analysis story (ROADMAP direction 4): the
reference's multi-device SSA graph builder was a *transform* tier, and
this package gives the reproduction one.

  passes        Pass / PassManager over core/program.py's IR, with a
                built-in bitwise re-execution verifier. Shipped passes:
                  constant_fold  evaluate all-constant pure ops into
                                 initialized (assign_value) vars
                  cse            common-subexpression elimination
                  dead_op        liveness-rooted dead-op elimination
                                 (beyond Program.prune's target walk)
                  fusion         pattern fusion (matmul+bias+act ->
                                 fused ops, inverse transpose/reshape
                                 chains, scale/cast pairs)
                  bf16_cast      OPT-IN bf16 operand cast (rtol-gated,
                                 excluded from 'all')
  infer         specialize_for_inference — prune + the pipeline into
                the io.save_inference_model servable artifact
  memory        memory_plan — compile-time liveness + greedy best-fit
                buffer reuse (the BuddyAllocator question, static)
  calibrate     --calibrate microbenches -> platform-stamped
                calib.json for plan_cost (flag autoparallel_calib)
  autoparallel  enumerate valid dp/tp/pp/sp/ep DistributedStrategy
                assignments, price them with analysis/cost.step_costs
                + an analytic comm/bubble model calibrated against
                PERF.md, recommend() a ranked list or apply() the top
                plan as a configured ParallelExecutor.

Arm at runtime with PADDLE_TPU_TRANSFORM=1 (pass selection via
PADDLE_TPU_TRANSFORM_PASSES): every compile-cache miss builds from the
transformed clone while the cache key stays the caller's program.

CLI:  python -m paddle_tpu.transform --all           pass pipeline +
                                                     verification gate
      python -m paddle_tpu.transform --plan transformer 8
"""

from .passes import (  # noqa: F401
    Pass, PassManager, TransformResult, ConstantFoldPass, CSEPass,
    DeadOpEliminationPass, default_passes, passes_by_name,
    resolve_passes, maybe_transform_for_build, verify_bitwise)
from .fusion import FusionPass, PATTERN_NAMES  # noqa: F401
from .infer import (Bf16CastPass, SpecializeResult,  # noqa: F401
                    specialize_for_inference)
from .memory import MemoryPlan, memory_plan  # noqa: F401
from .autoparallel import (  # noqa: F401
    ModelSpec, Plan, pipeline_utilization, calibration, candidates,
    plan_cost, plan_hbm_bytes, rank, recommend, apply, model_spec,
    embedding_wire_costs, recommend_embedding_placement, PLANNABLE)
