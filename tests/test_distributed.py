"""Distributed tier tests: RPC serde, VariableServer, pserver-mode
DistributeTranspiler — the localhost multi-process pattern of
test_dist_train.py, run with the server on a thread."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.distributed.rpc import (VariableServer, RPCClient,
                                        serialize_var, deserialize_var)
from paddle_tpu.distributed import ops as dist_ops


def test_serde_roundtrip_dense_and_sparse():
    arr = np.random.rand(3, 4).astype(np.float32)
    got = deserialize_var(serialize_var(arr))
    np.testing.assert_array_equal(got, arr)

    sr = SelectedRows([1, 5], np.random.rand(2, 4).astype(np.float32), 10)
    got = deserialize_var(serialize_var(sr))
    assert isinstance(got, SelectedRows)
    np.testing.assert_array_equal(got.rows, sr.rows)
    np.testing.assert_allclose(got.value, sr.value)
    assert got.height == 10


def test_selected_rows_merge_and_dense():
    a = SelectedRows([0, 2], [[1., 1.], [2., 2.]], 4)
    b = SelectedRows([2, 3], [[3., 3.], [4., 4.]], 4)
    m = a.merge(b)
    dense = m.to_dense()
    np.testing.assert_allclose(dense, [[1, 1], [0, 0], [5, 5], [4, 4]])


def test_variable_server_put_get_prefetch_barrier():
    applied = []

    def opt(store, grads):
        applied.append({k: np.asarray(v) for k, v in grads.items()})
        for k, g in grads.items():
            p = k.replace("@GRAD", "")
            if p in store:
                store[p] = store[p] - 0.1 * (
                    g.to_dense() if isinstance(g, SelectedRows)
                    else np.asarray(g))

    server = VariableServer(fan_in=2, optimize_fn=opt).start()
    try:
        c1 = RPCClient("127.0.0.1:%d" % server.port)
        c2 = RPCClient("127.0.0.1:%d" % server.port)
        w = np.ones((4, 2), np.float32)
        c1.put_var("w", w)
        np.testing.assert_array_equal(c1.get_var("w"), w)
        # prefetch rows
        sr = c1.prefetch("w", [0, 3])
        np.testing.assert_array_equal(sr.rows, [0, 3])
        np.testing.assert_allclose(sr.value, w[[0, 3]])
        # two trainers send grads then barrier → optimize runs once
        g = np.full((4, 2), 1.0, np.float32)
        c1.send_var("w@GRAD", g)
        c2.send_var("w@GRAD", g)
        t = threading.Thread(target=c2.barrier)
        t.start()
        c1.barrier()
        t.join(timeout=5)
        assert len(applied) == 1
        # merged grad = 2.0 each; w = 1 - 0.1*2 = 0.8
        np.testing.assert_allclose(c1.get_var("w"), 0.8, rtol=1e-6)
    finally:
        server.stop()
        dist_ops.reset_clients()


def _probe_port():
    """Grab an ephemeral port by briefly binding a VariableServer."""
    probe = VariableServer()
    port = probe.port
    probe.stop()
    return "127.0.0.1:%d" % port


def _boot_pserver(pserver_prog, server_scope, lr=0.1):
    """Shared pserver bootstrap: set the optimizer sub-block's
    LearningRate var in the server scope and run listen_and_serv on a
    daemon thread. Returns (thread, listen_and_serv op)."""
    lanv = [op for op in pserver_prog.global_block().ops
            if op.type == "listen_and_serv"][0]
    lr_name = lanv.attr("optimize_blocks")[0].ops[0].input(
        "LearningRate")[0]
    server_scope.set(lr_name, np.asarray([lr], np.float32))

    def run():
        fluid.Executor(fluid.CPUPlace()).run(
            pserver_prog, feed={}, fetch_list=[], scope=server_scope)

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th, lanv


def _build_trainer(lr=0.1):
    x = fluid.layers.data("x", [4])
    y = fluid.layers.data("y", [1])
    pred = fluid.layers.fc(x, 1, bias_attr=False,
                           param_attr=fluid.ParamAttr(
                               name="w_dist",
                               initializer=fluid.initializer.Constant(0.0)))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return loss


def test_pserver_mode_training_matches_local():
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype(np.float32)
    yv = (xv @ np.array([1., 2., 3., 4.], np.float32))[:, None]

    # ---- local baseline -------------------------------------------------
    loss = _build_trainer()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for _ in range(5):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    w_local = np.asarray(fluid.global_scope().find_var("w_dist")).copy()

    # ---- distributed: 1 trainer, 1 pserver ------------------------------
    main2, startup2 = fluid.Program(), fluid.Program()
    scope2 = fluid.Scope()
    with fluid.program_guard(main2, startup2), fluid.scope_guard(scope2):
        loss2 = _build_trainer()
        t = fluid.DistributeTranspiler(mode="pserver")
        t.transpile(trainer_id=0, program=main2,
                    pservers="127.0.0.1:0", trainers=1)
        # server on an ephemeral port: build program after picking a port
        ep = _probe_port()
        t._eps = [ep]
        # rewrite trainer endpoints
        for op in main2.global_block().ops:
            if op.type in ("send", "recv"):
                op.attrs["epmap"] = [ep] * len(op.attrs.get("epmap", [ep]))
                op.attrs["endpoints"] = [ep]
        pserver_prog = t.get_pserver_program(ep)
        server_scope = fluid.Scope()
        # initialize server-held state: param + the lr var value
        exe2 = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope2):
            exe2.run(startup2)
        server_scope.set("w_dist", np.zeros((4, 1), np.float32))
        th, _ = _boot_pserver(pserver_prog, server_scope)
        time.sleep(0.5)

        try:
            for _ in range(5):
                exe2.run(main2, feed={"x": xv, "y": yv},
                         fetch_list=[loss2], scope=scope2)
            w_dist = np.asarray(scope2.find_var("w_dist")).copy()
        finally:
            cli = RPCClient(ep)
            cli.shutdown_server()
            cli.close()
            dist_ops.reset_clients()
        th.join(timeout=5)

    np.testing.assert_allclose(w_dist, w_local, rtol=1e-4, atol=1e-5)


def test_variable_server_async_mode():
    """Async SGD (ParameterServer2 async paths): each SEND applies its
    gradient immediately — no fan-in barrier, updates may be stale."""
    def opt(store, grads):
        for k, g in grads.items():
            p = k.replace("@GRAD", "")
            if p in store:
                store[p] = store[p] - 0.1 * (
                    g.to_dense() if isinstance(g, SelectedRows)
                    else np.asarray(g))

    server = VariableServer(fan_in=2, optimize_fn=opt, sync=False).start()
    try:
        c1 = RPCClient("127.0.0.1:%d" % server.port)
        c2 = RPCClient("127.0.0.1:%d" % server.port)
        w = np.ones((4, 2), np.float32)
        c1.put_var("w", w)
        g = np.full((4, 2), 1.0, np.float32)
        # send without any barrier: applied on arrival, sequentially stale
        c1.send_var("w@GRAD", g)
        np.testing.assert_allclose(c1.get_var("w"), 0.9, rtol=1e-6)
        c2.send_var("w@GRAD", g)
        np.testing.assert_allclose(c2.get_var("w"), 0.8, rtol=1e-6)
        # barrier is a no-op in async mode (doesn't block on fan_in=2)
        c1.barrier()
    finally:
        server.stop()
        dist_ops.reset_clients()


def test_async_pserver_training_reaches_local_loss():
    """1-trainer async pserver run converges to the sync/local result:
    with a single trainer, apply-on-arrival is the same update sequence."""
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype(np.float32)
    yv = (xv @ np.array([1., 2., 3., 4.], np.float32))[:, None]

    loss = _build_trainer()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    for _ in range(5):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    w_local = np.asarray(fluid.global_scope().find_var("w_dist")).copy()

    main2, startup2 = fluid.Program(), fluid.Program()
    scope2 = fluid.Scope()
    with fluid.program_guard(main2, startup2), fluid.scope_guard(scope2):
        _build_trainer()
        t = fluid.DistributeTranspiler(mode="pserver")
        t.transpile(trainer_id=0, program=main2, pservers="127.0.0.1:0",
                    trainers=1, sync_mode=False)
        ep = _probe_port()
        t._eps = [ep]
        for op in main2.global_block().ops:
            if op.type in ("send", "recv"):
                op.attrs["epmap"] = [ep] * len(op.attrs.get("epmap", [ep]))
                op.attrs["endpoints"] = [ep]
        pserver_prog = t.get_pserver_program(ep)
        server_scope = fluid.Scope()
        exe2 = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope2):
            exe2.run(startup2)
        server_scope.set("w_dist", np.zeros((4, 1), np.float32))
        th, lanv = _boot_pserver(pserver_prog, server_scope)
        assert lanv.attr("sync_mode") is False
        time.sleep(0.5)
        try:
            for _ in range(5):
                exe2.run(main2, feed={"x": xv, "y": yv}, fetch_list=[],
                         scope=scope2)
            w_dist = np.asarray(scope2.find_var("w_dist")).copy()
        finally:
            cli = RPCClient(ep)
            cli.shutdown_server()
            cli.close()
            dist_ops.reset_clients()
        th.join(timeout=5)

    np.testing.assert_allclose(w_dist, w_local, rtol=1e-4, atol=1e-5)


def test_two_pserver_training_matches_local():
    """Round-robin param placement across TWO pservers
    (distributed_splitter parity): a 2-param model trains to the same
    weights as local SGD with each server owning one param."""
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype(np.float32)
    yv = (xv @ np.array([1., 2., 3., 4.], np.float32))[:, None] + 0.5

    def build():
        x = fluid.layers.data("x", [4])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(
            x, 1,
            param_attr=fluid.ParamAttr(
                name="w2p", initializer=fluid.initializer.Constant(0.)),
            bias_attr=fluid.ParamAttr(
                name="b2p", initializer=fluid.initializer.Constant(0.)))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return loss

    # local baseline
    main1, startup1 = fluid.Program(), fluid.Program()
    scope1 = fluid.Scope()
    with fluid.program_guard(main1, startup1), fluid.scope_guard(scope1):
        loss1 = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup1)
        for _ in range(5):
            exe.run(main1, feed={"x": xv, "y": yv}, fetch_list=[loss1])
        w_ref = np.asarray(scope1.find_var("w2p")).copy()
        b_ref = np.asarray(scope1.find_var("b2p")).copy()

    # distributed: 1 trainer, 2 pservers (one param each)
    main2, startup2 = fluid.Program(), fluid.Program()
    scope2 = fluid.Scope()
    with fluid.program_guard(main2, startup2), fluid.scope_guard(scope2):
        loss2 = build()
        eps = [_probe_port(), _probe_port()]
        t = fluid.DistributeTranspiler(mode="pserver")
        t.transpile(trainer_id=0, program=main2, pservers=",".join(eps),
                    trainers=1, startup_program=startup2)
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup2)
        threads = []
        for ep in eps:
            sprog = t.get_pserver_program(ep)
            sscope = fluid.Scope()
            with fluid.scope_guard(sscope):
                fluid.Executor(fluid.CPUPlace()).run(
                    t.get_startup_program(ep))
            th, _ = _boot_pserver(sprog, sscope)
            threads.append(th)
        time.sleep(0.5)
        try:
            for _ in range(5):
                exe2.run(main2, feed={"x": xv, "y": yv},
                         fetch_list=[loss2], scope=scope2)
            w_dist = np.asarray(scope2.find_var("w2p")).copy()
            b_dist = np.asarray(scope2.find_var("b2p")).copy()
        finally:
            for ep in eps:
                cli = RPCClient(ep)
                cli.shutdown_server()
                cli.close()
            dist_ops.reset_clients()
        for th in threads:
            th.join(timeout=5)

    np.testing.assert_allclose(w_dist, w_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(b_dist, b_ref, rtol=1e-4, atol=1e-5)


def test_pserver_startup_program_initializes_owned_params():
    """get_startup_program clones the owned params' initializer ops
    (no longer an empty-Program stub)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        _build_trainer()
        t = fluid.DistributeTranspiler(mode="pserver")
        t.transpile(trainer_id=0, program=main,
                    pservers="127.0.0.1:6170", trainers=1,
                    startup_program=startup)
        sprog = t.get_startup_program("127.0.0.1:6170")
        assert len(sprog.global_block().ops) >= 1
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(sprog)
        w = np.asarray(scope.find_var("w_dist"))
        np.testing.assert_allclose(w, np.zeros((4, 1), np.float32))


def test_split_ids_and_selected_rows_ops():
    ids = np.array([[0], [3], [4], [7]], np.int64)
    x = fluid.layers.data("ids", [1], dtype="int64")
    blk = fluid.default_main_program().current_block()
    o1 = blk.create_var(name="ids_p0", dtype="int64")
    o2 = blk.create_var(name="ids_p1", dtype="int64")
    blk.append_op(type="split_ids", inputs={"Ids": [x]},
                  outputs={"Out": [o1, o2]})
    exe = fluid.Executor(fluid.CPUPlace())
    g1, g2 = exe.run(feed={"ids": ids}, fetch_list=[o1, o2])
    np.testing.assert_array_equal(np.asarray(g1).reshape(-1), [0, 4])
    np.testing.assert_array_equal(np.asarray(g2).reshape(-1), [3, 7])


def test_chunk_parallel_push_roundtrip(monkeypatch):
    """Large values pushed over chunk-parallel side streams arrive
    intact (forced on regardless of core count: the path must be
    correct wherever it is enabled), for PUT, tagged SEND, and
    SelectedRows."""
    from paddle_tpu.distributed import rpc as rpc_mod
    from paddle_tpu.core.selected_rows import SelectedRows
    monkeypatch.setattr(rpc_mod, "_CHUNK_THRESHOLD", 1 << 10)
    monkeypatch.setattr(rpc_mod, "_CHUNK_STREAMS", 3)
    applied = []

    def opt(store, grads):
        applied.append({k: v for k, v in grads.items()})

    server = rpc_mod.VariableServer(fan_in=1, optimize_fn=opt).start()
    cli = rpc_mod.RPCClient("127.0.0.1:%d" % server.port)
    try:
        w = np.arange(300_000, dtype=np.float32).reshape(500, 600)
        cli.put_var("w", w)
        np.testing.assert_array_equal(cli.get_var("w"), w)
        cli.send_var("w@GRAD", 2 * w, tag="t0:iaaa:s0")
        cli.barrier(tag="t0:iaaa:s0")
        assert len(applied) == 1
        np.testing.assert_array_equal(
            np.asarray(applied[0]["w@GRAD"]), 2 * w)
        sr = SelectedRows(np.arange(400, dtype=np.int64),
                          np.ones((400, 700), np.float32), 100000)
        cli.send_var("emb@GRAD", sr)
        with server._lock:
            got = list(server.grads["emb@GRAD"].values())[0]
        np.testing.assert_array_equal(np.asarray(got.rows), sr.rows)
        np.testing.assert_array_equal(np.asarray(got.value), sr.value)
        assert not server._pending_chunks      # transfers fully consumed
    finally:
        cli.shutdown_server()
        cli.close()
