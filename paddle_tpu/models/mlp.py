"""MNIST MLP + conv models (reference benchmark/fluid/mnist.py cnn_model)."""

import paddle_tpu as fluid


def mlp(img, label, hidden_sizes=(128, 64), num_classes=10):
    x = img
    for h in hidden_sizes:
        x = fluid.layers.fc(x, h, act="relu")
    prediction = fluid.layers.fc(x, num_classes, act="softmax")
    cost = fluid.layers.cross_entropy(prediction, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(prediction, label)
    return prediction, avg_cost, acc


def cnn(img, label, num_classes=10):
    """LeNet-style conv net (mnist.py cnn_model: two conv-pool blocks +
    fc softmax head)."""
    conv1 = fluid.layers.conv2d(img, num_filters=20, filter_size=5,
                                act="relu")
    pool1 = fluid.layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = fluid.layers.conv2d(pool1, num_filters=50, filter_size=5,
                                act="relu")
    pool2 = fluid.layers.pool2d(conv2, pool_size=2, pool_stride=2)
    prediction = fluid.layers.fc(pool2, num_classes, act="softmax")
    cost = fluid.layers.cross_entropy(prediction, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(prediction, label)
    return prediction, avg_cost, acc


def zoo_spec():
    """(build_fn, feed_fn) for the MLP Adam train step — one source
    for the analysis (traced) and transform (Program-level) zoo."""
    def build():
        img = fluid.layers.data("img", [784])
        label = fluid.layers.data("label", [1], dtype="int64")
        _, avg_cost, acc = mlp(img, label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        return avg_cost, acc

    def feeds(rng):
        return {"img": rng.rand(8, 784).astype("float32"),
                "label": rng.randint(0, 10, (8, 1)).astype("int64")}

    return build, feeds


def zoo_spec_cnn():
    """(build_fn, feed_fn) for the LeNet CNN Adam train step."""
    def build():
        img = fluid.layers.data("img", [1, 28, 28])
        label = fluid.layers.data("label", [1], dtype="int64")
        _, avg_cost, acc = cnn(img, label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        return avg_cost, acc

    def feeds(rng):
        return {"img": rng.rand(4, 1, 28, 28).astype("float32"),
                "label": rng.randint(0, 10, (4, 1)).astype("int64")}

    return build, feeds


def zoo_spec_cnn_infer():
    """(build_fn, feed_fn) for a COMPOSED inference pipeline — the
    op-chain shapes real deployments accumulate at module seams, which
    the fusion tier exists to erase (ISSUE 15): a raw uint8 image feed
    normalized in-graph (cast -> scale), a conv stage whose producer
    exports NHWC while the consumer expects NCHW (inverse transposes),
    and a flatten the consumer immediately regroups (reshape of a
    reshape), ending in the fc softmax head. Program-zoo only: its
    traced twin would duplicate the CNN's analysis coverage."""
    def build():
        img = fluid.layers.data("img", [1, 28, 28], dtype="uint8")
        x = fluid.layers.cast(img, "float32")
        x = fluid.layers.scale(x, scale=1.0 / 255.0)
        conv = fluid.layers.conv2d(x, num_filters=8, filter_size=5,
                                   act="relu")
        pool = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2)
        nhwc = fluid.layers.transpose(pool, [0, 2, 3, 1])
        nchw = fluid.layers.transpose(nhwc, [0, 3, 1, 2])
        flat = fluid.layers.reshape(nchw, [-1, 8 * 12 * 12])
        grouped = fluid.layers.reshape(flat, [-1, 8, 144])
        pred = fluid.layers.fc(grouped, 10, act="softmax")
        return (pred,)

    def feeds(rng):
        return {"img": rng.randint(
            0, 256, (4, 1, 28, 28)).astype("uint8")}

    return build, feeds


def analysis_entry():
    """Static-analyzer entry: MLP Adam train step (see models/harness)."""
    from .harness import program_entry
    return program_entry(*zoo_spec())


def analysis_entry_cnn():
    """Static-analyzer entry: LeNet CNN Adam train step."""
    from .harness import program_entry
    return program_entry(*zoo_spec_cnn())

