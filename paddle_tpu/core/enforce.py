"""Enforce-style error layer.

Reference parity: paddle/fluid/platform/enforce.h:203 (PADDLE_ENFORCE) and
operator.cc's exception annotation — every kernel failure there carries the
op type and an input/output summary. Here the equivalent surface is *lowering
time*: when an op's lowering rule throws during tracing, the raw JAX error
has no program context, so the Executor wraps it in :class:`EnforceError`
listing the op type, each input/output slot with the traced shape+dtype, and
the op's attributes.
"""

import numpy as np


class EnforceError(RuntimeError):
    """A framework error with program context (PADDLE_ENFORCE analog)."""


def enforce(cond, fmt, *args):
    if not cond:
        raise EnforceError(fmt % args if args else fmt)


def _describe_value(v):
    if v is None:
        return "<not materialized>"
    if isinstance(v, (list, tuple)):
        return "list[%d]" % len(v)
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is None:
        return repr(type(v).__name__)
    return "%s%s" % (np.dtype(dtype).name if dtype is not None else "?",
                     list(shape))


def op_error(op, env, cause, phase="lowering"):
    """Build an EnforceError describing `op` with traced values from `env`."""
    lines = ["%s of op %r failed: %s: %s"
             % (phase, op.type, type(cause).__name__, cause)]
    for slot, names in sorted(op.inputs.items()):
        for n in names:
            lines.append("  in  %s=%r: %s"
                         % (slot, n, _describe_value(env.get(n))))
    for slot, names in sorted(op.outputs.items()):
        for n in names:
            lines.append("  out %s=%r: %s"
                         % (slot, n, _describe_value(env.get(n))))
    attrs = {k: v for k, v in sorted(op.attrs.items())
             if not k.startswith("_")}
    if attrs:
        lines.append("  attrs: %s" % (attrs,))
    err = EnforceError("\n".join(lines))
    err.__cause__ = cause
    return err
