"""Book test: understand_sentiment (reference
python/paddle/fluid/tests/book/test_understand_sentiment.py, stacked-LSTM
variant) — embedding -> fc -> dynamic LSTM -> sequence pools -> softmax
classifier on imdb, trained to an accuracy threshold."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu as fluid


def stacked_lstm_net(data, label, input_dim, class_dim=2, emb_dim=32,
                     hid_dim=64, stacked_num=2):
    # reference stacked_lstm_net shape: fc+lstm pairs (dynamic_lstm's `size`
    # equals the input width = 4*hidden), pool the last pair
    emb = fluid.layers.embedding(data, size=[input_dim, emb_dim])
    fc1 = fluid.layers.fc(emb, hid_dim)
    lstm1, cell1 = fluid.layers.dynamic_lstm(fc1, size=hid_dim)
    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = fluid.layers.fc(fluid.layers.concat(inputs, axis=1), hid_dim)
        lstm, cell = fluid.layers.dynamic_lstm(
            fc, size=hid_dim, is_reverse=True)
        inputs = [fc, lstm]
    fc_last = fluid.layers.sequence_pool(inputs[0], "max")
    lstm_last = fluid.layers.sequence_pool(inputs[1], "max")
    prediction = fluid.layers.fc(
        fluid.layers.concat([fc_last, lstm_last], axis=1),
        class_dim, act="softmax")
    cost = fluid.layers.cross_entropy(prediction, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(prediction, label)
    return avg_cost, acc, prediction


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_understand_sentiment_stacked_lstm():
    data = fluid.layers.data("words", [1], dtype="int64", lod_level=1)
    label = fluid.layers.data("label", [1], dtype="int64")
    avg_cost, acc, _ = stacked_lstm_net(
        data, label, input_dim=paddle.dataset.imdb.VOCAB_SIZE)
    fluid.optimizer.Adam(learning_rate=0.002).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    reader = paddle.batch(paddle.dataset.imdb.train(None), batch_size=16)
    feeder = fluid.DataFeeder([data, label], fluid.CPUPlace())

    first = last = last_acc = None
    for epoch in range(6):
        accs = []
        for batch in reader():
            feed = feeder.feed(batch)
            feed["label"] = np.asarray(feed["label"]).reshape(-1, 1)
            lv, av = exe.run(feed=feed, fetch_list=[avg_cost, acc])
            if first is None:
                first = float(lv)
            last = float(lv)
            accs.append(float(np.asarray(av).ravel()[0]))
        last_acc = float(np.mean(accs))
    assert last < first, (first, last)
    # ABSOLUTE: binary CE starts at ln(2)=0.693; require real learning
    assert last < 0.6, (first, last)
    assert last_acc > 0.7, last_acc   # reference threshold: acc converges
