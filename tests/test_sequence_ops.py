"""Sequence-op tests: flat LoD layout + lengths, numpy references."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _lod_feed(seqs):
    flat = np.concatenate(seqs, axis=0).astype(np.float32)
    t = fluid.create_lod_tensor(flat, [[len(s) for s in seqs]])
    return t, [np.asarray(s, np.float32) for s in seqs]


@pytest.mark.parametrize("ptype,ref", [
    ("sum", lambda s: s.sum(0)),
    ("average", lambda s: s.mean(0)),
    ("sqrt", lambda s: s.sum(0) / np.sqrt(len(s))),
    ("max", lambda s: s.max(0)),
    ("last", lambda s: s[-1]),
    ("first", lambda s: s[0]),
])
def test_sequence_pool(ptype, ref):
    seqs = [np.random.rand(3, 4), np.random.rand(5, 4), np.random.rand(1, 4)]
    t, seqs_f = _lod_feed(seqs)
    x = fluid.layers.data("x", [4], lod_level=1)
    out = fluid.layers.sequence_pool(x, ptype)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": t}, fetch_list=[out])
    want = np.stack([ref(s) for s in seqs_f])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_sequence_first_last_step():
    seqs = [np.random.rand(2, 3), np.random.rand(4, 3)]
    t, seqs_f = _lod_feed(seqs)
    x = fluid.layers.data("x", [3], lod_level=1)
    first = fluid.layers.sequence_first_step(x)
    last = fluid.layers.sequence_last_step(x)
    exe = fluid.Executor(fluid.CPUPlace())
    g1, g2 = exe.run(feed={"x": t}, fetch_list=[first, last])
    np.testing.assert_allclose(g1, np.stack([s[0] for s in seqs_f]),
                               rtol=1e-6)
    np.testing.assert_allclose(g2, np.stack([s[-1] for s in seqs_f]),
                               rtol=1e-6)


def test_sequence_softmax():
    seqs = [np.random.rand(3, 1), np.random.rand(2, 1)]
    t, seqs_f = _lod_feed(seqs)
    x = fluid.layers.data("x", [1], lod_level=1)
    out = fluid.layers.sequence_softmax(x)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": t}, fetch_list=[out])
    want = np.concatenate([np.exp(s) / np.exp(s).sum() for s in seqs_f])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_sequence_expand_encoder_to_decoder():
    # encoder last-state [2, 3] expanded to decoder token counts [4, 2]
    x = fluid.layers.data("x", [3])
    y = fluid.layers.data("y", [1], lod_level=1)
    out = fluid.layers.sequence_expand(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    yv = fluid.create_lod_tensor(np.zeros((6, 1), np.float32), [[4, 2]])
    got, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[out])
    want = np.concatenate([np.tile(xv[0], (4, 1)), np.tile(xv[1], (2, 1))])
    np.testing.assert_allclose(got, want)


def test_sequence_reshape():
    seqs = [np.random.rand(2, 6), np.random.rand(4, 6)]
    t, seqs_f = _lod_feed(seqs)
    x = fluid.layers.data("x", [6], lod_level=1)
    out = fluid.layers.sequence_reshape(x, new_dim=12)
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": t}, fetch_list=[out])
    want = np.concatenate(seqs_f).reshape(-1, 12)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_sequence_conv_respects_boundaries():
    seqs = [np.random.rand(3, 2), np.random.rand(4, 2)]
    t, seqs_f = _lod_feed(seqs)
    x = fluid.layers.data("x", [2], lod_level=1)
    out = fluid.layers.sequence_conv(x, num_filters=5, filter_size=3,
                                     bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    got, = exe.run(feed={"x": t}, fetch_list=[out])
    # numpy reference: per-seq zero-padded context window matmul
    w = np.asarray(fluid.global_scope().find_var(
        [op for op in fluid.default_main_program().global_block().ops
         if op.type == "sequence_conv"][0].input("Filter")[0]))
    outs = []
    for s in seqs_f:
        tlen, d = s.shape
        ctx_rows = []
        for i in range(tlen):
            row = []
            for off in (-1, 0, 1):
                j = i + off
                row.append(s[j] if 0 <= j < tlen else np.zeros(d))
            ctx_rows.append(np.concatenate(row))
        outs.append(np.asarray(ctx_rows) @ w)
    want = np.concatenate(outs)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sequence_pad_unpad_roundtrip():
    seqs = [np.random.rand(2, 3), np.random.rand(4, 3)]
    t, seqs_f = _lod_feed(seqs)
    x = fluid.layers.data("x", [3], lod_level=1)
    padded, length = fluid.layers.sequence_pad(x, maxlen=5)
    unpadded = fluid.layers.sequence_unpad(padded, length)
    exe = fluid.Executor(fluid.CPUPlace())
    gp, gl, gu = exe.run(feed={"x": t},
                         fetch_list=[padded, length, unpadded])
    assert gp.shape == (2, 5, 3)
    np.testing.assert_allclose(gp[0, :2], seqs_f[0], rtol=1e-6)
    np.testing.assert_allclose(gp[0, 2:], 0.0)
    np.testing.assert_allclose(gp[1, :4], seqs_f[1], rtol=1e-6)
    np.testing.assert_array_equal(gl, [2, 4])
    np.testing.assert_allclose(gu[:6], np.concatenate(seqs_f), rtol=1e-6)


def test_sequence_erase():
    seqs = [np.array([[1], [2], [3]]), np.array([[2], [5]])]
    flat = np.concatenate(seqs).astype(np.int32)
    t = fluid.create_lod_tensor(flat, [[3, 2]])
    x = fluid.layers.data("x", [1], dtype="int32", lod_level=1)
    out = fluid.layers.sequence_erase(x, tokens=[2])
    exe = fluid.Executor(fluid.CPUPlace())
    got, = exe.run(feed={"x": t}, fetch_list=[out])
    np.testing.assert_array_equal(got[:3].reshape(-1), [1, 3, 5])


def test_text_classifier_with_sequence_pool_trains():
    # sentiment-style bow model: embedding -> seq avg pool -> fc
    words = fluid.layers.data("words", [1], dtype="int64", lod_level=1)
    label = fluid.layers.data("label", [1], dtype="int64")
    emb = fluid.layers.embedding(words, size=[100, 16])
    pooled = fluid.layers.sequence_pool(emb, "average")
    pred = fluid.layers.fc(pooled, 2, act="softmax")
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    losses = []
    lens = [5, 3, 7, 2]
    ids = rng.randint(0, 100, (sum(lens), 1)).astype(np.int64)
    labels = (ids[np.cumsum(lens) - 1] % 2).astype(np.int64)
    t = fluid.create_lod_tensor(ids, [lens])
    for _ in range(40):
        lv, = exe.run(feed={"words": t, "label": labels},
                      fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    # must memorize 4 sequences, i.e. drop well below the ln(2)=0.693 class
    # prior — a bias-only fit cannot get here (regression guard for LoD
    # propagation through embedding)
    assert losses[-1] < 0.3, losses[-1]


def test_sequence_pool_min_grad_routes_to_winner(rng):
    """MIN pooling's gradient flows to the stored arg-min row only
    (the gather-based backward that replaced segment_min autodiff —
    same remat-safety rework as MAX; ops/sequence_ops._argext_pool)."""
    x = rng.rand(6, 3).astype(np.float32)
    lens = [4, 2]
    xv = fluid.layers.data("xmin", [3], lod_level=1)
    pooled = fluid.layers.sequence_pool(xv, "min")
    loss = fluid.layers.reduce_sum(pooled)
    g, = fluid.calc_gradient([loss], [xv])
    exe = fluid.Executor(fluid.CPUPlace())
    t = fluid.LoDTensor(x)
    t.set_recursive_sequence_lengths([lens])
    gv, pv = exe.run(feed={"xmin": t}, fetch_list=[g.name, pooled.name])
    gv, pv = np.asarray(gv)[:6], np.asarray(pv)
    np.testing.assert_allclose(pv, np.stack(
        [x[:4].min(0), x[4:].min(0)]), rtol=1e-6)
    # exactly one winner row per (segment, feature) gets gradient 1
    assert gv.sum() == 6.0
    for s, (a, b) in enumerate(((0, 4), (4, 6))):
        for f in range(3):
            w = np.argmin(x[a:b, f])
            assert gv[a + w, f] == 1.0


def test_sequence_pool_max_empty_segment_identity(rng):
    """A zero-length sequence's MAX pool row is the dtype identity
    (finfo.min) and leaks no gradient into other rows."""
    from paddle_tpu.ops.sequence_ops import _argext_pool, _segments
    import jax.numpy as jnp
    x = jnp.asarray(rng.rand(5, 2).astype(np.float32))
    lens = jnp.asarray([3, 0, 2], jnp.int32)
    seg = _segments(lens, 5)
    out, idx = _argext_pool(x, seg, 3, lens, is_max=True)
    out = np.asarray(out)
    np.testing.assert_allclose(out[0], np.asarray(x[:3]).max(0))
    np.testing.assert_allclose(out[2], np.asarray(x[3:]).max(0))
    assert (out[1] == np.finfo(np.float32).min).all()

    import jax
    g = jax.grad(lambda x: _argext_pool(x, seg, 3, lens, True)[0].sum())(x)
    # row 0-2 and 3-4 winners get grad; the empty segment adds NOTHING
    assert float(np.asarray(g).sum()) == 4.0   # 2 features x 2 segments
