"""paddle_tpu.serving paged KV + prefix cache + sampling (ISSUE 10).

tests/test_serving.py already gates the broad paged contract (the
engine default is paged there: slot recycling, multi-chunk prefill,
mid-flight admission, bf16, megastep K>1, full instrumentation — all
token-identical to sequential decode). This module holds the pins that
need paged-specific scenarios:

  * host accounting units: BlockPool refcounts, RadixCache
    match/insert/LRU-evict, bytes_per_block math;
  * prefix-cache hit vs cold: a shared system prompt across 8 requests
    SKIPS the cached prefill chunks (measured chunk count drops vs the
    dense arithmetic) at token identity, with hit/miss counters and
    metrics landing;
  * copy-on-write: a fully block-aligned cached prompt is decoded
    without corrupting the shared chain;
  * preemption-and-resume: a pool too small for two long requests
    preempts the lowest-priority one (blocks freed, re-queued,
    re-prefilled) and BOTH outputs stay identical to sequential —
    greedy and seeded-sampled;
  * sampling: pure-function distribution properties (top-k never
    leaves the k-set, dominant-token top-p, temperature->0 converges
    to argmax), engine-level seeded reproducibility, and temperature-0
    staying bitwise-greedy;
  * the fleet wire: SamplingParams over SUBM (replica executes them),
    and the router journal carrying them (what resubmission re-sends);
  * kv telemetry: serving_step rows carry kv_used_blocks, the SLO
    engine gates on it, and `monitor watch` renders the KV line.

Budget: ONE module-scoped 1-layer LM + one shared paged engine carry
most tests; the preemption tests build one extra tiny-pool engine.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as fluid
from paddle_tpu import serving
from paddle_tpu.models import transformer
from paddle_tpu.models.transformer_infer import TransformerLMInfer
from paddle_tpu.monitor import runtime as monrt
from paddle_tpu.serving import kvpool, sampling
from paddle_tpu.serving.sampling import SamplingParams

N_LAYER, N_HEAD, D_MODEL, MAX_LEN, VOCAB = 1, 2, 32, 32, 40
BS = 4                        # block_size: small so short prompts cache


@pytest.fixture(scope="module")
def lm():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        transformer.transformer_lm(
            vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        # end_id past the vocab: greedy/sampled continuations never hit
        # EOS, so decode lengths (and pool pressure) are deterministic
        return TransformerLMInfer(main, scope, N_LAYER, N_HEAD,
                                  D_MODEL, MAX_LEN, end_id=VOCAB)


@pytest.fixture(scope="module")
def eng(lm):
    """The shared paged engine (block_size=4, auto pool = 16 blocks,
    prefix cache on) — one compile of step/prefill/activate for the
    whole module."""
    e = serving.Engine(lm, slots=2, prefill_chunk=4, block_size=BS)
    assert e._paged and e._pool.num_blocks == 2 * (MAX_LEN // BS)
    yield e
    e.close()


@pytest.fixture(scope="module")
def shared_prefix():
    rng = np.random.RandomState(101)
    return [1] + rng.randint(3, VOCAB, 9).tolist()   # 10 tokens


def _ident(seq, out):
    for i, ((st, ss), (et, es)) in enumerate(zip(seq, out)):
        assert st == et, "request %d diverged: %r vs %r" % (i, st, et)
        np.testing.assert_allclose(es, ss, rtol=1e-5, atol=1e-5)


# -- host accounting units -------------------------------------------------

def test_bytes_per_block_accounting():
    # 2 (K and V) * L * H * bs * dk * dtype
    assert kvpool.bytes_per_block(3, 4, 16, 64, 4) \
        == 2 * 3 * 4 * 16 * 64 * 4
    assert kvpool.bytes_per_block(1, 1, 1, 1, 2) == 4


def test_block_pool_alloc_free_share_refcounts():
    pool = kvpool.BlockPool(4, 16)
    a = pool.alloc(2)
    assert a == [0, 1] and pool.used == 2 and pool.free_blocks == 2
    assert pool.alloc(3) is None          # all-or-nothing
    pool.share(a[0])
    pool.free(a[0])                       # still referenced (shared)
    assert pool.used == 2 and pool.refcount(a[0]) == 1
    pool.free(a[0])
    assert pool.used == 1                 # now back on the free list
    pool.free(a[1])
    assert pool.used == 0
    with pytest.raises(ValueError):
        pool.free(a[1])                   # double free is loud
    with pytest.raises(ValueError):
        pool.share(99)                    # share of unreferenced block
    # recycled FIFO: determinism of block assignment
    assert pool.alloc(4) == [2, 3, 0, 1]


def test_radix_cache_match_insert_evict():
    pool = kvpool.BlockPool(8, 2)
    cache = kvpool.RadixCache(2, pool)
    toks = [5, 6, 7, 8, 9, 10]
    blocks = pool.alloc(3)                # request owns 3 full blocks
    assert cache.insert(toks, blocks) == 3
    assert cache.blocks_cached() == 3
    # a second publisher of the same prefix creates nothing new
    dup = pool.alloc(3)
    assert cache.insert(toks, dup) == 0
    for b in dup:
        pool.free(b)
    # match takes reader refs and reports hit tokens
    got, n = cache.match([5, 6, 7, 8, 99, 100])
    assert got == blocks[:2] and n == 4
    assert cache.hits == 1 and cache.hit_tokens == 4
    _, n0 = cache.match([42, 43])
    assert n0 == 0 and cache.misses == 1
    # the original owner retires: cache refs keep the chain alive
    for b in blocks:
        pool.free(b)
    assert pool.used == 3 + 0             # 3 cached (2 also read-ref'd)
    # eviction skips blocks a reader still references (refcount > 1):
    # only the unreferenced leaf [9, 10] is evictable now
    assert cache.evict(3) == 1 and cache.evictions == 1
    # release the reader refs -> the whole chain drains LRU
    for b in got:
        pool.free(b)
    assert cache.evict(5) == 2
    assert pool.used == 0 and cache.blocks_cached() == 0


# -- sampling: pure-function distribution properties -----------------------

def _keys(n, seed0=0):
    return sampling.step_keys(
        jnp.arange(seed0, seed0 + n, dtype=jnp.uint32),
        jnp.zeros((n,), jnp.int32))


# one [48, 16] shape for every distribution test: jax caches ONE
# compile of sample() instead of three (tier-1 seconds, not assertions)
_S, _V = 48, 16


def test_sampling_top_k_never_leaves_the_k_set():
    rng = np.random.RandomState(3)
    logits = jnp.asarray(np.tile(rng.randn(1, _V), (_S, 1)),
                         jnp.float32)
    top3 = set(np.asarray(jnp.argsort(logits[0])[::-1][:3]).tolist())
    ids = sampling.sample(logits, jnp.ones((_S,)),
                          jnp.full((_S,), 3, jnp.int32),
                          jnp.ones((_S,)), _keys(_S))
    drawn = set(np.asarray(ids).tolist())
    assert drawn <= top3
    assert len(drawn) > 1                 # it does explore the k-set


def test_sampling_top_p_keeps_dominant_token():
    logits = np.zeros((_S, _V), np.float32)
    logits[:, 5] = 10.0                   # p(5) ~ 0.999
    ids = sampling.sample(jnp.asarray(logits), jnp.ones((_S,)),
                          jnp.zeros((_S,), jnp.int32),
                          jnp.full((_S,), 0.5), _keys(_S, 7))
    assert set(np.asarray(ids).tolist()) == {5}


def test_sampling_temperature_to_zero_converges_to_argmax():
    rng = np.random.RandomState(5)
    logits = jnp.asarray(np.tile(rng.randn(1, _V), (_S, 1)),
                         jnp.float32)
    best = int(jnp.argmax(logits[0]))
    ids = sampling.sample(logits, jnp.full((_S,), 0.01),
                          jnp.zeros((_S,), jnp.int32),
                          jnp.ones((_S,)), _keys(_S, 11))
    assert set(np.asarray(ids).tolist()) == {best}


def test_sampling_params_validation_and_wire():
    sp = SamplingParams(temperature=0.7, top_k=5, top_p=0.9, seed=42)
    assert SamplingParams.from_dict(sp.to_dict()).to_dict() \
        == sp.to_dict()
    assert SamplingParams().greedy and not sp.greedy
    # non-dict wire payloads raise ValueError too (NOT AttributeError):
    # the fleet's BADR typed-reject depends on it — a torn connection
    # would get the poison request retried into every replica
    # misspelled knobs must not silently run greedy, and non-dict wire
    # payloads must raise ValueError (NOT AttributeError) — the
    # fleet's BADR typed-reject depends on it
    for bad in ({"temperature": -1}, {"top_k": -2}, {"top_p": 0.0},
                {"top_p": 1.5}, {"seed": -1}, {"temp": 0.9},
                {"topK": 4}, "hot", [0.7], 42):
        with pytest.raises(ValueError):
            SamplingParams.from_dict(bad)


# -- prefix cache: hit vs cold, COW ----------------------------------------

def test_prefix_cache_hit_skips_prefill_chunks(eng, lm, shared_prefix):
    """ISSUE-10 acceptance: 8 requests sharing a 10-token system
    prompt. The first (cold) request publishes 2 full blocks; every
    later admission matches them and SKIPS those prefill chunks —
    measured chunks executed drop well below the dense arithmetic —
    at token identity."""
    rng = np.random.RandomState(7)
    reqs = [(list(shared_prefix) + rng.randint(3, VOCAB, 2).tolist(), 6)
            for _ in range(8)]
    seq = serving.sequential_generate(lm, reqs)
    h0, m0 = eng.stats["prefix_hits"], eng.stats["prefix_misses"]
    c0, t0 = eng.stats["prefill_chunks"], eng.stats["prefix_hit_tokens"]
    mh0 = monrt.PREFIX_HITS.value()
    # cold first (awaited, so its chain is published), then the rest
    first = eng.submit(*reqs[0])
    out = [first.result(timeout=60)]
    rest = [eng.submit(p, m) for p, m in reqs[1:]]
    out += [h.result(timeout=60) for h in rest]
    _ident(seq, out)
    assert eng.stats["prefix_hits"] - h0 == 7
    assert eng.stats["prefix_misses"] - m0 == 1
    # every hit skipped the 2 cached blocks' 8 positions
    assert eng.stats["prefix_hit_tokens"] - t0 == 7 * 8
    chunks = eng.stats["prefill_chunks"] - c0
    dense_chunks = sum(-(-(len(p) - 1) // 4) for p, _ in reqs)
    assert chunks < dense_chunks          # 10 vs 24 here
    assert chunks == dense_chunks - 7 * 2
    assert monrt.PREFIX_HITS.value() - mh0 == 7


def test_cow_on_fully_cached_block_aligned_prompt(eng, lm,
                                                  shared_prefix):
    """A prompt that IS a cached block-aligned chain (8 tokens = 2
    blocks, published by the previous test) decodes through a
    copy-on-write of the last shared block: the cache chain stays
    intact (the next identical admission still fully matches) and the
    output is identical to sequential."""
    prompt = list(shared_prefix[:8])
    [want] = serving.sequential_generate(lm, [(prompt, 5)])
    cow0 = eng.stats["cow_copies"]
    r1 = eng.submit(prompt, 5).result(timeout=60)
    r2 = eng.submit(prompt, 5).result(timeout=60)
    assert eng.stats["cow_copies"] >= cow0 + 2
    assert r1[0] == want[0] == r2[0]
    np.testing.assert_allclose(r1[1], want[1], rtol=1e-5, atol=1e-5)


# -- preemption-and-resume -------------------------------------------------

@pytest.fixture(scope="module")
def tiny_pool_eng(lm):
    """9 blocks of 4 positions: two 24-token decodes (7 blocks each)
    cannot coexist, so the second-admitted request is preempted and
    resumed. Prefix cache off — pressure must hit the preemption
    ladder, not eviction."""
    e = serving.Engine(lm, slots=2, prefill_chunk=4, block_size=BS,
                       num_blocks=9, prefix_cache=False)
    yield e
    e.close()


def test_preemption_and_resume_token_identical(tiny_pool_eng, lm):
    rng = np.random.RandomState(13)
    reqs = [([1] + rng.randint(3, VOCAB, 3).tolist(), 24)
            for _ in range(2)]
    seq = serving.sequential_generate(lm, reqs)
    p0 = monrt.SERVING_PREEMPTIONS.value()
    hs = [tiny_pool_eng.submit(p, m) for p, m in reqs]
    out = [h.result(timeout=120) for h in hs]
    _ident(seq, out)
    assert tiny_pool_eng.stats["preemptions"] >= 1
    assert monrt.SERVING_PREEMPTIONS.value() > p0
    # the victim's handle records its preemption(s); exactly-once held
    assert sum(h.preemptions for h in hs) \
        == tiny_pool_eng.stats["preemptions"]
    # all blocks returned after retirement (no leak through the churn)
    assert tiny_pool_eng._pool.used == 0


@pytest.mark.slow
def test_preempted_seeded_sampling_reproduces(tiny_pool_eng, lm):
    """Seeded sampling across preemption: the counter-keyed PRNG
    (fold_in(seed, tokens_generated)) restarts with the re-prefill, so
    two runs of the same preempting workload emit the same tokens —
    the property the fleet's exactly-once dedup needs for stochastic
    traffic. Behind -m slow (tier-1 is at its wall-clock budget; the
    tier-1 pins above/below cover greedy preemption identity and
    un-preempted seeded reproducibility — this is the cross product)."""
    rng = np.random.RandomState(17)
    reqs = [([1] + rng.randint(3, VOCAB, 3).tolist(), 24)
            for _ in range(2)]
    sp = {"temperature": 0.8, "top_k": 12, "seed": 29}
    p0 = tiny_pool_eng.stats["preemptions"]
    runs = []
    for _ in range(2):
        hs = [tiny_pool_eng.submit(p, m, sampling=sp) for p, m in reqs]
        runs.append([h.result(timeout=120)[0] for h in hs])
    assert runs[0] == runs[1]
    # the SAMPLED workload itself preempted (not a leftover count)
    assert tiny_pool_eng.stats["preemptions"] > p0
    # stochastic output really is stochastic (differs from greedy)
    greedy = serving.sequential_generate(lm, reqs)
    assert runs[0] != [t for t, _ in greedy]


def test_zero_block_admission_yields_no_pingpong(lm):
    """Priority regression pin: the pool holds exactly ONE request's
    working set, so the second admission reaches pool pressure while
    holding zero blocks. It must YIELD (self-preempt) rather than
    evict the older block-holding request — and because admission
    priority is preserved across preemption, the pair cannot
    ping-pong: the head-of-line request finishes first, then the
    yielded one, both token-identical."""
    rng = np.random.RandomState(31)
    # the head-of-line request grows to ALL 8 blocks (positions 0..31),
    # so the later one eventually reaches pool pressure holding zero
    reqs = [([1] + rng.randint(3, VOCAB, 3).tolist(), 29),
            ([1] + rng.randint(3, VOCAB, 3).tolist(), 24)]
    seq = serving.sequential_generate(lm, reqs)
    with serving.Engine(lm, slots=2, prefill_chunk=4, block_size=BS,
                        num_blocks=8, prefix_cache=False) as e:
        hs = [e.submit(p, m) for p, m in reqs]
        out = [h.result(timeout=120) for h in hs]
        _ident(seq, out)
        # only the LATER request ever yielded; the head-of-line one
        # was never preempted (its blocks stayed put)
        assert hs[0].preemptions == 0
        assert hs[1].preemptions >= 1
        assert e._pool.used == 0


# -- engine-level sampling contracts ---------------------------------------

def test_seeded_sampling_reproducible_and_temp0_bitwise_greedy(eng):
    prompt = [1, 5, 9]
    sp = {"temperature": 0.9, "top_k": 0, "top_p": 0.95, "seed": 7}
    t1, s1 = eng.submit(prompt, 8, sampling=sp).result(timeout=60)
    t2, s2 = eng.submit(prompt, 8, sampling=sp).result(timeout=60)
    assert t1 == t2 and s1 == s2          # same seed ⇒ same tokens
    other, _ = eng.submit(prompt, 8, sampling=dict(sp, seed=8)).result(
        timeout=60)
    g0, sc0 = eng.submit(prompt, 8).result(timeout=60)
    gt, sct = eng.submit(
        prompt, 8,
        sampling={"temperature": 0.0, "seed": 99}).result(timeout=60)
    assert g0 == gt and sc0 == sct        # temp-0 is bitwise-greedy
    assert t1 != g0 or other != g0        # sampling actually samples
    with pytest.raises(ValueError):
        eng.submit(prompt, 4, sampling={"temperature": -0.5})


def test_megastep_sampled_matches_single_step(eng, lm):
    """Seeded sampling is megastep-invariant: the PRNG count rides the
    scan carry, so a fused K=4 paged engine draws the SAME tokens as
    the K=1 engine at the same seed — the megastep leg of the ISSUE-10
    acceptance (temperature-0 megastep identity is pinned in
    test_serving.py's megastep test, which runs paged)."""
    sp = {"temperature": 0.9, "top_k": 8, "seed": 31}
    reqs = [([1, 6, 11], 12), ([1, 7], 10)]
    one = [eng.submit(p, m, sampling=sp).result(timeout=60)
           for p, m in reqs]
    with serving.Engine(lm, slots=2, prefill_chunk=4, block_size=BS,
                        megastep=4, name="kv-mega") as mega:
        # no warmup(): only the sampled fused path matters here, and
        # compiling the greedy twins would double the compile bill
        fused = [mega.submit(p, m, sampling=sp).result(timeout=60)
                 for p, m in reqs]
        assert mega.stats["megastep_dispatches"] > 0
    assert [t for t, _ in fused] == [t for t, _ in one]
    for (_, sf), (_, so) in zip(fused, one):
        np.testing.assert_allclose(sf, so, rtol=1e-6, atol=1e-6)


# -- fleet wire: sampling params over SUBM + the router journal ------------

def test_sampling_over_replica_wire_and_router_journal(eng, tmp_path):
    """The SUBM frame carries SamplingParams: a replica-served seeded
    request returns the same tokens as a direct same-seed submit (so a
    resubmission to a survivor replica — which re-sends the journaled
    params — re-executes identically). The router journals sampling
    with the request, which is exactly what its at-least-once
    re-dispatch replays."""
    from paddle_tpu.distributed.membership import KVServer
    from paddle_tpu.serving import fleet
    sp = {"temperature": 0.8, "top_k": 10, "top_p": 1.0, "seed": 123}
    direct, _ = eng.submit([1, 4, 7], 7, sampling=sp).result(timeout=60)
    server = fleet.ReplicaServer(eng).start()
    try:
        client = fleet.ReplicaClient(server.endpoint, timeout=5.0)
        client.submit("rid-samp", [1, 4, 7], 7, sp)
        done = []
        for _ in range(200):
            done = client.poll(wait=0.2)
            if done:
                break
        assert done and done[0]["id"] == "rid-samp"
        assert done[0]["tokens"] == direct
        client.cancel("rid-samp")
        client.close()
    finally:
        server.stop()
    # the router journal carries sampling (resubmission replays it)
    kvs = KVServer(sweep_interval=0.05).start()
    try:
        router = fleet.Router(kvs.endpoint, name="samp-router",
                              refresh_interval=0.05)
        h = router.submit([1, 4, 7], 7,
                          sampling=SamplingParams.from_dict(sp))
        assert h.sampling == sp
        with router._lock:
            assert router._journal[h.rid]["sampling"] == sp
        router.close()
    finally:
        kvs.stop()


# -- kv telemetry: rows, SLO gate, watch line ------------------------------

def test_kv_rows_slo_gate_and_watch_line(eng, lm, tmp_path):
    from paddle_tpu import monitor, slo
    from paddle_tpu.monitor.watch import watch as mwatch
    rng = np.random.RandomState(23)
    reqs = [([1] + rng.randint(3, VOCAB, 4).tolist(), 6)
            for _ in range(4)]
    mlog = str(tmp_path / "kv.jsonl")
    monitor.enable(log_path=mlog)
    try:
        eng.generate_many([p for p, _ in reqs], [m for _, m in reqs])
    finally:
        monitor.disable()
    rows = [r for r in monitor.read_jsonl(mlog)
            if r["ev"] == "serving_step"]
    assert rows
    for r in rows:
        assert 0 <= r["kv_used_blocks"] <= r["kv_total_blocks"]
        assert r["kv_total_blocks"] == eng._pool.num_blocks
        assert r["prefix_hits"] >= 0 and r["prefix_misses"] >= 0
    assert max(r["kv_used_blocks"] for r in rows) > 0
    # the SLO engine gates pool pressure from the same rows
    samples = slo.samples_from_monitor_log(mlog)
    assert samples["kv_used_blocks"]
    ok = slo.evaluate(
        {"objectives": [{"metric": "kv_used_blocks",
                         "max_value": eng._pool.num_blocks}]}, samples)
    assert ok["pass"] is True
    bad = slo.evaluate(
        {"objectives": [{"metric": "kv_used_blocks", "max_value": 0}]},
        samples)
    assert bad["pass"] is False
    with pytest.raises(ValueError):
        slo.load_spec({"objectives": [{"metric": "kv_used_blocks"}]})
    # the live dashboard renders the KV-occupancy / prefix-hit line
    frame = mwatch(mlog, once=True)
    kvlines = [ln for ln in frame.split("\n") if ln.startswith("kv ")]
    assert kvlines, "watch frame misses the KV line:\n%s" % frame
    assert "blocks" in kvlines[0] and "hit rate" in kvlines[0] \
        and "preemptions" in kvlines[0]


def test_metrics_gauges_reflect_pool(eng):
    assert monrt.KV_BLOCKS_TOTAL.value() == eng._pool.num_blocks
    used = monrt.KV_BLOCKS_USED.value()
    assert used is not None and 0 <= used <= eng._pool.num_blocks
