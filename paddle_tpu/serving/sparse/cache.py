"""Hot-ID embedding cache + batched pserver prefetch client.

The trainer-side sparse path (distributed/ops.py ``_prefetch``) pulls
touched rows per STEP and throws them away; serving traffic is zipfian
— a small hot set of ids dominates every scoring batch — so the serving
tier fronts the live pserver shards with a per-process LRU keyed by
(table, id), the Monolith-style shape: collisionless rows, realtime
updates, bounded staleness.

Staleness contract (the part a naive cache gets wrong while training
keeps mutating the tables underneath):

  * every cached row carries the (round, incarnation) version
    coordinates its PRFT reply was stamped with (rpc.py serves them in
    the reply name; a pre-versioning server yields unversioned rows
    that only the time bound governs),
  * a row older than ``staleness_s`` re-fetches (bounded staleness —
    the time an online update can take to become visible through the
    cache is capped by construction),
  * an observed ROUND bump on a shard marks that shard's older-round
    rows stale (version-bump invalidation: one fresh fetch reveals the
    update round, and every colder row re-fetches on next touch
    instead of waiting out its clock),
  * an observed INCARNATION change drops the shard's rows outright — a
    replacement pserver recovered from checkpoint may have rolled back
    past rounds the cache has seen, so round arithmetic against it
    would be lying (the chaos gate pins "no stale-forever rows").

``SparseClient`` composes the cache with the existing wire machinery:
PRFT against the row shards (ids mod-sharded exactly like
``distributed/ops._prefetch``), DEDUPLICATED and batched per shard, the
resilience retry ``Policy`` underneath, and an optional membership
resolver per shard so a replacement pserver on a new port is followed
transparently. The measured miss-path cost (EWMA seconds/row) feeds the
autoparallel placement pricing hook
(``transform.autoparallel.recommend_embedding_placement``).
"""

import collections
import threading
import time

import numpy as np

from ...distributed import membership as _membership
from ...distributed.rpc import RPCClient
from ...monitor import runtime as _monrt
from ...resilience.retry import default_policy
from ..engine import _flag

__all__ = ["HotIDCache", "SparseClient"]


class HotIDCache:
    """Per-process LRU of embedding rows with bounded staleness.

    Keys are (table, id); values carry the row, its fetch time and its
    shard version coordinates. Thread-safe (the scoring loop and an
    online staleness probe may share one cache). ``capacity`` bounds
    ROWS, not bytes — rows of one table are same-width, and mixed
    tables stay comparable enough for an LRU."""

    def __init__(self, capacity=None, staleness_s=None):
        self.capacity = int(capacity if capacity is not None
                            else _flag("serving_sparse_cache_rows",
                                       65536))
        self.staleness_s = float(
            staleness_s if staleness_s is not None
            else _flag("serving_sparse_staleness_s", 5.0))
        self._lock = threading.Lock()
        self._rows = collections.OrderedDict()  # (table,id) -> entry
        # (table, shard) -> {"inc": str|None, "round": int}: the newest
        # version coordinates EVER OBSERVED for the shard — the bar a
        # cached row's own version is judged against
        self._latest = {}
        self.stats = {"hits": 0, "misses": 0, "stale": 0,
                      "evictions": 0, "invalidations": 0}

    # -- version observation ------------------------------------------------
    def observe_version(self, table, shard, ver):
        """Fold one PRFT reply's version coordinates into the shard's
        high-water mark. An incarnation CHANGE drops every cached row
        of the shard (a respawned server's store may have rolled back —
        round comparison against it is meaningless); a round advance
        just raises the bar, lazily staling colder rows."""
        if ver is None:
            return
        key = (table, int(shard))
        with self._lock:
            cur = self._latest.get(key)
            if cur is not None and cur["inc"] != ver["inc"]:
                self._invalidate_shard_locked(table, shard)
            if cur is None or cur["inc"] != ver["inc"] \
                    or ver["round"] > cur["round"]:
                self._latest[key] = {"inc": ver["inc"],
                                     "round": int(ver["round"])}

    def _invalidate_shard_locked(self, table, shard):
        n = 0
        for k in [k for k in self._rows
                  if k[0] == table and k[1] % self._nshards(table)
                  == shard]:
            del self._rows[k]
            n += 1
        if n:
            self.stats["evictions"] += n
            self.stats["invalidations"] += 1
            _monrt.on_sparse_evictions(n)

    def _nshards(self, table):
        # shard count inferred from observed shards (max index + 1);
        # only used to map cached ids back to shards on invalidation
        shards = [s for (t, s) in self._latest if t == table]
        return max(shards) + 1 if shards else 1

    # -- row access ---------------------------------------------------------
    def split(self, table, ids, nshards, now=None):
        """Partition deduplicated ``ids`` into (served, need_fetch):
        ``served`` maps id -> row for entries that are present, within
        the staleness bound AND not older than the shard's observed
        version; everything else lands in ``need_fetch``. Counters
        tick here (one batched lookup = one hook call)."""
        now = time.monotonic() if now is None else now
        served, need, stale = {}, [], 0
        with self._lock:
            for i in ids:
                key = (table, int(i))
                ent = self._rows.get(key)
                if ent is None:
                    need.append(int(i))
                    continue
                latest = self._latest.get((table, int(i) % nshards))
                ok = (now - ent["t"]) <= self.staleness_s
                if ok and latest is not None and ent["ver"] is not None:
                    if ent["ver"]["inc"] != latest["inc"] \
                            or ent["ver"]["round"] < latest["round"]:
                        ok = False
                if ok:
                    self._rows.move_to_end(key)
                    served[int(i)] = ent["row"]
                else:
                    del self._rows[key]
                    stale += 1
                    need.append(int(i))
        _monrt.on_sparse_lookup(hits=len(served), misses=len(need),
                                stale=stale)
        self.stats["hits"] += len(served)
        self.stats["misses"] += len(need)
        self.stats["stale"] += stale
        return served, need

    def insert(self, table, ids, rows, ver, now=None):
        """Publish freshly fetched rows (one shard's batch) with their
        version coordinates; LRU-evicts past capacity."""
        now = time.monotonic() if now is None else now
        evicted = 0
        with self._lock:
            for i, row in zip(ids, rows):
                self._rows[(table, int(i))] = {
                    "row": np.asarray(row), "t": now, "ver": ver}
                self._rows.move_to_end((table, int(i)))
            while len(self._rows) > self.capacity:
                self._rows.popitem(last=False)
                evicted += 1
        if evicted:
            self.stats["evictions"] += evicted
            _monrt.on_sparse_evictions(evicted)

    def __len__(self):
        with self._lock:
            return len(self._rows)

    def clear(self):
        with self._lock:
            n = len(self._rows)
            self._rows.clear()
            self._latest.clear()
        if n:
            self.stats["evictions"] += n
            _monrt.on_sparse_evictions(n)


class SparseClient:
    """Batched, deduplicated, cache-fronted row reads of ONE sharded
    embedding table living on live pservers.

    ``endpoints``: the shard endpoints in shard order (id % n routing,
    the ``distributed/ops._prefetch`` placement). ``kv``: optional
    membership KVClient — each shard's RPCClient then gets a resolver
    following role-slot ``/<role>/<shard>``, so a replacement pserver
    that recovered from checkpoint after a lease expiry is found at its
    new port (PRs 3-4 machinery, reused verbatim). ``cache``: a shared
    ``HotIDCache`` (one per process, possibly shared across tables) or
    None for a private one."""

    def __init__(self, table, endpoints, kv=None, role="ps",
                 cache=None, retry=None, timeout=10.0):
        self.table = table
        self._eps = list(endpoints)
        if not self._eps:
            raise ValueError("SparseClient needs >= 1 shard endpoint")
        self._kv = kv
        self._role = role
        self._timeout = float(timeout)
        self._retry = retry if retry is not None else default_policy()
        self.cache = cache if cache is not None else HotIDCache()
        self._clients = [None] * len(self._eps)
        self._lock = threading.Lock()
        # EWMA per-row seconds of the MISS path (wire round trip /
        # rows fetched) — the measured figure the autoparallel
        # placement hook prices the pserver tier with
        self._miss_row_s = None
        self.stats = {"lookups": 0, "wire_rows": 0, "wire_bytes": 0,
                      "prefetches": 0}

    # -- wiring -------------------------------------------------------------
    def _client(self, shard):
        with self._lock:
            cli = self._clients[shard]
            if cli is not None:
                return cli
            resolver = None
            if self._kv is not None:
                key = _membership.role_prefix(self._role) + str(shard)
                kv = self._kv

                def resolver(key=key):
                    ep = kv.get(key)
                    if ep and not ep.startswith(
                            _membership.EVICTED_PREFIX):
                        return ep
                    return None

            cli = RPCClient(self._eps[shard], timeout=self._timeout,
                            retry=self._retry, resolver=resolver)
            self._clients[shard] = cli
            return cli

    def _drop_client(self, shard):
        with self._lock:
            cli, self._clients[shard] = self._clients[shard], None
        if cli is not None:
            cli.close()

    @property
    def num_shards(self):
        return len(self._eps)

    # -- the read path ------------------------------------------------------
    def lookup(self, ids):
        """ids (any int array/list, duplicates fine) -> rows [len, D]
        aligned with the request order. One deduplicated, per-shard
        batched PRFT per miss set; hits come straight from the hot-ID
        cache under the staleness contract."""
        ids_arr = np.asarray(ids, np.int64).reshape(-1)
        self.stats["lookups"] += 1
        n = len(self._eps)
        uniq = np.unique(ids_arr)
        served, need = self.cache.split(self.table, uniq, n)
        if need:
            need = np.asarray(need, np.int64)
            for shard in range(n):
                part = need[need % n == shard]
                if len(part) == 0:
                    continue
                sr, ver = self._prefetch_shard(shard, part)
                self.cache.observe_version(self.table, shard, ver)
                rows = sr.value.reshape(len(part), -1)
                self.cache.insert(self.table, part, rows, ver)
                for i, row in zip(part, rows):
                    served[int(i)] = row
        width = next(iter(served.values())).shape[-1] if served else 1
        if not len(ids_arr):
            return np.zeros((0, width), np.float32)
        return np.stack([np.asarray(served[int(i)], np.float32)
                         for i in ids_arr])

    def _prefetch_shard(self, shard, part):
        t0 = time.perf_counter()
        try:
            sr, ver = self._client(shard).prefetch(
                self.table, part, want_version=True)
        except BaseException:
            # the cached client may hold a dead socket to a replaced
            # endpoint — rebuild lazily so the NEXT attempt re-resolves
            self._drop_client(shard)
            raise
        dt = time.perf_counter() - t0
        nbytes = int(sr.value.nbytes + sr.rows.nbytes)
        self.stats["prefetches"] += 1
        self.stats["wire_rows"] += len(part)
        self.stats["wire_bytes"] += nbytes
        _monrt.on_sparse_prefetch(len(part), nbytes)
        per_row = dt / max(1, len(part))
        self._miss_row_s = per_row if self._miss_row_s is None \
            else 0.8 * self._miss_row_s + 0.2 * per_row
        return sr, ver

    def miss_row_seconds(self):
        """Measured miss-path cost (EWMA seconds per fetched row), or
        None before the first wire pull — feed it to
        ``transform.autoparallel.recommend_embedding_placement(...,
        measured_sparse_row_s=...)`` to price placement with THIS
        deployment's wire instead of the PERF.md constants."""
        return self._miss_row_s

    def latest_versions(self):
        """{shard: {"inc", "round"}} — the newest version coordinates
        observed per shard (the 'cache version' a scoring request is
        pinned against)."""
        with self.cache._lock:
            return {s: dict(v) for (t, s), v in
                    self.cache._latest.items() if t == self.table}

    def close(self):
        with self._lock:
            clients, self._clients = self._clients, \
                [None] * len(self._eps)
        for cli in clients:
            if cli is not None:
                cli.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
