"""Pipeline parallelism over the ``pp`` mesh axis (GPipe schedule).

Beyond the 2018 reference (SURVEY.md §2.7: PP absent; the closest legacy
analog is ParallelNeuralNetwork's static layer placement). TPU-native
design: stage parameters are STACKED on a leading [S, ...] axis sharded on
``pp`` — every device runs the same stage function on its own parameter
shard, and activations ride the ICI ring via ``ppermute``. One jitted
computation, S + M - 1 ticks for M microbatches (the classic GPipe bubble),
differentiable end-to-end (grads flow through ppermute).

Output handling: only the LAST stage produces real outputs, so the result
leaves the shard_map with its leading axis sharded on ``pp`` and the
caller slices stage S-1 — a single sliced transfer sized like the output,
instead of an S-redundant psum of the whole buffer. Heterogeneous stages
(per-stage parameter SHAPES) are supported by passing a list of per-stage
param pytrees: those are replicated to every device and selected by
``lax.switch`` on the stage index — functional, at the memory cost of
holding all stages' params per device; the stacked form is the scalable
path.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._shard_map import shard_map


def _run_ticks(apply, xs, s_idx, n_stage, axis_name):
    """The GPipe tick loop for one shard. apply: x -> stage output for
    THIS stage. xs [M, mb, ...] microbatches (replicated or dp-sharded).
    Returns [1, M, mb, ...]: final-stage outputs (zeros on other
    shards). The buffer is allocated per shard (SPMD executes one
    program), but only the last stage ever writes it."""
    m = xs.shape[0]

    def tick(t, carry):
        state_in, outputs = carry
        mb_idx = jnp.clip(t, 0, m - 1)
        inject = jnp.where(t < m, xs[mb_idx], jnp.zeros_like(xs[0]))
        inp = jnp.where(s_idx == 0, inject, state_in)
        out = apply(inp)
        out_mb = t - (n_stage - 1)
        write = jnp.logical_and(s_idx == n_stage - 1, out_mb >= 0)
        upd = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(write, out, outputs[jnp.clip(out_mb, 0, m - 1)]),
            jnp.clip(out_mb, 0, m - 1), 0)
        outputs = jnp.where(write, upd, outputs)
        state_next = lax.ppermute(
            out, axis_name,
            [(j, (j + 1) % n_stage) for j in range(n_stage)])
        return state_next, outputs

    state0 = jnp.zeros_like(xs[0])
    outputs0 = jnp.zeros_like(xs)
    _, outputs = lax.fori_loop(0, n_stage + m - 1, tick,
                               (state0, outputs0))
    # leading singleton axis: the caller's out_spec shards it on pp, so
    # the global result is [S, M, mb, ...] and slicing [-1] pulls ONLY
    # the last stage's buffer — no collective inside the loop or after
    return outputs[None]


def _gpipe_sharded(params, xs, stage_fn, axis_name):
    """Stacked (homogeneous) path: params leaves arrive [1, ...] — this
    shard's slice of the [S, ...] stack."""
    s_idx = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    local_params = jax.tree_util.tree_map(lambda p: p[0], params)
    return _run_ticks(lambda x: stage_fn(local_params, x), xs, s_idx,
                      n_stage, axis_name)


def _gpipe_hetero(params_seq, xs, stage_fn, axis_name):
    """Heterogeneous path: params_seq is a tuple of per-stage pytrees
    (arbitrary, differing shapes), replicated; lax.switch picks this
    stage's branch."""
    s_idx = lax.axis_index(axis_name)
    n_stage = lax.psum(1, axis_name)
    branches = [functools.partial(stage_fn, p) for p in params_seq]
    return _run_ticks(lambda x: lax.switch(s_idx, branches, x), xs, s_idx,
                      n_stage, axis_name)


def gpipe(stage_fn, stacked_params, microbatches, mesh, axis_name="pp",
          batch_axis=None):
    """Run ``stage_fn(params_i, x)`` as an S-stage pipeline.

    stacked_params: EITHER a pytree whose leaves have leading dim S
                    (= mesh[axis]) — sharded on ``axis_name``, the
                    scalable form — OR a list/tuple of S per-stage
                    pytrees with arbitrary per-stage shapes (replicated
                    to every device, selected by stage index).
    microbatches:   [M, mb, ...] array of M microbatches.
    batch_axis:     mesh axis the mb dim is data-sharded on (e.g. "dp"),
                    None if replicated.
    Returns [M, mb, ...] outputs of the final stage.
    """
    s = mesh.shape[axis_name]
    xspec = P(None, batch_axis)
    out_spec = P(axis_name, None, batch_axis)

    if isinstance(stacked_params, (list, tuple)):
        if len(stacked_params) != s:
            raise ValueError(
                "per-stage params list has %d entries != %d pipeline "
                "stages" % (len(stacked_params), s))
        params_seq = tuple(stacked_params)
        pspec = jax.tree_util.tree_map(lambda _: P(), params_seq)
        fn = shard_map(
            functools.partial(_gpipe_hetero, stage_fn=stage_fn,
                              axis_name=axis_name),
            mesh=mesh, in_specs=(pspec, xspec), out_specs=out_spec,
            check_vma=False)
        return fn(params_seq, microbatches)[-1]

    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != s:
            raise ValueError(
                "stacked_params leading dim %d != %d pipeline stages"
                % (leaf.shape[0], s))
    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    fn = shard_map(
        functools.partial(_gpipe_sharded, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh, in_specs=(pspec, xspec), out_specs=out_spec,
        check_vma=False)
    return fn(stacked_params, microbatches)[-1]
