"""Tensor layers — parity with python/paddle/fluid/layers/tensor.py."""

import numpy as np

from ..core.program import Variable, convert_dtype
from .layer_helper import LayerHelper


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..initializer import ConstantInitializer
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name)
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype, shape=x.shape)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    shape = None
    if all(x.shape is not None for x in input):
        shape = list(input[0].shape)
        ax = axis % len(shape)
        shape[ax] = sum(x.shape[ax] for x in input) \
            if all(x.shape[ax] > 0 for x in input) else -1
        shape = tuple(shape)
    out = helper.create_variable_for_type_inference(input[0].dtype,
                                                    shape=shape)
    helper.append_op(type="concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(
            input[0].dtype, shape=input[0].shape)
    helper.append_op(type="sum", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(
                input.dtype, shape=input.shape)
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(input.dtype), shape=input.shape)
        helper.append_op(type="assign_value", outputs={"Out": [output]},
                         attrs={"shape": list(input.shape),
                                "dtype": str(input.dtype),
                                "values": input})
    else:
        raise TypeError("assign expects Variable or ndarray")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(
            convert_dtype(dtype), shape=tuple(shape), stop_gradient=True)
    helper.append_op(type="fill_constant", outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": convert_dtype(dtype),
                            "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(
        convert_dtype(dtype), shape=tuple(shape), stop_gradient=True)
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input]}, outputs={"Out": [out]},
                     attrs={"shape": list(shape),
                            "dtype": convert_dtype(dtype),
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", act=act, name=name)
    new_shape = list(shape)
    if x.shape is not None:
        resolved = [x.shape[i] if s == 0 else s
                    for i, s in enumerate(new_shape)]
    else:
        resolved = new_shape
    out = helper.create_variable_for_type_inference(
        x.dtype, shape=tuple(resolved))
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"shape": new_shape})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    shape = tuple(x.shape[p] for p in perm) if x.shape is not None else None
    out = helper.create_variable_for_type_inference(x.dtype, shape=shape)
    helper.append_op(type="transpose", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    in_shape = input.shape
    ax = dim % len(in_shape) if in_shape is not None else dim
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = None
        sizes = ([in_shape[ax] // num] * num
                 if in_shape is not None and in_shape[ax] > 0 else None)
    else:
        sections = list(num_or_sections)
        num = 0
        sizes = sections
    outs = []
    for i in range(len(sizes) if sizes else num):
        shape = None
        if in_shape is not None and sizes:
            s = list(in_shape)
            s[ax] = sizes[i]
            shape = tuple(s)
        outs.append(helper.create_variable_for_type_inference(
            input.dtype, shape=shape))
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"axis": dim, "num": num,
                            "sections": sections or []})
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    shape = None
    if x.shape is not None:
        shape = tuple(s * t if s > 0 else -1
                      for s, t in zip(x.shape, expand_times))
    out = helper.create_variable_for_type_inference(x.dtype, shape=shape)
    helper.append_op(type="expand", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    shape = None
    if input.shape is not None and index.shape is not None:
        shape = tuple(index.shape[:1]) + tuple(input.shape[1:])
    out = helper.create_variable_for_type_inference(input.dtype, shape=shape)
    helper.append_op(type="gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    shape=input.shape)
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    shape = None
    if x.shape is not None:
        shape = tuple(s for i, s in enumerate(x.shape)
                      if i != axis % len(x.shape))
    out = helper.create_variable_for_type_inference("int64", shape=shape)
    helper.append_op(type="arg_max", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    shape = None
    if x.shape is not None:
        shape = tuple(s for i, s in enumerate(x.shape)
                      if i != axis % len(x.shape))
    out = helper.create_variable_for_type_inference("int64", shape=shape)
    helper.append_op(type="arg_min", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype,
                                                        shape=x.shape)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def slice(input, axes, starts, ends, name=None):
    """fluid.layers.slice parity (slice_op.cc)."""
    helper = LayerHelper("slice", name=name)
    shape = None
    if input.shape is not None:
        shape = list(input.shape)
        for ax, s, e in zip(axes, starts, ends):
            n = shape[ax]
            if n is not None and n >= 0:
                s2 = s if s >= 0 else n + s
                e2 = min(e if e >= 0 else n + e, n)
                shape[ax] = max(0, e2 - s2)
            else:
                shape[ax] = -1
        shape = tuple(shape)
    out = helper.create_variable_for_type_inference(input.dtype, shape=shape)
    helper.append_op(type="slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out
