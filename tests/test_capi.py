"""C inference API (paddle_tpu/native/capi): a pure-C program loads a
saved inference model and runs forward — the reference's
paddle/capi/gradient_machine.h deployment capability (C ABI over an
embedded CPython driving the same load_inference_model path)."""

import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native")


@pytest.fixture(scope="module")
def capi_bin():
    try:
        subprocess.run(["make", "-C", NATIVE, "build/libcapi.so",
                        "build/test_capi"],
                       check=True, capture_output=True, text=True)
    except (OSError, subprocess.CalledProcessError) as e:
        pytest.skip("C API build failed: %s"
                    % (getattr(e, "stderr", "") or str(e))[-400:])
    return os.path.join(NATIVE, "build", "test_capi")


def test_c_program_runs_saved_model(tmp_path, capi_bin):
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 3, act="relu")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [y], exe)
    want, = exe.run(feed={"x": np.ones((1, 4), np.float32)},
                    fetch_list=[y])

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(NATIVE.rstrip("/")).rsplit(
        "/paddle_tpu", 1)[0]
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([capi_bin, model_dir, "4"], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-500:]
    line = [l for l in out.stdout.splitlines() if l.startswith("OUT")][0]
    got = np.array([float(v) for v in line.split()[1:]], np.float32)
    np.testing.assert_allclose(got, np.asarray(want).reshape(-1),
                               rtol=1e-5, atol=1e-6)


def test_c_program_reports_missing_model(capi_bin):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(NATIVE.rstrip("/")).rsplit(
        "/paddle_tpu", 1)[0]
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([capi_bin, "/nonexistent/model", "4"], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode != 0
    assert "failed" in out.stderr


@pytest.fixture(scope="module")
def capi_multi_bin():
    try:
        subprocess.run(["make", "-C", NATIVE, "build/libcapi.so",
                        "build/test_capi_multi"],
                       check=True, capture_output=True, text=True)
    except (OSError, subprocess.CalledProcessError) as e:
        pytest.skip("C API build failed: %s"
                    % (getattr(e, "stderr", "") or str(e))[-400:])
    return os.path.join(NATIVE, "build", "test_capi_multi")


def test_c_program_multi_io_seq2seq(tmp_path, capi_multi_bin):
    """2-in/2-out typed C inference (round-2 verdict #10): a seq2seq-style
    model — int64 token ids + float mask in, int64 greedy next-token ids
    + float32 probabilities out — driven end-to-end from pure C through
    pt_predictor_run_multi."""
    T, VOCAB, D = 4, 11, 16
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        src = fluid.layers.data("src", [T], dtype="int64")
        mask = fluid.layers.data("mask", [T])
        emb = fluid.layers.embedding(src, size=[VOCAB, D])      # [B,T,D]
        masked = fluid.layers.elementwise_mul(
            emb, fluid.layers.reshape(mask, [-1, T, 1]), axis=0)
        enc = fluid.layers.reduce_sum(masked, dim=[1])          # [B,D]
        logits = fluid.layers.fc(enc, VOCAB)                    # [B,V]
        probs = fluid.layers.softmax(logits)
        next_ids = fluid.layers.cast(
            fluid.layers.argmax(logits, axis=-1), "int64")
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        model_dir = str(tmp_path / "s2s")
        fluid.io.save_inference_model(model_dir, ["src", "mask"],
                                      [next_ids, probs], exe)
        srcv = np.arange(1, T + 1, dtype=np.int64)[None, :]
        maskv = np.ones((1, T), np.float32)
        want_ids, want_probs = exe.run(
            main, feed={"src": srcv, "mask": maskv},
            fetch_list=[next_ids, probs])

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(NATIVE.rstrip("/")).rsplit(
        "/paddle_tpu", 1)[0]
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([capi_multi_bin, model_dir, str(T)], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-500:]
    ids_line = [l for l in out.stdout.splitlines()
                if l.startswith("IDS")][0]
    probs_line = [l for l in out.stdout.splitlines()
                  if l.startswith("PROBS")][0]
    got_ids = np.array([int(v) for v in ids_line.split()[1:]], np.int64)
    got_probs = np.array([float(v) for v in probs_line.split()[1:]],
                         np.float32)
    np.testing.assert_array_equal(
        got_ids, np.asarray(want_ids).reshape(-1))
    np.testing.assert_allclose(
        got_probs, np.asarray(want_probs).reshape(-1).astype(np.float32),
        rtol=1e-4, atol=1e-6)
