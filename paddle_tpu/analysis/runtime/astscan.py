"""One-pass AST index over the package sources.

Every runtime rule consumes a ``SourceIndex``: each ``*.py`` file under
``paddle_tpu/`` parsed ONCE with stdlib ``ast`` (never imported, never
executed — linting must not spin up jax, sockets, or threads), plus the
raw text of non-Python catalog inputs (README.md). Rules therefore see
the same tree and share the parse cost; the whole index builds in well
under a second, which is what keeps the ``--runtime`` gate inside the
tier-1 seconds budget.

``SourceIndex.from_sources`` builds the same structure from in-memory
``{relpath: text}`` mappings so the golden-fixture tests can lint tiny
synthetic modules through the exact production rule path.
"""

import ast
import os

__all__ = ["SourceFile", "SourceIndex", "dotted_name", "literal_str",
           "class_methods", "iter_lock_scopes", "repo_root"]


def repo_root():
    """The repository root (the directory holding ``paddle_tpu/``)."""
    here = os.path.abspath(os.path.dirname(__file__))   # .../analysis/runtime
    return os.path.dirname(os.path.dirname(os.path.dirname(here)))


def dotted_name(node):
    """``a.b.c`` for a Name/Attribute chain (incl. ``self.x``), else
    None for anything non-trivial (subscripts, calls, literals)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def literal_str(node):
    """The value of a string-literal node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def class_methods(cls):
    """{name: FunctionDef} for a ClassDef's direct (a)sync methods."""
    out = {}
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[stmt.name] = stmt
    return out


def iter_lock_scopes(stmts, lock_of, held=()):
    """Walk a statement list tracking which locks are held, yielding
    ``(kind, node, held, lock)`` tuples:

      ("acquire", with_item_expr, held_before, lock)  entering a
          ``with <lock>:`` item recognised by ``lock_of(expr)``;
      ("node", ast_node, held, None)  every other expression-level
          node, with the tuple of locks held at that point (innermost
          last).

    Nested function/class definitions are separate scopes and are NOT
    descended into. ``lock_of`` maps a with-item context expression to
    a canonical lock name, or None for non-lock context managers."""
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(s, (ast.With, ast.AsyncWith)):
            cur = list(held)
            for item in s.items:
                lk = lock_of(item.context_expr)
                if lk is not None:
                    yield ("acquire", item.context_expr, tuple(cur), lk)
                    cur.append(lk)
                else:
                    for sub in ast.walk(item.context_expr):
                        yield ("node", sub, tuple(cur), None)
            for t in iter_lock_scopes(s.body, lock_of, tuple(cur)):
                yield t
        elif isinstance(s, ast.Try):
            for part in (s.body, s.orelse, s.finalbody):
                for t in iter_lock_scopes(part, lock_of, held):
                    yield t
            for h in s.handlers:
                for t in iter_lock_scopes(h.body, lock_of, held):
                    yield t
        elif isinstance(s, (ast.If, ast.While)):
            for sub in ast.walk(s.test):
                yield ("node", sub, held, None)
            for part in (s.body, s.orelse):
                for t in iter_lock_scopes(part, lock_of, held):
                    yield t
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(s.iter):
                yield ("node", sub, held, None)
            for part in (s.body, s.orelse):
                for t in iter_lock_scopes(part, lock_of, held):
                    yield t
        else:
            for sub in ast.walk(s):
                yield ("node", sub, held, None)


class SourceFile:
    """One parsed Python source: repo-relative path + text + tree."""

    __slots__ = ("path", "text", "lines", "tree")

    def __init__(self, path, text):
        self.path = path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.path)

    def classes(self):
        """Top-level ClassDef nodes."""
        return [n for n in self.tree.body if isinstance(n, ast.ClassDef)]

    def functions(self):
        """Top-level (a)sync FunctionDef nodes."""
        return [n for n in self.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


class SourceIndex:
    """All parsed sources + raw catalog texts, keyed by relative path."""

    def __init__(self, files, texts=None, root=None):
        self.files = dict(files)          # relpath -> SourceFile
        self.texts = dict(texts or {})    # relpath -> raw text (README)
        self.root = root                  # filesystem root, when real

    @classmethod
    def from_root(cls, root=None):
        """Index every ``paddle_tpu/**/*.py`` under ``root`` (default:
        this repository) plus README.md when present. Unparseable files
        raise — a syntax error in the tree IS a finding-worthy state,
        but it belongs to the interpreter, not a lint waiver."""
        root = os.path.abspath(root or repo_root())
        files = {}
        pkg = os.path.join(root, "paddle_tpu")
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, "r", encoding="utf-8") as f:
                    files[rel] = SourceFile(rel, f.read())
        texts = {}
        readme = os.path.join(root, "README.md")
        if os.path.exists(readme):
            with open(readme, "r", encoding="utf-8") as f:
                texts["README.md"] = f.read()
        return cls(files, texts, root=root)

    @classmethod
    def from_sources(cls, sources, texts=None):
        """Index in-memory ``{relpath: python_text}`` (fixture path)."""
        return cls({p: SourceFile(p, t) for p, t in sources.items()},
                   texts=texts, root=None)

    def find(self, suffix):
        """The SourceFile whose path ends with ``suffix`` (deterministic:
        shortest, then lexicographic, on ties), or None."""
        hits = sorted((p for p in self.files if p.endswith(suffix)),
                      key=lambda p: (len(p), p))
        return self.files[hits[0]] if hits else None

    def iter_files(self):
        for path in sorted(self.files):
            yield self.files[path]

    def iter_classes(self):
        """(SourceFile, ClassDef) over every top-level class."""
        for sf in self.iter_files():
            for cls_node in sf.classes():
                yield sf, cls_node
