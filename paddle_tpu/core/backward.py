"""Autodiff: append_backward and calc_gradient.

Reference parity: python/paddle/fluid/backward.py:425 ``append_backward``.
The reference walks ops in reverse calling each op's C++ GradOpDescMaker to
synthesize explicit grad ops into the program. On TPU the gradient program is
*derived, not authored*: we record a single ``backward_marker`` op carrying
(loss, parameter list, no_grad set); at trace time the Executor runs the
forward segment under ``jax.value_and_grad`` (core/executor.py:_lower_with_grad),
which is both exact and XLA-fusable — and keeps the reference's naming
contract: every parameter P gets a fetchable gradient variable ``P@GRAD``.

Rematerialization policy (the reference's memory_optimize analog) is a
``checkpoint`` attr on the marker: when set, forward lowering wraps selected
layers in jax.checkpoint.
"""

from .program import Parameter, Variable, default_main_program


def _find_loss_block(loss):
    return loss.block.program


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoint=False):
    """Append the gradient computation for `loss` and return
    [(param, grad_var), ...] like the reference.

    parameter_list: restrict to these parameter names (or Variables).
    no_grad_set: names excluded from differentiation (their grads are zero and
    they are treated as constants — parity with backward.py no_grad handling).
    """
    program = _find_loss_block(loss)
    block = program.global_block()

    if parameter_list:
        pnames = [p.name if isinstance(p, Variable) else p
                  for p in parameter_list]
        params = [block.var(n) for n in pnames]
    else:
        params = [p for p in block.all_parameters() if p.trainable]

    no_grad = {n if isinstance(n, str) else n.name for n in (no_grad_set or ())}
    params = [p for p in params if p.name not in no_grad
              and not p.stop_gradient]

    param_grads = []
    for p in params:
        g = block.create_var(
            name=p.name + "@GRAD", shape=p.shape, dtype=p.dtype,
            persistable=False, stop_gradient=True)
        param_grads.append((p, g))

    loss_grad = block.create_var(
        name=loss.name + "@GRAD", shape=loss.shape or (1,), dtype=loss.dtype,
        persistable=False, stop_gradient=True)

    block.append_op(
        type="backward_marker",
        inputs={"Loss": [loss]},
        outputs={"Grads": [g for _, g in param_grads] + [loss_grad]},
        attrs={
            "loss_name": loss.name,
            "param_names": [p.name for p, _ in param_grads],
            "no_grad_set": sorted(no_grad),
            "checkpoint": bool(checkpoint),
        })
    program._loss_name = loss.name
    return param_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of `targets` w.r.t. arbitrary `inputs` (backward.py:555).

    Implemented with the same marker mechanism: the Executor computes
    d(sum(targets))/d(inputs) via jax.grad; returns the grad Variables."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    program = targets[0].block.program
    block = program.global_block()
    no_grad = {n if isinstance(n, str) else n.name for n in (no_grad_set or ())}

    grads = []
    for x in inputs:
        g = block.create_var(
            name=x.name + "@GRAD", shape=x.shape, dtype=x.dtype,
            stop_gradient=True)
        grads.append(g)

    block.append_op(
        type="calc_gradient_marker",
        inputs={"Targets": list(targets), "Inputs": list(inputs)},
        outputs={"Grads": grads},
        attrs={
            "target_names": [t.name for t in targets],
            "input_names": [x.name for x in inputs],
            "no_grad_set": sorted(no_grad),
        })
    return grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
