"""Oxford 102 flowers — reference parity: python/paddle/dataset/flowers.py.

Readers yield (image[3,224,224] float32, label int in [0,102)).
"""

import numpy as np

from . import common

NUM_CLASSES = 102
IMAGE_SHAPE = (3, 224, 224)


def _make_reader(name, n, seed, shape=IMAGE_SHAPE):
    def reader():
        rng = common.synthetic_rng(name, seed)
        base = common.synthetic_rng(name + "_centers", 0).rand(
            NUM_CLASSES, 8).astype(np.float32)
        for _ in range(n):
            label = int(rng.randint(0, NUM_CLASSES))
            img = np.tile(base[label].reshape(1, 8, 1),
                          (shape[0], shape[1] // 8 + 1, shape[2]))
            img = img[:, :shape[1], :] + \
                0.1 * rng.rand(*shape).astype(np.float32)
            yield img.astype(np.float32), label
    return reader


def train(n=1024, mapper=None, buffered_size=1024, use_xmap=False):
    return _make_reader("flowers", n, seed=0)


def test(n=256, mapper=None, buffered_size=1024, use_xmap=False):
    return _make_reader("flowers", n, seed=1)


def valid(n=256, mapper=None, buffered_size=1024, use_xmap=False):
    return _make_reader("flowers", n, seed=2)


def fetch():
    pass
