"""RT03 catalog-consistency: ptpu_* metrics and flags vs their tables.

Metrics: a REGISTRATION is a ``.counter("ptpu_x", ...)`` /
``.gauge(...)`` / ``.histogram(...)`` call with a literal name; a
REFERENCE is any whole string literal matching ``ptpu_[a-z0-9_]+``
anywhere in the package (watch/slo/fleet_lines read metrics back by
name) or any name in the README catalog (brace groups expand:
``ptpu_fleet_{a,b}_total`` documents two metrics; a trailing
``{label}`` group is stripped; Prometheus ``_bucket``/``_sum``/
``_count`` suffixes resolve to their histogram). Checks:

  * reference to a never-registered name       -> ERROR
  * one name registered with two kinds         -> ERROR (kind mismatch)
  * one name registered at two sites           -> WARNING (duplicate)
  * README documents an unregistered name      -> ERROR (ghost metric)
  * registered name absent from the README     -> WARNING (catalog
    drift — regenerate the catalog section)

Flags: every ``get_flag("x")`` / ``set_flag("x")`` / ``_flag("x")``
literal read must name a flag registered in ``flags.py``'s
``_register`` table (ERROR), and a registered flag with no literal
read anywhere is an INFO (env-only flags are legitimate, but the
inventory should be conscious). Dynamic (non-literal) reads are
invisible to the lint and intentionally out of scope.
"""

import ast
import re

from ..astscan import dotted_name, literal_str
from ..engine import (Finding, RuntimeRule, register_runtime_rule,
                      ERROR, WARNING, INFO)

__all__ = ["CatalogConsistencyRule"]

_METRIC_RE = re.compile(r"^ptpu_[a-z0-9_]*[a-z0-9]$")
_README_RE = re.compile(
    r"ptpu_[a-z0-9_]*"                    # base (may end at a brace)
    r"(?:\{[a-z0-9_,]+\}[a-z0-9_]*)?")    # one brace group + tail
_KINDS = ("counter", "gauge", "histogram")
_PROM_SUFFIXES = ("_bucket", "_sum", "_count")
_FLAG_READS = ("get_flag", "set_flag", "_flag")

# paths whose literals are not part of the runtime catalog (this lint's
# own sources and docs mention metric names as examples)
_SELF = "analysis/runtime"


def _skip(sf):
    return _SELF in sf.path


def _expand_readme_token(tok):
    """['ptpu_a_total', ...] for one README token. A brace group after
    a trailing underscore brace-expands the name
    (``ptpu_fleet_{shed,queue_depth}`` documents two metrics); a group
    right after a complete name is a label annotation and is stripped
    (``ptpu_alert_transitions_total{rule,severity,state}``). A bare
    token ending in '_' is a prefix mention in prose, not a name."""
    if "{" not in tok:
        return [tok] if _METRIC_RE.match(tok) else []
    head, rest = tok.split("{", 1)
    group, tail = rest.split("}", 1)
    parts = group.split(",")
    if head.endswith("_"):
        return [n for n in (head + p + tail for p in parts)
                if _METRIC_RE.match(n)]
    return [head + tail] if _METRIC_RE.match(head + tail) else []


class CatalogConsistencyRule(RuntimeRule):
    name = "catalog-consistency"
    id = "RT03"
    doc = ("every ptpu_* metric referenced in code or the README "
           "catalog registered exactly once with one kind; every "
           "flag read registered")
    max_reports = 80

    def check(self, index):
        for f in self._check_metrics(index):
            yield f
        for f in self._check_flags(index):
            yield f

    # -- metrics -----------------------------------------------------------
    def _check_metrics(self, index):
        regs = {}       # name -> [(kind, file, line)]
        reg_sites = set()
        for sf in index.iter_files():
            if _skip(sf):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                name = dotted_name(node.func)
                tail = name.split(".")[-1] if name else None
                if tail not in _KINDS:
                    continue
                metric = literal_str(node.args[0])
                if metric is None or not metric.startswith("ptpu_"):
                    continue
                regs.setdefault(metric, []).append(
                    (tail, sf.path, node.args[0].lineno))
                reg_sites.add((sf.path, node.args[0].lineno, metric))
        # kind mismatches + duplicates
        for metric in sorted(regs):
            sites = regs[metric]
            kinds = sorted({k for k, _, _ in sites})
            if len(kinds) > 1:
                _, path, line = sites[1]
                yield Finding(
                    self.name, ERROR, path, line,
                    "metric '%s' registered with mismatched kinds: %s"
                    % (metric, "/".join(kinds)),
                    hint="first registration: %s:%d as %s"
                         % (sites[0][1], sites[0][2], sites[0][0]))
            elif len(sites) > 1:
                _, path, line = sites[1]
                yield Finding(
                    self.name, WARNING, path, line,
                    "metric '%s' registered %d times (first: %s:%d)"
                    % (metric, len(sites), sites[0][1], sites[0][2]),
                    hint="register once at module scope and share it")

        def registered(name):
            if name in regs:
                return True
            for suf in _PROM_SUFFIXES:
                if name.endswith(suf) and name[: -len(suf)] in regs:
                    return True
            return False

        # code references
        for sf in index.iter_files():
            if _skip(sf):
                continue
            for node in ast.walk(sf.tree):
                metric = literal_str(node)
                if metric is None or not _METRIC_RE.match(metric):
                    continue
                if (sf.path, node.lineno, metric) in reg_sites:
                    continue
                if not registered(metric):
                    yield Finding(
                        self.name, ERROR, sf.path, node.lineno,
                        "metric '%s' referenced but never registered"
                        % metric,
                        hint="register it (monitor registry) or fix "
                             "the name")
        # README catalog
        documented = set()
        for path, text in sorted(index.texts.items()):
            for lineno, line in enumerate(text.splitlines(), 1):
                for m in _README_RE.finditer(line):
                    end = m.end()
                    if end < len(line) and line[end] in "*<":
                        continue        # wildcard/placeholder in prose
                    for name in _expand_readme_token(m.group(0)):
                        documented.add(name)
                        for suf in _PROM_SUFFIXES:
                            if name.endswith(suf):
                                documented.add(name[: -len(suf)])
                        if not registered(name):
                            yield Finding(
                                self.name, ERROR, path, lineno,
                                "README documents metric '%s' which "
                                "is not registered" % name,
                                hint="ghost catalog entry — fix the "
                                     "name or register the metric")
        if index.texts:
            for metric in sorted(regs):
                if metric not in documented:
                    _, path, line = regs[metric][0]
                    yield Finding(
                        self.name, WARNING, path, line,
                        "metric '%s' is registered but absent from "
                        "the README catalog" % metric,
                        hint="add it to the README metrics section")

    # -- flags -------------------------------------------------------------
    def _check_flags(self, index):
        flags_sf = index.find("paddle_tpu/flags.py")
        if flags_sf is None:
            return
        table = {}      # name -> line
        for node in ast.walk(flags_sf.tree):
            if isinstance(node, ast.Call) and node.args:
                name = dotted_name(node.func)
                if name and name.split(".")[-1] == "_register":
                    flag = literal_str(node.args[0])
                    if flag is not None:
                        table.setdefault(flag, node.args[0].lineno)
        if not table:
            return
        read = set()
        for sf in index.iter_files():
            if _skip(sf) or sf is flags_sf:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                name = dotted_name(node.func)
                tail = name.split(".")[-1] if name else None
                if tail not in _FLAG_READS:
                    continue
                flag = literal_str(node.args[0])
                if flag is None:
                    continue
                read.add(flag)
                if flag not in table:
                    yield Finding(
                        self.name, ERROR, sf.path, node.lineno,
                        "flag '%s' read via %s() but not registered "
                        "in flags.py" % (flag, tail),
                        hint="add a _register(...) entry with type, "
                             "default and help text")
        for flag in sorted(set(table) - read):
            yield Finding(
                self.name, INFO, flags_sf.path, table[flag],
                "flag '%s' is registered but never read via a "
                "literal get_flag/_flag call" % flag,
                hint="env-only or dynamic use — confirm it is still "
                     "live")


register_runtime_rule(CatalogConsistencyRule)
