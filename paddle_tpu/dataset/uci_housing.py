"""UCI housing — reference parity: python/paddle/dataset/uci_housing.py.

Readers yield (features[13] float32, price float32). The synthetic data is a
fixed linear model + noise so fit_a_line-style book tests converge.
"""

import numpy as np

from . import common

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]
FEATURE_DIM = 13


def _make_reader(n, seed):
    def reader():
        rng = common.synthetic_rng("uci_housing", seed)
        w = common.synthetic_rng("uci_housing_w", 0).randn(FEATURE_DIM)
        for _ in range(n):
            x = rng.randn(FEATURE_DIM).astype(np.float32)
            y = float(x @ w + 0.1 * rng.randn())
            yield x, np.array([y], np.float32)
    return reader


def train(n=404):
    return _make_reader(n, seed=0)


def test(n=102):
    return _make_reader(n, seed=1)


def fetch():
    pass
