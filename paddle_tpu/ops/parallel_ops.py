"""Framework-level SP / PP / EP ops.

These make the parallel/ subsystem reachable from the Program IR (VERDICT
r1 #4: "PP/SP/EP are libraries, not framework features"): a user building a
program through fluid.layers gets sequence-parallel attention, a pipelined
transformer stack, and MoE FFN as ordinary ops. Each lowering consults
ctx.mesh (set by ParallelExecutor): with the matching mesh axis present the
distributed path runs (shard_map over sp/pp, GSPMD all-to-all over ep);
without it the op falls back to the mathematically-identical dense form, so
the same Program runs single-device for tests and parity checks.

Reference note: the 2018 reference has no SP/PP/EP (SURVEY.md §2.7) — these
are beyond-reference capabilities required by the long-context/distributed
mandate; the op-level integration mirrors how ParallelExecutor made DP a
two-line change in the reference API.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


def _mesh_axis(ctx, name):
    mesh = ctx.mesh
    if mesh is not None and name in mesh.axis_names \
            and mesh.shape[name] > 1:
        return mesh
    return None


def _batch_axis(mesh):
    return "dp" if (mesh is not None and "dp" in mesh.axis_names) else None


def _dense_attention(q, k, v, causal, scale):
    # routes to the Pallas flash kernel on TPU (streaming softmax, no
    # [T, T] HBM materialization); dense XLA math elsewhere
    from .flash_attention import flash_attention
    return flash_attention(q, k, v, causal=causal, scale=scale)


@register("sp_attention")
def _sp_attention(ctx, op):
    """Sequence-parallel attention. Inputs Q/K/V [B, H, T, dk] (T sharded
    on the mesh's sp axis when present); attrs: causal, variant
    ("ring" | "ulysses"). Dense-math-identical fallback off-mesh."""
    q = ctx.in1(op, "Q")
    k = ctx.in1(op, "K")
    v = ctx.in1(op, "V")
    causal = bool(op.attr("causal", False))
    scale = float(op.attr("scale", 0.0)) or q.shape[-1] ** -0.5
    mesh = _mesh_axis(ctx, "sp")
    if mesh is None:
        out = _dense_attention(q, k, v, causal, scale)
    else:
        from ..parallel import ring
        fn = (ring.ulysses_attention
              if op.attr("variant", "ring") == "ulysses"
              else ring.ring_attention)
        out = fn(q, k, v, mesh, axis_name="sp", causal=causal, scale=scale,
                 batch_axis=_batch_axis(mesh))
    ctx.set_out(op, "Out", out)


@register("moe_ffn", stateful_rng=True)
def _moe_ffn(ctx, op):
    """MoE FFN: Switch top-1 (attr top_k=1) or GShard top-2 with
    normalized combine weights (top_k=2). Inputs X [B, T, D] or [T, D],
    GateW [D, E], WUp [E, D, H], WDown [E, H, D]; attrs capacity_factor,
    top_k. Outputs Out (same shape as X), AuxLoss (scalar load-balancing
    loss) and, when wired, Overflow (fraction of token-expert assignments
    dropped by capacity — the routing-health metric). Expert dim rides
    the ep mesh axis via GSPMD when present."""
    x = ctx.in1(op, "X")
    gate_w = ctx.in1(op, "GateW")
    w_up = ctx.in1(op, "WUp")
    w_down = ctx.in1(op, "WDown")
    cf = float(op.attr("capacity_factor", 1.25))
    top_k = int(op.attr("top_k", 1))
    from ..parallel import moe
    shape = x.shape
    flat = x.reshape(-1, shape[-1])
    out, aux, stats = moe.moe_ffn(
        flat, gate_w, w_up, w_down, capacity_factor=cf, top_k=top_k,
        mesh=ctx.mesh if _mesh_axis(ctx, "ep") else None,
        return_stats=True)
    ctx.set_out(op, "Out", out.reshape(shape))
    ctx.set_out(op, "AuxLoss", aux)
    if op.output("Overflow"):
        ctx.set_out(op, "Overflow", stats["overflow"])


def _decoder_layer_apply(p, x, n_head):
    """One pre-LN-free (post-LN, matching models/transformer.py 'dan')
    decoder-only layer from a param dict of arrays."""
    b, t, d = x.shape
    dk = d // n_head

    def heads(z):
        return z.reshape(b, t, n_head, dk).transpose(0, 2, 1, 3)

    q = heads(x @ p["wq"])
    k = heads(x @ p["wk"])
    v = heads(x @ p["wv"])
    a = _dense_attention(q, k, v, True, dk ** -0.5)
    a = a.transpose(0, 2, 1, 3).reshape(b, t, d) @ p["wo"]
    x = _ln_apply(x + a, p["ln1_s"], p["ln1_b"])
    h = jax.nn.relu(x @ p["w1"] + p["b1"])
    f = h @ p["w2"] + p["b2"]
    return _ln_apply(x + f, p["ln2_s"], p["ln2_b"])


def _ln_apply(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    m = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - m) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


_STACK_SLOTS = ("WQ", "WK", "WV", "WO", "LN1S", "LN1B", "W1", "B1", "W2",
                "B2", "LN2S", "LN2B")
_STACK_KEYS = ("wq", "wk", "wv", "wo", "ln1_s", "ln1_b", "w1", "b1", "w2",
               "b2", "ln2_s", "ln2_b")


@register("pipeline_stack")
def _pipeline_stack(ctx, op):
    """A stack of L identical causal decoder layers with layer-STACKED
    parameters (leading dim L). With a pp mesh axis of size S the stack
    runs as an S-stage GPipe (L/S layers per stage, activations on the ICI
    ring); otherwise as a lax.scan over layers. Attrs: n_head,
    num_microbatches (0 = auto 2*S), recompute (jax.checkpoint per
    layer — scan-over-layers + remat is the standard memory-efficient
    deep stack)."""
    x = ctx.in1(op, "X")
    n_head = int(op.attr("n_head", 8))
    layer_apply = functools.partial(_decoder_layer_apply, n_head=n_head)
    if op.attr("recompute"):
        layer_apply = jax.checkpoint(layer_apply)
    params = {key: ctx.in1(op, slot)
              for key, slot in zip(_STACK_KEYS, _STACK_SLOTS)}
    n_layer = params["wq"].shape[0]
    mesh = _mesh_axis(ctx, "pp")

    if mesh is None:
        def body(carry, layer_p):
            return layer_apply(layer_p, carry), None

        out, _ = lax.scan(body, x, params)
        ctx.set_out(op, "Out", out)
        return

    from ..parallel import pipeline
    s = mesh.shape["pp"]
    if n_layer % s:
        raise ValueError("pipeline_stack: %d layers not divisible by "
                         "pp=%d stages" % (n_layer, s))
    per = n_layer // s
    stacked = {k: v.reshape((s, per) + v.shape[1:])
               for k, v in params.items()}

    def stage_fn(stage_params, mb):
        def body(carry, layer_p):
            return layer_apply(layer_p, carry), None

        out, _ = lax.scan(body, mb, stage_params)
        return out

    m = int(op.attr("num_microbatches", 0)) or 2 * s
    b = x.shape[0]
    if b % m:
        raise ValueError("pipeline_stack: batch %d not divisible by %d "
                         "microbatches" % (b, m))
    mb = x.reshape((m, b // m) + x.shape[1:])
    out = pipeline.gpipe(stage_fn, stacked, mb, mesh, axis_name="pp",
                         batch_axis=_batch_axis(mesh))
    ctx.set_out(op, "Out", out.reshape(x.shape))
