"""Book test: recognize_digits (reference
python/paddle/fluid/tests/book/test_recognize_digits.py) — train an MNIST MLP
until the loss crosses a threshold. This is the M1 acceptance test."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu as fluid


def test_recognize_digits_mlp():
    img = fluid.layers.data("img", [784])
    label = fluid.layers.data("label", [1], dtype="int64")
    hidden = fluid.layers.fc(img, 128, act="relu")
    hidden = fluid.layers.fc(hidden, 64, act="relu")
    prediction = fluid.layers.fc(hidden, 10, act="softmax")
    cost = fluid.layers.cross_entropy(prediction, label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(prediction, label)
    fluid.optimizer.Adam(learning_rate=0.003).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    train_reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.mnist.train(2048), 500),
        batch_size=64)
    feeder = fluid.DataFeeder([img, label], fluid.CPUPlace())

    first_loss = last_loss = last_acc = None
    for epoch in range(4):
        for batch in train_reader():
            feed = feeder.feed(batch)
            feed["label"] = feed["label"].reshape(-1, 1)
            loss_v, acc_v = exe.run(feed=feed, fetch_list=[avg_cost, acc])
            if first_loss is None:
                first_loss = float(loss_v)
            last_loss = float(loss_v)
            last_acc = float(acc_v)
    assert last_loss < first_loss * 0.5, (first_loss, last_loss)
    assert last_acc > 0.8, last_acc
