"""Megastep execution (ISSUE 7): K logical steps fused into ONE device
dispatch.

The contract pinned here is the tentpole acceptance story:
``Executor.run_steps`` (and the ParallelExecutor twin) advance K real
training steps — forward, backward AND optimizer/persistable-state
update — in one ``lax.scan`` dispatch, BITWISE-identical to K
sequential ``run()`` calls (per-step RNG stream included), with
per-step fetches/NaN-guards streamed out of the scan, LoD feeds riding
the host pre-stack path, feed-plan-cache hits accounted, the
``[k, ...]`` DeviceLoader staging stack consumable directly, and the
monitor/trace tier reporting PER-LOGICAL-STEP figures at any K.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.core import unique_name
from paddle_tpu.monitor import runtime as monrt


def _build_mlp(prefix, seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard(prefix):
        x = fluid.layers.data("x", [8])
        y = fluid.layers.data("y", [1])
        h = fluid.layers.fc(x, 16, act="tanh")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    return main, scope, exe, loss


def _mlp_feeds(n=4, batch=8):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, 8).astype(np.float32),
             "y": rng.rand(batch, 1).astype(np.float32)}
            for _ in range(n)]


def _params(main, scope):
    return {v.name: np.asarray(scope.find_var(v.name)).copy()
            for v in main.global_block().vars.values()
            if v.persistable and scope.find_var(v.name) is not None}


@pytest.fixture(scope="module")
def seq_baseline():
    """Four sequential run() steps on the shared feed set — the
    identity reference every K compares against."""
    feeds = _mlp_feeds()
    main, scope, exe, loss = _build_mlp("ms_")
    losses = [np.asarray(exe.run(main, feed=f, fetch_list=[loss],
                                 scope=scope)[0]) for f in feeds]
    return feeds, losses, _params(main, scope)


# -- train-path identity matrix (the tentpole contract) --------------------

@pytest.mark.parametrize("k", [1, 2, 4])
def test_run_steps_bitwise_identical_to_sequential(k, seq_baseline):
    """4 logical steps at megastep K: every per-step loss and every
    final parameter is BITWISE equal to the 4 sequential run() calls
    (same feeds, same per-step RNG stream)."""
    feeds, seq_losses, seq_params = seq_baseline
    main, scope, exe, loss = _build_mlp("ms_")
    mega_losses = []
    for i in range(0, len(feeds), k):
        out = exe.run_steps(main, feeds=feeds[i:i + k],
                            fetch_list=[loss], scope=scope)
        assert len(out) == k
        mega_losses += [np.asarray(o[0]) for o in out]
    for i, (a, b) in enumerate(zip(seq_losses, mega_losses)):
        np.testing.assert_array_equal(a, b, err_msg="step %d" % i)
    params = _params(main, scope)
    assert params.keys() == seq_params.keys()
    for n in params:
        np.testing.assert_array_equal(params[n], seq_params[n],
                                      err_msg=n)


def _lod(arr, lengths):
    t = fluid.LoDTensor(np.asarray(arr))
    t.set_recursive_sequence_lengths([list(lengths)])
    return t


def _build_lod_net(prefix):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard(prefix):
        x = fluid.layers.data("x", [4], lod_level=1)
        h = fluid.layers.fc(x, 8, act="tanh")
        pooled = fluid.layers.sequence_pool(h, "max")
        loss = fluid.layers.mean(pooled)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    return main, scope, exe, loss


def _lod_feeds():
    """Two LoD batches whose lengths DIFFER but normalize to one
    signature (same total 8, same MAXLEN bucket) — the shared-signature
    contract run_steps documents."""
    rng = np.random.RandomState(3)
    return [{"x": _lod(rng.rand(8, 4).astype(np.float32), lens)}
            for lens in ([3, 5], [5, 3])]


def test_run_steps_lod_feeds_identical():
    feeds = _lod_feeds()
    m1, s1, e1, l1 = _build_lod_net("ml_")
    seq = [np.asarray(e1.run(m1, feed=f, fetch_list=[l1],
                             scope=s1)[0]) for f in feeds]
    m2, s2, e2, l2 = _build_lod_net("ml_")
    out = e2.run_steps(m2, feeds=feeds, fetch_list=[l2], scope=s2)
    for i, o in enumerate(out):
        np.testing.assert_array_equal(np.asarray(o[0]), seq[i],
                                      err_msg="step %d" % i)
    p1, p2 = _params(m1, s1), _params(m2, s2)
    for n in p1:
        np.testing.assert_array_equal(p2[n], p1[n], err_msg=n)


def test_run_steps_rejects_mixed_signatures():
    rng = np.random.RandomState(4)
    main, scope, exe, loss = _build_mlp("ms_")
    feeds = [{"x": rng.rand(8, 8).astype(np.float32),
              "y": rng.rand(8, 1).astype(np.float32)},
             {"x": rng.rand(4, 8).astype(np.float32),
              "y": rng.rand(4, 1).astype(np.float32)}]
    with pytest.raises(ValueError, match="ONE compiled-step signature"):
        exe.run_steps(main, feeds=feeds, fetch_list=[loss], scope=scope)


def test_run_steps_arg_validation():
    main, scope, exe, loss = _build_mlp("ms_")
    with pytest.raises(ValueError, match="k >= 1"):
        exe.run_steps(main, feeds=[], fetch_list=[loss], scope=scope)
    with pytest.raises(ValueError, match="k="):
        exe.run_steps(main, feeds={"x": np.zeros((2, 8, 8))},
                      fetch_list=[loss], scope=scope)
    with pytest.raises(ValueError, match="k=3 but 2"):
        exe.run_steps(main, feeds=_mlp_feeds(2), k=3,
                      fetch_list=[loss], scope=scope)


def test_run_steps_prestacked_rejects_lod():
    main, scope, exe, loss = _build_lod_net("ml_")
    t = _lod(np.zeros((8, 4), np.float32), [3, 5])
    with pytest.raises(ValueError, match="LIST of per-step feed dicts"):
        exe.run_steps(main, feeds={"x": t}, k=2, fetch_list=[loss],
                      scope=scope)


def test_nan_guard_names_the_failing_logical_step():
    flags.set_flag("check_nan_inf", True)
    try:
        main, scope, exe, loss = _build_mlp("ms_")
        feeds = _mlp_feeds(3)
        feeds[1] = dict(feeds[1])
        bad = feeds[1]["x"].copy()
        bad[0, 0] = np.nan
        feeds[1]["x"] = bad
        with pytest.raises(FloatingPointError,
                           match="logical step 1 of 3"):
            exe.run_steps(main, feeds=feeds, fetch_list=[loss],
                          scope=scope)
    finally:
        flags.set_flag("check_nan_inf", None)


def test_feed_plan_cache_accounting_across_megastep():
    """K same-signature per-step feeds derive ONE plan: the first feed
    misses, the remaining K-1 hit (PR-5 counter contract extended)."""
    main, scope, exe, loss = _build_mlp("ms_")
    feeds = _mlp_feeds(3)
    n0, h0 = monrt.FEED_NORMALIZATIONS.value(), \
        monrt.FEED_PLAN_HITS.value()
    exe.run_steps(main, feeds=feeds, fetch_list=[loss], scope=scope)
    assert monrt.FEED_NORMALIZATIONS.value() == n0 + 1
    assert monrt.FEED_PLAN_HITS.value() == h0 + 2
    # a second megastep on the same signature is all hits
    exe.run_steps(main, feeds=feeds, fetch_list=[loss], scope=scope)
    assert monrt.FEED_NORMALIZATIONS.value() == n0 + 1
    assert monrt.FEED_PLAN_HITS.value() == h0 + 5


# -- async double-buffered dispatch ----------------------------------------

def test_async_window_returns_device_fetches():
    """return_numpy=False keeps fetches device-resident and async; the
    double-buffer window (megastep_inflight) bounds un-fetched
    dispatches without changing results; window=1 (serialized) matches
    window=2 bitwise."""
    import jax
    feeds = _mlp_feeds(4)
    vals = {}
    for window in (2, 1):
        flags.set_flag("megastep_inflight", window)
        try:
            main, scope, exe, loss = _build_mlp("ms_")
            outs = []
            for i in range(0, 4, 2):
                outs.append(exe.run_steps(
                    main, feeds=feeds[i:i + 2], fetch_list=[loss],
                    scope=scope, return_numpy=False))
            assert len(exe._inflight) == min(window, 2)
            flat = [v for out in outs for (v,) in out]
            assert all(isinstance(v, jax.Array) for v in flat)
            vals[window] = [np.asarray(v) for v in flat]
        finally:
            flags.set_flag("megastep_inflight", None)
    np.testing.assert_array_equal(vals[1], vals[2])


# -- DeviceLoader staging stack --------------------------------------------

def test_device_loader_megabatches_feed_run_steps():
    """The [k, ...] staging stack the loader builds is directly
    consumable by run_steps(feeds=stack, k=k), matching the host
    list-of-feeds path bitwise; a trailing short group keeps its true
    length."""
    from paddle_tpu.reader.device_loader import DeviceLoader
    feeds = _mlp_feeds(3)
    stacks = list(DeviceLoader(iter(feeds)).megabatches(2))
    assert len(stacks) == 2
    assert stacks[0]["x"].shape == (2, 8, 8)
    assert stacks[1]["x"].shape == (1, 8, 8)   # trailing group
    m1, s1, e1, l1 = _build_mlp("ms_")
    seq = [np.asarray(e1.run(m1, feed=f, fetch_list=[l1],
                             scope=s1)[0]) for f in feeds]
    m2, s2, e2, l2 = _build_mlp("ms_")
    got = []
    for st in stacks:
        k = int(np.shape(st["x"])[0])
        got += [np.asarray(o[0]) for o in e2.run_steps(
            m2, feeds=st, k=k, fetch_list=[l2], scope=s2)]
    np.testing.assert_array_equal(got, seq)


def test_device_loader_megabatches_reject_lod():
    from paddle_tpu.reader.device_loader import DeviceLoader
    feeds = [{"x": _lod(np.zeros((8, 4), np.float32), [3, 5])}]
    with pytest.raises(ValueError, match="per-step feed dicts"):
        list(DeviceLoader(iter(feeds)).megabatches(2))


def test_device_loader_passes_lod_feeds_through_intact():
    """ISSUE-7 satellite fix: the plain prefetch path must yield LoD
    feeds UNTOUCHED (previously np.asarray silently stripped the LoD),
    so the consuming executor's own normalization still sees lengths."""
    from paddle_tpu.reader.device_loader import DeviceLoader
    t = _lod(np.random.RandomState(0).rand(8, 4).astype(np.float32),
             [3, 5])
    [batch] = list(DeviceLoader(iter([{"x": t, "d": np.ones(
        (2, 3), np.float32)}])))
    assert isinstance(batch["x"], fluid.LoDTensor)
    assert batch["x"].recursive_sequence_lengths() == [[3, 5]]
    import jax
    assert isinstance(batch["d"], jax.Array)


# -- ParallelExecutor twin -------------------------------------------------

def test_parallel_run_steps_identical_and_rejects_accum():
    from paddle_tpu import parallel
    feeds = _mlp_feeds(4)

    def run(mode):
        main, scope, exe, loss = _build_mlp("ms_")
        pexe = fluid.ParallelExecutor(loss_name=loss.name,
                                      main_program=main, scope=scope)
        if mode == "seq":
            losses = [np.asarray(pexe.run([loss], feed=f)[0])
                      for f in feeds]
        else:
            losses = [np.asarray(o[0]) for o in
                      pexe.run_steps([loss], feeds=feeds)]
        return losses, _params(main, scope), pexe

    seq, pseq, _ = run("seq")
    mega, pmega, pexe = run("mg")
    np.testing.assert_array_equal(mega, seq)
    for n in pseq:
        np.testing.assert_array_equal(pmega[n], pseq[n], err_msg=n)

    strat = parallel.DistributedStrategy(gradient_accumulation_steps=2)
    main, scope, exe, loss = _build_mlp("ms_")
    pexe2 = fluid.ParallelExecutor(loss_name=loss.name,
                                   main_program=main, scope=scope,
                                   strategy=strat)
    with pytest.raises(ValueError, match="gradient_accumulation"):
        pexe2.run_steps([loss], feeds=feeds[:2])


# -- monitor / trace integration -------------------------------------------

def test_megastep_monitor_counters_and_recorder_row(tmp_path):
    from paddle_tpu import monitor
    main, scope, exe, loss = _build_mlp("ms_")
    feeds = _mlp_feeds(2)
    log = str(tmp_path / "mega.jsonl")
    d0 = monrt.MEGASTEP_DISPATCHES.value(executor="exe")
    s0 = monrt.MEGASTEP_STEPS.value(executor="exe")
    st0 = monrt.STEPS.value(executor="exe")
    monitor.enable(log_path=log)
    try:
        exe.run_steps(main, feeds=feeds, fetch_list=[loss],
                      scope=scope)
    finally:
        monitor.disable()
    assert monrt.MEGASTEP_DISPATCHES.value(executor="exe") == d0 + 1
    assert monrt.MEGASTEP_STEPS.value(executor="exe") == s0 + 2
    # the fusion is visible as steps advanced vs dispatches: 2 logical
    # steps, ONE host dispatch
    assert monrt.STEPS.value(executor="exe") == st0 + 2
    rows = [r for r in monitor.read_jsonl(log) if r["ev"] == "step"]
    assert len(rows) == 1
    r = rows[0]
    assert r["k"] == 2 and r["megastep_dt"] > 0
    # dt is the PER-LOGICAL-STEP figure (megastep wall time / K)
    assert abs(r["dt"] - r["megastep_dt"] / 2) < 1e-9


def test_megastep_trace_span_carries_k(tmp_path):
    from paddle_tpu import monitor
    from paddle_tpu.trace import runtime as trt
    main, scope, exe, loss = _build_mlp("ms_")
    tlog = str(tmp_path / "spans.jsonl")
    trt.enable(log_path=tlog, sample_rate=1.0, proc="mega-test")
    try:
        exe.run_steps(main, feeds=_mlp_feeds(2), fetch_list=[loss],
                      scope=scope)
    finally:
        trt.disable()
    spans = [r for r in monitor.read_jsonl(tlog) if r["ev"] == "span"
             and r["name"] == "exe.step"]
    assert spans and spans[-1]["attrs"]["k"] == 2
