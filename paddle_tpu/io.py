"""Model / parameter persistence.

Reference parity: python/paddle/fluid/io.py:66-418 (save/load_vars, params,
persistables, inference model) and the save/load ops (operators/save_op.cc,
load_op.cc, save_combine_op.cc, load_combine_op.cc).

TPU-first: persistable state lives in a Scope as host-transferable jax
arrays, so persistence is host-side numpy serialization — there is no need
for in-graph save/load kernels (the reference needed them because variables
lived on the C++ side). Formats: one ``.npy`` per var, or a single ``.npz``
for the *_combine variants. Inference model = pruned Program JSON
(``__model__``) + params, mirroring io.py:298-418.

Checkpointing follows the Go-pserver pattern (go/pserver/service.go:346):
write to a temp file, fsync, then atomically rename, with a CRC + meta JSON
so a torn write can never be mistaken for a checkpoint.
"""

import json
import os
import shutil
import tempfile
import zlib

import numpy as np

from .core.program import Program, Parameter, default_main_program
from .core.scope import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "get_inference_program",
    "save_checkpoint", "load_checkpoint",
]


def _is_parameter(var):
    return isinstance(var, Parameter)


def _is_persistable(var):
    return var.persistable


def _collect(main_program, predicate, vars=None):
    main_program = main_program or default_main_program()
    if vars is not None:
        out = []
        for v in vars:
            out.append(main_program.global_block().var(v)
                       if isinstance(v, str) else v)
        return out
    return [v for v in main_program.list_vars() if predicate(v)]


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Save scope values of selected vars under `dirname`
    (io.py:66 save_vars)."""
    scope = scope or global_scope()
    varlist = _collect(main_program, predicate or _is_persistable, vars)
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        arrays = {}
        for v in varlist:
            val = scope.find_var(v.name)
            if val is None:
                raise ValueError("var %r has no value in scope" % v.name)
            arrays[v.name] = np.asarray(val)
        np.savez(os.path.join(dirname, filename), **arrays)
        return
    for v in varlist:
        val = scope.find_var(v.name)
        if val is None:
            raise ValueError("var %r has no value in scope" % v.name)
        np.save(os.path.join(dirname, v.name + ".npy"), np.asarray(val))


def save_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename, scope=scope)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename,
                     scope=scope)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """Load saved arrays into the scope (io.py:132 load_vars)."""
    scope = scope or global_scope()
    varlist = _collect(main_program, predicate or _is_persistable, vars)
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not path.endswith(".npz"):
            path += ".npz"
        arrays = np.load(path)
        for v in varlist:
            if v.name in arrays:
                scope.set(v.name, arrays[v.name])
        return
    for v in varlist:
        path = os.path.join(dirname, v.name + ".npy")
        if os.path.exists(path):
            scope.set(v.name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_parameter, filename=filename, scope=scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename,
                     scope=scope)


# --------------------------------------------------------------------------
# inference model (io.py:298-418)
# --------------------------------------------------------------------------

def get_inference_program(target_vars, main_program=None):
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    pruned = main_program.prune(target_vars)
    return pruned.clone(for_test=True)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename="__model__",
                         params_filename=None, scope=None):
    main_program = main_program or default_main_program()
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    os.makedirs(dirname, exist_ok=True)

    inference_program = get_inference_program(target_vars, main_program)
    d = inference_program.to_dict()
    d["feed_names"] = list(feeded_var_names)
    d["fetch_names"] = [v.name if not isinstance(v, str) else v
                        for v in target_vars]
    with open(os.path.join(dirname, model_filename), "w") as f:
        json.dump(d, f)
    save_persistables(executor, dirname, inference_program,
                      filename=params_filename, scope=scope)
    return d["fetch_names"]


def load_inference_model(dirname, executor, model_filename="__model__",
                         params_filename=None, scope=None):
    """Returns (program, feed_target_names, fetch_targets)."""
    with open(os.path.join(dirname, model_filename)) as f:
        d = json.load(f)
    program = Program.from_dict(d)
    load_persistables(executor, dirname, program, filename=params_filename,
                      scope=scope)
    fetch_targets = [program.global_block().var(n)
                     for n in d.get("fetch_names", [])]
    return program, d.get("feed_names", []), fetch_targets


# --------------------------------------------------------------------------
# atomic checkpoint (Go pserver pattern: CRC + atomic meta — service.go:346)
# --------------------------------------------------------------------------

def save_checkpoint(dirname, step, main_program=None, scope=None,
                    keep_last=3):
    """Atomic checkpoint: npz written to tmp + fsync + rename; meta JSON with
    CRC32 written last, also atomically. A reader only trusts checkpoints
    whose meta exists and whose CRC matches."""
    scope = scope or global_scope()
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    ckpt_name = "ckpt-%d.npz" % step
    arrays = {}
    for v in main_program.list_vars():
        if v.persistable:
            val = scope.find_var(v.name)
            if val is not None:
                arrays[v.name] = np.asarray(val)

    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        path = os.path.join(dirname, ckpt_name)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    with open(path, "rb") as f:
        crc = zlib.crc32(f.read())
    meta = {"step": step, "file": ckpt_name, "crc32": crc}
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dirname, "meta-%d.json" % step))

    # prune old checkpoints
    steps = sorted(int(n.split("-")[1].split(".")[0])
                   for n in os.listdir(dirname) if n.startswith("meta-"))
    for s in steps[:-keep_last]:
        for n in ("ckpt-%d.npz" % s, "meta-%d.json" % s):
            p = os.path.join(dirname, n)
            if os.path.exists(p):
                os.unlink(p)
    return os.path.join(dirname, ckpt_name)


def load_checkpoint(dirname, main_program=None, scope=None):
    """Load the newest valid checkpoint; returns its step, or None if no
    valid checkpoint exists (corrupt ones are skipped, pserver-style)."""
    scope = scope or global_scope()
    if not os.path.isdir(dirname):
        return None
    steps = sorted((int(n.split("-")[1].split(".")[0])
                    for n in os.listdir(dirname) if n.startswith("meta-")),
                   reverse=True)
    for step in steps:
        try:
            with open(os.path.join(dirname, "meta-%d.json" % step)) as f:
                meta = json.load(f)
            path = os.path.join(dirname, meta["file"])
            with open(path, "rb") as f:
                if zlib.crc32(f.read()) != meta["crc32"]:
                    continue
            arrays = np.load(path)
            for name in arrays.files:
                scope.set(name, arrays[name])
            return step
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            continue
    return None
