"""Transformer inference path: parameter extraction from the trained
Program, teacher-forced logit parity between the Program forward and the
KV-cached incremental decoder, and beam/greedy translate smoke checks."""

import numpy as np
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.models import transformer
from paddle_tpu.models.transformer_infer import TransformerInfer

N_LAYER, N_HEAD, D_MODEL, MAX_LEN, VOCAB = 2, 4, 32, 16, 30


def _build_and_init():
    avg_cost, logits = transformer.transformer(
        src_vocab_size=VOCAB, trg_vocab_size=VOCAB, max_len=MAX_LEN,
        n_layer=N_LAYER, n_head=N_HEAD, d_model=D_MODEL, d_inner=64,
        dropout_rate=0.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe, avg_cost, logits


def _feeds(rng, batch):
    src = rng.randint(3, VOCAB, (batch, MAX_LEN)).astype(np.int64)
    trg = rng.randint(3, VOCAB, (batch, MAX_LEN)).astype(np.int64)
    ones = np.ones((batch, MAX_LEN), np.float32)
    pos = np.tile(np.arange(MAX_LEN, dtype=np.int64), (batch, 1))
    return {"src_word": src, "src_pos": pos, "src_mask": ones,
            "trg_word": trg, "trg_pos": pos, "trg_mask": ones,
            "lbl_word": trg}


def test_teacher_forced_logit_parity(rng):
    exe, avg_cost, logits = _build_and_init()
    feeds = _feeds(rng, batch=2)
    prog_logits, = exe.run(feed=feeds, fetch_list=[logits])
    prog_logits = np.asarray(prog_logits)

    infer = TransformerInfer(fluid.default_main_program(),
                             fluid.global_scope(), N_LAYER, N_HEAD, D_MODEL,
                             MAX_LEN)
    src = jnp.asarray(feeds["src_word"].astype(np.int32))
    mask = jnp.asarray(feeds["src_mask"])
    enc = infer.encode(src, mask)
    state = infer._init_decode_state(enc, mask, rows=2)
    trg = feeds["trg_word"].astype(np.int32)
    for t in range(MAX_LEN):
        step_logits, state = infer._step_logits(jnp.asarray(trg[:, t]),
                                                state, t)
        np.testing.assert_allclose(np.asarray(step_logits),
                                   prog_logits[:, t, :], rtol=2e-3,
                                   atol=2e-4)


def test_translate_beam_and_greedy(rng):
    exe, avg_cost, logits = _build_and_init()
    infer = TransformerInfer(fluid.default_main_program(),
                             fluid.global_scope(), N_LAYER, N_HEAD, D_MODEL,
                             MAX_LEN)
    batch, beam = 2, 3
    src = jnp.asarray(rng.randint(3, VOCAB, (batch, MAX_LEN)),
                      dtype=jnp.int32)
    mask = jnp.ones((batch, MAX_LEN), jnp.float32)
    sents, scores = infer.translate(src, mask, beam_size=beam,
                                    max_out_len=8)
    assert sents.shape == (batch, beam, 8)
    assert scores.shape == (batch, beam)
    sc = np.asarray(scores)
    assert (np.diff(sc, axis=1) <= 1e-5).all(), "beams sorted best-first"

    toks, gsc = infer.translate_greedy(src, mask, max_out_len=8)
    assert toks.shape == (batch, 8)
    # greedy == the path a beam of size 1 takes
    s1, _ = infer.translate(src, mask, beam_size=1, max_out_len=8)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(s1)[:, 0, :])


def test_extract_params_mismatch_is_loud(rng):
    exe, avg_cost, logits = _build_and_init()
    try:
        TransformerInfer(fluid.default_main_program(), fluid.global_scope(),
                         N_LAYER + 1, N_HEAD, D_MODEL, MAX_LEN)
    except AssertionError as e:
        assert "mismatch" in str(e) or "exhausted" in str(e)
    else:
        raise AssertionError("wrong n_layer must not silently mis-wire")


def test_lm_teacher_forced_logit_parity(rng):
    """TransformerLMInfer replays transformer_lm weights: incremental
    KV-cached step logits must match the Program's full forward."""
    from paddle_tpu.models.transformer_infer import TransformerLMInfer
    avg_cost, logits = transformer.transformer_lm(
        vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
        n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    batch = 2
    src = rng.randint(3, VOCAB, (batch, MAX_LEN)).astype(np.int64)
    pos = np.tile(np.arange(MAX_LEN, dtype=np.int64), (batch, 1))
    ones = np.ones((batch, MAX_LEN), np.float32)
    prog_logits, = exe.run(
        feed={"src": src, "pos": pos, "mask": ones, "label": src},
        fetch_list=[logits])
    prog_logits = np.asarray(prog_logits)

    infer = TransformerLMInfer(fluid.default_main_program(),
                               fluid.global_scope(), N_LAYER, N_HEAD,
                               D_MODEL, MAX_LEN)
    state = infer._init_state(batch)
    toks = src.astype(np.int32)
    for t in range(MAX_LEN):
        step_logits, state = infer._step_logits(
            jnp.asarray(toks[:, t]), state, t)
        np.testing.assert_allclose(np.asarray(step_logits),
                                   prog_logits[:, t, :], rtol=2e-3,
                                   atol=2e-4)


def test_lm_generate_greedy_and_beam(rng):
    from paddle_tpu.models.transformer_infer import TransformerLMInfer
    transformer.transformer_lm(
        vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
        n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    infer = TransformerLMInfer(fluid.default_main_program(),
                               fluid.global_scope(), N_LAYER, N_HEAD,
                               D_MODEL, MAX_LEN)
    toks, g_scores = infer.generate(batch=2, max_out_len=8)
    assert np.asarray(toks).shape == (2, 8)
    assert np.asarray(g_scores).shape == (2,)
    sents, scores = infer.generate(batch=2, max_out_len=8, beam_size=3)
    assert np.asarray(sents).shape == (2, 3, 8)
    sc = np.asarray(scores)
    assert (np.diff(sc, axis=1) <= 1e-6).all()   # best beam first


def test_lm_bf16_decode_matches_f32_logits(rng):
    """dtype=bfloat16 serving mode: weights + KV caches in bf16, score
    softmax/log-probs in f32. Teacher-forced logits stay within bf16
    tolerance of the f32 replay and generation runs end-to-end."""
    import jax.numpy as jnp2
    from paddle_tpu.models.transformer_infer import TransformerLMInfer
    transformer.transformer_lm(
        vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
        n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    f32 = TransformerLMInfer(fluid.default_main_program(),
                             fluid.global_scope(), N_LAYER, N_HEAD,
                             D_MODEL, MAX_LEN)
    bf16 = TransformerLMInfer(fluid.default_main_program(),
                              fluid.global_scope(), N_LAYER, N_HEAD,
                              D_MODEL, MAX_LEN, dtype=jnp2.bfloat16)
    toks = rng.randint(3, VOCAB, (2, 4)).astype(np.int32)
    s32, s16 = f32._init_state(2), bf16._init_state(2)
    assert s16["k0"].dtype == jnp2.bfloat16
    for t in range(4):
        l32, s32 = f32._step_logits(jnp.asarray(toks[:, t]), s32, t)
        l16, s16 = bf16._step_logits(jnp.asarray(toks[:, t]), s16, t)
        np.testing.assert_allclose(np.asarray(l16, np.float32),
                                   np.asarray(l32), rtol=0.1, atol=0.05)
    out, scores = bf16.generate(batch=2, max_out_len=6)
    assert np.asarray(out).shape == (2, 6)
    assert np.isfinite(np.asarray(scores)).all()


def test_seq2seq_bf16_translate_runs(rng):
    """bf16 serving mode on the seq2seq decoder too (shared
    _cast_params): beam translate runs, fp16 rejected loudly."""
    import jax.numpy as jnp2
    _build_and_init()
    bf16 = TransformerInfer(fluid.default_main_program(),
                            fluid.global_scope(), N_LAYER, N_HEAD,
                            D_MODEL, MAX_LEN, dtype=jnp2.bfloat16)
    assert bf16.src_word_emb.dtype == jnp2.bfloat16
    src = jnp.asarray(rng.randint(3, VOCAB, (2, MAX_LEN)), jnp.int32)
    mask = jnp.ones((2, MAX_LEN), jnp.float32)
    sents, scores = bf16.translate(src, mask, beam_size=2, max_out_len=6)
    assert np.asarray(sents).shape == (2, 2, 6)
    assert np.isfinite(np.asarray(scores)).all()
    import pytest as _pytest
    with _pytest.raises(ValueError, match="bfloat16"):
        TransformerInfer(fluid.default_main_program(),
                         fluid.global_scope(), N_LAYER, N_HEAD, D_MODEL,
                         MAX_LEN, dtype=jnp2.float16)
