"""Book test: recognize_digits through the paddle.v2 API, written in
the canonical v2 script shape (reference capability:
python/paddle/v2/* driving the recognize_digits book chapter — data
layers with data_type, activation objects, networks.simple_img_conv_pool,
parameters.create, trainer.SGD event loop, paddle.infer). Both the MLP
and the convnet variants must train.

L9 closure (round-4 directive #6): this and
test_v2_understand_sentiment.py are the 'two reference v2 book scripts
run nearly-verbatim' evidence for COVERAGE's L9 row."""

import numpy as np

import paddle_tpu.v2 as paddle


def softmax_regression(img):
    predict = paddle.layer.fc(input=img, size=10,
                              act=paddle.activation.Softmax())
    return predict


def multilayer_perceptron(img):
    hidden1 = paddle.layer.fc(input=img, size=64,
                              act=paddle.activation.Relu())
    hidden2 = paddle.layer.fc(input=hidden1, size=32,
                              act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=hidden2, size=10,
                              act=paddle.activation.Softmax())
    return predict


def convolutional_neural_network(img):
    conv_pool_1 = paddle.networks.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=8, num_channel=1,
        pool_size=2, pool_stride=2, act=paddle.activation.Relu())
    conv_pool_2 = paddle.networks.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=16, num_channel=8,
        pool_size=2, pool_stride=2, act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=conv_pool_2, size=10,
                              act=paddle.activation.Softmax())
    return predict


def _train(net_fn, passes=4, lr=0.05):
    import paddle_tpu as fluid
    fluid.switch_main_program(fluid.Program())
    fluid.switch_startup_program(fluid.Program())

    paddle.init(use_gpu=False, trainer_count=1)
    images = paddle.layer.data(
        name="pixel", type=paddle.data_type.dense_vector(784))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(10))
    predict = net_fn(images)
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(
        learning_rate=lr / 128.0, momentum=0.9,
        regularization=paddle.optimizer.L2Regularization(rate=5e-4))
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)

    costs = []

    def event_handler(event):
        if isinstance(event, paddle.event.EndPass):
            costs.append(event.cost)

    trainer.train(
        reader=paddle.batch(
            paddle.reader.shuffle(paddle.dataset.mnist.train(n=512),
                                  buf_size=512),
            batch_size=64),
        num_passes=passes, event_handler=event_handler)
    assert costs[-1] < costs[0], costs

    # paddle.infer over the test split (book-script inference shape)
    test_data = [(s[0],) for s in paddle.dataset.mnist.test(n=32)()]
    probs = paddle.infer(output_layer=predict, parameters=parameters,
                         input=test_data)
    probs = np.asarray(probs)
    assert probs.shape == (32, 10)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-3)
    return costs


def test_v2_recognize_digits_mlp():
    _train(multilayer_perceptron)


def test_v2_recognize_digits_conv():
    _train(convolutional_neural_network, passes=3)


def test_v2_recognize_digits_softmax():
    _train(softmax_regression)
