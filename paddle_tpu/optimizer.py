"""Optimizer family.

Reference parity: python/paddle/fluid/optimizer.py:35-812 — the base class
creates a learning-rate variable and per-parameter accumulators, and
``minimize`` = append_backward + (regularize, clip) + per-param optimize ops.
The optimize ops themselves (ops/optimizer_ops.py) update state functionally;
state threading + donation makes them in-place on device.
"""

from .core import unique_name
from .core.backward import append_backward
from .core.program import Variable, default_main_program, default_startup_program
from .clip import append_gradient_clip_ops, error_clip_callback
from .initializer import ConstantInitializer
from .layers.layer_helper import LayerHelper
from .regularizer import append_regularization_ops


class Optimizer:
    def __init__(self, learning_rate, regularization=None, LARS_weight_decay=0.0):
        if not isinstance(learning_rate, (float, int, Variable)):
            raise TypeError("learning rate must be float or Variable")
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._learning_rate_map = {}
        self._accumulators = {}       # name -> {param_name: var}
        self.helper = None
        self._global_step = None

    # -- lr ------------------------------------------------------------------
    def _create_global_learning_rate(self):
        prog = default_main_program()
        lr = self._learning_rate_map.get(prog)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[prog] = self._learning_rate
            return
        from .layers import tensor as tensor_layers
        lr = tensor_layers.create_global_var(
            name=unique_name.generate("learning_rate"),
            shape=[1], value=float(self._learning_rate),
            dtype="float32", persistable=True)
        self._learning_rate_map[prog] = lr

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate()
        param_lr = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        if param_lr == 1.0:
            return base
        from .layers import math_ops
        return math_ops.scale_var(base, param_lr)

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            raise Exception("accumulator %s for %s exists" % (name, param.name))
        self._accumulators.setdefault(name, {})
        helper = self.helper or LayerHelper("optimizer")
        var = helper.create_global_variable(
            name=unique_name.generate(param.name + "_" + name),
            persistable=True, dtype=dtype or param.dtype,
            shape=shape or param.shape)
        helper.set_variable_initializer(var, ConstantInitializer(fill_value))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- subclass hooks ------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    # -- main entry ----------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        block = program.global_block()
        self.helper = LayerHelper(self.__class__.__name__,
                                  startup_program=startup_program)
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if g is not None])
        self._create_global_learning_rate()

        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if getattr(param_and_grad[0], "trainable", True):
                optimize_ops.append(
                    self._append_optimize_op(block, param_and_grad))
        self._finish_update(block)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = append_backward(loss, parameter_list, no_grad_set,
                                       [error_clip_callback])
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(
            params_grads, loss, startup_program)
        return optimize_ops, params_grads

    def apply_gradients(self, params_grads):
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)

        class _Loss:
            block = params_grads[0][0].block
        return self._create_optimization_pass(params_grads, _Loss)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return append_backward(loss, parameter_list, no_grad_set)


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p]})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator("velocity", p)
        return block.append_op(
            type="momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            type="adam",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "update_beta_pow": True})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        inf_norm = self._get_accumulator("inf_norm", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        return block.append_op(
            type="adamax",
            inputs={"Param": [p], "Grad": [g], "Moment": [moment],
                    "InfNorm": [inf_norm], "Beta1Pow": [b1p],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [moment],
                     "InfNormOut": [inf_norm], "Beta1PowOut": [b1p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "update_beta_pow": True})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [p], "Grad": [g], "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("__avg_squared_grad", p)
            self._add_accumulator("__avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("__avg_squared_grad", p)
        asu = self._get_accumulator("__avg_squared_update", p)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [p], "Grad": [g], "AvgSquaredGrad": [asg],
                    "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [p], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 **kw):
        super().__init__(learning_rate, **kw)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator("momentum", p)
        ms = self._get_accumulator("mean_square", p)
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [p], "Grad": [g], "Moment": [mom],
                    "MeanSquare": [ms],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "MomentOut": [mom],
                     "MeanSquareOut": [ms]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [p], "Grad": [g], "SquaredAccumulator": [sq],
                    "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [p], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class ModelAverage(Optimizer):
    """Maintains a sliding-window average of parameters for evaluation
    (reference optimizer.py:812). apply()/restore() swap averaged weights in
    and out of the scope."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        prog = default_main_program()
        for param in prog.global_block().all_parameters():
            if getattr(param, "do_model_average", None) is not False:
                self.params_grads.append((param, None))
        self.helper = LayerHelper("model_average")
        self._create_accumulators(prog.global_block(),
                                  [p for p, _ in self.params_grads])
        for p, _ in self.params_grads:
            self._append_average_accumulate_op(p)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("sum_1", p)
            self._add_accumulator("sum_2", p)
            self._add_accumulator("sum_3", p)
            self._add_accumulator("num_accumulates", p, dtype="int64",
                                  shape=[1])
            self._add_accumulator("old_num_accumulates", p, dtype="int64",
                                  shape=[1])
            self._add_accumulator("num_updates", p, dtype="int64", shape=[1])

    def _append_average_accumulate_op(self, param):
        s1 = self._get_accumulator("sum_1", param)
        s2 = self._get_accumulator("sum_2", param)
        s3 = self._get_accumulator("sum_3", param)
        na = self._get_accumulator("num_accumulates", param)
        ona = self._get_accumulator("old_num_accumulates", param)
        nu = self._get_accumulator("num_updates", param)
        default_main_program().global_block().append_op(
            type="average_accumulates",
            inputs={"param": [param], "in_sum_1": [s1], "in_sum_2": [s2],
                    "in_sum_3": [s3], "in_num_accumulates": [na],
                    "in_old_num_accumulates": [ona], "in_num_updates": [nu]},
            outputs={"out_sum_1": [s1], "out_sum_2": [s2], "out_sum_3": [s3],
                     "out_num_accumulates": [na],
                     "out_old_num_accumulates": [ona],
                     "out_num_updates": [nu]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window})

    def apply(self, executor, need_restore=True):
        """Swap averaged values into the scope (host-side, like the reference's
        apply program but without building one)."""
        import numpy as np
        from .core.scope import global_scope
        scope = global_scope()
        self._backup = {}
        for p, _ in self.params_grads:
            s1 = np.asarray(scope.find_var(
                self._get_accumulator("sum_1", p).name))
            s2 = np.asarray(scope.find_var(
                self._get_accumulator("sum_2", p).name))
            s3 = np.asarray(scope.find_var(
                self._get_accumulator("sum_3", p).name))
            na = np.asarray(scope.find_var(
                self._get_accumulator("num_accumulates", p).name))
            ona = np.asarray(scope.find_var(
                self._get_accumulator("old_num_accumulates", p).name))
            total = float(na[0] + ona[0])
            if total <= 0:
                continue
            self._backup[p.name] = np.asarray(scope.find_var(p.name))
            scope.set(p.name, ((s1 + s2 + s3) / total).astype(
                self._backup[p.name].dtype))

    def restore(self, executor=None):
        from .core.scope import global_scope
        for name, val in getattr(self, "_backup", {}).items():
            global_scope().set(name, val)
        self._backup = {}


# fluid-compatible aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
