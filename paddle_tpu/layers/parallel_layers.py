"""Layer DSL for SP / PP / EP (ops/parallel_ops.py lowerings).

Makes the distributed subsystem reachable from fluid-style model code:

    attn = layers.sequence_parallel_attention(q, k, v, causal=True)
    out, aux = layers.sparse_moe(x, num_experts=8, d_inner=2048)
    y = layers.pipelined_decoder_stack(x, n_layer=8, n_head=8, d_inner=2048)

Each runs the distributed path when ParallelExecutor's mesh has the
matching axis (sp / ep / pp) and an identical-math dense fallback
otherwise, so programs stay testable single-device.
"""

import numpy as np

from .layer_helper import LayerHelper
from ..initializer import Normal, Constant
from ..param_attr import ParamAttr

__all__ = ["sequence_parallel_attention", "sparse_moe",
           "pipelined_decoder_stack"]


def sequence_parallel_attention(q, k, v, causal=False, variant="ring",
                                scale=0.0, name=None):
    """q/k/v: [B, H, T, dk] variables (T sharded on the sp mesh axis under
    ParallelExecutor). Returns [B, H, T, dk]."""
    helper = LayerHelper("sp_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype, shape=q.shape)
    helper.append_op(
        type="sp_attention", inputs={"Q": [q], "K": [k], "V": [v]},
        outputs={"Out": [out]},
        attrs={"causal": causal, "variant": variant, "scale": scale})
    return out


def sparse_moe(x, num_experts, d_inner, capacity_factor=1.25,
               top_k=1, return_overflow=False, param_attr=None, name=None):
    """MoE FFN over [B, T, D] (or [T, D]) input: Switch top-1 (top_k=1)
    or GShard top-2 with normalized combine weights (top_k=2). Expert
    weights are stacked [E, ...] and sharded on the ep mesh axis. Returns
    (out, aux_loss) — add aux_loss (scaled) to the training cost — plus
    the scalar capacity-overflow fraction (the routing-health metric to
    monitor) when return_overflow=True."""
    helper = LayerHelper("moe_ffn", param_attr=param_attr, name=name)
    d = int(x.shape[-1])
    gate = helper.create_parameter(helper.param_attr, shape=[d, num_experts],
                                   dtype=x.dtype,
                                   default_initializer=Normal(0., 0.02))
    w_up = helper.create_parameter(
        ParamAttr(name=helper.name + ".w_up"),
        shape=[num_experts, d, d_inner], dtype=x.dtype,
        default_initializer=Normal(0., d ** -0.5))
    w_down = helper.create_parameter(
        ParamAttr(name=helper.name + ".w_down"),
        shape=[num_experts, d_inner, d], dtype=x.dtype,
        default_initializer=Normal(0., d_inner ** -0.5))
    # expert dim rides the ep axis
    prog = helper.main_program
    prog._sharding_hints[w_up.name] = ("ep", None, None)
    prog._sharding_hints[w_down.name] = ("ep", None, None)

    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    aux = helper.create_variable_for_type_inference("float32", shape=())
    outputs = {"Out": [out], "AuxLoss": [aux]}
    overflow = None
    if return_overflow:
        overflow = helper.create_variable_for_type_inference(
            "float32", shape=())
        overflow.stop_gradient = True
        outputs["Overflow"] = [overflow]
    helper.append_op(
        type="moe_ffn",
        inputs={"X": [x], "GateW": [gate], "WUp": [w_up],
                "WDown": [w_down]},
        outputs=outputs,
        attrs={"capacity_factor": capacity_factor, "top_k": int(top_k)})
    if return_overflow:
        return out, aux, overflow
    return out, aux


def pipelined_decoder_stack(x, n_layer, n_head, d_inner,
                            num_microbatches=0, recompute=False,
                            schedule="gpipe", virtual_stages=0,
                            tp_shard=False, num_experts=0, moe_top_k=1,
                            moe_capacity_factor=1.25, moe_gate_groups=1,
                            name=None):
    """L identical causal decoder layers with layer-stacked parameters
    ([L, ...], leading dim sharded on the pp mesh axis → pipeline
    schedule under ParallelExecutor; lax.scan over layers otherwise).
    recompute=True rematerializes each layer's activations in the
    backward pass (jax.checkpoint on the scan body).
    schedule: "gpipe" (M >= S regime) or "interleaved" (Megatron
    virtual stages — bubble cut by `virtual_stages` chunks per device;
    requires M <= S). tp_shard=True adds Megatron col/row sharding
    hints for a tp mesh axis (the pp x tp composition — the stage body
    then psums per sublayer; ops/parallel_ops._decoder_layer_apply_tp).

    num_experts > 0 replaces every layer's dense FFN with a routed MoE
    layer (experts' hidden dim = d_inner) — the pp x ep composition:
    expert stacks shard on the ep mesh axis and the dispatch
    all-to-alls inside the stage body. Requires an explicit
    num_microbatches and moe_gate_groups = dp*ep of the target mesh
    (routing is per-microbatch per token-group; the static attrs let
    the dense fallback reproduce it exactly), and the call then
    returns (out, aux_loss) instead of out.

    x: [B, T, D]. Returns [B, T, D]."""
    helper = LayerHelper("pipeline_stack", name=name)
    d = int(x.shape[-1])
    L = int(n_layer)
    # storage-placement hints for the GLOBAL [L, ...] params (the op
    # re-blocks them per schedule inside the jit); col/row tp tails
    # mirror ops/parallel_ops._TP_SPEC_TAILS
    tp_tails = {
        ".wq": (None, "tp"), ".wk": (None, "tp"), ".wv": (None, "tp"),
        ".wo": ("tp", None), ".w1": (None, "tp"), ".b1": ("tp",),
        ".w2": ("tp", None),
    }

    def p(suffix, shape, init):
        w = helper.create_parameter(ParamAttr(name=helper.name + suffix),
                                    shape=list(shape), dtype=x.dtype,
                                    default_initializer=init)
        tail = tp_tails.get(suffix) if tp_shard else None
        helper.main_program._sharding_hints[w.name] = \
            ("pp",) + (tail or (None,) * (len(shape) - 1))
        return w

    std = d ** -0.5
    params = {
        "WQ": p(".wq", (L, d, d), Normal(0., std)),
        "WK": p(".wk", (L, d, d), Normal(0., std)),
        "WV": p(".wv", (L, d, d), Normal(0., std)),
        "WO": p(".wo", (L, d, d), Normal(0., std)),
        "LN1S": p(".ln1_s", (L, d), Constant(1.0)),
        "LN1B": p(".ln1_b", (L, d), Constant(0.0)),
        "LN2S": p(".ln2_s", (L, d), Constant(1.0)),
        "LN2B": p(".ln2_b", (L, d), Constant(0.0)),
    }
    moe = int(num_experts) > 0
    if moe:
        e = int(num_experts)
        # gate replicated (routing needs every logit); expert stacks
        # shard on ep (storage hints for the GLOBAL [L, E, ...] params)
        gate = helper.create_parameter(
            ParamAttr(name=helper.name + ".gate_w"),
            shape=[L, d, e], dtype=x.dtype,
            default_initializer=Normal(0., 0.02))
        w_up = helper.create_parameter(
            ParamAttr(name=helper.name + ".w_up"),
            shape=[L, e, d, d_inner], dtype=x.dtype,
            default_initializer=Normal(0., std))
        w_down = helper.create_parameter(
            ParamAttr(name=helper.name + ".w_down"),
            shape=[L, e, d_inner, d], dtype=x.dtype,
            default_initializer=Normal(0., d_inner ** -0.5))
        hints = helper.main_program._sharding_hints
        hints[gate.name] = ("pp", None, None)
        hints[w_up.name] = ("pp", "ep", None, None)
        hints[w_down.name] = ("pp", "ep", None, None)
        params.update({"GateW": gate, "WUp": w_up, "WDown": w_down})
    else:
        params.update({
            "W1": p(".w1", (L, d, d_inner), Normal(0., std)),
            "B1": p(".b1", (L, d_inner), Constant(0.0)),
            "W2": p(".w2", (L, d_inner, d), Normal(0., d_inner ** -0.5)),
            "B2": p(".b2", (L, d), Constant(0.0)),
        })
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    outputs = {"Out": [out]}
    aux = None
    if moe:
        aux = helper.create_variable_for_type_inference(
            "float32", shape=())
        outputs["AuxLoss"] = [aux]
    helper.append_op(
        type="pipeline_stack",
        inputs=dict({"X": [x]}, **{s: [w] for s, w in params.items()}),
        outputs=outputs,
        attrs={"n_head": n_head, "num_microbatches": num_microbatches,
               "recompute": bool(recompute), "schedule": str(schedule),
               "virtual_stages": int(virtual_stages),
               "moe_top_k": int(moe_top_k),
               "moe_capacity_factor": float(moe_capacity_factor),
               "moe_gate_groups": int(moe_gate_groups)})
    if moe:
        return out, aux
    return out
