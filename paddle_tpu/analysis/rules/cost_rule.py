"""R006 static cost model roll-up.

Per-eqn FLOPs/bytes (analysis/cost.py, matmul FLOPs shared with
ops/matmul_stats) aggregated into a per-graph summary plus hotspot
diagnostics, so every other rule's findings can be read against "what
actually costs something". A single eqn above ``hot_flops`` is flagged
for sharding/fusion review — on a multi-chip mesh that eqn is the one
worth a parallel.shard hint or a Pallas kernel.
"""

from ..diagnostics import Diagnostic, WARNING, INFO
from ..engine import Rule, register_rule
from ..cost import fmt_flops, fmt_bytes


@register_rule
class CostModelRule(Rule):
    name = "cost-model"
    id = "R006"
    doc = ("per-eqn FLOPs/bytes roll-up, top hotspots, dominant-cost "
           "eqns above the hot_flops threshold")

    def __init__(self, hot_flops=1e9, report_top=3):
        self.hot_flops = hot_flops
        self.report_top = report_top

    def check(self, a):
        costs = a.costs
        total_f = max(costs.total_flops, 1.0)
        yield Diagnostic(
            self.name, INFO,
            "static cost: %s, %s touched (arithmetic intensity %.1f "
            "FLOP/byte) over %d eqn(s)"
            % (fmt_flops(costs.total_flops),
               fmt_bytes(costs.total_bytes),
               costs.total_flops / max(costs.total_bytes, 1.0),
               sum(len(v.jaxpr.eqns) for v in a.views)))
        ranked = sorted(
            ((view, eqn) for view, eqn in a.iter_eqns()),
            key=lambda ve: costs.flops(ve[1]), reverse=True)
        for view, eqn in ranked[:self.report_top]:
            f = costs.flops(eqn)
            if f <= 0:
                break
            share = 100.0 * f / total_f
            if f >= self.hot_flops:
                yield Diagnostic(
                    self.name, WARNING,
                    "dominant-cost eqn: %s (%.0f%% of the graph's "
                    "FLOPs)" % (fmt_flops(f), share),
                    path=view.eqn_path(eqn), cost_flops=f,
                    hint="first candidate for a parallel.shard hint, "
                         "a Pallas kernel, or recompute exclusion")
            else:
                yield Diagnostic(
                    self.name, INFO,
                    "hotspot: %s (%.0f%% of FLOPs)"
                    % (fmt_flops(f), share),
                    path=view.eqn_path(eqn), cost_flops=f)
