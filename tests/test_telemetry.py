"""Fleet telemetry plane (ISSUE 11): METR/HLTH scrape verbs, the
collector's exact-sum merge + restart detection, the shared histogram
merge primitive, watch --fleet, and per-log staleness.

The tier-1 smoke at the bottom runs a REAL 3-process mini-fleet
(master+pserver subprocess, telemetry-armed trainer subprocess, and
this process hosting the KV registry + a replica-role endpoint),
scraped live by the collector.
"""

import io
import json
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.monitor import metrics as mm
from paddle_tpu.monitor.collector import (Collector, TelemetryClient,
                                          TelemetryServer,
                                          render_prometheus_snapshot)
from paddle_tpu.monitor.recorder import FlightRecorder
from paddle_tpu.monitor.watch import (WatchState, render_frame,
                                      staleness_lines, watch,
                                      watch_fleet)


# -- satellite: Histogram.merge / merge_snapshots / snapshot meta ----------

def test_histogram_merge_bucketwise():
    a = mm.Histogram("h", buckets=(0.1, 1.0, 10.0))
    b = mm.Histogram("h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        a.observe(v)
    for v in (0.5, 50.0):
        b.observe(v)
    a.merge(b)
    snap = a.snapshot()[()]
    assert snap["counts"] == [1, 3, 1, 1]   # bucket-wise exact sum
    assert snap["count"] == 6
    assert abs(snap["sum"] - (0.05 + 0.5 * 3 + 5.0 + 50.0)) < 1e-9


def test_histogram_merge_boundary_mismatch_is_loud():
    a = mm.Histogram("h", buckets=(0.1, 1.0))
    b = mm.Histogram("h", buckets=(0.2, 1.0))
    b.observe(0.5)
    with pytest.raises(ValueError, match="boundaries differ"):
        a.merge(b)


def test_merge_snapshots_counters_gauges_histograms():
    r1, r2 = mm.Registry(), mm.Registry()
    r1.counter("c", "", ("op",)).inc(5, op="GET")
    r2.counter("c", "", ("op",)).inc(7, op="GET")
    r2.counter("c", "", ("op",)).inc(3, op="PUT")
    r1.gauge("g").set(1.5)
    r2.gauge("g").set(2.5)
    r1.histogram("h", buckets=(1.0,)).observe(0.5)
    r2.histogram("h", buckets=(1.0,)).observe(2.0)
    merged = mm.merge_snapshots(r1.snapshot(), r2.snapshot())
    assert merged["c"]["series"] == {"GET": 12, "PUT": 3}
    assert merged["g"]["series"][""] == 4.0
    assert merged["h"]["series"][""]["counts"] == [1, 1]
    # src meta ignored; into keeps its own
    assert merged[mm.META_KEY]["incarnation"] == r1.incarnation


def test_merge_snapshots_mismatches_are_loud():
    r1, r2 = mm.Registry(), mm.Registry()
    r1.counter("x").inc()
    r2.gauge("x").set(1)
    with pytest.raises(ValueError, match="kind mismatch"):
        mm.merge_snapshots(r1.snapshot(), r2.snapshot())
    r3, r4 = mm.Registry(), mm.Registry()
    r3.histogram("h", buckets=(1.0,)).observe(0.5)
    r4.histogram("h", buckets=(2.0,)).observe(0.5)
    with pytest.raises(ValueError, match="boundaries differ"):
        mm.merge_snapshots(r3.snapshot(), r4.snapshot())


def test_prometheus_render_label_value_with_comma():
    reg = mm.Registry()
    reg.counter("t_total", "", ("shape",)).inc(1, shape="(8, 128)")
    reg.counter("u_total", "", ("a", "b")).inc(2, a="x,y", b="z")
    reg.gauge("g_val", "", ("lbl",)).set(5.0, lbl="")
    text = render_prometheus_snapshot(reg.snapshot())
    # comma-bearing values survive whole in ANY label position (the
    # series key uses a lossless separator, not ",")
    assert 't_total{shape="(8, 128)"} 1' in text
    assert 'u_total{a="x,y",b="z"} 2' in text
    # an EMPTY single label value still renders its label — it must
    # not collide with an unlabeled series of the same name
    assert 'g_val{lbl=""} 5.0' in text


def test_registry_snapshot_carries_incarnation_and_uptime():
    reg = mm.Registry()
    t0 = reg.uptime_s()
    meta = reg.snapshot()[mm.META_KEY]
    assert meta["incarnation"] == reg.incarnation
    assert meta["uptime_s"] >= t0
    inc0 = reg.incarnation
    reg.reset()          # a reset IS a restart to any scraper
    assert reg.incarnation != inc0
    json.dumps(reg.snapshot())               # stays JSON-able


def test_recorder_ring_events_since(tmp_path):
    rec = FlightRecorder(str(tmp_path / "r.jsonl"), ring=4)
    for i in range(3):
        rec.record("note", i=i)
    cur, rows, lost = rec.events_since(None)
    assert [r["i"] for r in rows] == [0, 1, 2] and lost == 0
    rec.record("note", i=3)
    cur2, rows2, lost2 = rec.events_since(cur)
    assert [r["i"] for r in rows2] == [3] and lost2 == 0
    for i in range(4, 10):                   # overflow the ring of 4
        rec.record("note", i=i)
    cur3, rows3, lost3 = rec.events_since(cur2)
    assert [r["i"] for r in rows3] == [6, 7, 8, 9]
    assert lost3 == 2                        # i=4,5 aged out
    rec.close()


# -- collector: golden scrape -> merge over 3 fake processes ---------------

def _fake_proc_registry(get_count, step_count, hist_vals,
                        queue_depth):
    reg = mm.Registry()
    reg.counter("ptpu_rpc_requests_total", "", ("op",)).inc(
        get_count, op="GET")
    reg.counter("ptpu_steps_total", "", ("executor",)).inc(
        step_count, executor="exe")
    h = reg.histogram("ptpu_serving_ttft_seconds", "", ("engine",),
                      buckets=(0.01, 0.1, 1.0))
    for v in hist_vals:
        h.observe(v, engine="e")
    reg.gauge("ptpu_serving_queue_depth").set(queue_depth)
    return reg


def test_collector_three_process_scrape_merge_golden():
    regs = [_fake_proc_registry(5, 100, (0.005, 0.05), 2),
            _fake_proc_registry(7, 200, (0.05, 0.5), 3),
            _fake_proc_registry(11, 300, (0.5, 0.5, 2.0), 4)]
    servers = [TelemetryServer(registry=r, role="trainer").start()
               for r in regs]
    col = Collector(static=[("trainer", s.endpoint) for s in servers])
    try:
        col.scrape_once()
        snap = col.fleet_snapshot()
        # counters: exact sum across the three processes
        assert snap["ptpu_rpc_requests_total"]["series"]["GET"] == 23
        assert snap["ptpu_steps_total"]["series"]["exe"] == 600
        # gauges: sum over live processes
        assert snap["ptpu_serving_queue_depth"]["series"][""] == 9.0
        # histogram: bucket-wise merged counts, hand-computed
        h = snap["ptpu_serving_ttft_seconds"]
        assert h["buckets"] == [0.01, 0.1, 1.0]
        # per-process counts [1,1,0,0]+[0,1,1,0]+[0,0,2,1], summed
        assert h["series"]["e"]["counts"] == [1, 2, 3, 1]
        assert h["series"]["e"]["count"] == 7
        # merged percentile vs hand computation: target 3.5 of 7,
        # cumulative [1,3,6,7] -> bucket (0.1, 1.0], frac (3.5-3)/3
        p50 = col.fleet_percentile("ptpu_serving_ttft_seconds", 0.5)
        assert abs(p50 - (0.1 + 0.9 * (0.5 / 3.0))) < 1e-9
        meta = snap[mm.META_KEY]
        assert meta["fleet"] and meta["processes"] == 3
        # second scrape with no progress adds nothing (delta = 0)
        col.scrape_once()
        snap2 = col.fleet_snapshot()
        assert snap2["ptpu_rpc_requests_total"]["series"]["GET"] == 23
        # progress on one process lands as its exact delta
        regs[0].counter("ptpu_rpc_requests_total", "",
                        ("op",)).inc(4, op="GET")
        col.scrape_once()
        snap3 = col.fleet_snapshot()
        assert snap3["ptpu_rpc_requests_total"]["series"]["GET"] == 27
        # prometheus re-export carries the merged series
        text = render_prometheus_snapshot(snap3)
        assert 'ptpu_rpc_requests_total{op="GET"} 27' in text
        assert '# TYPE ptpu_serving_ttft_seconds histogram' in text
    finally:
        col.close()
        for s in servers:
            s.stop()


def test_collector_restart_detection_no_negative_deltas():
    reg = _fake_proc_registry(50, 10, (), 1)
    srv = TelemetryServer(registry=reg, role="trainer").start()
    col = Collector(static=[("trainer", srv.endpoint)])
    try:
        col.scrape_once()
        s = col.fleet_snapshot()
        assert s["ptpu_rpc_requests_total"]["series"]["GET"] == 50
        # process "restarts": fresh registry, counters back near zero
        srv.registry = _fake_proc_registry(3, 2, (), 1)
        col.scrape_once()
        s2 = col.fleet_snapshot()
        # monotonic: 50 (dead incarnation's contribution) + 3 fresh
        assert s2["ptpu_rpc_requests_total"]["series"]["GET"] == 53
        srv.registry.counter("ptpu_rpc_requests_total", "",
                             ("op",)).inc(2, op="GET")
        col.scrape_once()
        s3 = col.fleet_snapshot()
        assert s3["ptpu_rpc_requests_total"]["series"]["GET"] == 55
    finally:
        col.close()
        srv.stop()


def test_collector_dedupes_same_process_endpoints():
    reg = _fake_proc_registry(9, 4, (), 0)
    s1 = TelemetryServer(registry=reg, role="a").start()
    s2 = TelemetryServer(registry=reg, role="b").start()
    col = Collector(static=[("a", s1.endpoint), ("b", s2.endpoint)])
    try:
        col.scrape_once()
        snap = col.fleet_snapshot()
        # one registry behind two ports: counted ONCE, not twice
        assert snap["ptpu_rpc_requests_total"]["series"]["GET"] == 9
        assert snap[mm.META_KEY]["processes"] == 1
        assert len(snap[mm.META_KEY]["endpoints"]) == 2
    finally:
        col.close()
        s1.stop()
        s2.stop()


def test_metr_hlth_served_by_dispatch_loops():
    """Every tier that hosts a dispatch loop answers the scrape verbs
    (pserver / master / KV), with its role stamped."""
    from paddle_tpu.distributed.master import MasterServer, TaskQueue
    from paddle_tpu.distributed.membership import KVServer
    from paddle_tpu.distributed.rpc import VariableServer
    ps = VariableServer(fan_in=1).start()
    ms = MasterServer(TaskQueue(payloads=[1])).start()
    kv = KVServer().start()
    try:
        for srv, port, role in ((ps, ps.port, "pserver"),
                                (ms, ms.port, "master"),
                                (kv, kv.port, "kv")):
            with TelemetryClient("127.0.0.1:%d" % port) as tc:
                h = tc.hlth()
                assert h["role"] == role and h["alive"]
                m = tc.metr()
                assert m["role"] == role
                assert m["incarnation"] == h["incarnation"]
                assert "ptpu_rpc_requests_total" in m["snapshot"]
    finally:
        ps.stop()
        ms.stop()
        kv.stop()


# -- watch: per-log staleness + fleet frame --------------------------------

def test_staleness_lines_relative_and_flagged():
    lines = staleness_lines({"a.jsonl": 100.0, "b.jsonl": 90.0,
                             "c.jsonl": None})
    text = "\n".join(lines)
    assert "a.jsonl" in text and "last row   0.0s ago" in text
    assert "10.0s ago   [STALE]" in text
    assert "no rows yet" in text
    # single log: no staleness block (nothing to compare against)
    assert staleness_lines({"a.jsonl": 100.0}) == []


def test_watch_once_multi_log_staleness(tmp_path):
    t = time.time()
    live = tmp_path / "live.jsonl"
    dead = tmp_path / "dead.jsonl"
    live.write_text(json.dumps(
        {"ts": t, "ev": "step", "dt": 0.01}) + "\n")
    dead.write_text(json.dumps(
        {"ts": t - 42.0, "ev": "step", "dt": 0.01}) + "\n")
    buf = io.StringIO()
    frame = watch([str(live), str(dead)], once=True, out=buf)
    assert "dead.jsonl" in frame
    assert "[STALE]" in frame          # 42s behind the newest row
    assert "live.jsonl" in frame and "0.0s ago" in frame


def test_watch_fleet_once_renders_scraped_dashboard():
    reg = _fake_proc_registry(5, 10, (), 2)
    reg.counter("ptpu_serving_tokens_total").inc(123)
    srv = TelemetryServer(registry=reg, role="replica").start()
    col = Collector(static=[("replica", srv.endpoint)])
    try:
        buf = io.StringIO()
        frame = watch_fleet(collector=col, once=True, out=buf)
        assert "fleet" in frame
        assert "replica" in frame and srv.endpoint in frame
        assert "serving tokens 123" in frame
        assert "steps 10" in frame
    finally:
        col.close()
        srv.stop()


def test_collector_survives_recorder_replacement(tmp_path):
    """monitor.enable() mid-process replaces the flight recorder (a
    fresh ring, sequence restarted) WITHOUT a registry restart: the
    collector's old cursor must not silently filter every new row —
    the ring id in the METR reply restarts the delta."""
    from paddle_tpu import monitor
    srv = TelemetryServer(role="trainer").start()   # global registry
    col = Collector(static=[("trainer", srv.endpoint)])
    try:
        monitor.enable(log_path=str(tmp_path / "a.jsonl"))
        monitor.recorder().record("note", run=1)
        ev1 = [e for e in col.scrape_once() if e.get("ev") == "note"]
        assert [e["run"] for e in ev1] == [1]
        # second enable: new recorder, new ring, seq restarts at 1
        monitor.enable(log_path=str(tmp_path / "b.jsonl"))
        monitor.recorder().record("note", run=2)
        ev2 = [e for e in col.scrape_once() if e.get("ev") == "note"]
        assert [e["run"] for e in ev2] == [2]
        # disable -> scrape (reply carries NO ring) -> re-enable: the
        # saved cursor must be dropped, or the fresh ring's rows would
        # be silently filtered against it
        monitor.disable()
        col.scrape_once()
        monitor.enable(log_path=str(tmp_path / "c.jsonl"))
        monitor.recorder().record("note", run=3)
        ev3 = [e for e in col.scrape_once() if e.get("ev") == "note"]
        assert [e["run"] for e in ev3] == [3]
    finally:
        monitor.disable()
        col.close()
        srv.stop()


def test_collector_registry_flap_does_not_replay_ring(tmp_path):
    """An endpoint that vanishes from discovery for a round (lease
    hiccup) keeps its endpoint->incarnation link for a grace window:
    the next scrape continues from the saved ring cursor instead of
    replaying the whole ring as 'new' events."""
    from paddle_tpu import monitor
    srv = TelemetryServer(role="trainer").start()
    col = Collector(static=[("trainer", srv.endpoint)])
    try:
        monitor.enable(log_path=str(tmp_path / "f.jsonl"))
        monitor.recorder().record("note", i=1)
        assert len([e for e in col.scrape_once()
                    if e.get("ev") == "note"]) == 1
        # a LONG registry outage (many rounds past the retention
        # bound) while the endpoint keeps answering: successful
        # scrapes reset the missing counter, so the cursor link
        # survives arbitrarily long KV downtime
        real = col._discover
        col._discover = lambda: []
        for _ in range(Collector._MISSING_ROUNDS_MAX + 5):
            assert col.scrape_once() == []
        col._discover = real
        monitor.recorder().record("note", i=2)
        notes = [e for e in col.scrape_once()
                 if e.get("ev") == "note"]
        assert [e["i"] for e in notes] == [2]   # no i=1 replay
    finally:
        monitor.disable()
        col.close()
        srv.stop()


def test_watch_goodput_rolls_up_per_source():
    """The watch surfaces' goodput_fraction comes from per-SOURCE
    raw-event windows (training rows included), rolled up per
    process — not from the serving-only deques, and never over a
    union timeline."""
    state = WatchState(window=64)
    # source A: one fully-productive second of training
    state.feed_event({"ts": 10.0, "ev": "run_meta"}, source="a")
    state.feed_event({"ts": 11.0, "ev": "step", "dt": 1.0},
                     source="a")
    # source B: a 1 s window that is ALL idle
    state.feed_event({"ts": 10.0, "ev": "run_meta"}, source="b")
    state.feed_event({"ts": 11.0, "ev": "note"}, source="b")
    samples = state.request_samples()
    g = samples["goodput"]
    # union timeline would claim 100% productive; per-process rollup
    # reports 1 productive second of 2 wall seconds
    assert g["wall_s"] == pytest.approx(2.0)
    assert g["goodput_fraction"] == pytest.approx(0.5)
    # and a TRAINING log alone yields a verdict (no serving rows)
    from paddle_tpu import slo as _slo
    v = _slo.evaluate({"objectives": [
        {"metric": "goodput_fraction", "min_ratio": 0.4}]}, samples)
    assert v["pass"]


def test_watch_fleet_once_nothing_reachable_exits_nonzero(tmp_path):
    srv = TelemetryServer(role="x")          # never started
    srv.stop()                               # port closed
    col = Collector(static=[("x", srv.endpoint)])
    try:
        buf = io.StringIO()
        frame = watch_fleet(collector=col, once=True, out=buf)
        assert frame is None                 # CLI maps this to exit 1
        assert "no endpoint answered" in buf.getvalue()
    finally:
        col.close()


# -- tier-1 e2e smoke: 3-process mini-fleet scraped live -------------------

_MASTER_PS_PROC = '''\
import os, sys, time
sys.path.insert(0, %(repo)r)
import paddle_tpu
from paddle_tpu import monitor
from paddle_tpu.distributed.master import MasterServer, TaskQueue
from paddle_tpu.distributed.rpc import VariableServer

monitor.enable(log_path=%(mon_log)r)
monitor.recorder().record("note", who="serverproc", n=1)
ps = VariableServer(fan_in=1, port_file=%(ps_port_file)r).start()
master = MasterServer(TaskQueue(payloads=list(range(%(n_tasks)d))),
                      port_file=%(master_port_file)r).start()
deadline = time.time() + 120
while not os.path.exists(%(stop_file)r) and time.time() < deadline:
    time.sleep(0.05)
master.stop()
ps.stop()
'''

_TRAINER_PROC = '''\
import os, sys, time
sys.path.insert(0, %(repo)r)
import paddle_tpu                     # telemetry armed via env flags
from paddle_tpu.monitor import metrics
from paddle_tpu.monitor.collector import _ARMED
assert _ARMED is not None, "telemetry flag did not arm"
metrics.registry().counter(
    "ptpu_steps_total", "", ("executor",)).inc(37, executor="exe")
open(%(ready_file)r, "w").write("up")
deadline = time.time() + 120
while not os.path.exists(%(stop_file)r) and time.time() < deadline:
    time.sleep(0.05)
'''


class _FakeEngine:
    """Just enough engine for a ReplicaServer to front: the smoke
    scrapes METR/HLTH/STAT, it never SUBMs."""

    slots = 4
    stats = {"steps": 0, "tokens": 0, "admissions": 0}
    on_retire = None


def _wait_file(path, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path) and open(path).read().strip():
            return open(path).read().strip()
        time.sleep(0.05)
    raise TimeoutError("no %s" % path)


def test_fleet_scrape_smoke_three_processes(tmp_path):
    """ISSUE-11 acceptance: master + pserver (one real subprocess),
    a telemetry-armed trainer (second real subprocess), and this
    process's replica-role endpoint + KV registry, scraped by ONE
    collector: fleet counters are exact sums, the recorder event
    delta streams over METR, and watch --fleet renders it."""
    import numpy as np
    from paddle_tpu.distributed.master import MasterClient
    from paddle_tpu.distributed.membership import (KVServer, KVClient,
                                                   register_endpoint)
    from paddle_tpu.distributed.rpc import RPCClient
    from paddle_tpu.serving.fleet import ReplicaServer
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stop_file = str(tmp_path / "stop")
    ps_port_file = str(tmp_path / "ps.port")
    master_port_file = str(tmp_path / "master.port")
    ready_file = str(tmp_path / "trainer.ready")
    mon_log = str(tmp_path / "server_mon.jsonl")
    n_tasks = 3

    kv_srv = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kv_srv.endpoint)

    script_a = tmp_path / "server_proc.py"
    script_a.write_text(_MASTER_PS_PROC % {
        "repo": repo, "ps_port_file": ps_port_file,
        "master_port_file": master_port_file,
        "stop_file": stop_file, "n_tasks": n_tasks,
        "mon_log": mon_log})
    script_b = tmp_path / "trainer_proc.py"
    script_b.write_text(_TRAINER_PROC % {
        "repo": repo, "ready_file": ready_file,
        "stop_file": stop_file})
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu"})
    for k in ("PADDLE_TPU_MONITOR", "PADDLE_TPU_TRACE",
              "PADDLE_TPU_TELEMETRY"):
        env.pop(k, None)
    env_b = dict(env)
    env_b.update({"PADDLE_TPU_TELEMETRY": "1",
                  "PADDLE_TPU_TELEMETRY_KV": kv_srv.endpoint})
    procs = [subprocess.Popen([sys.executable, str(script_a)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True),
             subprocess.Popen([sys.executable, str(script_b)],
                              env=env_b, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)]
    rep_srv = None
    col = None
    try:
        ps_port = int(_wait_file(ps_port_file))
        master_port = int(_wait_file(master_port_file))
        _wait_file(ready_file)

        # replica-role endpoint in THIS process, lease-registered
        rep_srv = ReplicaServer(_FakeEngine()).start()
        _, lease = register_endpoint(kv, "replica", 2,
                                     rep_srv.endpoint, ttl=1.0)

        # deterministic traffic whose server-side counters we can sum
        cli = RPCClient("127.0.0.1:%d" % ps_port)
        cli.put_var("w", np.ones((4,), np.float32))
        for _ in range(3):
            cli.get_var("w")
        mcli = MasterClient("127.0.0.1:%d" % master_port)
        done = 0
        while done < n_tasks:
            tid, payload = mcli.get_task()
            if tid is None:
                time.sleep(0.02)
                continue
            mcli.task_done(tid)
            done += 1

        col = Collector(
            kv_endpoint=kv_srv.endpoint,
            roles=("replica", "telemetry"),
            static=[("pserver", "127.0.0.1:%d" % ps_port),
                    ("master", "127.0.0.1:%d" % master_port)])
        events = col.scrape_once()
        snap = col.fleet_snapshot()

        # the TEST process's registry (served by the kv + replica
        # endpoints) carries whatever earlier tests in this pytest
        # process did — subtract it so the sums stay exact under any
        # suite ordering. Nothing in this test bumps these locally.
        def _local(name, **labels):
            m = mm.registry().get(name)
            try:
                return (m.value(**labels) or 0) if m is not None \
                    else 0
            except ValueError:
                return 0

        loc_get = _local("ptpu_rpc_requests_total", op="GET")
        loc_put = _local("ptpu_rpc_requests_total", op="PUT")
        loc_done = _local("ptpu_master_tasks_total", state="done")
        loc_exe = _local("ptpu_steps_total", executor="exe")
        meta = snap[mm.META_KEY]
        # 3 OS processes: server subprocess (one incarnation behind
        # two endpoints), trainer subprocess, this test process (KV +
        # replica share its registry)
        assert meta["processes"] >= 3
        roles = {e["role"] for e in meta["endpoints"]}
        assert {"pserver", "master", "kv", "replica",
                "telemetry"} <= roles
        # exact sums: pserver counters from the REAL subprocess
        reqs = snap["ptpu_rpc_requests_total"]["series"]
        assert reqs["PUT"] == 1 + loc_put
        assert reqs["GET"] == 3 + loc_get
        tasks = snap["ptpu_master_tasks_total"]["series"]
        assert tasks["done"] == n_tasks + loc_done
        # the trainer's hand-bumped steps ride the telemetry role
        assert snap["ptpu_steps_total"]["series"]["exe"] == \
            37 + loc_exe
        # recorder event delta streamed over METR from subprocess A
        notes = [e for e in events if e.get("ev") == "note"]
        assert notes and notes[0]["who"] == "serverproc"
        assert notes[0]["proc"].split("@")[0] in ("pserver", "master")
        # scrape again: counters must not double (delta accumulation)
        col.scrape_once()
        snap2 = col.fleet_snapshot()
        assert snap2["ptpu_rpc_requests_total"]["series"]["PUT"] == \
            1 + loc_put
        assert snap2["ptpu_steps_total"]["series"]["exe"] == \
            37 + loc_exe
        # the live scraped dashboard renders the merged view
        buf = io.StringIO()
        frame = watch_fleet(collector=col, once=True, out=buf)
        assert "pserver" in frame and "telemetry" in frame
        assert "replica" in frame
        # one spec gates the whole fleet from the scraped snapshot
        from paddle_tpu import slo as _slo
        fleet_json = str(tmp_path / "fleet.json")
        col.dump_json(fleet_json)
        verdict = _slo.evaluate(
            {"name": "fleet", "objectives": [
                {"metric": "error_rate", "max_ratio": 0.5}]},
            _slo.samples_from_metrics(fleet_json))
        # what matters: the fleet snapshot IS a valid --metrics
        # surface (request totals may carry earlier suite traffic
        # through this process's shared registry — no exact bound)
        assert isinstance(verdict["pass"], bool)
        assert verdict["objectives"][0]["metric"] == "error_rate"
        assert verdict["source"].startswith("metrics snapshot")
        cli.close()
        mcli.close()
        lease.revoke()
    finally:
        open(stop_file, "w").write("stop")
        if col is not None:
            col.close()
        if rep_srv is not None:
            rep_srv.stop()
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
        kv.shutdown_server()
        kv.close()
    assert procs[0].returncode == 0, outs[0][-3000:]
    assert procs[1].returncode == 0, outs[1][-3000:]
