"""Rule engine for the runtime lint (mirrors analysis.engine/diagnostics).

Same shape as the jaxpr tier so both CLIs feel identical: registered
rule classes, per-rule capped+deduped findings, severities ERROR >
WARNING > INFO, text and ``--json`` renderings, exit 1 when anything at
or above ``--fail-on`` survives.

The one new mechanism is the WAIVER file: deliberate findings at HEAD
(e.g. KVClient serializing its socket under the client lock BY DESIGN)
are checked in to ``analysis/runtime/waivers.json`` with a one-line
justification each, keyed by exact ``(rule, file, line)``. Waivers are
themselves linted loudly:

  * an entry whose anchor no longer exists (file gone, line out of
    range) is STALE -> ERROR finding (the code moved; re-justify);
  * an entry matching no current finding is UNMATCHED -> ERROR finding
    (the defect was fixed; delete the waiver);
  * a malformed/unreadable waiver file is a usage error -> exit 2.

So the gate can never silently rot: waivers pin findings the way golden
tests pin behavior.
"""

import json

from ..diagnostics import ERROR, WARNING, INFO, severity_rank
from .astscan import SourceIndex

import os

__all__ = ["Finding", "RuntimeReport", "RuntimeRule",
           "register_runtime_rule", "registered_runtime_rules",
           "default_runtime_rules", "run_rules", "run_runtime",
           "load_waivers", "WaiverError", "default_waivers_path"]

_SEVERITIES = (ERROR, WARNING, INFO)


def default_waivers_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "waivers.json")


class Finding:
    """One runtime-lint finding, anchored to ``file:line``."""

    __slots__ = ("rule", "severity", "file", "line", "message", "where",
                 "hint", "waived")

    def __init__(self, rule, severity, file, line, message, where=None,
                 hint=None):
        assert severity in _SEVERITIES, severity
        self.rule = rule
        self.severity = severity
        self.file = file
        self.line = int(line)
        self.message = message
        self.where = where          # qualname context, e.g. Class.method
        self.hint = hint
        self.waived = None          # justification string once waived

    @property
    def anchor(self):
        return (self.rule, self.file, self.line)

    def to_dict(self):
        d = {"rule": self.rule, "severity": self.severity,
             "file": self.file, "line": self.line,
             "message": self.message}
        if self.where:
            d["where"] = self.where
        if self.hint:
            d["hint"] = self.hint
        if self.waived is not None:
            d["waived"] = self.waived
        return d

    def render(self):
        loc = "%s:%d" % (self.file, self.line)
        head = "[%s] %s %s: %s" % (self.severity, self.rule, loc,
                                   self.message)
        if self.where:
            head += "  (in %s)" % self.where
        if self.waived is not None:
            head += "  [waived: %s]" % self.waived
        out = [head]
        if self.hint:
            out.append("    hint: %s" % self.hint)
        return "\n".join(out)


class RuntimeRule:
    """Base class: subclass, set ``name``/``id``/``doc``, implement
    ``check(index)`` yielding Findings. ``run`` dedups identical
    (anchor, message) findings and caps at ``max_reports`` keeping the
    most severe first — same contract as analysis.engine.Rule."""

    name = "abstract"
    id = "RT00"
    doc = ""
    max_reports = 50

    def check(self, index):
        raise NotImplementedError

    def run(self, index):
        seen = set()
        out = []
        for f in self.check(index):
            key = f.anchor + (f.message,)
            if key in seen:
                continue
            seen.add(key)
            out.append(f)
        out.sort(key=lambda f: (-severity_rank(f.severity), f.file,
                                f.line, f.message))
        return out[: self.max_reports]


_RULES = {}


def register_runtime_rule(cls):
    _RULES[cls.name] = cls
    return cls


def registered_runtime_rules():
    return dict(_RULES)


def default_runtime_rules():
    return [_RULES[name]() for name in sorted(_RULES)]


class WaiverError(Exception):
    """Malformed waiver file (usage error: CLI exits 2)."""


def load_waivers(path):
    """Parse the waiver file. Returns a list of dicts with rule/file/
    line/reason. Raises WaiverError on any malformed entry — a waiver
    without a justification is not a waiver."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except OSError as e:
        raise WaiverError("cannot read waiver file %s: %s" % (path, e))
    except ValueError as e:
        raise WaiverError("invalid JSON in %s: %s" % (path, e))
    entries = data.get("waivers") if isinstance(data, dict) else None
    if not isinstance(entries, list):
        raise WaiverError('%s: expected {"waivers": [...]}' % path)
    out = []
    for i, ent in enumerate(entries):
        if not isinstance(ent, dict):
            raise WaiverError("%s: waiver #%d is not an object"
                              % (path, i))
        missing = [k for k in ("rule", "file", "line", "reason")
                   if k not in ent]
        if missing:
            raise WaiverError("%s: waiver #%d missing %s"
                              % (path, i, ",".join(missing)))
        if not str(ent["reason"]).strip():
            raise WaiverError("%s: waiver #%d has an empty reason"
                              % (path, i))
        out.append({"rule": str(ent["rule"]), "file": str(ent["file"]),
                    "line": int(ent["line"]),
                    "reason": str(ent["reason"])})
    return out


class RuntimeReport:
    """Findings from one run, split into live vs waived."""

    def __init__(self, findings, waived=(), root=None):
        self.findings = list(findings)    # live (gate these)
        self.waived = list(waived)        # matched by a waiver entry
        self.root = root

    def counts(self):
        c = {s: 0 for s in _SEVERITIES}
        for f in self.findings:
            c[f.severity] += 1
        return c

    def at_least(self, severity):
        floor = severity_rank(severity)
        return [f for f in self.findings
                if severity_rank(f.severity) >= floor]

    def render_text(self):
        out = []
        for f in self.findings:
            out.append(f.render())
        for f in self.waived:
            out.append(f.render())
        c = self.counts()
        out.append("runtime lint: %d error(s), %d warning(s), %d "
                   "info(s), %d waived"
                   % (c[ERROR], c[WARNING], c[INFO], len(self.waived)))
        return "\n".join(out)

    def to_json(self):
        return json.dumps(
            {"counts": self.counts(),
             "findings": [f.to_dict() for f in self.findings],
             "waived": [f.to_dict() for f in self.waived]},
            indent=2, sort_keys=True)


def _apply_waivers(findings, waivers, index):
    """Split findings into (live, waived); append loud findings for
    stale or unmatched waiver entries."""
    live, waived = [], []
    by_anchor = {}
    for f in findings:
        by_anchor.setdefault(f.anchor, []).append(f)
    matched = set()
    for ent in waivers:
        anchor = (ent["rule"], ent["file"], ent["line"])
        sf = index.files.get(ent["file"])
        if sf is None or not (1 <= ent["line"] <= len(sf.lines)):
            live.append(Finding(
                "waivers", ERROR, ent["file"], ent["line"],
                "stale waiver for rule '%s': anchor does not exist"
                % ent["rule"],
                hint="the code moved; re-anchor or delete the entry"))
            continue
        if anchor in by_anchor:
            matched.add(anchor)
        else:
            live.append(Finding(
                "waivers", ERROR, ent["file"], ent["line"],
                "unmatched waiver for rule '%s': no current finding "
                "at this anchor" % ent["rule"],
                hint="the finding was fixed; delete the waiver entry"))
    reasons = {(e["rule"], e["file"], e["line"]): e["reason"]
               for e in waivers}
    for f in findings:
        if f.anchor in matched:
            f.waived = reasons[f.anchor]
            waived.append(f)
        else:
            live.append(f)
    return live, waived


def run_rules(index, rules=None, waivers=None):
    """Run ``rules`` (default: all registered) over a SourceIndex and
    apply ``waivers`` (a parsed entry list, or None)."""
    rules = list(rules) if rules is not None else default_runtime_rules()
    findings = []
    for rule in rules:
        findings.extend(rule.run(index))
    live, waived = _apply_waivers(findings, waivers or [], index)
    live.sort(key=lambda f: (-severity_rank(f.severity), f.file,
                             f.line, f.rule, f.message))
    waived.sort(key=lambda f: (f.file, f.line, f.rule))
    return RuntimeReport(live, waived, root=index.root)


def run_runtime(root=None, rules=None, waivers_path=""):
    """Whole-repo entry point: index the package at ``root``, run every
    rule, apply the checked-in waiver file. ``waivers_path``: "" means
    the default file (missing -> no waivers), None/'none' disables."""
    index = SourceIndex.from_root(root)
    entries = []
    if waivers_path == "":
        path = default_waivers_path()
        if os.path.exists(path):
            entries = load_waivers(path)
    elif waivers_path not in (None, "none"):
        entries = load_waivers(waivers_path)
    return run_rules(index, rules=rules, waivers=entries)
