"""C inference API (paddle_tpu/native/capi): a pure-C program loads a
saved inference model and runs forward — the reference's
paddle/capi/gradient_machine.h deployment capability (C ABI over an
embedded CPython driving the same load_inference_model path)."""

import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native")


@pytest.fixture(scope="module")
def capi_bin():
    try:
        subprocess.run(["make", "-C", NATIVE, "build/libcapi.so",
                        "build/test_capi"],
                       check=True, capture_output=True, text=True)
    except (OSError, subprocess.CalledProcessError) as e:
        pytest.skip("C API build failed: %s"
                    % (getattr(e, "stderr", "") or str(e))[-400:])
    return os.path.join(NATIVE, "build", "test_capi")


def test_c_program_runs_saved_model(tmp_path, capi_bin):
    x = fluid.layers.data("x", [4])
    y = fluid.layers.fc(x, 3, act="relu")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    model_dir = str(tmp_path / "model")
    fluid.io.save_inference_model(model_dir, ["x"], [y], exe)
    want, = exe.run(feed={"x": np.ones((1, 4), np.float32)},
                    fetch_list=[y])

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(NATIVE.rstrip("/")).rsplit(
        "/paddle_tpu", 1)[0]
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([capi_bin, model_dir, "4"], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-500:]
    line = [l for l in out.stdout.splitlines() if l.startswith("OUT")][0]
    got = np.array([float(v) for v in line.split()[1:]], np.float32)
    np.testing.assert_allclose(got, np.asarray(want).reshape(-1),
                               rtol=1e-5, atol=1e-6)


def test_c_program_reports_missing_model(capi_bin):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(NATIVE.rstrip("/")).rsplit(
        "/paddle_tpu", 1)[0]
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([capi_bin, "/nonexistent/model", "4"], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode != 0
    assert "failed" in out.stderr
