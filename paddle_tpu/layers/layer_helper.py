"""LayerHelper: shared machinery for all layer functions.

Reference parity: python/paddle/fluid/layer_helper.py — creates parameters
(with initializer ops on the startup program), intermediate variables, bias
add and activation append.
"""

from ..core import unique_name
from ..core.program import default_main_program, default_startup_program
from ..param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return self.kwargs.get("main_program") or default_main_program()

    @property
    def startup_program(self):
        return self.kwargs.get("startup_program") or default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def append_op(self, *args, **kwargs):
        return self.block.append_op(*args, **kwargs)

    # -- creation ------------------------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name,
                                                       "b" if is_bias else "w"]))
        init = (default_initializer or
                attr._default_initializer(is_bias))
        # create in main program (for the graph) and in startup program
        # (for the init op), same name — reference behavior.
        param = self.block.create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs())
        sb = self.startup_program.global_block()
        sparam = sb.create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs())
        init(sparam, sb)
        return param

    def create_variable_for_type_inference(self, dtype, shape=None,
                                           stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, shape=shape, stop_gradient=stop_gradient)

    # keep the reference's (older) name too
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, **kwargs):
        return self.block.create_var(**kwargs)

    def create_global_variable(self, persistable=False, **kwargs):
        return self.main_program.global_block().create_var(
            persistable=persistable, **kwargs)

    def set_variable_initializer(self, var, initializer):
        sb = self.startup_program.global_block()
        svar = sb.create_var(
            name=var.name, shape=var.shape, dtype=var.dtype,
            persistable=True)
        initializer(svar, sb)

    # -- common fragments ----------------------------------------------------
    def input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name)
        if inputs is None:
            raise ValueError("%s must be set" % input_param_name)
        return inputs

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if bias_attr is False or bias_attr is None:
            return input_var
        b = self.create_parameter(bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        out = self.create_variable_for_type_inference(
            input_var.dtype, shape=input_var.shape)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [out]},
            attrs={"axis": dim_start})
        return out

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(
            input_var.dtype, shape=input_var.shape)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [out]}, attrs=act)
        return out
