"""Isolate device step time from Executor host overhead: call the cached
jitted step in a tight loop, threading state, single sync at end."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import resnet

PEAK_BF16 = 197e12
FLOPS_PER_IMG_TRAIN = 3 * 4.1e9


def run(bs, iters=10):
    fluid.amp.enable_amp()
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        sys.path.insert(0, "benchmarks")
        from common import synthetic_feeds
        synth = synthetic_feeds({
            "data": ((bs, 3, 224, 224), "float32", 1.0),
            "label": ((bs, 1), "int64", 1000)})
        image, label, avg_cost, acc = resnet.build_train_net(
            model="resnet_imagenet", depth=50, image_shape=(3, 224, 224),
            num_classes=1000, learning_rate=0.01,
            image=synth["data"], label=synth["label"])
        exe = fluid.Executor(fluid.TPUPlace())
        exe.run(startup)
        # one executor run to populate the compiled-step cache
        exe.run(feed={}, fetch_list=[avg_cost])
        (entry,) = [v for k, v in exe._cache.items() if k[0] is main]

        persistable = [v.name for v in main.global_block().vars.values()
                       if v.persistable]
        state = {n: scope.find_var(n) for n in persistable
                 if scope.find_var(n) is not None}
        key = jax.random.key(0)

        # warm
        fetches, state = entry(state, {}, key)
        jax.block_until_ready(state)
        t0 = time.perf_counter()
        for _ in range(iters):
            fetches, state = entry(state, {}, key)
        jax.block_until_ready(state)
        dt = (time.perf_counter() - t0) / iters
        # the tight loop donated the scope's buffers — commit fresh state
        # back so the executor comparison below reads live arrays
        for n, v in state.items():
            scope.set(n, v)
    ips = bs / dt
    print("bs=%4d  tight loop: %7.2f ms/step  %8.1f img/s  MFU=%5.1f%%"
          % (bs, dt * 1e3, ips,
             ips * FLOPS_PER_IMG_TRAIN / PEAK_BF16 * 100), flush=True)

    # per-call executor overhead comparison
    t0 = time.perf_counter()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        for _ in range(iters):
            exe.run(feed={}, fetch_list=[avg_cost])
    dt2 = (time.perf_counter() - t0) / iters
    print("bs=%4d  exe.run loop: %7.2f ms/step (overhead %.2f ms)"
          % (bs, dt2 * 1e3, (dt2 - dt) * 1e3), flush=True)


if __name__ == "__main__":
    for bs in [int(a) for a in sys.argv[1:]] or [256]:
        run(bs)
