"""Chaos tier: the full resilience composition under one seeded fault
plan. A zoo-MLP trainer pulls batch tasks from the elastic master,
computes loss+grads with the real Executor, pushes tagged gradient
rounds to a pserver under a membership TTL lease, and runs inside
``resilience.resilient_loop`` (background trainer checkpoints, NaN
rollback guard). The armed plan then:

  * drops / delays / duplicates / tears RPC frames to the pserver
    (the retry policy reconnects; tagged rounds stay exactly-once),
  * KILLS the pserver mid-run (its lease expires, a supervisor boots a
    replacement recovered from the pserver checkpoint, the trainer's
    membership resolver follows it to the new port),
  * corrupts one trainer checkpoint on disk (the rollback CRC-scan
    must skip it),
  * injects one NaN batch (rollback-and-skip; the restored params are
    re-pushed to the pserver).

Pass criteria (ISSUE 3 acceptance): the run completes, final loss
within 10% of a fault-free run from the same init/data, and EXACT
at-least-once task accounting on the master (every task done once,
none failed). ``test_chaos_smoke`` is the fast tier-1 gate; the
``slow``-marked soak repeats the scenario 3x proving the fixed fault
seed is deterministic.
"""

import itertools
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.distributed.master import (TaskQueue, MasterServer,
                                           MasterClient)
from paddle_tpu.distributed.membership import (KVServer, KVClient,
                                               register_pserver,
                                               PS_PREFIX)
from paddle_tpu.distributed.rpc import VariableServer, RPCClient
from paddle_tpu.models.mlp import mlp
from paddle_tpu.resilience import Policy, faults, resilient_loop

DIM = 64
N_CLASSES = 10
LR = 0.15


def _make_batches(n_tasks, batch=16, seed=0):
    """Deterministic learnable data: labels from a fixed projection."""
    rng = np.random.RandomState(seed)
    proj = rng.randn(DIM, N_CLASSES).astype(np.float32)
    out = []
    for _ in range(n_tasks):
        x = rng.rand(batch, DIM).astype(np.float32)
        y = np.argmax(x @ proj, axis=1).astype(np.int64)[:, None]
        out.append({"img": x, "label": y})
    return out


def _build_trainer_program():
    """Zoo MLP WITHOUT a local optimizer: grads are computed here,
    applied server-side (pserver SGD) — the distributed split."""
    img = fluid.layers.data("img", [DIM])
    label = fluid.layers.data("label", [1], dtype="int64")
    _, avg_cost, _ = mlp(img, label, hidden_sizes=(32,),
                         num_classes=N_CLASSES)
    param_grads = fluid.backward.append_backward(avg_cost)
    return avg_cost, param_grads


def _sgd_optimize(store, grads):
    for k, g in grads.items():
        p = k.replace("@GRAD", "")
        if p in store:
            store[p] = store[p] - LR * np.asarray(g)


class _PServerCell:
    """The pserver 'process': server + its membership lease + a
    checkpoint thread. The supervisor replaces the whole cell."""

    def __init__(self, kv, ckpt_path, recover=False):
        self.ckpt_path = ckpt_path
        self.server = VariableServer(fan_in=1, optimize_fn=_sgd_optimize,
                                     sync=True)
        self.recovered_round = (self.server.recover(ckpt_path)
                                if recover else None)
        self.server.start()
        self.endpoint = "127.0.0.1:%d" % self.server.port
        _, self.lease = register_pserver(kv, 1, self.endpoint, ttl=0.4)
        self._stop = threading.Event()
        self._ckpt_thread = threading.Thread(target=self._ckpt_loop,
                                             daemon=True)
        self._ckpt_thread.start()

    def _ckpt_loop(self):
        while not self._stop.wait(0.05):
            try:
                self.server.checkpoint(self.ckpt_path)
            except Exception:
                pass

    def crash(self):
        """The injected kill already broke the server; the lease thread
        'dies with the process'."""
        self._stop.set()
        self.lease._stop.set()

    def shutdown(self):
        self._stop.set()
        try:
            self.lease.revoke()
        except Exception:
            pass
        try:
            self.server.stop()
        except Exception:
            pass


def _run_training(batches, ckpt_dir, cell1, init_params=None,
                  kv_endpoint=None, master_ep=None, ps_ckpt=None,
                  plan=None, checkpoint_every=4):
    """One complete trainer run against live master/pserver/KV services.
    Returns (summary, init_params, final_params, replacement_info)."""
    pol = Policy(max_attempts=12, base_delay=0.05, max_delay=2.0,
                 deadline=25.0, seed=5)
    resolver_kv = KVClient(kv_endpoint)
    supervisor_kv = KVClient(kv_endpoint)
    state = {"killed": False, "cell1": cell1, "cell2": None}
    stop_sup = threading.Event()

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        avg_cost, param_grads = _build_trainer_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        params = [p.name for p, _ in param_grads]
        grad_names = [g.name for _, g in param_grads]
        if init_params is not None:
            for name, v in init_params.items():
                scope.set(name, v.copy())
        init_snapshot = {p: np.asarray(scope.find_var(p)).copy()
                         for p in params}

        def ps_resolver():
            return resolver_kv.get(PS_PREFIX + "0")

        cli = RPCClient(ps_resolver(), retry=pol, resolver=ps_resolver)
        for p in params:
            cli.put_var(p, np.asarray(scope.find_var(p)))
        mcli = MasterClient(master_ep, retry=pol)
        inc = "%016x" % time.time_ns() + "c0ffee00"
        seq = itertools.count()

        def supervise():
            """Watches for the injected pserver kill: stops the dead
            cell's lease ('the process died'), waits for the slot to
            expire, boots a replacement recovered from the pserver
            checkpoint, registered under the SAME slot."""
            while not stop_sup.wait(0.03):
                if plan is None:
                    return
                if not state["killed"]:
                    if ("kill", "pserver") in plan.trips:
                        state["killed"] = True
                        state["cell1"].crash()
                elif state["cell2"] is None:
                    if supervisor_kv.get(PS_PREFIX + "0") is None:
                        state["cell2"] = _PServerCell(
                            supervisor_kv, ps_ckpt, recover=True)
                        return

        def step_fn(step, feeds):
            outs = exe.run(main, feed=feeds,
                           fetch_list=[avg_cost.name] + grad_names)
            loss = float(np.asarray(outs[0]).reshape(-1)[0])
            if not np.isfinite(loss):
                return loss          # poisoned: push NOTHING, roll back
            tag = "t0:i%s:s%d" % (inc, next(seq))
            for name, gval in zip(grad_names, outs[1:]):
                cli.send_var(name, np.asarray(gval), tag=tag)
            cli.barrier(tag=tag)
            for p in params:
                scope.set(p, cli.get_var(p))
            return loss

        def on_rollback(step):
            # after a rollback the trainer scope is the source of
            # truth: re-push the restored params to the pserver
            for p in params:
                cli.put_var(p, np.asarray(scope.find_var(p)))

        def batches_from_master():
            while True:
                tid, payload = mcli.get_task()
                if tid is None:
                    if payload == "done":
                        return
                    time.sleep(0.02)
                    continue
                yield batches[payload]
                mcli.task_done(tid)

        sup = threading.Thread(target=supervise, daemon=True)
        try:
            sup.start()
            summary = resilient_loop(
                step_fn, batches_from_master(), ckpt_dir, program=main,
                scope=scope, checkpoint_every=checkpoint_every,
                max_rollbacks=4, background=True,
                on_rollback=on_rollback)
            final_params = {p: np.asarray(scope.find_var(p)).copy()
                            for p in params}
        finally:
            stop_sup.set()
            sup.join(timeout=5)
            cli.close()
            mcli.close()
            resolver_kv.close()
            supervisor_kv.close()
    return summary, init_snapshot, final_params, state


def _chaos_scenario(n_tasks, fault_spec, seed, tmp_path, tag):
    """Stand up KV + master + pserver, run baseline (no faults) then
    the chaos run (same init, same data), return both results."""
    batches = _make_batches(n_tasks, seed=seed)

    def run(run_tag, init_params, spec):
        kvs = KVServer(sweep_interval=0.05).start()
        kv = KVClient(kvs.endpoint)
        ps_ckpt = str(tmp_path / ("ps-%s.ckpt" % run_tag))
        cell = _PServerCell(kv, ps_ckpt)
        master = MasterServer(TaskQueue(
            payloads=list(range(n_tasks)), timeout_s=60,
            snapshot_path=str(tmp_path / ("q-%s.json" % run_tag)))).start()
        master_ep = "127.0.0.1:%d" % master.port
        plan = None
        if spec is not None:
            spec = dict(spec)
            rpc_spec = dict(spec.get("rpc") or {})
            rpc_spec["ports"] = [cell.server.port]
            spec["rpc"] = rpc_spec
            plan = faults.arm(spec, seed=seed)
        try:
            summary, init_snap, final, state = _run_training(
                batches, str(tmp_path / ("ck-%s" % run_tag)), cell,
                init_params=init_params, kv_endpoint=kvs.endpoint,
                master_ep=master_ep, ps_ckpt=ps_ckpt, plan=plan)
            with MasterClient(master_ep) as mc:
                counts = mc.counts()
        finally:
            faults.disarm()
            for c in (state.get("cell2"), cell):
                if c is not None:
                    c.shutdown()
            master.stop()
            try:
                kv.shutdown_server()
                kv.close()
            except OSError:
                pass
        return summary, init_snap, final, counts, plan, state

    base_summary, init_snap, _, base_counts, _, _ = run(
        "base-" + tag, None, None)
    chaos_summary, _, _, chaos_counts, plan, state = run(
        "chaos-" + tag, init_snap, fault_spec)
    return (base_summary, base_counts, chaos_summary, chaos_counts,
            plan, state)


SMOKE_SPEC = {
    "rpc": {"drop": 0.06, "duplicate": 0.05, "close_mid_frame": 0.03,
            "delay": 0.08, "delay_s": 0.003, "max": 10},
    "kill": [{"target": "pserver", "after": 14}],
    "ckpt": {"nth": 2, "mode": "bitflip"},
    "nan": {"step": 9, "name": "img"},
}


def _assert_chaos_run(base_summary, base_counts, chaos_summary,
                      chaos_counts, plan, state, n_tasks):
    # exact at-least-once task accounting on the master
    for counts in (base_counts, chaos_counts):
        assert counts == {"todo": 0, "pending": 0, "done": n_tasks,
                          "failed": 0}
    # every planned fault class actually fired
    kinds = {k for k, _ in plan.trips}
    assert "kill" in kinds, plan.trips
    assert "nan" in kinds, plan.trips
    assert "ckpt_corrupt" in kinds, plan.trips
    assert kinds & {"drop", "duplicate", "close_mid_frame", "delay"}, \
        plan.trips
    # the pserver was replaced via lease expiry and RECOVERED state
    assert state["killed"]
    assert state["cell2"] is not None, "replacement pserver never booted"
    assert state["cell2"].recovered_round is not None \
        and state["cell2"].recovered_round > 0
    # the NaN batch was rolled back and skipped, and the run completed
    assert chaos_summary["rollbacks"] == 1
    assert chaos_summary["steps"] == n_tasks - 1      # one batch skipped
    assert base_summary["steps"] == n_tasks
    assert all(np.isfinite(chaos_summary["losses"]))
    # final loss within 10% of the fault-free run (+ absolute slack for
    # near-zero plateaus)
    fb, ff = base_summary["final_loss"], chaos_summary["final_loss"]
    assert abs(ff - fb) <= 0.10 * abs(fb) + 0.05, (fb, ff)
    # training actually learned something in both runs
    assert fb < base_summary["losses"][0]
    assert ff < chaos_summary["losses"][0]


def test_chaos_smoke(tmp_path):
    """Tier-1 gate: the full kill/drop/corrupt/NaN composition on a
    small model with tight timeouts."""
    n_tasks = 26
    log = str(tmp_path / "chaos.jsonl")
    with monitor.session(log_path=log):
        results = _chaos_scenario(n_tasks, SMOKE_SPEC, seed=1301,
                                  tmp_path=tmp_path, tag="smoke")
    _assert_chaos_run(*results, n_tasks=n_tasks)
    # the flight recorder captured the whole story
    evs = {e["ev"] for e in monitor.read_jsonl(log)}
    assert {"fault", "retry", "reconnect", "rollback",
            "checkpoint"} <= evs, evs


@pytest.mark.slow
def test_chaos_soak_deterministic_three_runs(tmp_path):
    """The acceptance soak: the same seeded fault plan passes 3
    consecutive times (fresh services each time) on a longer run."""
    n_tasks = 60
    spec = dict(SMOKE_SPEC)
    spec["kill"] = [{"target": "pserver", "after": 30}]
    spec["nan"] = {"step": 20, "name": "img"}
    spec["ckpt"] = {"nth": 3, "mode": "truncate"}
    for attempt in range(3):
        results = _chaos_scenario(n_tasks, spec, seed=4242,
                                  tmp_path=tmp_path,
                                  tag="soak%d" % attempt)
        _assert_chaos_run(*results, n_tasks=n_tasks)
