"""Shared analyzer entry-point harness for the model zoo.

``program_entry(build_fn, feed_fn)`` stages a model exactly the way the
Executor would run it — build the Program, run startup init, extract
state, and return the pure ``step(state, feeds, key)`` the jit would
compile — so paddle_tpu.analysis lints the real training/inference
graph, not a simplified stand-in. Each models/* module wraps this in a
small ``analysis_entry()`` so the zoo registry (models/__init__.ZOO)
can enumerate every workload.

``monitored_run(build_fn, feed_fn, steps)`` is the RUNTIME sibling:
execute a zoo entry for a few real steps under paddle_tpu.monitor and
return the telemetry summary — the one-call health check (step p50,
recompiles, cost-model MFU) for any model the zoo can name.

``resilient_run(build_fn, feed_fn, steps, ckpt_dir)`` is the
SELF-HEALING sibling: the same real Executor steps, driven through
``resilience.driver.resilient_loop`` — periodic checkpoints off the
step path, auto-resume from the newest valid checkpoint, and the
NaN/Inf rollback-and-skip guard — so any zoo model can run under an
armed fault plan (the chaos tests do exactly this with the MLP).
"""

import numpy as np


def staged_programs(build_fn, feed_fn):
    """(main, startup, feed_fn, fetch_names) with the programs freshly
    built under their own guards — the Program-level zoo surface
    ``paddle_tpu.transform`` rewrites and verifies. Build only: nothing
    compiles or executes here (the transform verifier runs startup
    itself so both the original and the transformed program start from
    one identical initialized state)."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch_vars = build_fn()
        if not isinstance(fetch_vars, (tuple, list)):
            fetch_vars = (fetch_vars,)
    return main, startup, feed_fn, [v.name for v in fetch_vars]


def program_entry(build_fn, feed_fn, seed=0):
    """(fn, example_args) for the analyzer.

    build_fn() -> fetch Variables (called under fresh program guards);
    feed_fn(rng) -> feed dict (arrays or LoDTensors).
    """
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core import executor as core_exec

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        fetch_vars = build_fn()
        if not isinstance(fetch_vars, (tuple, list)):
            fetch_vars = (fetch_vars,)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
    state = {n: np.asarray(scope.find_var(n))
             for n in scope.local_var_names()
             if scope.find_var(n) is not None}
    feeds = feed_fn(np.random.RandomState(seed))
    feed_arrays, static_info = core_exec._normalize_feeds(feeds)
    fn = exe._build(main, tuple(sorted(feed_arrays)),
                    tuple(v.name for v in fetch_vars),
                    tuple(sorted(state)), static_info=static_info)
    return fn, (state, feed_arrays, jax.random.key(seed))


def monitored_run(build_fn, feed_fn, steps=3, seed=0, log_path=None,
                  **enable_kwargs):
    """Run a zoo entry for ``steps`` real Executor steps under
    paddle_tpu.monitor; returns a ``monitor.summary()``-shaped dict
    whose COUNT fields (steps/compiles/recompiles/cache_hits/
    feed_bytes) are deltas for THIS run; latency percentiles and the
    MFU/tokens-s gauges reflect the ambient session (last values).
    Programs/scope are fresh. The process-wide registry is never reset
    (counters are monotonic by contract); if the monitor is ALREADY
    armed (e.g. PADDLE_TPU_MONITOR=1) the ambient session is reused
    untouched, otherwise one is armed for the call and disarmed after."""
    import paddle_tpu as fluid
    from paddle_tpu import monitor

    with monitor.session(log_path=log_path, **enable_kwargs) as sess:
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope):
            fetch_vars = build_fn()
            if not isinstance(fetch_vars, (tuple, list)):
                fetch_vars = (fetch_vars,)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(seed)
            for _ in range(steps):
                exe.run(main, feed=feed_fn(rng), fetch_list=fetch_vars)
    return sess.summary()


def resilient_run(build_fn, feed_fn, ckpt_dir, steps=8, seed=0,
                  checkpoint_every=2, **loop_kwargs):
    """Run a zoo entry for ``steps`` real Executor steps under
    ``resilience.driver.resilient_loop``; returns the loop summary
    (steps, rollbacks, resumed_from, losses, ...). Convention: the
    FIRST fetch build_fn returns is the loss the NaN guard watches.
    A fresh program/scope per call; auto-resume means a repeated call
    with the same ckpt_dir restores the previous call's weights before
    training (kill-and-resume in one process)."""
    import paddle_tpu as fluid
    from paddle_tpu.resilience import resilient_loop

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        fetch_vars = build_fn()
        if not isinstance(fetch_vars, (tuple, list)):
            fetch_vars = (fetch_vars,)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(seed)
        batches = [feed_fn(rng) for _ in range(steps)]

        def step_fn(step, feeds):
            outs = exe.run(main, feed=feeds, fetch_list=list(fetch_vars))
            return outs[0]

        return resilient_loop(step_fn, batches, ckpt_dir, program=main,
                              scope=scope,
                              checkpoint_every=checkpoint_every,
                              **loop_kwargs)
