"""Multi-host (multi-process) mesh training end-to-end.

Two OS processes form a jax.distributed group via
distributed.launch.init_parallel_env (PADDLE_COORDINATOR env contract),
build one GLOBAL dp=8 mesh spanning both processes' devices (4 virtual
CPU devices each — the DCN tier the reference ran over gRPC pserver
rounds), and train the same program through ParallelExecutor. Both
ranks must see identical losses and identical final weights, and the
loss must actually converge.

Covers: launch.py bootstrap, ParallelExecutor's global-array feed/state
placement (make_array_from_callback), non-addressable fetch handling,
and local-device placement of single-device executors on non-zero ranks
(places.py jax.local_devices).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# -- capability probe -------------------------------------------------------
# Every test here spawns a two-process jax.distributed pair on the CPU
# backend (the workers pin JAX_PLATFORMS=cpu). Some jaxlib CPU backends
# (0.4.37 among them) refuse cross-process computations outright:
# "Multiprocess computations aren't implemented on the CPU backend" —
# a toolchain limitation, not a product bug (ROADMAP "known issues").
# Probe it EXPLICITLY once per module run with a minimal two-process
# reduction (a couple of seconds — far cheaper than four full
# model-training pairs failing) and skip the module on the limitation;
# chip containers with a capable jaxlib keep the tests live. The probe
# runs lazily (module-scoped autouse fixture), so collection and runs
# that deselect this module pay nothing.

_PROBE_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.distributed.initialize(
    coordinator_address=os.environ["PROBE_COORD"],
    num_processes=2, process_id=int(os.environ["PROBE_RANK"]))
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(jax.devices(), ("dp",))
arr = jax.make_array_from_callback(
    (4,), NamedSharding(mesh, P("dp")), lambda idx: jnp.ones((1,)))
out = jax.jit(lambda x: x.sum(),
              out_shardings=NamedSharding(mesh, P()))(arr)
jax.block_until_ready(out)
print("MULTIHOST_PROBE_OK", flush=True)
"""

_CPU_MULTIPROCESS_LIMITATION = \
    "Multiprocess computations aren't implemented"


def _cpu_multiprocess_unsupported():
    """(skip?, reason): run the minimal cross-process CPU collective
    once; skip only on the KNOWN backend limitation — any other probe
    failure keeps the tests live so real regressions stay visible."""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        script = os.path.join(d, "probe.py")
        with open(script, "w") as f:
            f.write(_PROBE_WORKER)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        procs = []
        for r in range(2):
            env = dict(os.environ)
            env.update({"PROBE_COORD": "127.0.0.1:%d" % port,
                        "PROBE_RANK": str(r)})
            procs.append(subprocess.Popen(
                [sys.executable, script], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=90)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                return False, "probe timeout (not the known limitation)"
            outs.append(out)
    if all("MULTIHOST_PROBE_OK" in o for o in outs):
        return False, "cpu backend supports multiprocess"
    if any(_CPU_MULTIPROCESS_LIMITATION in o for o in outs):
        return True, ("jaxlib CPU backend limitation: %s"
                      % _CPU_MULTIPROCESS_LIMITATION)
    return False, "probe failed for an unexpected reason"


@pytest.fixture(scope="module", autouse=True)
def _require_cpu_multiprocess():
    skip, reason = _cpu_multiprocess_unsupported()
    if skip:
        pytest.skip(reason)


_WORKER = r"""
import json, os, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.distributed import launch

launch.init_parallel_env()
rank = launch.trainer_id()
assert launch.trainer_count() == 2
axes = json.loads(os.environ["TEST_MESH_AXES"])
mesh = launch.global_mesh(axes)

x = fluid.layers.data("x", [4])
y = fluid.layers.data("y", [1])
pred = fluid.layers.fc(x, 8, bias_attr=False, act="tanh",
                       param_attr=fluid.ParamAttr(
                           name="w",
                           initializer=fluid.initializer.Constant(0.1)))
pred = fluid.layers.fc(pred, 1, bias_attr=False,
                       param_attr=fluid.ParamAttr(
                           name="w2",
                           initializer=fluid.initializer.Constant(0.0)))
if "tp" in axes:
    # Megatron pair: col-shard the in-projection, row-shard the
    # out-projection — the allreduce rides the cross-process mesh
    parallel.shard("w", None, "tp")
    parallel.shard("w2", "tp", None)
loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
pexe = fluid.ParallelExecutor(loss_name=loss.name, mesh=mesh)
rng = np.random.RandomState(0)   # same global batch on every host
xv = rng.rand(16, 4).astype(np.float32)
yv = (xv @ np.array([1., 2., 3., 4.], np.float32))[:, None]
losses = []
for _ in range(10):
    l, = pexe.run([loss], feed={"x": xv, "y": yv})
    losses.append(float(np.asarray(l)))
import jax
wv = fluid.global_scope().find_var("w2")
if isinstance(wv, jax.Array) and not wv.is_fully_addressable:
    w0 = 0.0     # tp-sharded across processes: no local full value
else:
    w0 = float(np.asarray(wv).ravel()[0])
assert losses[-1] < 0.5 * losses[0], losses
print("RESULT rank=%%d first=%%.6f last=%%.6f w0=%%.6f"
      %% (rank, losses[0], losses[-1], w0), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pair(tmp_path, axes):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % {"repo": repo})
    port = _free_port()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_COORDINATOR": "127.0.0.1:%d" % port,
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ID": str(r),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TEST_MESH_AXES": axes,
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines()
                if ln.startswith("RESULT")][0]
        kv = dict(tok.split("=") for tok in line.split()[1:])
        results[int(kv["rank"])] = (float(kv["first"]), float(kv["last"]),
                                    float(kv["w0"]))
    assert set(results) == {0, 1}
    # both hosts observed the SAME replicated loss and weights
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)


def test_two_process_mesh_training(tmp_path):
    _run_pair(tmp_path, '{"dp": 8}')


def test_two_process_tensor_parallel(tmp_path):
    # tp FIRST (slowest-varying) so each tp pair is (device_i of rank 0,
    # device_i of rank 1): the Megatron allreduce genuinely crosses the
    # process boundary. ({"dp":4,"tp":2} would give intra-process pairs.)
    _run_pair(tmp_path, '{"tp": 2, "dp": 4}')


_FETCH_WORKER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.distributed import launch

launch.init_parallel_env()
rank = launch.trainer_id()
mesh = launch.global_mesh({"dp": 8})

x = fluid.layers.data("x", [4])
pred = fluid.layers.fc(x, 3, bias_attr=False,
                       param_attr=fluid.ParamAttr(
                           name="w",
                           initializer=fluid.initializer.Constant(0.5)))
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
pexe = fluid.ParallelExecutor(loss_name=None, mesh=mesh)
xv = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)

# default: dp-sharded activation fetch refuses loudly
try:
    pexe.run([pred], feed={"x": xv})
    print("RESULTF rank=%%d refused=0 ok=0" %% rank, flush=True)
    sys.exit(0)
except NotImplementedError as e:
    assert "GATHER_SHARDED_FETCHES" in str(e), e

# flag on: fetch-time all-gather -> every process sees the FULL batch
fluid.flags.set_flag("gather_sharded_fetches", True)
v, = pexe.run([pred], feed={"x": xv})
got = np.asarray(v)
want = xv @ np.full((4, 3), 0.5, np.float32)
ok = int(got.shape == (16, 3) and np.allclose(got, want, rtol=1e-5))
print("RESULTF rank=%%d refused=1 ok=%%d" %% (rank, ok), flush=True)
"""


def test_two_process_sharded_fetch_gather(tmp_path):
    """parallel_executor.cc:190-197 parity: with gather_sharded_fetches
    on, a dp-sharded activation fetch all-gathers so each process gets
    the merged global batch; default stays the loud refusal."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker_fetch.py"
    script.write_text(_FETCH_WORKER % {"repo": repo})
    port = _free_port()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_COORDINATOR": "127.0.0.1:%d" % port,
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ID": str(r),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, out[-3000:]
        line = [ln for ln in out.splitlines()
                if ln.startswith("RESULTF")][0]
        assert "refused=1" in line and "ok=1" in line, line


_PP_WORKER = r"""
import sys
sys.path.insert(0, %(repo)r)
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.distributed import launch
from paddle_tpu.models import transformer as T

launch.init_parallel_env()
rank = launch.trainer_id()
mesh = launch.global_mesh({"pp": 2, "dp": 4})

st = parallel.DistributedStrategy(dp=4, pp=2)
avg, _ = T.transformer_lm_parallel(
    vocab_size=64, max_len=16, n_layer=2, n_head=4, d_model=32,
    d_inner=64, strategy=st)
fluid.optimizer.SGD(learning_rate=0.1).minimize(avg)
exe = fluid.Executor(fluid.CPUPlace())
exe.run(fluid.default_startup_program())
pexe = fluid.ParallelExecutor(loss_name=avg.name, mesh=mesh)
losses = []
for i in range(6):
    feeds = {k: np.asarray(v) for k, v in
             T.make_lm_batch(np.random.RandomState(100 + i),
                             16, 16, 64).items()}
    l, = pexe.run([avg], feed=feeds)
    losses.append(float(np.asarray(l)))
assert losses[-1] < losses[0], losses
print("RESULTP rank=%%d first=%%.6f last=%%.6f"
      %% (rank, losses[0], losses[-1]), flush=True)
"""


def test_two_process_pipeline_parallel(tmp_path):
    """Pipeline parallelism ACROSS a process boundary: the pp=2 mesh
    axis spans the two hosts, so the GPipe stage ring (ppermute) and
    the stacked-parameter shards ride the cross-process transport — the
    reference's multi-node model-parallel story, on jax.distributed."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker_pp.py"
    script.write_text(_PP_WORKER % {"repo": repo})
    port = _free_port()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.update({
            "PADDLE_COORDINATOR": "127.0.0.1:%d" % port,
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ID": str(r),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    results = {}
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, out[-3000:]
        line = [ln for ln in out.splitlines()
                if ln.startswith("RESULTP")][0]
        kv = dict(tok.split("=") for tok in line.split()[1:])
        results[int(kv["rank"])] = (float(kv["first"]), float(kv["last"]))
    assert set(results) == {0, 1}
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
