"""ResNet-50 inference benchmark — parity with the reference's
IntelOptimizedPaddle.md infer tables (ResNet-50 infer @bs16: 217.69
img/s MKL-DNN; BASELINE.md). Builds the train net, prunes to the logits
via save/load_inference_model, and times test-mode forward."""

import os
import tempfile

import numpy as np

from common import parse_args, get_place, time_loop, synthetic_feeds  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import resnet  # noqa: E402


def main():
    args = parse_args(
        "resnet_infer", batch_size=16, iterations=30,
        extra=lambda p: (
            p.add_argument("--depth", type=int, default=50),
            p.add_argument("--image_size", type=int, default=224)))
    shape = (3, args.image_size, args.image_size)

    image = fluid.layers.data("data", list(shape))
    logits = resnet.resnet_imagenet(image, depth=args.depth,
                                    num_classes=1000)
    if args.dtype == "bfloat16":
        fluid.amp.enable_amp()
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        fluid.io.save_inference_model(path, ["data"], [logits], exe)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            prog, feed_names, fetch_vars = \
                fluid.io.load_inference_model(path, exe)
            x = np.random.RandomState(0).rand(
                args.batch_size, *shape).astype(np.float32)
            # transfer once; steady-state times compute, not the host
            # tunnel (train benches use in-graph data for the same reason)
            import jax
            x = jax.device_put(x, get_place(args).jax_device())

            last = []

            def step(i):
                out, = exe.run(prog, feed={feed_names[0]: x},
                               fetch_list=fetch_vars, return_numpy=False)
                last[:] = [out]

            def sync():
                print("logit[0,0] %.4f"
                      % float(np.asarray(last[0])[0, 0]))

            return time_loop(step, args, args.batch_size, "imgs",
                             sync=sync)


if __name__ == "__main__":
    main()
