"""Scope: hierarchical name → value store for runtime state.

Parity with paddle/fluid/framework/scope.h:39 (Var/FindVar/NewScope), but the
stored values are host numpy arrays or committed jax.Arrays rather than
C++ Variables: persistable state (parameters, optimizer accumulators) lives
here between compiled steps, and the Executor threads it through the jitted
step function as donated inputs/outputs.
"""

import numpy as np


class Scope:
    def __init__(self, parent=None):
        self.parent = parent
        self._vars = {}      # name -> value (np.ndarray | jax.Array | LoDTensor | py obj)
        self._kids = []

    # -- reference API -------------------------------------------------------
    def var(self, name):
        """Find-or-create (returns current value holder name)."""
        if name not in self._vars:
            self._vars[name] = None
        return name

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return True
            s = s.parent
        return False

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    # -- value access --------------------------------------------------------
    def set(self, name, value):
        self._vars[name] = value

    def get(self, name, default=None):
        v = self.find_var(name)
        return default if v is None else v

    def get_numpy(self, name):
        v = self.find_var(name)
        if v is None:
            return None
        return np.asarray(v)

    def erase(self, name):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars)

    def __contains__(self, name):
        return self.has_var(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        old, _global_scope = _global_scope, scope
        try:
            yield
        finally:
            _global_scope = old
    return guard()
