"""Compile-time memory planning (ISSUE 15): the survey's
BuddyAllocator capability recast as a liveness pass over the Program
IR.

The reference managed activation memory at runtime (a buddy allocator
grabbing and splitting chunks per op as the interpreter walked the
block). In a jit world the whole block compiles to ONE XLA program, so
the equivalent lever is static: compute every intermediate's live range
over the (topologically ordered — append order IS execution order)
global block, then pack non-overlapping ranges into one arena with
greedy best-fit offset assignment. The resulting plan answers the
question the runtime allocator answered — "how much memory does this
program actually need?" — before anything executes:

  naive_bytes      every intermediate gets its own buffer (no reuse —
                   what a never-freeing allocator would hold)
  peak_live_bytes  max over time of simultaneously-live bytes (the
                   reuse lower bound no allocator can beat)
  arena_bytes      the greedy best-fit plan's arena size (achieved
                   reuse; >= peak_live_bytes, usually equal or close)
  param_bytes      persistable state, reported separately (it lives in
                   the Scope arena for the program's whole life)

Sizes come from the same static accounting the analysis cost model
prices bytes with: ``shape x dtype`` via the runtime dtype table
(64-bit narrowing included). ``-1`` batch dims resolve against the
``batch`` argument.

Surface: ``transform.memory_plan(program)`` and the
``python -m paddle_tpu.transform --plan-memory MODEL`` CLI view.
"""

import numpy as np

from ..core.program import runtime_dtype
from .passes import op_inputs

__all__ = ["Buffer", "MemoryPlan", "memory_plan"]


class Buffer:
    """One planned intermediate: [start, end] op-index live range and
    the arena offset the greedy packer assigned."""

    __slots__ = ("name", "nbytes", "start", "end", "offset")

    def __init__(self, name, nbytes, start, end):
        self.name = name
        self.nbytes = int(nbytes)
        self.start = start
        self.end = end
        self.offset = None

    def overlaps(self, other):
        return not (self.end < other.start or other.end < self.start)

    def to_dict(self):
        return {"name": self.name, "nbytes": self.nbytes,
                "start": self.start, "end": self.end,
                "offset": self.offset}


class MemoryPlan:
    def __init__(self, buffers, naive_bytes, peak_live_bytes,
                 arena_bytes, param_bytes, unsized):
        self.buffers = buffers              # list[Buffer], offset set
        self.naive_bytes = naive_bytes
        self.peak_live_bytes = peak_live_bytes
        self.arena_bytes = arena_bytes
        self.param_bytes = param_bytes
        self.unsized = unsized              # names we could not size

    @property
    def reuse_ratio(self):
        if not self.arena_bytes:
            return 1.0
        return self.naive_bytes / float(self.arena_bytes)

    def to_dict(self):
        return {"naive_bytes": self.naive_bytes,
                "peak_live_bytes": self.peak_live_bytes,
                "arena_bytes": self.arena_bytes,
                "param_bytes": self.param_bytes,
                "reuse_ratio": round(self.reuse_ratio, 3),
                "buffers": [b.to_dict() for b in self.buffers],
                "unsized": list(self.unsized)}

    def render(self, top=12):
        lines = [
            "memory plan: %d intermediate buffer(s)" % len(self.buffers),
            "  no-reuse (naive): %12s" % _fmt(self.naive_bytes),
            "  planned arena:    %12s  (%.2fx reuse)"
            % (_fmt(self.arena_bytes), self.reuse_ratio),
            "  peak-live bound:  %12s" % _fmt(self.peak_live_bytes),
            "  persistables:     %12s  (scope arena, unplanned)"
            % _fmt(self.param_bytes),
        ]
        if self.unsized:
            lines.append("  unsized (dynamic shape, excluded): %s"
                         % ", ".join(sorted(self.unsized)[:8]))
        biggest = sorted(self.buffers, key=lambda b: -b.nbytes)[:top]
        if biggest:
            lines.append("  largest buffers (offset @ live range):")
            for b in biggest:
                lines.append("    %-28s %10s  @%-10d ops [%d, %d]"
                             % (b.name[:28], _fmt(b.nbytes), b.offset,
                                b.start, b.end))
        return "\n".join(lines)


def _fmt(b):
    for unit, scale in (("GiB", 2 ** 30), ("MiB", 2 ** 20),
                        ("KiB", 2 ** 10)):
        if b >= scale:
            return "%.2f %s" % (b / scale, unit)
    return "%d B" % b


def _var_nbytes(v, batch):
    if v is None or v.shape is None:
        return None
    n = 1
    for s in v.shape:
        s = int(s)
        if s < 0:
            s = batch
        n *= max(1, s)
    return n * np.dtype(runtime_dtype(v.dtype)).itemsize


def memory_plan(program, keep=(), batch=1):
    """Liveness + buffer-reuse plan for ``program``'s global block.

    ``keep`` names stay live to the end of the block (fetch targets);
    ``batch`` resolves ``-1`` leading dims. Persistables are excluded
    from the plan (they are the Scope's permanent arena) and summed
    into ``param_bytes``; vars without a static shape are listed in
    ``unsized`` rather than silently mispriced."""
    gb = program.global_block()
    ops = gb.ops
    keep = {str(k) for k in keep}
    persistable = {n for n, v in gb.vars.items() if v.persistable}

    first_def, last_use = {}, {}
    for t, op in enumerate(ops):
        for n in op_inputs(op):
            last_use[n] = t
        for n in op.output_names:
            first_def.setdefault(n, t)
            last_use[n] = t
    end_t = len(ops)
    for n in keep:
        last_use[n] = end_t

    param_bytes = 0
    for n in persistable:
        nb = _var_nbytes(gb.vars.get(n), batch)
        if nb:
            param_bytes += nb

    buffers, unsized = [], []
    for n, t0 in first_def.items():
        if n in persistable:
            continue
        nb = _var_nbytes(gb.vars.get(n), batch)
        if nb is None:
            if gb.vars.get(n) is not None:
                unsized.append(n)
            continue
        buffers.append(Buffer(n, nb, t0, last_use.get(n, t0)))
    # feeds (is_data vars actually read) are live from block entry
    for n, v in gb.vars.items():
        if v.is_data and not v.persistable and n in last_use \
                and n not in first_def:
            nb = _var_nbytes(v, batch)
            if nb is not None:
                buffers.append(Buffer(n, nb, -1, last_use[n]))

    naive = sum(b.nbytes for b in buffers)

    # exact peak-live lower bound: sweep op boundaries
    events = []
    for b in buffers:
        events.append((b.start, b.nbytes))
        events.append((b.end + 1, -b.nbytes))
    events.sort()
    live = peak = 0
    for _, delta in events:
        live += delta
        peak = max(peak, live)

    # greedy best-fit: place big buffers first; each takes the
    # smallest gap (among range-overlapping neighbours) that fits
    arena = 0
    for b in sorted(buffers, key=lambda x: (-x.nbytes, x.start,
                                            x.name)):
        neighbours = sorted(
            ((o.offset, o.offset + o.nbytes) for o in buffers
             if o.offset is not None and o.overlaps(b)),
            key=lambda iv: iv[0])
        best_off, best_gap = None, None
        cursor = 0
        for lo, hi in neighbours:
            gap = lo - cursor
            if gap >= b.nbytes and (best_gap is None or gap < best_gap):
                best_off, best_gap = cursor, gap
            cursor = max(cursor, hi)
        b.offset = cursor if best_off is None else best_off
        arena = max(arena, b.offset + b.nbytes)

    buffers.sort(key=lambda b: (b.start, b.name))
    return MemoryPlan(buffers, naive, peak, arena, param_bytes,
                      unsized)
