"""Transformer LM benchmark (north star: tokens/sec/chip)."""

import numpy as np

from common import parse_args, get_place, time_loop  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import transformer as T  # noqa: E402


def main():
    args = parse_args(
        "transformer", batch_size=16, iterations=30,
        extra=lambda p: (
            p.add_argument("--max_len", type=int, default=256),
            p.add_argument("--n_layer", type=int, default=4),
            p.add_argument("--n_head", type=int, default=8),
            p.add_argument("--d_model", type=int, default=512),
            p.add_argument("--d_inner", type=int, default=2048),
            p.add_argument("--vocab", type=int, default=8192)))
    avg_cost, _ = T.transformer_lm(
        vocab_size=args.vocab, max_len=args.max_len, n_layer=args.n_layer,
        n_head=args.n_head, d_model=args.d_model, d_inner=args.d_inner)
    fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    if args.dtype == "bfloat16":
        fluid.amp.enable_amp()
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    feeds = T.make_lm_batch(rng, args.batch_size, args.max_len, args.vocab)
    tokens_per_batch = int(feeds["mask"].sum())
    total = args.iterations + args.skip_batch_num
    loader = iter(fluid.reader.DeviceLoader(
        fluid.reader.repeat_feed(feeds, total + 1)))

    last = []

    def step(i):
        loss, = exe.run(feed=next(loader), fetch_list=[avg_cost],
                        return_numpy=False)
        last[:] = [loss]

    def sync():
        print("loss %.4f" % float(np.asarray(last[0])))

    return time_loop(step, args, tokens_per_batch, "tokens", sync=sync)


if __name__ == "__main__":
    main()
