"""ISSUE 15: compiler tier v2 — pattern fusion, inference
specialization (`save_inference_model` artifact round-trip), and
compile-time memory planning.

Contracts pinned here:
  * per-pattern golden fixtures: exact before/after op lists for every
    fusion pattern, plus the safety rules (multi-consumer
    intermediates, protected outputs) that keep the non-SSA IR honest;
  * fusion execution identity: the cnn_infer zoo model — the one that
    exercises ALL pattern families — re-executes bitwise (the full-zoo
    sweep rides tests/test_transform.py's slow tier, which now runs
    fusion via default_passes);
  * specialize_for_inference: training machinery stripped, chains
    fused, forward bitwise vs the source program; the opt-in bf16 pass
    is rtol-gated (NOT bitwise) with the f32-stats contract visible in
    the rewritten IR;
  * the artifact: save -> load (fresh scope AND a REAL fresh process)
    -> serve BITWISE/token-identical to the source engine, and every
    corruption mode raises the typed ArtifactError instead of serving
    garbage;
  * serving cold-start: Engine(model=<dir>), fleet Replica routed
    decode identity, ScoringEngine.from_artifact;
  * memory planning: hand-computed naive/peak/arena golden;
  * autoparallel calibration: measured record loads through the
    autoparallel_calib flag, bad records fall back to placeholders.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving
from paddle_tpu.io import ArtifactError
from paddle_tpu.models import transform_zoo_entry, transformer
from paddle_tpu.models.transformer_infer import TransformerLMInfer
from paddle_tpu.transform import (
    Bf16CastPass, FusionPass, PassManager, default_passes, memory_plan,
    plan_cost, specialize_for_inference, verify_bitwise)
from paddle_tpu.transform.autoparallel import ModelSpec

N_LAYER, N_HEAD, D_MODEL, MAX_LEN, VOCAB = 1, 2, 32, 48, 40


def _ops(program):
    return [op.type for op in program.global_block().ops]


def _staged(build):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build()
    return main, startup, fetches


# -- per-pattern golden fixtures (build + rewrite only; no compiles) -------

def test_fusion_golden_matmul_bias_act():
    def build():
        x = fluid.layers.data("x", [8])
        h = fluid.layers.fc(x, 4, act="relu")     # mul+add+relu -> 1
        return fluid.layers.fc(h, 2)              # mul+add       -> 1

    main, _, out = _staged(build)
    assert _ops(main) == ["mul", "elementwise_add", "relu",
                          "mul", "elementwise_add"]
    res = PassManager([FusionPass()]).run(main, keep=[out.name])
    assert _ops(res.program) == ["fused_matmul_bias_act"] * 2
    assert res.patterns["matmul_bias_act"] == 1
    assert res.patterns["matmul_bias"] == 1
    assert res.stats["fusion"] == 3               # 5 ops -> 2
    first, second = res.program.global_block().ops
    assert first.attr("mm_type") == "mul"
    assert first.attr("act_type") == "relu"
    assert second.attr("act_type") == ""
    # the fused op writes the chain's ORIGINAL final name
    assert second.output("Out") == [out.name]


def test_fusion_golden_transpose_pairs():
    def build():
        x = fluid.layers.data("x", [2, 3, 4])
        a = fluid.layers.transpose(x, [0, 2, 3, 1])
        b = fluid.layers.transpose(a, [0, 3, 1, 2])   # inverse: gone
        c = fluid.layers.scale(b, 2.0)
        d = fluid.layers.transpose(c, [0, 2, 1, 3])
        e = fluid.layers.transpose(d, [0, 1, 3, 2])   # composes
        return fluid.layers.scale(e, 3.0)

    main, _, out = _staged(build)
    res = PassManager([FusionPass()]).run(main, keep=[out.name])
    got = _ops(res.program)
    # the inverse pair vanished outright (consumers renamed); the
    # non-inverse pair composed into ONE transpose
    assert got == ["scale", "transpose", "scale"]
    assert res.patterns["transpose_transpose"] == 2
    tr = res.program.global_block().ops[1]
    assert tr.attr("axis") == [0, 2, 3, 1]
    # the scale reads the ORIGINAL input name after the rename
    sc = res.program.global_block().ops[0]
    assert sc.input("X") == ["x"]


def test_fusion_golden_transpose_identity_protected_keeps_assign():
    """An inverse pair whose OUTPUT is a fetch target cannot be
    renamed away — the name must hold a value — so the pair collapses
    to a passthrough assign instead."""
    def build():
        x = fluid.layers.data("x", [2, 3])
        a = fluid.layers.transpose(x, [0, 2, 1])
        return fluid.layers.transpose(a, [0, 2, 1])

    main, _, out = _staged(build)
    res = PassManager([FusionPass()]).run(main, keep=[out.name])
    assert _ops(res.program) == ["assign"]
    a = res.program.global_block().ops[0]
    assert a.input("X") == ["x"] and a.output("Out") == [out.name]


def test_fusion_golden_reshape_chain_and_scale_cast():
    def build():
        x = fluid.layers.data("x", [2, 6])
        r = fluid.layers.reshape(x, [-1, 12])
        r2 = fluid.layers.reshape(r, [-1, 3, 4])      # outer wins
        c = fluid.layers.cast(r2, "float32")
        return fluid.layers.scale(c, 0.5, bias=1.0)   # pairs with cast

    main, _, out = _staged(build)
    assert _ops(main) == ["reshape", "reshape", "cast", "scale"]
    res = PassManager([FusionPass()]).run(main, keep=[out.name])
    assert _ops(res.program) == ["reshape", "fused_scale_cast"]
    assert res.patterns["reshape_reshape"] == 1
    assert res.patterns["scale_cast"] == 1
    rs = res.program.global_block().ops[0]
    assert rs.attr("shape") == [-1, 3, 4] and rs.input("X") == ["x"]
    fsc = res.program.global_block().ops[1]
    assert [t for t, _ in fsc.attr("ops")] == ["cast", "scale"]


def test_fusion_safety_rules():
    """A multi-consumer intermediate, a keep-set intermediate and an
    RNG-adjacent chain all refuse to fuse."""
    def build():
        x = fluid.layers.data("x", [4])
        mm = fluid.layers.fc(x, 4, bias_attr=False)    # bare mul
        y = fluid.layers.elementwise_add(mm, mm)       # reads it twice
        return y, mm

    main, _, (y, mm) = _staged(build)
    res = PassManager([FusionPass()]).run(main, keep=[y.name])
    assert _ops(res.program) == _ops(main)             # no match
    assert sum(res.patterns.values()) == 0

    # keep-set protection: fusing would erase a fetched intermediate
    def build2():
        x = fluid.layers.data("x", [4])
        h = fluid.layers.fc(x, 4, act="relu")
        return h

    main2, _, h = _staged(build2)
    gb = main2.global_block()
    pre_act = gb.ops[1].output("Out")[0]               # the add's out
    res2 = PassManager([FusionPass()]).run(main2,
                                           keep=[h.name, pre_act])
    # mul+add may still fuse (their output IS the kept name), but the
    # activation must stay separate — the kept pre-activation value
    # must exist
    kept = _ops(res2.program)
    assert kept[-1] == gb.ops[-1].type                 # act survives
    assert pre_act in [n for op in res2.program.global_block().ops
                       for n in op.output_names]


def test_fusion_pattern_counters_tick():
    from paddle_tpu.monitor import runtime as monrt

    def build():
        x = fluid.layers.data("x", [8])
        return fluid.layers.fc(x, 4, act="relu")

    main, _, out = _staged(build)
    before = monrt.TRANSFORM_PATTERNS.value(pattern="matmul_bias_act")
    PassManager([FusionPass()]).run(main, keep=[out.name])
    after = monrt.TRANSFORM_PATTERNS.value(pattern="matmul_bias_act")
    assert after == before + 1


def test_cnn_infer_zoo_fuses_all_patterns_bitwise():
    """The composed-inference zoo model exercises EVERY pattern family
    and re-executes bitwise — the tier-1 representative of the
    full-zoo slow sweep."""
    main, startup, feed_fn, fetch_names = transform_zoo_entry(
        "cnn_infer")
    res = PassManager(default_passes()).run(main, keep=fetch_names)
    assert _ops(res.program) == [
        "fused_scale_cast", "fused_matmul_bias_act", "pool2d",
        "reshape", "fused_matmul_bias_act"]
    for pat in ("matmul_bias_act", "transpose_transpose",
                "reshape_reshape", "scale_cast"):
        assert res.patterns[pat] >= 1, res.patterns
    ok, detail = verify_bitwise(main, startup, feed_fn, fetch_names,
                                res.program)
    assert ok, detail


# -- specialize_for_inference ----------------------------------------------

def _train_net():
    x = fluid.layers.data("x", [8])
    label = fluid.layers.data("label", [1], dtype="int64")
    h = fluid.layers.fc(x, 6, act="relu")
    pred = fluid.layers.fc(h, 3, act="softmax")
    cost = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    return pred, cost


def test_specialize_strips_training_and_stays_bitwise():
    main, startup, (pred, cost) = _staged(_train_net)
    assert "sgd" in _ops(main) and "backward_marker" in _ops(main)
    spec = specialize_for_inference(main, ["x"], [pred.name])
    got = _ops(spec.program)
    assert got == ["fused_matmul_bias_act", "fused_matmul_bias_act"]
    assert spec.transform.patterns["matmul_bias_act"] == 2
    # the source program was never mutated
    assert "sgd" in _ops(main)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {"x": np.random.RandomState(0).rand(4, 8)
                .astype(np.float32)}
        # forward-only reference (running the FULL main would apply
        # the sgd update and move the weights under the comparison)
        src = exe.run(main.prune([pred.name]), feed=feed,
                      fetch_list=[pred.name])[0]
        got = exe.run(spec.program, feed=feed,
                      fetch_list=[pred.name])[0]
    assert np.asarray(got).tobytes() == np.asarray(src).tobytes()


def test_specialize_validates_names():
    main, _, (pred, _) = _staged(_train_net)
    with pytest.raises(ValueError, match="not a variable"):
        specialize_for_inference(main, ["bogus"], [pred.name])


def test_bf16_pass_rtol_contract_not_bitwise():
    """The opt-in bf16 cast: matmul-class operands round to bf16,
    every output casts straight back to f32 (stats contract), weights
    flip to bf16 storage — outputs move (NOT bitwise) but stay inside
    the pinned rtol envelope. Off by default: bf16=False emits no
    casts."""
    main, startup, (pred, cost) = _staged(_train_net)
    plain = specialize_for_inference(main, ["x"], [pred.name])
    assert "cast" not in _ops(plain.program)          # off by default

    spec = specialize_for_inference(main, ["x"], [pred.name],
                                    bf16=True)
    ops = spec.program.global_block().ops
    assert spec.bf16_sites == 2
    # every fused matmul's output feeds a cast BACK to f32 — the
    # f32-stats contract in IR form
    for i, op in enumerate(ops):
        if op.type == "fused_matmul_bias_act":
            nxt = ops[i + 1]
            assert nxt.type == "cast" \
                and nxt.attr("out_dtype") == "float32"
    gb = spec.program.global_block()
    w = [v for n, v in gb.vars.items() if n.endswith(".w_0")]
    assert w and all(v.dtype == "bfloat16" for v in w)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {"x": np.random.RandomState(1).rand(4, 8)
                .astype(np.float32)}
        src = np.asarray(exe.run(main.prune([pred.name]), feed=feed,
                                 fetch_list=[pred.name])[0])
        got = np.asarray(exe.run(spec.program, feed=feed,
                                 fetch_list=[pred.name])[0])
    assert got.tobytes() != src.tobytes()             # it DID round
    np.testing.assert_allclose(got, src, rtol=2e-2, atol=2e-2)


# -- the artifact round trip ------------------------------------------------

@pytest.fixture()
def small_artifact(tmp_path):
    main, startup, (pred, cost) = _staged(_train_net)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        d = str(tmp_path / "art")
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main, scope=scope)
    return {"dir": d, "main": main, "scope": scope, "pred": pred}


def test_artifact_roundtrip_bitwise_and_manifest(small_artifact):
    s = small_artifact
    feed = {"x": np.random.RandomState(2).rand(4, 8)
            .astype(np.float32)}
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(s["scope"]):
        src = np.asarray(exe.run(
            s["main"].prune([s["pred"].name]), feed=feed,
            fetch_list=[s["pred"].name]))

    m = fluid.io.load_inference_manifest(s["dir"])
    assert m["format"] == 2
    assert m["feed_names"] == ["x"]
    assert m["fetch_names"] == [s["pred"].name]
    assert m["transform"]["patterns"]["matmul_bias_act"] == 2
    assert isinstance(m["model_crc32"], int)
    assert isinstance(m["params_crc32"], int)

    fresh = fluid.Scope()
    with fluid.scope_guard(fresh):
        prog, feeds, fetches = fluid.io.load_inference_model(
            s["dir"], exe)
        assert feeds == ["x"]
        assert [v.name for v in fetches] == [s["pred"].name]
        assert _ops(prog) == ["fused_matmul_bias_act"] * 2
        got = np.asarray(exe.run(prog, feed=feed,
                                 fetch_list=fetches))
    assert got.tobytes() == src.tobytes()


def test_artifact_corrupt_matrix(small_artifact):
    """Every corruption mode raises the TYPED ArtifactError naming the
    damaged piece — a serving replica must never boot garbage
    weights."""
    d = small_artifact["dir"]
    m = fluid.io.load_inference_manifest(d)
    exe = fluid.Executor(fluid.CPUPlace())

    def reload():
        with fluid.scope_guard(fluid.Scope()):
            return fluid.io.load_inference_model(d, exe)

    pf = os.path.join(d, m["params_file"])
    blob = open(pf, "rb").read()

    # truncated params
    open(pf, "wb").write(blob[:-16])
    with pytest.raises(ArtifactError, match="params CORRUPT"):
        reload()
    # bit-flipped params
    flipped = bytearray(blob)
    flipped[len(flipped) // 2] ^= 0x40
    open(pf, "wb").write(bytes(flipped))
    with pytest.raises(ArtifactError, match="params CORRUPT"):
        reload()
    open(pf, "wb").write(blob)
    reload()                                   # restored: loads again

    # missing model file
    mf = os.path.join(d, m["model_file"])
    model_bytes = open(mf, "rb").read()
    os.unlink(mf)
    with pytest.raises(ArtifactError, match="program missing"):
        reload()
    # bit-flipped program
    open(mf, "wb").write(model_bytes[:-4] + b"xxxx")
    with pytest.raises(ArtifactError, match="program CORRUPT"):
        reload()
    open(mf, "wb").write(model_bytes)

    # torn manifest
    man = os.path.join(d, fluid.io.MANIFEST)
    man_bytes = open(man, "rb").read()
    open(man, "wb").write(man_bytes[:-8])
    with pytest.raises(ArtifactError, match="manifest"):
        reload()
    open(man, "wb").write(man_bytes)
    reload()

    # and a non-servable artifact: serving boot needs the config block
    with pytest.raises(ArtifactError, match="not a serving artifact"):
        serving.model_from_artifact(str(d) + "-nope")  # no manifest


def test_artifact_legacy_dir_still_loads(tmp_path):
    """Pre-manifest directories (the original save format) load
    through the unchanged legacy path."""
    main, startup, (pred, cost) = _staged(_train_net)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    d = str(tmp_path / "legacy")
    os.makedirs(d)
    with fluid.scope_guard(scope):
        exe.run(startup)
        prog = fluid.io.get_inference_program([pred], main)
        dd = prog.to_dict()
        dd["feed_names"], dd["fetch_names"] = ["x"], [pred.name]
        with open(os.path.join(d, "__model__"), "w") as f:
            json.dump(dd, f)
        fluid.io.save_persistables(exe, d, prog, scope=scope)
    fresh = fluid.Scope()
    with fluid.scope_guard(fresh):
        prog2, feeds, fetches = fluid.io.load_inference_model(d, exe)
    assert feeds == ["x"] and [v.name for v in fetches] == [pred.name]
    assert fresh.find_var("fc_0.w_0") is not None


def test_bf16_artifact_stores_half_width_params(tmp_path):
    main, startup, (pred, cost) = _staged(_train_net)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        d = str(tmp_path / "bf16art")
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main, scope=scope,
                                      bf16=True)
    m = fluid.io.load_inference_manifest(d)
    assert m["bf16"] is True
    assert m["param_dtypes"].get("fc_0.w_0") == "bfloat16"
    fresh = fluid.Scope()
    with fluid.scope_guard(fresh):
        fluid.io.load_inference_model(d, exe)
    import ml_dtypes
    arr = np.asarray(fresh.find_var("fc_0.w_0"))
    assert arr.dtype == np.dtype(ml_dtypes.bfloat16)
    src = np.asarray(scope.find_var("fc_0.w_0"))
    np.testing.assert_array_equal(
        arr.astype(np.float32),
        src.astype(np.dtype(ml_dtypes.bfloat16)).astype(np.float32))


# -- serving cold-start -----------------------------------------------------

@pytest.fixture(scope="module")
def lm_setup(tmp_path_factory):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        avg_cost, logits = transformer.transformer_lm(
            vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        lm = TransformerLMInfer(main, scope, N_LAYER, N_HEAD, D_MODEL,
                                MAX_LEN)
    art = str(tmp_path_factory.mktemp("lm") / "artifact")
    serving.save_lm_artifact(art, main, scope, [logits], N_LAYER,
                             N_HEAD, D_MODEL, MAX_LEN)
    return {"lm": lm, "art": art}


def _requests(rng, n, max_prompt=8, min_new=4, max_new=10):
    reqs = []
    for _ in range(n):
        plen = int(rng.randint(1, max_prompt + 1))
        prompt = [1] + rng.randint(3, VOCAB, plen - 1).tolist()
        reqs.append((prompt, int(rng.randint(min_new, max_new + 1))))
    return reqs


def test_engine_from_artifact_token_identity(lm_setup):
    """The ISSUE acceptance core, in process: an engine booted from
    the artifact DIRECTORY serves token-identically (scores bitwise)
    to the source-model engine. The artifact's fused program replays
    the identical parameter stream (extract_params learned fused
    ops)."""
    m = fluid.io.load_inference_manifest(lm_setup["art"])
    assert m["config"]["kind"] == "transformer_lm"
    assert sum(m["transform"]["patterns"].values()) >= 1
    reqs = _requests(np.random.RandomState(7), 6)
    e1 = serving.Engine(lm_setup["lm"], slots=2, prefill_chunk=4,
                        name="src")
    e2 = serving.Engine(lm_setup["art"], slots=2, prefill_chunk=4,
                        name="art")
    try:
        o1 = e1.generate_many([p for p, _ in reqs], 8)
        o2 = e2.generate_many([p for p, _ in reqs], 8)
    finally:
        e1.close()
        e2.close()
    for i, ((t1, s1), (t2, s2)) in enumerate(zip(o1, o2)):
        assert t1 == t2, "request %d diverged" % i
        assert float(s1) == float(s2)


def test_fresh_process_artifact_serve_bitwise(lm_setup, tmp_path):
    """THE acceptance criterion: a FRESH PROCESS holding nothing but
    the artifact directory serves the same tokens/scores as the
    source-model engine here."""
    reqs = _requests(np.random.RandomState(11), 4, max_new=8)
    e1 = serving.Engine(lm_setup["lm"], slots=2, prefill_chunk=4,
                        name="src2")
    try:
        want = e1.generate_many([p for p, _ in reqs], 6)
    finally:
        e1.close()

    script = tmp_path / "serve_artifact.py"
    script.write_text(
        "import json, sys\n"
        "from paddle_tpu import serving\n"
        "eng = serving.engine_from_artifact(sys.argv[1], slots=2,\n"
        "                                   prefill_chunk=4)\n"
        "outs = eng.generate_many(json.loads(sys.argv[2]),\n"
        "                         int(sys.argv[3]))\n"
        "eng.close()\n"
        "print('ARTOUT ' + json.dumps([[t, float(s)]\n"
        "                              for t, s in outs]))\n")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (root, os.environ.get("PYTHONPATH"))
                   if p))
    proc = subprocess.run(
        [sys.executable, str(script), lm_setup["art"],
         json.dumps([p for p, _ in reqs]), "6"],
        capture_output=True, text=True, timeout=240, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("ARTOUT ")][-1]
    got = json.loads(line[len("ARTOUT "):])
    assert len(got) == len(want)
    for (wt, ws), (gt, gs) in zip(want, got):
        assert list(wt) == list(gt)
        assert float(ws) == float(gs)


def test_replica_cold_start_routed_identity(lm_setup, tmp_path):
    """Fleet seam (ROADMAP direction 2(b)): a Replica handed the
    artifact DIRECTORY boots its engine from disk; routed decode is
    token-identical to the source-model engine."""
    from paddle_tpu.distributed.membership import KVClient, KVServer
    from paddle_tpu.serving import fleet
    from paddle_tpu.serving.fleet import Router

    reqs = _requests(np.random.RandomState(13), 5, max_new=8)
    e1 = serving.Engine(lm_setup["lm"], slots=2, prefill_chunk=4,
                        name="src3")
    try:
        want = e1.generate_many([p for p, _ in reqs], 6)
    finally:
        e1.close()

    kvs = KVServer(sweep_interval=0.05).start()
    kv = KVClient(kvs.endpoint)
    cell = router = None
    try:
        cell = fleet.Replica(kv, lm_setup["art"], desired=1, slots=2,
                             prefill_chunk=4, ttl=0.5)
        router = Router(kvs.endpoint, window=4, max_queue=64,
                        refresh_interval=0.05, name="router-art")
        router.wait_for_replicas(1, timeout=15)
        got = router.generate_many([p for p, _ in reqs],
                                   [6] * len(reqs), timeout=120)
        for (wt, ws), (gt, gs) in zip(want, got):
            assert list(wt) == list(gt)
    finally:
        if router is not None:
            router.close()
        if cell is not None:
            cell.shutdown()
        try:
            kv.shutdown_server()
            kv.close()
        except OSError:
            pass


def test_scoring_engine_from_artifact_bitwise(tmp_path):
    """The dense-scoring cold-start twin: ScoringEngine.from_artifact
    scores bitwise vs a direct run of the source program."""
    from paddle_tpu.models import deepfm as dfm
    from paddle_tpu.serving.sparse.scoring import ScoringEngine

    F, DIM = 3, 4
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        prob, _ = dfm.build_scoring_net(F, DIM, dnn_dims=(8,))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        d = str(tmp_path / "scoring-art")
        fluid.io.save_inference_model(
            d, ["fm_first_rows", "fm_second_rows"], [prob], exe,
            main_program=main, scope=scope)

    rng = np.random.RandomState(3)
    feats = [{"first": rng.rand(F).astype(np.float32),
              "second": rng.rand(F, DIM).astype(np.float32)}
             for _ in range(3)]

    def featurizer(features, batch):
        first = np.zeros((batch, F), np.float32)
        second = np.zeros((batch, F, DIM), np.float32)
        for i, f in enumerate(features):
            first[i], second[i] = f["first"], f["second"]
        first.setflags(write=False)
        second.setflags(write=False)
        return {"fm_first_rows": first, "fm_second_rows": second}

    eng = ScoringEngine.from_artifact(d, featurizer, batch=2,
                                      name="art-scoring")
    try:
        got = eng.score_many(feats)
    finally:
        eng.close()
    with fluid.scope_guard(scope):
        for i, f in enumerate(feats):
            out, = exe.run(main, feed=featurizer([f], 2),
                           fetch_list=[prob.name])
            want = float(np.asarray(out).reshape(-1)[0])
            assert got[i] == want, (i, got[i], want)


# -- memory planning --------------------------------------------------------

def test_memory_plan_golden_hand_computed():
    """x(feed) -> a -> b -> add(a,b)=c, batch 2, f32 [., 4] = 32 B
    each. Hand-computed: naive 128 B; at the add, a+b+c live = 96 B
    peak; greedy packs b into x's slot -> 96 B arena."""
    def build():
        x = fluid.layers.data("x", [4])
        a = fluid.layers.scale(x, 2.0)
        b = fluid.layers.scale(a, 3.0)
        return fluid.layers.elementwise_add(a, b)

    main, _, c = _staged(build)
    plan = memory_plan(main, keep=[c.name], batch=2)
    assert plan.naive_bytes == 128
    assert plan.peak_live_bytes == 96
    assert plan.arena_bytes == 96
    assert plan.param_bytes == 0
    by_name = {b.name: b for b in plan.buffers}
    assert by_name["x"].start == -1 and by_name["x"].end == 0
    assert by_name[c.name].end == len(main.global_block().ops)
    # no two live-overlapping buffers share bytes
    bufs = plan.buffers
    for i, b1 in enumerate(bufs):
        for b2 in bufs[i + 1:]:
            if b1.overlaps(b2):
                assert (b1.offset + b1.nbytes <= b2.offset
                        or b2.offset + b2.nbytes <= b1.offset), \
                    (b1.to_dict(), b2.to_dict())
    assert "planned arena" in plan.render()


def test_memory_plan_shrinks_after_fusion():
    """Fusion erases intermediates, so the planned arena of the
    transformed program never exceeds the source's (cnn_infer: the
    transpose/reshape copies disappear outright)."""
    main, _, _, fetch_names = transform_zoo_entry("cnn_infer")
    src = memory_plan(main, keep=fetch_names, batch=4)
    res = PassManager(default_passes()).run(main, keep=fetch_names)
    opt = memory_plan(res.program, keep=fetch_names, batch=4)
    assert opt.naive_bytes < src.naive_bytes
    assert opt.arena_bytes <= src.arena_bytes
    assert src.reuse_ratio >= 1.0 and opt.reuse_ratio >= 1.0


# -- autoparallel calibration ----------------------------------------------

def test_calibration_record_drives_plan_cost(tmp_path):
    from paddle_tpu import flags
    from paddle_tpu.transform import autoparallel as ap
    from paddle_tpu.transform.calibrate import (load_calibration,
                                                write_calibration)

    spec = ModelSpec("toy", flops=1e12, bytes=1e9, param_bytes=4e8,
                     batch=8, seq=128, d_model=256, n_layer=4,
                     n_head=8)
    axes = {"dp": 2, "tp": 1, "pp": 1, "sp": 1, "ep": 1}
    path = str(tmp_path / "calib.json")
    write_calibration(path, {
        "schema": 1, "platform": "cpu", "devices": 8,
        "peak_flops": 2e12, "ici_bps": 5e10})
    rec = load_calibration(path)
    assert rec["peak_flops"] == 2e12

    baseline = plan_cost(spec, axes)[0]
    flags.set_flag("autoparallel_calib", path)
    try:
        measured = plan_cost(spec, axes)[0]
        explicit = plan_cost(spec, axes, peak_flops=2e12,
                             ici_bps=5e10)[0]
        assert measured == explicit != baseline
        peak, ici, source = ap.calibration()
        assert (peak, ici) == (2e12, 5e10)
        assert source.startswith("measured:")

        # a bad record falls back to placeholders, loudly but safely
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            f.write("{not json")
        flags.set_flag("autoparallel_calib", bad)
        assert plan_cost(spec, axes)[0] == baseline
    finally:
        flags.set_flag("autoparallel_calib", None)

    with pytest.raises(ValueError, match="peak_flops"):
        write_calibration(str(tmp_path / "bad2.json"),
                          {"peak_flops": -1})
        load_calibration(str(tmp_path / "bad2.json"))


def test_committed_cpu_calibration_record_loads():
    """The CPU-container record this PR commits (the chip round
    re-runs --calibrate and replaces it) is a valid, platform-stamped
    record."""
    from paddle_tpu.transform.calibrate import load_calibration
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rec = load_calibration(os.path.join(root, "CALIB_r01.json"))
    assert rec["platform"] == "cpu"
    assert rec["peak_flops"] > 0


# -- CLI surfaces -----------------------------------------------------------

def test_cli_plan_memory_and_pattern_json(capsys):
    from paddle_tpu.transform.__main__ import main as tmain

    assert tmain(["--plan-memory", "cnn_infer", "--json",
                  "--batch", "2"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["transformed"]["arena_bytes"] <= \
        out["source"]["arena_bytes"]
    assert out["transform"]["patterns"]["scale_cast"] == 1

    # satellite: the pipeline --json emits machine-readable per-pass
    # pattern counts
    assert tmain(["cnn_infer", "--no-verify", "--json"]) == 0
    out2 = json.loads(capsys.readouterr().out)
    pats = out2["models"][0]["patterns"]
    assert pats["matmul_bias_act"] >= 1
    assert pats["transpose_transpose"] == 1

    assert tmain(["--plan-memory", "nope"]) == 2
