"""Conv-ceiling probe (round-2 verdict #2c): is the measured ~26% MFU
fwd+bwd conv ceiling an XLA-conv artifact, or the chip's real limit?

Tests, per representative ResNet-50 layer shape, fwd+bwd throughput of:
  a) lax.conv_general_dilated (the framework's lowering),
  b) im2col (conv_general_dilated_patches) + MXU matmul,
and a pure-matmul control with the SAME FLOP count as (b)'s GEMM.
Run on the real chip: python benchmarks/perf_probe_conv.py
"""

import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

PEAK = 197e12

# (name, N, H, W, Cin, Cout, k, stride) — ResNet-50 working set
SHAPES = [
    ("conv2_3x3", 64, 56, 56, 64, 64, 3, 1),
    ("conv3_3x3", 64, 28, 28, 128, 128, 3, 1),
    ("conv4_3x3", 64, 14, 14, 256, 256, 3, 1),
    ("conv2_1x1", 64, 56, 56, 64, 256, 1, 1),
    ("conv4_1x1", 64, 14, 14, 1024, 256, 1, 1),
]


_FETCH_COST = None


def _fetch_cost():
    """Median cost of a bare device->host scalar fetch (the tunnel round
    trip, ~90ms here)."""
    global _FETCH_COST
    if _FETCH_COST is None:
        x = jnp.zeros(())
        np.asarray(x)
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(x + 1.0)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        _FETCH_COST = ts[len(ts) // 2]
    return _FETCH_COST


def time_fn(fn, *args, rounds=3, min_window=1.5):
    """fn must return a SMALL array; sync is a value fetch — on this
    sandbox's axon platform block_until_ready does not actually block,
    so only a device->host read orders the timeline. The fetch costs a
    ~90ms tunnel round trip, so reps grow until one window is
    >= min_window seconds of enqueued work, and the single fetch cost is
    subtracted; median over `rounds`."""
    fetch = _fetch_cost()

    def window(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        np.asarray(out)
        return time.perf_counter() - t0

    out = fn(*args)
    np.asarray(out)                       # compile + settle
    reps = 64
    t = window(reps)
    while t < min_window + fetch and reps < 1 << 16:
        reps *= 2
        t = window(reps)
    est = [max(t - fetch, 1e-9) / reps]
    for _ in range(rounds - 1):
        est.append(max(window(reps) - fetch, 1e-9) / reps)
    est.sort()
    return est[len(est) // 2]


def conv_flops(n, h, w, cin, cout, k, stride):
    oh, ow = h // stride, w // stride
    return 2 * n * oh * ow * cin * cout * k * k


def main():
    rng = np.random.RandomState(0)
    print("%-11s %10s %10s %10s  (fwd+bwd TF/s, MFU at %.0f TF/s peak)"
          % ("shape", "lax.conv", "im2col+mm", "matmul", PEAK / 1e12))
    for name, n, h, w, cin, cout, k, stride in SHAPES:
        x = jnp.asarray(rng.randn(n, h, w, cin).astype(np.float32),
                        dtype=jnp.bfloat16)
        wt = jnp.asarray(rng.randn(k, k, cin, cout).astype(np.float32)
                         * 0.1, dtype=jnp.bfloat16)
        pad = "SAME" if k > 1 else "VALID"
        dn = lax.conv_dimension_numbers(x.shape, wt.shape,
                                        ("NHWC", "HWIO", "NHWC"))

        def conv_loss(x, wt):
            y = lax.conv_general_dilated(x, wt, (stride, stride), pad,
                                         dimension_numbers=dn)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        def conv_probe(x, wt):
            dx, dw = jax.grad(conv_loss, argnums=(0, 1))(x, wt)
            return jnp.float32(jnp.sum(dx.astype(jnp.float32))
                               + jnp.sum(dw.astype(jnp.float32)))

        t_conv = time_fn(jax.jit(conv_probe), x, wt)

        oh, ow = h // stride, w // stride

        def im2col_loss(x, wt):
            # patches: [N, OH, OW, k*k*Cin] then one MXU GEMM
            p = lax.conv_general_dilated_patches(
                x, (k, k), (stride, stride), pad,
                dimension_numbers=dn)
            p2 = p.reshape(n * oh * ow, k * k * cin)
            w2 = wt.transpose(2, 0, 1, 3).reshape(k * k * cin, cout)
            y = p2 @ w2
            return jnp.sum(y.astype(jnp.float32) ** 2)

        def im2col_probe(x, wt):
            dx, dw = jax.grad(im2col_loss, argnums=(0, 1))(x, wt)
            return jnp.float32(jnp.sum(dx.astype(jnp.float32))
                               + jnp.sum(dw.astype(jnp.float32)))

        t_im2col = time_fn(jax.jit(im2col_probe), x, wt)

        # control: the same GEMM with materialized inputs
        a = jnp.asarray(rng.randn(n * oh * ow, k * k * cin)
                        .astype(np.float32), dtype=jnp.bfloat16)
        b = jnp.asarray(rng.randn(k * k * cin, cout).astype(np.float32),
                        dtype=jnp.bfloat16)

        def mm_loss(a, b):
            return jnp.sum((a @ b).astype(jnp.float32) ** 2)

        def mm_probe(a, b):
            da, db = jax.grad(mm_loss, argnums=(0, 1))(a, b)
            return jnp.float32(jnp.sum(da.astype(jnp.float32))
                               + jnp.sum(db.astype(jnp.float32)))

        t_mm = time_fn(jax.jit(mm_probe), a, b)

        fl = 3 * conv_flops(n, h, w, cin, cout, k, stride)  # fwd+bwd
        print("%-11s %7.1f/%2.0f%% %7.1f/%2.0f%% %7.1f/%2.0f%%"
              % (name,
                 fl / t_conv / 1e12, 100 * fl / t_conv / PEAK,
                 fl / t_im2col / 1e12, 100 * fl / t_im2col / PEAK,
                 fl / t_mm / 1e12, 100 * fl / t_mm / PEAK))


if __name__ == "__main__":
    main()
