"""Fused ops — the lowering targets of the transform tier's pattern
fusion (transform/fusion.py, ISSUE 15).

Each fused op replays its component ops' REGISTERED lowerings in
sequence through synthetic Operator nodes sharing the trace env, so the
traced jaxpr is primitive-for-primitive the unfused chain's — bitwise
identity (and identical grads under value_and_grad) hold by
construction, while the Executor's per-op ``jax.named_scope`` wraps the
whole chain in ONE op-path scope (one analysis path, one profile lane,
one trace step instead of three).

Ops:
  fused_matmul_bias_act   anchor (mul/matmul/conv2d/depthwise_conv2d)
                          + elementwise_add bias + optional activation.
                          Inputs X/Y/Bias, output Out; attrs ``mm_type``
                          / ``mm_attrs`` / ``add_attrs`` / ``act_type``
                          / ``act_attrs`` carry the component ops.
  fused_scale_cast        a two-op scale/cast chain; attr ``ops`` =
                          [[type, attrs], [type, attrs]] applied in
                          order.
"""

from ..core.program import Operator
from ..core.registry import register, lookup

# anchor op type -> (lhs slot, rhs slot, output slot). The rhs slot is
# where a weight parameter lives (models/transformer_infer.extract_params
# reads fused ops through this table too).
FUSABLE_ANCHORS = {
    "mul": ("X", "Y", "Out"),
    "matmul": ("X", "Y", "Out"),
    "conv2d": ("Input", "Filter", "Output"),
    "depthwise_conv2d": ("Input", "Filter", "Output"),
}


def fusable_act_types():
    """Single-input pure activation op types a chain may end in: the
    unary activation table plus the softmax head fc() appends."""
    from .activations import _SIMPLE
    return frozenset(_SIMPLE) | {"softmax", "log_softmax"}


def _run_component(ctx, block, op_type, inputs, outputs, attrs):
    """Lower one component op through its registered rule. The synthetic
    Operator shares the fused op's block (some lowerings consult block
    metadata) but is never appended to it."""
    info = lookup(op_type)
    if info is None:
        raise NotImplementedError(
            "fused op delegates to unregistered op %r" % (op_type,))
    syn = Operator(block, op_type, inputs, outputs, dict(attrs or {}))
    info.lower(ctx, syn)


@register("fused_matmul_bias_act")
def _fused_matmul_bias_act(ctx, op):
    mm_type = op.attr("mm_type", "mul")
    lhs_slot, rhs_slot, out_slot = FUSABLE_ANCHORS[mm_type]
    out = ctx.out_name(op, "Out")
    t_mm, t_add = out + "@fused:mm", out + "@fused:add"
    blk = op.block
    _run_component(
        ctx, blk, mm_type,
        {lhs_slot: op.input("X"), rhs_slot: op.input("Y")},
        {out_slot: [t_mm]}, op.attr("mm_attrs"))
    _run_component(
        ctx, blk, "elementwise_add",
        {"X": [t_mm], "Y": op.input("Bias")},
        {"Out": [t_add]}, op.attr("add_attrs"))
    act = op.attr("act_type") or None
    if act:
        _run_component(ctx, blk, act, {"X": [t_add]}, {"Out": [out]},
                       op.attr("act_attrs"))
    else:
        ctx.env[out] = ctx.env[t_add]
    # temps are trace-local — drop them so the env (and anything that
    # sweeps it: constant folding's declared-output check, state
    # extraction) sees only the declared output
    ctx.env.pop(t_mm, None)
    ctx.env.pop(t_add, None)


@register("fused_scale_cast")
def _fused_scale_cast(ctx, op):
    chain = op.attr("ops") or []
    out = ctx.out_name(op, "Out")
    blk = op.block
    src = op.input("X")
    temps = []
    for i, (op_type, attrs) in enumerate(chain):
        dst = out if i == len(chain) - 1 else "%s@fused:%d" % (out, i)
        _run_component(ctx, blk, op_type, {"X": src}, {"Out": [dst]},
                       attrs)
        if dst != out:
            temps.append(dst)
        src = [dst]
    for t in temps:
        ctx.env.pop(t, None)


FUSED_OP_TYPES = ("fused_matmul_bias_act", "fused_scale_cast")
