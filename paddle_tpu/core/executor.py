"""Executor: runs a Program by compiling it to one XLA computation.

Reference parity: python/paddle/fluid/executor.py:181 + framework/executor.cc:133.
The reference interprets a Program op-by-op, re-running shape inference and
kernel dispatch per op per step (executor.cc:333 — its hot loop). Here the
whole block is *traced once* through the op lowering registry into a pure
function ``step(state, feeds, key) -> (fetches, new_state)`` and jit-compiled;
subsequent runs with the same (program version, feed signature, fetch list)
hit the compiled-step cache (the analog of executor.py:165's program cache,
but caching an XLA executable instead of a cloned ProgramDesc).

State threading: persistable variables (parameters, optimizer accumulators)
live in a Scope between steps and are passed through the jitted function as a
donated pytree, so in-place optimizer updates reuse device buffers instead of
reallocating (the role the reference's buddy allocator + in-place var reuse
played).

Autodiff: a ``backward_marker`` op recorded by append_backward (core/backward.py)
switches the tracer into ``jax.value_and_grad`` over the forward segment —
replacing the reference's per-op GradOpDescMaker machinery (backward.py:425)
with JAX's program transform.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp

from . import registry
from .enforce import EnforceError, op_error
from .program import Program, Variable, default_main_program
from .scope import Scope, global_scope
from .places import CPUPlace, Place, _default_place
from .lod import LoDTensor
from ..trace import runtime as _trc

_NANGUARD = "__nanguard__"


def _flag_on(name):
    """Env-flag lookup through the central flag table (paddle_tpu.flags;
    gflags semantics — '0'/'false'/'off'/'no' mean OFF). Flags must be
    registered there; the table is the single source of parsing truth."""
    from .. import flags
    return bool(flags.get_flag(name.replace("PADDLE_TPU_", "").lower()))


def _normalize_feeds(feed, accum_steps=1, plan_cache=None):
    """LoDTensor/array feeds → (feed_arrays, static_info).

    Sequence (LoD) feeds become FLAT row buffers + ``<name>@LOD`` length
    vectors, with the flat total BUCKETED to the next power of two (zero
    pad rows at the tail). Bucketing keeps the compiled-step signature
    stable across batches whose token totals differ — without it every
    batch of a text model recompiles (the shape-key design of SURVEY §7).
    Pad rows carry segment id N (out of range), which every lengths-aware
    sequence op drops (jax segment_* ignore out-of-range ids; packers mask
    by lengths). Programs that apply a raw elementwise reduction straight
    over flat LoD rows should disable via PADDLE_TPU_LOD_BUCKETING=0.
    static_info additionally carries ``<name>@MAXLEN`` — the bucketed max
    per-sequence length that bounds scan depth in the RNN packers.

    accum_steps > 1: LoD feeds are pre-split HOST-SIDE into that many
    microbatches of equal SEQUENCE count (the ragged split is
    data-dependent, so it cannot happen inside the jit): the flat buffer
    becomes [k, bucket, ...] (every microbatch zero-padded to one shared
    bucketed total) and the lengths [k, n_seqs/k]; static_info marks the
    feed ``<name>@ACCUM_LOD`` so the accumulation scan indexes
    microbatch i instead of reshape-chunking a dense batch dim.

    ``plan_cache`` (a FeedPlanCache) skips the derivation on repeated
    feed signatures — the fix for the measured per-call re-marshal tax
    of the in-process serving path (PERF.md round 5).
    """
    if plan_cache is not None and _flag_on("PADDLE_TPU_FEED_PLAN_CACHE"):
        return plan_cache.normalize(feed, accum_steps)
    return _apply_feed_plan(_derive_feed_plan(feed, accum_steps), feed,
                            None)


class _FeedPlan:
    """One cached _normalize_feeds derivation: the per-feed transform
    instructions, the trace-time static_info, the derived length
    vectors (valid because the LoD lengths are part of the cache key),
    and any committed device buffers."""

    __slots__ = ("instrs", "static_info", "lods", "buffers")

    def __init__(self):
        self.instrs = []       # (kind, feed_name, *params)
        self.static_info = {}
        self.lods = {}         # @LOD / @ACCUM_TOKENS arrays
        self.buffers = {}      # feed_name -> (source obj, device array)


def _derive_feed_plan(feed, accum_steps=1):
    """Full normalization derivation (the feed-plan cache MISS path);
    see _normalize_feeds for the semantics each instruction encodes."""
    plan = _FeedPlan()
    bucket_on = _flag_on("PADDLE_TPU_LOD_BUCKETING")
    k_acc = max(1, int(accum_steps))
    for k, v in feed.items():
        if isinstance(v, LoDTensor):
            if v.lod:
                arr = v.data
                # sequence ops consume per-sequence LENGTHS (not offsets)
                lengths = np.asarray(
                    v.recursive_sequence_lengths()[-1], np.int32)
                mx = max(1, int(lengths.max(initial=1)))
                plan.static_info[k + "@MAXLEN"] = 1 << (mx - 1).bit_length()
                if k_acc > 1:
                    if len(lengths) % k_acc:
                        raise ValueError(
                            "feed %r has %d sequences, not divisible "
                            "into %d accumulation microbatches"
                            % (k, len(lengths), k_acc))
                    per = len(lengths) // k_acc
                    offs = np.concatenate(
                        [[0], np.cumsum(lengths)]).astype(np.int64)
                    totals = [int(offs[(g + 1) * per] - offs[g * per])
                              for g in range(k_acc)]
                    bucket = max(1, max(totals))
                    if bucket_on:
                        bucket = 1 << max(0, int(bucket - 1).bit_length())
                    plan.lods[k + "@LOD"] = lengths.reshape(k_acc, per)
                    # true (pre-bucket) token totals per microbatch: the
                    # loss-normalization weights for ragged accumulation
                    # (runtime VALUES, not trace constants — same shape
                    # every batch, so the compile cache stays stable)
                    plan.lods[k + "@ACCUM_TOKENS"] = np.asarray(
                        totals, np.float32)
                    plan.static_info[k + "@ACCUM_LOD"] = True
                    plan.instrs.append(("lod_accum", k, bucket, offs,
                                        per, totals))
                else:
                    plan.lods[k + "@LOD"] = lengths
                    total = int(arr.shape[0])
                    bucket = 1 << max(0, int(total - 1).bit_length())
                    pad_to = bucket if (bucket_on and bucket > total) \
                        else None
                    plan.instrs.append(("lod_pad", k, pad_to))
            else:
                plan.instrs.append(("lod_data", k))
        else:
            plan.instrs.append(("dense", k))
    from .. import monitor as _mon
    _mon.on_feed_plan(False)
    return plan


def _apply_feed_plan(plan, feed, cache):
    """Run a plan's mechanical transforms over THIS call's values."""
    feed_arrays = {}
    for instr in plan.instrs:
        kind, k = instr[0], instr[1]
        v = feed[k]
        if kind == "dense":
            if isinstance(v, jax.Array):
                feed_arrays[k] = v
                continue
            arr = np.asarray(v)
            dev = cache._committed(plan, k, v, arr) \
                if cache is not None else None
            feed_arrays[k] = arr if dev is None else dev
        elif kind == "lod_data":
            feed_arrays[k] = v.data
        elif kind == "lod_pad":
            arr, pad_to = v.data, instr[2]
            if pad_to is not None:
                pad = np.zeros((pad_to - arr.shape[0],) + arr.shape[1:],
                               arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
            feed_arrays[k] = arr
        else:                  # lod_accum
            _, _, bucket, offs, per, totals = instr
            arr = v.data
            stacked = np.zeros((len(totals), bucket) + arr.shape[1:],
                               arr.dtype)
            for g in range(len(totals)):
                stacked[g, :totals[g]] = \
                    arr[offs[g * per]:offs[(g + 1) * per]]
            feed_arrays[k] = stacked
    feed_arrays.update(plan.lods)
    return feed_arrays, dict(plan.static_info)


class FeedPlanCache:
    """Zero-copy host feed path: cached normalization plans + committed
    device feed buffers, keyed by feed signature (names, shapes, dtypes,
    LoD lengths, accumulation split, bucketing flag).

    Fixes the measured in-process serving re-marshal (PERF.md round 5:
    the pure-C predictor loop beat the python path because the latter
    re-ran _normalize_feeds + a fresh transfer every call): on a plan
    HIT only the mechanical per-call work runs. A dense feed value is
    additionally COMMITTED to a device buffer and reused zero-copy when
    it is the SAME numpy object as last call with ``writeable=False``
    (freeze with ``arr.flags.writeable = False``). Freezing is the
    caller's CONTRACT that the contents are final: numpy does allow an
    owning array to re-enable writeable, mutate, and re-freeze — doing
    that serves the stale committed buffer, exactly like mutating a
    buffer handed to any zero-copy API. Plain writeable feeds are never
    committed, so ordinary in-place mutation between calls stays
    correct. Values that are already jax.Arrays are inherently
    zero-copy.

    Counters: ``ptpu_feed_normalizations_total`` ticks per derivation,
    ``ptpu_feed_plan_hits_total`` per skipped one (monitor registry);
    instance fields ``hits/misses/buffer_reuses`` serve tests."""

    def __init__(self, capacity=64, device_fn=None):
        import collections
        import threading
        self._plans = collections.OrderedDict()
        self._lock = threading.Lock()
        self._capacity = int(capacity)
        self._device_fn = device_fn    # lazy: resolving may init jax
        self.hits = 0
        self.misses = 0
        self.buffer_reuses = 0

    def normalize(self, feed, accum_steps=1):
        key = self._key(feed, accum_steps)
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None:
                self._plans.move_to_end(key)
                self.hits += 1
        if plan is None:
            plan = _derive_feed_plan(feed, accum_steps)  # ticks the miss
            with self._lock:
                self.misses += 1
                self._plans[key] = plan
                while len(self._plans) > self._capacity:
                    self._plans.popitem(last=False)
        else:
            from .. import monitor as _mon
            _mon.on_feed_plan(True)
        return _apply_feed_plan(plan, feed, self)

    @staticmethod
    def _key(feed, accum_steps):
        from .. import flags
        items = []
        for k, v in feed.items():
            if isinstance(v, LoDTensor):
                if v.lod:
                    items.append(
                        (k, "lod", tuple(v.data.shape), str(v.data.dtype),
                         tuple(v.recursive_sequence_lengths()[-1])))
                else:
                    items.append((k, "lodd", tuple(v.data.shape),
                                  str(v.data.dtype)))
            else:
                dt = getattr(v, "dtype", None)
                items.append(
                    (k, "d", tuple(np.shape(v)),
                     str(dt) if dt is not None
                     else str(np.asarray(v).dtype)))
        return (int(accum_steps), bool(flags.get_flag("lod_bucketing")),
                tuple(sorted(items)))

    def _committed(self, plan, name, src, arr):
        """Device buffer for a frozen dense feed, reused by identity;
        None = not committable (writeable, or no device binding)."""
        if not isinstance(arr, np.ndarray) or arr.flags.writeable \
                or self._device_fn is None:
            return None
        ent = plan.buffers.get(name)
        if ent is not None and ent[0] is src:
            with self._lock:
                self.buffer_reuses += 1
            return ent[1]
        try:
            dev = jax.device_put(arr, self._device_fn())
        except Exception:
            return None            # advisory: fall back to the host array
        plan.buffers[name] = (src, dev)
        return dev

    def clear(self):
        with self._lock:
            self._plans.clear()


def _stack_step_feeds(feeds, plan_cache=None):
    """Normalize K per-step feed dicts and stack them into the
    ``[k, ...]`` megastep staging layout ``run_steps`` scans in-graph.

    Every per-step feed must land on ONE compiled-step signature (same
    names/shapes/dtypes and the same trace-time static_info): the scan
    body is compiled once, so a step whose bucketed LoD total or MAXLEN
    bucket differs cannot share the megastep. That is checked here with
    a loud error instead of a shape mismatch inside the scan. LoD feeds
    are supported — each step's flat buffer/length vectors normalize
    through the (shared) feed-plan cache exactly as ``run()`` would,
    then stack; only the PRE-STACKED staging path (DeviceLoader
    ``megabatches``) excludes them.

    Returns (feeds_k, static_info, per_step_signature)."""
    normed = [_normalize_feeds(dict(f or {}), plan_cache=plan_cache)
              for f in feeds]
    arrays0, info0 = normed[0]
    sig0 = _feed_signature(arrays0)
    for i, (arrays_i, info_i) in enumerate(normed[1:], 1):
        if _feed_signature(arrays_i) != sig0 or info_i != info0:
            raise ValueError(
                "run_steps feeds must share ONE compiled-step "
                "signature (the megastep scan body is compiled once): "
                "feed %d normalizes to %s / static %s, feed 0 to %s / "
                "static %s. Pad or re-bucket the odd batch, or run() "
                "it separately." % (i, _feed_signature(arrays_i),
                                    sorted(info_i.items()), sig0,
                                    sorted(info0.items())))
    feeds_k = {}
    for name in arrays0:
        vals = [a[name] for a, _ in normed]
        if all(isinstance(v, jax.Array) for v in vals):
            feeds_k[name] = jnp.stack(vals)
        else:
            feeds_k[name] = np.stack([np.asarray(v) for v in vals])
    return feeds_k, dict(info0), sig0


def _stage_prestacked_feeds(feeds, k):
    """Validate a pre-stacked ``[k, ...]`` staging dict (the
    DeviceLoader ``megabatches`` layout). Dense arrays only: a
    LoDTensor's normalization needs trace-time static_info only the
    per-step host path can derive, so it gets a clear error here
    instead of a shape mismatch inside the scan."""
    feeds_k = {}
    for name, v in feeds.items():
        if isinstance(v, LoDTensor):
            raise ValueError(
                "LoD feed %r cannot ride the pre-stacked [k, ...] "
                "megastep staging path: its flat/bucketed form and "
                "@LOD/@MAXLEN static_info must be derived per step by "
                "the executor's own normalization. Pass run_steps a "
                "LIST of per-step feed dicts instead (the host path "
                "normalizes and stacks LoD feeds correctly)." % name)
        arr = v if isinstance(v, jax.Array) else np.asarray(v)
        if getattr(arr, "ndim", 0) < 1 or arr.shape[0] != k:
            raise ValueError(
                "pre-stacked megastep feed %r must have leading dim "
                "k=%d, got shape %s" % (name, k, np.shape(arr)))
        feeds_k[name] = arr
    sig = tuple(sorted((n, tuple(np.shape(v)[1:]), str(v.dtype))
                       for n, v in feeds_k.items()))
    return feeds_k, {}, sig


def as_numpy(value):
    """Convert a fetched value (jax.Array / LoDTensor / list) to numpy."""
    from .selected_rows import SelectedRows
    if isinstance(value, (LoDTensor, SelectedRows)):
        return value  # structured values pass through
    if isinstance(value, (list, tuple)):
        return [as_numpy(v) for v in value]
    return np.asarray(value)


def _feed_signature(feed):
    return tuple(sorted(
        (k, tuple(np.shape(v)), str(np.asarray(v).dtype) if not hasattr(v, "dtype") else str(v.dtype))
        for k, v in feed.items()))


class Executor:
    """Single-device executor (CPU or one TPU chip).

    Multi-device execution is ParallelExecutor (paddle_tpu/parallel/),
    which shards the same traced step over a jax Mesh.
    """

    def __init__(self, place=None):
        if place is None:
            place = _default_place()
        if not isinstance(place, Place):
            raise TypeError("place must be a Place, got %r" % (place,))
        self.place = place
        self._cache = {}          # cache key -> (jitted fn, state_keys, static info)
        # zero-copy host feed path: repeated-shape run() calls skip the
        # per-call normalization derivation and reuse committed device
        # buffers (PERF.md round-5 in-process serving re-marshal fix)
        self._feed_plans = FeedPlanCache(device_fn=self.place.jax_device)
        self._rng_counter = 0
        import uuid
        import weakref
        # per-PROGRAM step counters for host-op send tags (retry
        # idempotency): another host-op program run on this executor
        # (e.g. an eval recv) must not advance a training program's
        # round sequence. Entry: program -> [seq, program_nonce].
        self._run_seqs = weakref.WeakKeyDictionary()
        # incarnation id: a RESTARTED trainer's seq restarts at 0 —
        # servers evict pending grads from the dead incarnation by it.
        # The 16-hex-digit time_ns prefix ORDERS incarnations, so a
        # server can drop a dead incarnation's straggler (its epoch is
        # below the replacement's) instead of letting it evict the live
        # replacement's pending state; the nonce suffix breaks ties.
        import time as _time
        self._incarnation = ("%016x" % _time.time_ns()
                             + uuid.uuid4().hex[:8])

    def _reincarnate(self, min_epoch):
        """A pserver judged our incarnation stale (possible after an
        elastic reschedule onto a host whose clock is behind the old
        one): mint a new incarnation with an epoch past the server's
        max, so retried sends are accepted instead of deadlocking."""
        import time as _time
        import uuid
        epoch = max(_time.time_ns(), int(min_epoch) + 1)
        self._incarnation = "%016x" % epoch + uuid.uuid4().hex[:8]
        return self._incarnation

    # ------------------------------------------------------------------
    def close(self):
        self._cache.clear()
        plans = getattr(self, "_feed_plans", None)  # __new__-built exe
        if plans is not None:
            plans.clear()

    def run(self, program=None, feed=None, fetch_list=None,
            feed_var_name="feed", fetch_var_name="fetch", scope=None,
            return_numpy=True, use_program_cache=True):
        trc = _trc._TRACER
        if trc is None:
            return self._run_impl(program, feed, fetch_list,
                                  feed_var_name, fetch_var_name, scope,
                                  return_numpy, use_program_cache)
        # distributed-trace root span per step: RPC verb spans issued
        # while this step runs (pserver sends/gets, prefetches) nest
        # under it, making the step the unit of the fleet timeline
        with trc.span("exe.step"):
            return self._run_impl(program, feed, fetch_list,
                                  feed_var_name, fetch_var_name, scope,
                                  return_numpy, use_program_cache)

    # -- megastep execution (ISSUE 7) ----------------------------------
    def run_steps(self, program=None, feeds=None, fetch_list=None,
                  scope=None, return_numpy=True, k=None,
                  use_program_cache=True):
        """K logical steps in ONE device dispatch (the megastep path).

        The per-step body ``run()`` compiles — forward, backward AND
        optimizer/persistable-state update — is scanned (``lax.scan``)
        over K batches, so one host dispatch advances K real training
        steps; per-step fetches (losses, NaN guards, fetch LoDs) stream
        out of the scan. The contract is numeric identity with K
        sequential ``run()`` calls on the same feeds (same per-step RNG
        stream included) — pinned in tests/test_megastep.py.

        ``feeds``: either a LIST of K per-step feed dicts (LoD feeds
        supported; each normalizes through the feed-plan cache and all
        K must share one signature), or ONE pre-stacked ``[k, ...]``
        dict (the DeviceLoader ``megabatches`` staging layout; dense
        only) together with ``k``.

        Returns a list of K per-step fetch lists. With
        ``return_numpy=False`` the fetches stay device-resident and the
        dispatch is ASYNC: up to ``PADDLE_TPU_MEGASTEP_INFLIGHT``
        (default 2 = double buffering) megastep dispatches may be in
        flight before the next call blocks on the oldest, so the host
        feed of megastep N+1 overlaps device compute of megastep N.

        Semantic differences vs K sequential runs, by design: NaN
        guards are checked after the whole dispatch (the error names
        the first failing logical step, but state has advanced all K
        steps), and programs with host (IO) ops or newly-materialized
        persistables (startup programs) are rejected — run() those."""
        feeds, k = self._check_run_steps_args(feeds, k)
        trc = _trc._TRACER
        if trc is None:
            return self._run_steps_impl(program, feeds, fetch_list,
                                        scope, return_numpy, k,
                                        use_program_cache)
        with trc.span("exe.step", k=k):
            return self._run_steps_impl(program, feeds, fetch_list,
                                        scope, return_numpy, k,
                                        use_program_cache)

    @staticmethod
    def _check_run_steps_args(feeds, k):
        if isinstance(feeds, dict):
            if k is None:
                raise ValueError(
                    "run_steps(feeds=<pre-stacked dict>) needs k= (the "
                    "leading staging dim); pass a list of per-step "
                    "feed dicts to infer it")
            k = int(k)
        else:
            feeds = list(feeds or [])
            if k is not None and int(k) != len(feeds):
                raise ValueError(
                    "run_steps got k=%r but %d per-step feeds"
                    % (k, len(feeds)))
            k = len(feeds)
        if k < 1:
            raise ValueError("run_steps needs k >= 1, got %d" % k)
        return feeds, k

    def _run_steps_impl(self, program, feeds, fetch_list, scope,
                        return_numpy, k, use_program_cache):
        import time as _time
        program = program or default_main_program()
        fetch_list = list(fetch_list or [])
        scope = scope or global_scope()
        fetch_names = tuple(
            f.name if isinstance(f, Variable) else str(f)
            for f in fetch_list)
        if any(registry.is_host_op(o.type)
               for o in program.global_block().ops):
            raise NotImplementedError(
                "run_steps cannot fuse programs with host (IO) ops — "
                "send/recv/prefetch must hit the wire once per step; "
                "use run() per step")
        if isinstance(feeds, dict):
            feeds_k, static_info, sig = _stage_prestacked_feeds(feeds, k)
        else:
            feeds_k, static_info, sig = _stack_step_feeds(
                feeds, plan_cache=getattr(self, "_feed_plans", None))

        persistable = [v.name
                       for v in program.global_block().vars.values()
                       if v.persistable]
        state = {n: scope.find_var(n) for n in persistable
                 if scope.find_var(n) is not None}
        state_keys = tuple(sorted(state))

        from ..amp import amp_enabled
        from ..flags import get_flag
        check_nan = _flag_on("PADDLE_TPU_CHECK_NAN_INF")
        key = ("megastep", k, program, program._version, sig,
               fetch_names, state_keys, amp_enabled(), check_nan,
               get_flag("fuse_conv_bn"),
               tuple(sorted(static_info.items())))
        from .. import monitor as _mon
        mon_on = _mon.enabled()
        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            mega = self._build_megastep(program, tuple(sorted(feeds_k)),
                                        fetch_names, state_keys,
                                        static_info, check_nan, k)
            entry = jax.jit(mega, donate_argnums=(0,))
            if use_program_cache:
                self._cache[key] = entry
            if mon_on and use_program_cache:
                rng0 = jax.vmap(jax.random.key)(
                    jnp.zeros((k,), jnp.uint32))
                _mon.on_compile(
                    program, key, key[4],
                    cost_fn=lambda: _step_costs_safe(
                        mega, dict(state), dict(feeds_k), rng0),
                    tokens=_mon.tokens_in_feeds(feeds_k))
        elif mon_on:
            _mon.on_cache_hit()

        # one RNG stream position per LOGICAL step — the same
        # derivation run() uses, so megastep output is bitwise equal to
        # K sequential run() calls (dropout masks included)
        base = program.random_seed * 1000003 + self._rng_counter
        self._rng_counter += k
        keys = jax.vmap(jax.random.key)(jnp.asarray(
            [np.uint32(base + i) for i in range(k)]))

        window = max(1, int(get_flag("megastep_inflight")))
        inflight = self.__dict__.setdefault("_inflight", [])
        while len(inflight) >= window:
            # double-buffer window full: the OLDEST dispatch must
            # retire before another joins the pipeline
            jax.block_until_ready(inflight.pop(0))

        t0 = _time.perf_counter() if mon_on else 0.0
        if mon_on:
            timer = _mon.step_timer(self)
            do_sync = timer.begin(t0)
        with jax.default_device(self.place.jax_device()):
            fetches_k, new_state, guards_k, lods_k = entry(
                state, feeds_k, keys)
        if mon_on:
            fb = _mon.feed_nbytes(feeds_k)
            tk = _mon.tokens_in_feeds(feeds_k)
            if do_sync:
                jax.block_until_ready(fetches_k)
                _mon.on_megastep(
                    key, timer.end_synced(_time.perf_counter(), t0), k,
                    feed_bytes=fb, tokens=tk)
            else:
                _mon.on_megastep(key, _time.perf_counter() - t0, k,
                                 feed_bytes=fb, tokens=tk, synced=False)

        for n, v in new_state.items():
            scope.set(n, v)
        if check_nan:
            self._check_guards_steps(guards_k, k)
        out = self._split_step_fetches(fetch_names, fetches_k, lods_k,
                                       k, return_numpy)
        if check_nan:
            for i, fi in enumerate(out):
                self._check_nan_inf(fetch_names, fi)
        if not return_numpy:
            # async dispatch: hand back device handles and track the
            # un-fetched dispatch in the in-flight window
            inflight.append(fetches_k)
        return out

    def _build_megastep(self, program, feed_names, fetch_names,
                        state_keys, static_info, check_nan, k):
        """Wrap the compiled-step body in a lax.scan over K stacked
        batches: ONE compile unit keyed on K, one dispatch per K
        logical steps."""
        step = self._build(program, feed_names, fetch_names, state_keys,
                           static_info=static_info, check_nan=check_nan)

        def mega(state, feeds_k, keys):
            def body(carry, xs):
                feeds_i, key_i = xs
                fetches, new_state, guards, fetch_lods = step(
                    carry, feeds_i, key_i)
                extra = sorted(set(new_state) - set(carry))
                if extra:       # trace-time check, not a runtime branch
                    raise ValueError(
                        "run_steps: the program materializes new "
                        "persistable vars %s inside the step — the "
                        "scan carry pytree must be stable. run() the "
                        "startup/first step once, then megastep."
                        % extra)
                carry = {n: new_state[n] for n in carry}
                return carry, (fetches, guards, fetch_lods)

            final, (fetches_k, guards_k, lods_k) = jax.lax.scan(
                body, state, (feeds_k, keys))
            return fetches_k, final, guards_k, lods_k

        return mega

    @staticmethod
    def _split_step_fetches(fetch_names, fetches_k, lods_k, k,
                            return_numpy):
        """[k, ...]-stacked scan outputs → K per-step fetch lists, with
        per-step LoD bucket-pad trimming (the run() contract)."""
        out = []
        for i in range(k):
            fi = [f[i] for f in fetches_k]
            lodi = {n: v[i] for n, v in lods_k.items()}
            fi = Executor._trim_fetches(fetch_names, fi, lodi)
            out.append([as_numpy(v) for v in fi] if return_numpy
                       else fi)
        return out

    @staticmethod
    def _check_guards_steps(guards_k, k):
        """Per-logical-step NaN-guard check over the [k]-stacked guard
        outputs; names the FIRST failing step. Unlike K sequential
        runs, state has already advanced all K steps by the time this
        raises (documented run_steps semantics)."""
        if not guards_k:
            return
        guards_k = jax.device_get(guards_k)
        for i in range(k):
            try:
                Executor._check_guards(
                    {g: np.asarray(v)[i] for g, v in guards_k.items()})
            except FloatingPointError as e:
                raise FloatingPointError(
                    "%s (at megastep logical step %d of %d; state has "
                    "advanced the full megastep)" % (e, i, k)) from None

    def _run_impl(self, program, feed, fetch_list, feed_var_name,
                  fetch_var_name, scope, return_numpy,
                  use_program_cache):
        program = program or default_main_program()
        feed = dict(feed or {})
        fetch_list = list(fetch_list or [])
        scope = scope or global_scope()

        fetch_names = tuple(
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list)

        # Normalize feeds to arrays; remember LoD for LoDTensor feeds.
        # static_info carries trace-time constants derived host-side from
        # the feed — the per-feed BUCKETED max sequence length (next power
        # of two), which bounds in-graph padding at ~Tmax instead of the
        # total token count (the shape-key bucketing of SURVEY.md §7).
        feed_arrays, static_info = _normalize_feeds(
            feed, plan_cache=getattr(self, "_feed_plans", None))

        # State = persistable vars of this program that exist in scope.
        persistable = [v.name for v in program.global_block().vars.values()
                       if v.persistable]
        state = {n: scope.find_var(n) for n in persistable
                 if scope.find_var(n) is not None}
        state_keys = tuple(sorted(state))

        # NB: the Program object itself is part of the key (kept alive by the
        # cache) so id-reuse after GC can never alias two programs. The AMP
        # flag changes lowering, so it is part of the key too.
        # Programs containing host (IO) ops — send/recv/listen_and_serv —
        # run in eager-interpreter mode: each lowering executes immediately
        # on concrete values, so IO happens for real. This is the
        # reference's op-by-op interpreter, kept ONLY for the distributed
        # edge where the reference also left graph land.
        if any(registry.is_host_op(o.type)
               for o in program.global_block().ops):
            # the send-tag sequence advances only on SUCCESS and is
            # per-program: a retried step reuses its tag, so the server
            # replaces (not doubles) the pending grad — elastic-recovery
            # idempotency. The program nonce keeps two programs' tags
            # distinct (a second SENDING program of the same grad names
            # within one round is not supported).
            import uuid
            entry = self._run_seqs.get(program)
            if entry is None:
                entry = self._run_seqs.setdefault(
                    program, [0, uuid.uuid4().hex[:4]])
            # seq/incarnation travel as ARGUMENTS, not instance state:
            # a shared Executor driven from two threads must not
            # cross-tag rounds
            result = self._run_eager(
                program, feed_arrays, fetch_names, scope, static_info,
                return_numpy, run_seq=entry[0],
                incarnation=self._incarnation + entry[1])
            entry[0] += 1
            return result

        from ..amp import amp_enabled
        from ..flags import get_flag
        check_nan = _flag_on("PADDLE_TPU_CHECK_NAN_INF")
        # every toggle the lowering consults at trace time must key the
        # cache, or flipping it after a run silently reuses a stale trace
        key = (program, program._version, _feed_signature(feed_arrays),
               fetch_names, state_keys, amp_enabled(), check_nan,
               get_flag("fuse_conv_bn"),
               tuple(sorted(static_info.items())))
        from .. import monitor as _mon
        mon_on = _mon.enabled()
        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            fn = self._build(program, tuple(sorted(feed_arrays)), fetch_names,
                             state_keys, static_info, check_nan=check_nan)
            entry = jax.jit(fn, donate_argnums=(0,))
            if use_program_cache:
                self._cache[key] = entry
            if mon_on and use_program_cache:
                # price the step with the static cost model (traced once
                # here, at compile time) so per-step MFU is derivable;
                # classify the compile against this program's history.
                # use_program_cache=False is a DELIBERATE cache bypass —
                # counting each of its runs as a recompile would report
                # key churn that isn't there
                rng0 = jax.random.key(0)
                _mon.on_compile(
                    program, key, key[2],
                    cost_fn=lambda: _step_costs_safe(
                        fn, dict(state), dict(feed_arrays), rng0),
                    tokens=_mon.tokens_in_feeds(feed_arrays))
        elif mon_on:
            _mon.on_cache_hit()

        rng_key = jax.random.key(
            np.uint32(program.random_seed * 1000003 + self._rng_counter))
        self._rng_counter += 1

        import time as _time
        from .. import profiler as _prof
        t0 = _time.perf_counter() if mon_on else 0.0
        if mon_on:
            # monitor_sync_every=N amortization: sync once per N steps
            # so async dispatch pipelines keep pipelining; the synced
            # step reports the window-average as per-step latency
            timer = _mon.step_timer(self)
            # with the profiler on every step blocks anyway — keep the
            # already-paid exact latencies instead of window-averaging
            do_sync = timer.begin(t0) or _prof._enabled
        with jax.default_device(self.place.jax_device()):
            if _prof._enabled:
                # step-level event; sync INSIDE the event so the row
                # records real step time, not async dispatch; with
                # profile_memory on it also samples live/peak HBM per
                # compiled step (the step IS the op)
                with _prof.RecordEvent("exe.run(compiled)"):
                    fetches, new_state, guards, fetch_lods = entry(
                        state, feed_arrays, rng_key)
                    jax.block_until_ready(fetches)
            else:
                fetches, new_state, guards, fetch_lods = entry(
                    state, feed_arrays, rng_key)
                if mon_on and do_sync:
                    # sync inside the span: the histogram must record
                    # real step latency, not async dispatch time
                    jax.block_until_ready(fetches)
        if mon_on:
            now = _time.perf_counter()
            fb = _mon.feed_nbytes(feed_arrays)
            tk = _mon.tokens_in_feeds(feed_arrays)
            if do_sync:
                _mon.on_step(key, timer.end_synced(now, t0),
                             feed_bytes=fb, tokens=tk)
            else:
                _mon.on_step(key, now - t0, feed_bytes=fb, tokens=tk,
                             synced=False)
        fetches = self._trim_fetches(fetch_names, fetches, fetch_lods)

        # Commit updated persistable state back to the scope.
        for n, v in new_state.items():
            scope.set(n, v)
        # New persistable vars materialized by this run (e.g. startup program
        # initializers) are committed too — _build returns them in new_state.

        if check_nan:
            self._check_guards(guards)
            self._check_nan_inf(fetch_names, fetches)

        if return_numpy:
            return [as_numpy(v) for v in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _run_eager(self, program, feed_arrays, fetch_names, scope,
                   static_info, return_numpy, run_seq=None,
                   incarnation=None):
        """Execution path for programs containing host (IO) ops.

        The COMPUTE runs between host ops are jit-compiled per segment and
        cached (so a pserver-mode trainer's forward+backward is one XLA
        executable, not an op-by-op interpretation — the reference also
        only left graph land for the RPC ops themselves,
        listen_and_serv_op.cc); the host ops execute eagerly between
        segments on concrete values. Falls back to full op-by-op
        interpretation when a host op feeds the forward of a grad marker
        (autodiff must trace through it — e.g. the sparse prefetch path)
        or when PADDLE_TPU_SEGMENT_COMPILE=0."""
        import time as _time
        from .. import monitor as _mon
        mon_on = _mon.enabled()
        t0 = _time.perf_counter() if mon_on else 0.0
        block = program.global_block()
        ops = list(block.ops)
        persistable = {v.name for v in block.vars.values() if v.persistable}
        env = {n: scope.find_var(n) for n in persistable
               if scope.find_var(n) is not None}
        env.update(feed_arrays)

        counter = [0]
        base_key = jax.random.key(
            np.uint32(program.random_seed * 1000003 + self._rng_counter))
        self._rng_counter += 1

        def rng_fn():
            counter[0] += 1
            return jax.random.fold_in(base_key, counter[0])

        ctx = registry.LowerContext(env, rng_fn, executor=self, block=block,
                                    mesh=getattr(self, "_mesh", None),
                                    static_info=static_info,
                                    fetch_names=fetch_names)
        ctx.check_nan = _flag_on("PADDLE_TPU_CHECK_NAN_INF")
        ctx.run_seq = run_seq         # send-tag round id (host ops)
        ctx.incarnation = incarnation or self._incarnation
        bwd_idx = None
        for i, o in enumerate(ops):
            if o.type in ("backward_marker", "calc_gradient_marker"):
                bwd_idx = i
                break
        host_idx = [i for i, o in enumerate(ops)
                    if registry.is_host_op(o.type)]
        segmentable = (_flag_on("PADDLE_TPU_SEGMENT_COMPILE")
                       and (bwd_idx is None
                            or all(i > bwd_idx for i in host_idx)
                            or self._grad_leaves_concrete(ops, bwd_idx)))
        if segmentable:
            self._run_segments(ctx, ops, bwd_idx, program, block,
                               static_info, base_key, fetch_names)
        elif bwd_idx is None:
            for o in ops:
                _lower_op_eager(ctx, o)
        else:
            # interpreter path: pre-marker host ops that PRODUCE a wrt
            # name (prefetch leaves) must run eagerly FIRST — the grad
            # trace skips them and reads their outputs from base_env.
            # Run the minimal dependency slice: the host ops plus any
            # earlier op whose output (transitively) feeds their inputs
            # (e.g. a compute op producing the lookup ids).
            wrt_names, _ = self._parse_marker(ops[bwd_idx])
            wrt = set(wrt_names)
            pre = ops[:bwd_idx]
            run_ids = set()
            needed = set()
            for o in pre:
                if registry.is_host_op(o.type) and any(
                        n in wrt for ns in o.outputs.values() for n in ns):
                    run_ids.add(id(o))
                    needed.update(n for ns in o.inputs.values()
                                  for n in ns)
            for o in reversed(pre):
                if id(o) in run_ids:
                    continue
                if any(n in needed for ns in o.outputs.values()
                       for n in ns):
                    run_ids.add(id(o))
                    needed.update(n for ns in o.inputs.values()
                                  for n in ns)
            # RNG-stateful slice ops must NOT re-run inside the grad
            # trace: the re-traced draw would diverge from the ids the
            # prefetch actually fetched, mispairing rows and gradients.
            # Track which ops drew from the stream and bind their eager
            # outputs as constants in the trace instead.
            rng_ops = set()
            for o in pre:
                if id(o) in run_ids:
                    drawn = counter[0]
                    _lower_op(ctx, o)
                    if counter[0] != drawn:
                        rng_ops.add(id(o))
            self._lower_with_grad(ctx, ops, bwd_idx, program, block,
                                  skip_op_ids=rng_ops)

        for n in persistable:
            if n in env:
                scope.set(n, env[n])
        if ctx.check_nan:
            self._check_guards(
                {k: v for k, v in env.items() if k.startswith(_NANGUARD)})
        fetches = [_fetch_from_env(env, n) for n in fetch_names]
        fetch_lods = {n: env[n + "@LOD"] for n in fetch_names
                      if env.get(n + "@LOD") is not None}
        fetches = self._trim_fetches(fetch_names, fetches, fetch_lods)
        if mon_on:
            # host-op (distributed trainer) step: no cached-step key, so
            # no MFU — latency/throughput telemetry still lands
            _mon.on_step(None, _time.perf_counter() - t0,
                         feed_bytes=_mon.feed_nbytes(feed_arrays),
                         tokens=_mon.tokens_in_feeds(feed_arrays),
                         executor="eager")
        if return_numpy:
            return [as_numpy(v) for v in fetches]
        return fetches

    # ------------------------------------------------------------------
    @staticmethod
    def _is_jit_value(v):
        return isinstance(v, (jax.Array, np.ndarray, np.generic))

    @staticmethod
    def _grad_leaves_concrete(ops, bwd_idx):
        """True when host ops BEFORE the grad marker cannot break gradient
        flow, so the step is still segment-compilable: every marker wrt
        name must enter the marker's compute segment as a concrete leaf
        (a parameter from the scope, or the output of a host op like
        ``prefetch``). If any op at or before the last pre-marker host op
        CONSUMES a wrt name — or a compute op PRODUCES one there — the
        chain from that wrt to the loss would cross a segment boundary
        and its gradient would silently be wrong → not segmentable.

        This is what lifts the full-eager fallback for the distributed
        sparse-embedding path (prefetch → fwd+bwd → sparse send): the
        prefetched rows are a differentiable leaf of the compiled
        segment, exactly like the reference's trainer treats the rows
        fetched from the pserver (distribute_transpiler.py:201-255)."""
        host_before = [i for i in range(bwd_idx)
                       if registry.is_host_op(ops[i].type)]
        if not host_before:
            return True
        h_last = max(host_before)
        wrt_names, _ = Executor._parse_marker(ops[bwd_idx])
        wrt = set(wrt_names)
        for o in ops[:h_last + 1]:
            ins = {n for ns in o.inputs.values() for n in ns}
            if ins & wrt:
                return False
            outs = {n for ns in o.outputs.values() for n in ns}
            if (outs & wrt) and not registry.is_host_op(o.type):
                return False
        return True

    def _run_segments(self, ctx, ops, bwd_idx, program, block, static_info,
                      base_key, fetch_names=()):
        """Interleave jit-compiled compute segments with eager host ops.

        Precondition (checked by the caller): any grad marker precedes the
        first host op, so each compute segment is traceable in isolation.
        A compute segment whose inputs include a non-array value (e.g. a
        SelectedRows produced by a host op) drops to eager for that
        segment only."""
        # greedy partition into ("host", [op]) / ("compute", [ops...])
        segments = []
        for i, o in enumerate(ops):
            kind = "host" if registry.is_host_op(o.type) else "compute"
            if segments and segments[-1][0] == kind == "compute":
                segments[-1][1].append((i, o))
            else:
                segments.append((kind, [(i, o)]))

        # names each segment touches, and what must survive PAST each
        # segment (later segments' refs + fetches + persistable state +
        # grad names the marker binds) — the jitted segment returns only
        # those, so XLA does not materialize every intermediate as output
        def _names(o):
            out = set()
            for coll in (o.inputs, o.outputs):
                for ns in coll.values():
                    out.update(ns)
            return out

        seg_names = [set().union(*(_names(o) for _, o in idx_ops))
                     for _, idx_ops in segments]
        persistable = {v.name for v in block.vars.values() if v.persistable}
        keep = set(fetch_names) | persistable
        keep |= {n + "@GRAD" for n in keep}
        needed_after = []
        acc = set(keep)
        for names in reversed(seg_names):
            needed_after.append(set(acc))
            acc |= names
        needed_after.reverse()

        check_nan = getattr(ctx, "check_nan", False)
        from ..amp import amp_enabled
        for seg_no, (kind, idx_ops) in enumerate(segments):
            if kind == "host":
                for _, o in idx_ops:
                    _lower_op_eager(ctx, o)
                continue
            seg_ops = [o for _, o in idx_ops]
            start = idx_ops[0][0]
            rel_bwd = None
            if bwd_idx is not None and start <= bwd_idx:
                for j, o in enumerate(seg_ops):
                    if o.type in ("backward_marker",
                                  "calc_gradient_marker"):
                        rel_bwd = j
                        break
            # a segment touches its ops' inputs AND outputs (outputs that
            # pre-exist in env: params being updated, feed-op targets),
            # plus the @LOD companions sequence lowerings read implicitly
            refs = {n for o in seg_ops
                    for coll in (o.inputs, o.outputs)
                    for ns in coll.values() for n in ns}
            refs |= {n + "@LOD" for n in refs}
            refs = {n for n in refs if n in ctx.env}
            if any(not self._is_jit_value(ctx.env[n]) for n in refs):
                ctx._nan_idx = start
                if rel_bwd is None:
                    for _, o in idx_ops:
                        _lower_op(ctx, o)
                else:
                    self._lower_with_grad(ctx, seg_ops, rel_bwd, program,
                                          block)
                continue
            array_env = {k: ctx.env[k] for k in refs}
            sig = tuple(sorted((k, tuple(np.shape(v)), str(v.dtype))
                               for k, v in array_env.items()))
            key = ("segment", program, program._version, seg_no, sig,
                   check_nan, amp_enabled(),
                   tuple(sorted(static_info.items())))
            entry = self._cache.get(key)
            if entry is None:
                needed = needed_after[seg_no]

                def seg_fn(array_env, rng_key, _rel_bwd=rel_bwd,
                           _seg_ops=seg_ops, _start=start, _needed=needed):
                    n_splits = [0]

                    def seg_rng():
                        n_splits[0] += 1
                        return jax.random.fold_in(rng_key, n_splits[0])

                    env = dict(array_env)
                    sctx = registry.LowerContext(
                        env, seg_rng, executor=self, block=block,
                        mesh=getattr(self, "_mesh", None),
                        static_info=static_info,
                        fetch_names=getattr(ctx, "fetch_names", ()))
                    sctx.check_nan = check_nan
                    sctx._nan_idx = _start   # program-order guard keys
                    if _rel_bwd is None:
                        for o in _seg_ops:
                            _lower_op(sctx, o)
                    else:
                        self._lower_with_grad(sctx, _seg_ops, _rel_bwd,
                                              program, block)
                    return {k: v for k, v in env.items()
                            if self._is_jit_value(v)
                            and (k in _needed
                                 or k.startswith(_NANGUARD)
                                 or (k.endswith("@LOD")
                                     and k[:-4] in _needed))}

                entry = self._cache[key] = jax.jit(seg_fn)
            seg_key = jax.random.fold_in(base_key, 1000 + seg_no)
            ctx.env.update(entry(array_env, seg_key))

    # ------------------------------------------------------------------
    def _build(self, program, feed_names, fetch_names, state_keys,
               static_info=None, check_nan=False, accum_steps=1,
               accum_loss_norm=None):
        """Build the pure step function for one (program, signature).

        accum_steps > 1: GRADIENT ACCUMULATION — the feed batch is split
        into that many microbatches, fwd+bwd runs as a lax.scan over them
        accumulating mean grads (and streaming persistable-state updates,
        e.g. batch-norm counters), then the optimizer ops apply once.
        In-graph, so one XLA executable per step regardless of
        accum_steps. Requires a grad marker; LoD feeds are supported via
        the host-side [k, ...] pre-split (_normalize_feeds). Only
        targets and persistables are fetchable (microbatch intermediates
        never leave the scan)."""
        # armed program transform (PADDLE_TPU_TRANSFORM=1): the pass
        # pipeline rewrites a CLONE and the trace below builds from it,
        # while the compile-cache key stays the caller's program +
        # version — a cache hit never re-transforms, and a transformed
        # program recompile is classified by the monitor via the
        # clone's _transform_meta (new program_version), not
        # mystery-counted. Disarmed cost: one flag check.
        from ..transform.passes import maybe_transform_for_build
        program = maybe_transform_for_build(program, fetch_names)
        static_info = static_info or {}
        block = program.global_block()
        ops = list(block.ops)
        persistable_names = {v.name for v in block.vars.values()
                             if v.persistable}

        bwd_idx = None
        for i, op in enumerate(ops):
            if op.type in ("backward_marker", "calc_gradient_marker"):
                bwd_idx = i
                break
        if accum_steps > 1:
            if bwd_idx is None:
                raise ValueError(
                    "gradient_accumulation_steps=%d needs a grad marker "
                    "(append_backward/minimize) in the program"
                    % accum_steps)
            if ops[bwd_idx].type != "backward_marker":
                # calc_gradient targets are SUM-reduced with unit
                # cotangents; microbatch-mean accumulation would change
                # both scale and (for non-scalar targets) shape
                raise NotImplementedError(
                    "gradient accumulation supports loss training "
                    "(append_backward) only, not calc_gradient")
            # LoD feeds arrive pre-split host-side ([k, ...] stacked by
            # _normalize_feeds(accum_steps=k)) and are scanned by index
            # — see static_info @ACCUM_LOD in _lower_with_grad_accum

        def step(state, feeds, rng_key):
            n_splits = [0]

            def rng_fn():
                n_splits[0] += 1
                return jax.random.fold_in(rng_key, n_splits[0])

            env = {}
            env.update(state)
            env.update(feeds)
            ctx = registry.LowerContext(env, rng_fn, executor=self,
                                        block=block,
                                        mesh=getattr(self, "_mesh", None),
                                        static_info=static_info,
                                        fetch_names=fetch_names)
            ctx.check_nan = check_nan
            if accum_steps > 1:
                self._lower_with_grad_accum(ctx, ops, bwd_idx, block,
                                            feeds, accum_steps,
                                            persistable_names,
                                            loss_norm=accum_loss_norm)
            elif bwd_idx is None:
                for op in ops:
                    _lower_op(ctx, op)
            else:
                self._lower_with_grad(ctx, ops, bwd_idx, program, block)

            fetches = tuple(_fetch_from_env(env, n) for n in fetch_names)
            new_state = {n: env[n] for n in state_keys if n in env}
            # newly-created persistable values (startup initializers)
            for n in persistable_names:
                if n not in new_state and n in env \
                        and not n.startswith(_NANGUARD):
                    new_state[n] = env[n]
            guards = {k: v for k, v in env.items() if k.startswith(_NANGUARD)}
            # per-fetch LoD lengths: the caller trims bucket-pad rows off
            # LoD-carrying fetches host-side (flat totals are bucketed, see
            # _normalize_feeds)
            fetch_lods = {n: env[n + "@LOD"] for n in fetch_names
                          if env.get(n + "@LOD") is not None}
            return fetches, new_state, guards, fetch_lods

        return step

    @staticmethod
    def _trim_fetches(fetch_names, fetches, fetch_lods):
        """Slice bucket-pad rows off fetched LoD values (true total =
        sum of the value's sequence lengths)."""
        if not fetch_lods:
            return list(fetches)
        out = []
        for n, v in zip(fetch_names, fetches):
            lod = fetch_lods.get(n)
            if lod is not None and getattr(v, "ndim", 0) >= 1:
                total = int(np.sum(np.asarray(lod)))
                if v.shape[0] > total:
                    v = v[:total]
            out.append(v)
        return out

    @staticmethod
    def _parse_marker(marker):
        """Grad-marker attrs → (wrt_names, target_names)."""
        if marker.type == "backward_marker":
            return (marker.attr("param_names") or [],
                    [marker.attr("loss_name")])
        # calc_gradient_marker
        return (marker.attr("input_names") or [],
                marker.attr("target_names") or [])

    @staticmethod
    def _lower_with_grad(ctx, ops, bwd_idx, program, block,
                         skip_op_ids=frozenset()):
        """Trace forward ops under value_and_grad, bind param@GRAD vars, then
        trace the remaining (optimizer) ops.

        ``append_backward(..., checkpoint=True)`` wraps the WHOLE forward
        in jax.checkpoint: only the step inputs are saved and the forward
        re-runs during the backward pass (maximal memory saving, ~1.33x
        forward FLOPs). In that mode only targets, persistables, @LOD
        lengths and guards survive the forward — fetching another forward
        intermediate would defeat the remat, so it raises a KeyError at
        fetch. Per-layer granularity is ``layers.recompute()``."""
        marker = ops[bwd_idx]
        wrt_names, target_names = Executor._parse_marker(marker)
        base_env = dict(ctx.env)
        wrt = {n: base_env[n] for n in wrt_names if n in base_env}
        use_ckpt = bool(marker.attr("checkpoint")) \
            if marker.type == "backward_marker" else False
        persistable = {v.name for v in block.vars.values()
                       if v.persistable}
        # post-marker (optimizer) ops may read forward intermediates —
        # computed learning-rate chains — so those survive the keep filter
        post_in = {n for op in ops[bwd_idx + 1:]
                   for ns in op.inputs.values() for n in ns}

        def forward(params):
            env = dict(base_env)
            env.update(params)
            fctx = registry.LowerContext(env, ctx._rng_fn,
                                         is_test=ctx.is_test,
                                         executor=ctx.executor, block=block,
                                         mesh=ctx.mesh,
                                         static_info=ctx.static_info,
                                         fetch_names=getattr(
                                             ctx, "fetch_names", ()))
            fctx.check_nan = getattr(ctx, "check_nan", False)
            wrt_set = set(wrt_names)
            for op in ops[:bwd_idx]:
                # a host op (e.g. prefetch) that PRODUCES a wrt name is a
                # gradient LEAF — its value is already bound as a param;
                # re-running it would overwrite the tracer with a concrete
                # value and silently zero that gradient
                if registry.is_host_op(op.type) and any(
                        n in wrt_set for ns in op.outputs.values()
                        for n in ns):
                    continue
                # RNG-stateful ops already run eagerly (prefetch id
                # slice): their concrete outputs sit in base_env; a
                # re-traced draw would produce DIFFERENT ids than the
                # rows the prefetch fetched
                if id(op) in skip_op_ids:
                    continue
                _lower_op(fctx, op)
            # scalar objective: mean-reduce each target (loss is already
            # scalar in the common case; calc_gradient uses unit cotangents,
            # i.e. sum of each target's elements)
            total = 0.0
            for tn in target_names:
                t = env[tn]
                total = total + (t if t.ndim == 0 else jnp.sum(t))
            if not use_ckpt:
                return total, env
            # checkpointed: exporting every intermediate as an output
            # would force XLA to store them all — keep only what the
            # post-marker ops and the scope commit can need
            keep = {n: v for n, v in env.items()
                    if n in persistable or n in target_names
                    or n in wrt or n in post_in
                    or n.startswith(_NANGUARD) or n.endswith("@LOD")}
            return total, keep

        fwd = jax.checkpoint(forward) if use_ckpt else forward
        (loss_val, env_after), grads = jax.value_and_grad(
            fwd, has_aux=True)(wrt)
        ctx.env.update(env_after)
        # continue the NaN-guard program-order index past the forward ops
        # (the forward fctx numbered its guards from 0; optimizer-op guards
        # recorded on `ctx` must sort after them, executor.cc:27-94 parity)
        fwd_guard_idx = [int(k[len(_NANGUARD):].split("|", 1)[0])
                         for k in env_after if k.startswith(_NANGUARD)]
        ctx._nan_idx = max(fwd_guard_idx, default=-1) + 1
        if marker.type == "backward_marker":
            ctx.env[target_names[0] + "@GRAD"] = jnp.ones_like(loss_val)
        for p, g in grads.items():
            ctx.env[p + "@GRAD"] = g
        for op in ops[bwd_idx + 1:]:
            _lower_op(ctx, op)

    @staticmethod
    def _lower_with_grad_accum(ctx, ops, bwd_idx, block, feeds,
                               accum_steps, persistable_names,
                               loss_norm=None):
        """Gradient accumulation: lax.scan of fwd+bwd over microbatches.

        Feeds with batch dim > 1 split into accum_steps equal chunks
        (scalar / leading-dim-1 feeds broadcast to every microbatch); the
        scan carry holds (grad sums, loss sum, persistable state) so
        streaming forward-state updates (e.g. batch-norm counters) and
        NaN guards thread through microbatches; grads and the loss are
        WEIGHTED sums over microbatches. The weights depend on how the
        user's loss is normalized (``loss_norm``):

        - ``"sequence"`` (and the dense equal-chunk case): w_i = 1/k.
          Exact when the loss is a mean over per-sequence values — each
          microbatch holds the same number of sequences.
        - ``"token"`` / ``"token:<feed>"``: w_i = T_i / sum(T_j), the
          true (pre-bucket) token totals of the ragged LoD pre-split
          (``<feed>@ACCUM_TOKENS`` from _normalize_feeds). Exact when
          the loss is a mean over TOKENS: full-batch token mean
          = sum_i (T_i/T) * (per-microbatch token mean).

        Ragged splits with UNEQUAL token totals and no explicit
        loss_norm are rejected host-side (ParallelExecutor.run) — equal
        weighting would silently mis-scale token-normalized losses.
        With either exact weighting, an optimizer step after
        accumulation matches the unaccumulated step. Each microbatch
        gets its own RNG stream (dropout masks differ per microbatch)."""
        marker = ops[bwd_idx]
        wrt_names, target_names = Executor._parse_marker(marker)
        base_env = dict(ctx.env)
        wrt = {n: base_env[n] for n in wrt_names if n in base_env}
        use_ckpt = bool(marker.attr("checkpoint"))
        post_in = {n for o in ops[bwd_idx + 1:]
                   for ns in o.inputs.values() for n in ns}

        k = int(accum_steps)
        static_info = getattr(ctx, "static_info", None) or {}
        # LoD feeds (and their @LOD lengths) were pre-split host-side
        # into [k, ...] stacks by _normalize_feeds(accum_steps=k): scan
        # them by leading index instead of reshape-chunking a batch dim
        stacked = {n for n in feeds if static_info.get(n + "@ACCUM_LOD")}
        stacked |= {n + "@LOD" for n in list(stacked)
                    if n + "@LOD" in feeds}
        chunked = {}
        for n in feeds:
            v = base_env[n]
            if n.endswith("@ACCUM_TOKENS"):
                continue          # weight inputs, consumed below
            if n in stacked:
                chunked[n] = v                 # already [k, ...]
                continue
            if getattr(v, "ndim", 0) < 1 or v.shape[0] <= 1:
                continue          # scalar/broadcast feed: replicate
            if v.shape[0] % k:
                raise ValueError(
                    "feed %r batch dim %s not divisible into %d "
                    "microbatches" % (n, getattr(v, "shape", ()), k))
            chunked[n] = v.reshape((k, v.shape[0] // k) + v.shape[1:])
        # persistable values the forward may update (streamed through the
        # scan carry; keys fixed before tracing for a stable carry pytree)
        pstate0 = {n: v for n, v in base_env.items()
                   if n in persistable_names and n not in wrt}
        accum_key = ctx._rng_fn()    # base for per-microbatch streams

        # Per-microbatch loss/grad weights (see docstring). Raggedness
        # and multi-feed ambiguity are checked host-side on the concrete
        # totals (parallel/executor.py); here the totals are tracers.
        _TOK = "@ACCUM_TOKENS"
        tok_arrays = {n[:-len(_TOK)]: base_env[n]
                      for n in feeds if n.endswith(_TOK)}
        norm = loss_norm or "sequence"
        if norm.startswith("token") and not tok_arrays:
            # the user asked for token weighting but no ragged LoD feed
            # carries token counts — silently falling back to 1/k would
            # be the exact mis-scaling this knob exists to prevent
            raise ValueError(
                "gradient_accumulation_loss_norm=%r: this program has no "
                "ragged LoD feeds, so per-microbatch token counts are "
                "unavailable; drop the knob (equal chunks weight equally) "
                "or feed the sequence data as LoDTensor" % (loss_norm,))
        if norm.startswith("token"):
            if ":" in norm:
                src = norm.split(":", 1)[1]
                if src not in tok_arrays:
                    raise ValueError(
                        "gradient_accumulation_loss_norm=%r: %r is not "
                        "a ragged LoD feed of this program (have %s)"
                        % (loss_norm, src, sorted(tok_arrays)))
                tok = tok_arrays[src]
            else:
                tok = next(iter(tok_arrays.values()))
            weights = tok / jnp.sum(tok)
        else:
            weights = jnp.full((k,), 1.0 / k, jnp.float32)

        def forward(params, pstate, feeds_i, key_i):
            env = dict(base_env)
            env.update(pstate)
            env.update(feeds_i)
            env.update(params)
            n_splits = [0]

            def micro_rng():
                n_splits[0] += 1
                return jax.random.fold_in(key_i, n_splits[0])

            fctx = registry.LowerContext(env, micro_rng,
                                         is_test=ctx.is_test,
                                         executor=ctx.executor,
                                         block=block, mesh=ctx.mesh,
                                         static_info=ctx.static_info,
                                         fetch_names=getattr(
                                             ctx, "fetch_names", ()))
            fctx.check_nan = getattr(ctx, "check_nan", False)
            for op in ops[:bwd_idx]:
                _lower_op(fctx, op)
            loss = env[target_names[0]]
            if use_ckpt:
                # checkpoint composes with accumulation: per-microbatch
                # residuals shrink to the microbatch inputs; keep only
                # what the carry/probe consumers read (the whole-forward
                # keep-filter contract of _lower_with_grad)
                env = {n: v for n, v in env.items()
                       if n in pstate0 or n in target_names
                       or n in post_in or n.startswith(_NANGUARD)}
            return (loss if loss.ndim == 0 else jnp.sum(loss)), env

        fwd = jax.checkpoint(forward) if use_ckpt else forward

        def body(carry, xs):
            gsum, lsum, pstate, guards_ok = carry
            feeds_i, idx, w_i = xs
            key_i = jax.random.fold_in(accum_key, idx)
            (loss, env_a), grads = jax.value_and_grad(
                fwd, has_aux=True)(wrt, pstate, feeds_i, key_i)
            gsum = jax.tree.map(
                lambda s, g: s + g * w_i.astype(g.dtype), gsum, grads)
            lsum = lsum + loss * w_i.astype(loss.dtype)
            pstate = {n: env_a.get(n, pstate[n]) for n in pstate}
            guards_ok = {g: guards_ok[g]
                         & env_a.get(g, jnp.asarray(True))
                         for g in guards_ok}
            return (gsum, lsum, pstate, guards_ok), None

        # One probe trace on microbatch 0: discovers the guard names (so
        # the scan carry pytree is fixed) and supplies the post-marker
        # ops' forward inputs — e.g. a computed learning-rate chain. Only
        # the subgraph whose outputs are actually exported below survives
        # XLA dead-code elimination; the heavy model compute in the probe
        # is dropped.
        _, probe_env = forward(wrt, pstate0,
                               {n: c[0] for n, c in chunked.items()},
                               accum_key)
        loss_name = target_names[0]
        if getattr(probe_env[loss_name], "ndim", 0) != 0:
            raise ValueError(
                "gradient accumulation requires a SCALAR (mean-reduced) "
                "loss; %r has shape %s — accumulating a per-element loss "
                "would silently rescale gradients by 1/%d"
                % (loss_name, probe_env[loss_name].shape, k))
        guard_names = [g for g in probe_env if g.startswith(_NANGUARD)]
        init = (jax.tree.map(jnp.zeros_like, wrt),
                jnp.zeros_like(probe_env[loss_name], shape=()),
                pstate0,
                {g: jnp.asarray(True) for g in guard_names})
        (gsum, lsum, pstate, guards_ok), _ = jax.lax.scan(
            body, init, (chunked, jnp.arange(k), weights))

        ctx.env.update(pstate)
        ctx.env.update(guards_ok)
        # Post-marker (optimizer) ops may read forward intermediates —
        # the computed-LR chain is the canonical case. Export those from
        # the PROBE trace, and for persistable vars that chain writes
        # (step counters: @LR_DECAY_COUNTER@) export the probe's
        # once-advanced value too, overriding the scan's k-advanced copy:
        # a counter's contract is one tick per executed STEP, while
        # batch-norm-style stats (not read post-marker) keep the
        # per-microbatch streamed values from the scan.
        producers = {}
        for op in ops[:bwd_idx]:
            for ns in op.outputs.values():
                for n in ns:
                    producers[n] = op
        frontier = [n for n in post_in
                    if n in producers and n in probe_env
                    and n not in base_env]
        seen_ops, stack = set(), list(frontier)
        counter_vars = set()
        while stack:
            nm = stack.pop()
            op = producers.get(nm)
            if op is None or id(op) in seen_ops:
                continue
            seen_ops.add(id(op))
            for ns in op.outputs.values():
                counter_vars.update(n for n in ns
                                    if n in persistable_names)
            for ns in op.inputs.values():
                stack.extend(ns)
        for n in frontier:
            ctx.env[n] = probe_env[n]
        for n in counter_vars:
            if n in probe_env:
                ctx.env[n] = probe_env[n]

        ctx.env[loss_name] = lsum     # weights sum to 1: already a mean
        fwd_guard_idx = [int(g[len(_NANGUARD):].split("|", 1)[0])
                         for g in guard_names]
        ctx._nan_idx = max(fwd_guard_idx, default=-1) + 1
        ctx.env[loss_name + "@GRAD"] = jnp.ones_like(lsum)
        for p in wrt:
            ctx.env[p + "@GRAD"] = gsum[p]
        for op in ops[bwd_idx + 1:]:
            _lower_op(ctx, op)

    @staticmethod
    def _check_nan_inf(names, values):
        # FLAGS_check_nan_inf parity (reference executor.cc:27-94).
        for n, v in zip(names, values):
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                from .. import monitor as _mon
                _mon.on_nan_trip("fetch", detail=n)
                raise FloatingPointError(
                    "NaN/Inf detected in fetched var %r" % n)

    @staticmethod
    def _check_guards(guards):
        """Report the FIRST (program-order) op output that went non-finite."""
        if not guards:
            return
        guards = jax.device_get(guards)  # one transfer for all guard scalars
        bad = [k for k, ok in guards.items() if not bool(np.asarray(ok))]
        if bad:
            k = min(bad, key=lambda s: int(s[len(_NANGUARD):].split("|")[0]))
            _, op_type, var = k[len(_NANGUARD):].split("|", 2)
            from .. import monitor as _mon
            _mon.on_nan_trip("guard", detail="%s/%s" % (op_type, var))
            raise FloatingPointError(
                "NaN/Inf detected in output %r of op %r "
                "(PADDLE_TPU_CHECK_NAN_INF)" % (var, op_type))


def _step_costs_safe(fn, state, feeds, rng_key):
    """Static (flops, bytes) of one step for the monitor's MFU gauge —
    abstract trace only (analysis.cost.step_costs)."""
    from ..analysis.cost import step_costs
    return step_costs(fn, (state, feeds, rng_key))


def _lower_op_eager(ctx, op):
    """_lower_op on CONCRETE values (the interpreter / host-segment
    path) with per-op profiling: each op gets its own RecordEvent, and
    with FLAGS profile_memory on, outputs sync before the memory sample
    so live/peak bytes attribute to THIS op — the reference's
    FLAGS_benchmark per-op wait+log (operator.cc:576-578), which also
    only existed in its interpreter."""
    from .. import profiler as _prof
    if not _prof._enabled:
        _lower_op(ctx, op)
        return
    with _prof.RecordEvent("op:%s" % op.type):
        _lower_op(ctx, op)
        if _prof.memory_enabled():
            outs = [ctx.env[n] for ns in op.outputs.values() for n in ns
                    if n in ctx.env]
            try:
                jax.block_until_ready(
                    [o for o in outs if isinstance(o, jax.Array)])
            except Exception:
                pass


def _lower_op(ctx, op):
    if op.type in ("feed", "fetch"):
        _lower_feed_fetch(ctx, op)
        return
    info = registry.lookup(op.type)
    if info is None:
        raise NotImplementedError(
            "no TPU lowering registered for op %r (registered: %d ops)"
            % (op.type, len(registry.registered_ops())))
    try:
        # scope every op's lowering as "<op_type>.<seq>": the name lands
        # in each jaxpr eqn's source_info name stack, which is (a) the
        # op path paddle_tpu.analysis diagnostics report and (b) the
        # metadata XLA profiles attribute — the analog of the
        # reference's per-op RecordEvent naming
        seq = getattr(ctx, "_op_seq", 0)
        ctx._op_seq = seq + 1
        with jax.named_scope("%s.%d" % (op.type, seq)):
            info.lower(ctx, op)
    except EnforceError:
        raise
    except Exception as e:  # annotate with op context (enforce.h:203 parity)
        raise op_error(op, ctx.env, e) from e
    _propagate_lod(ctx, op)
    if getattr(ctx, "check_nan", False):
        _record_nan_guards(ctx, op)


def _record_nan_guards(ctx, op):
    """FLAGS_check_nan_inf parity with the reference's EVERY-op-output scan
    (framework/executor.cc:27-94): one cheap isfinite reduction per float
    output, carried through the jitted step as extra scalar outputs under
    reserved ``__nanguard__`` env names (so they also flow through the
    value_and_grad aux in _lower_with_grad)."""
    for name in op.output_names:
        v = ctx.env.get(name)
        dt = getattr(v, "dtype", None)
        if dt is not None and jnp.issubdtype(dt, jnp.floating):
            fin = jnp.isfinite(v)
            lod = ctx.env.get(name + "@LOD")
            if lod is not None and getattr(v, "ndim", 0) >= 1:
                # bucket-pad rows (past sum(lengths)) are zero filler and
                # may legitimately be non-finite downstream of log/div —
                # only the real rows count (executor.cc:27-94 scans real
                # tensor contents only)
                valid = jnp.arange(v.shape[0]) < jnp.sum(lod)
                fin = fin | ~valid.reshape(
                    (v.shape[0],) + (1,) * (v.ndim - 1))
            idx = getattr(ctx, "_nan_idx", 0)
            ctx._nan_idx = idx + 1
            ctx.env["%s%d|%s|%s" % (_NANGUARD, idx, op.type, name)] = \
                fin.all()


def _propagate_lod(ctx, op):
    """LoD (sequence lengths) flow through row-preserving ops.

    The reference's ops copy LoD from input to output inside each kernel
    (ShareLoD in InferShape). Here: if a lowering didn't set ``out@LOD``
    itself (sequence_* ops do), any output with the same leading dim as an
    LoD-carrying input inherits that input's lengths. This is what lets
    ``embedding → sequence_pool`` see per-sequence boundaries."""
    in_lod = None
    lead = None
    src = None
    for name in op.input_names:
        lod = ctx.env.get(name + "@LOD")
        if lod is not None:
            val = ctx.env.get(name)
            if val is not None and getattr(val, "ndim", 0) >= 1:
                in_lod, lead, src = lod, val.shape[0], name
                break
    if in_lod is None:
        return
    maxlen = ctx.static_info.get(src + "@MAXLEN")
    for name in op.output_names:
        if name + "@LOD" in ctx.env:
            continue  # lowering set it explicitly
        val = ctx.env.get(name)
        if val is not None and getattr(val, "ndim", 0) >= 1 \
                and val.shape[0] == lead:
            ctx.env[name + "@LOD"] = in_lod
            if maxlen is not None:
                ctx.static_info.setdefault(name + "@MAXLEN", maxlen)


def _lower_feed_fetch(ctx, op):
    # Feeds are pre-bound into env by var name; a 'feed' op in a loaded
    # inference program is therefore a name passthrough, as is 'fetch'.
    if op.type == "feed":
        out = ctx.out_name(op, "Out")
        if out is not None and out not in ctx.env:
            raise KeyError("feed target %r was not provided in feed dict" % out)
    else:  # fetch
        src = op.input("X")
        out = ctx.out_name(op, "Out")
        if src and out:
            ctx.env[out] = ctx.get(src[0])


def _fetch_from_env(env, name):
    if name not in env:
        raise KeyError(
            "fetch var %r was not produced by the program; "
            "available: %s..." % (name, sorted(env)[:20]))
    val = env[name]
    if isinstance(val, list):     # LoDTensorArray — stack lazily on fetch
        val = jnp.stack(val)
    return val
