"""CLI: python -m paddle_tpu.analysis [models...] [--all] [--json] ...

Runs the static analyzer over zoo models and exits non-zero when any
diagnostic reaches --fail-on severity (default: error) — the CI gate
that keeps the model zoo honest without TPU time. Run under
JAX_PLATFORMS=cpu; tracing never touches a device.

``--runtime`` switches to the runtime-code lint
(paddle_tpu.analysis.runtime): AST rules over the package sources —
lock discipline, RPC verb conformance, metric/flag catalog
consistency, thread-shared-state heuristic — gated by the checked-in
waiver file. Exit codes match the zoo path: 0 clean (or fully
waived), 1 findings at/above --fail-on, 2 usage error (including a
malformed waiver file).
"""

import argparse
import sys

# Runtime-only packages the jaxpr analyzer cannot see into: a broken
# import here (a bad refactor, a missing stub) would sail straight past
# the zoo lint, so the CLI gate import-checks them too. Keep in sync
# with the package layout.
IMPORT_CHECK_PACKAGES = (
    "paddle_tpu.resilience",
    "paddle_tpu.resilience.faults",
    "paddle_tpu.resilience.retry",
    "paddle_tpu.resilience.driver",
    "paddle_tpu.monitor",
    "paddle_tpu.monitor.watch",
    "paddle_tpu.monitor.collector",
    "paddle_tpu.monitor.goodput",
    "paddle_tpu.monitor.signals",
    "paddle_tpu.perfgate",
    "paddle_tpu.serving",
    "paddle_tpu.serving.engine",
    "paddle_tpu.serving.fleet",
    "paddle_tpu.serving.autoscale",
    "paddle_tpu.serving.rollout",
    "paddle_tpu.serving.kvpool",
    "paddle_tpu.serving.sampling",
    "paddle_tpu.serving.spec",
    "paddle_tpu.serving.sparse",
    "paddle_tpu.serving.sparse.cache",
    "paddle_tpu.serving.sparse.scoring",
    "paddle_tpu.serving.sparse.online",
    "paddle_tpu.ops.paged_attention",
    "paddle_tpu.reader",
    "paddle_tpu.reader.device_loader",
    "paddle_tpu.slo",
    "paddle_tpu.transform",
    "paddle_tpu.transform.passes",
    "paddle_tpu.transform.fusion",
    "paddle_tpu.transform.infer",
    "paddle_tpu.transform.memory",
    "paddle_tpu.transform.calibrate",
    "paddle_tpu.transform.autoparallel",
    "paddle_tpu.serving.artifact",
    "paddle_tpu.trace",
    "paddle_tpu.trace.runtime",
    "paddle_tpu.trace.clock",
    "paddle_tpu.trace.merge",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.master",
    "paddle_tpu.distributed.membership",
    "paddle_tpu.analysis.runtime",
    "paddle_tpu.analysis.runtime.rules",
)


def import_check(packages=IMPORT_CHECK_PACKAGES):
    """Import every runtime-only package; returns [(name, error), ...]
    (empty = all clean). Part of the --all CI gate."""
    import importlib
    failures = []
    for name in packages:
        try:
            importlib.import_module(name)
        except Exception as e:        # any failure mode is a gate fail
            failures.append((name, repr(e)))
    return failures


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="jaxpr static analyzer over the paddle_tpu model "
                    "zoo")
    p.add_argument("models", nargs="*",
                   help="zoo model names (see --list-models)")
    p.add_argument("--all", action="store_true",
                   help="analyze every model in the zoo")
    p.add_argument("--json", action="store_true",
                   help="emit a JSON report instead of text")
    p.add_argument("--rules",
                   help="comma-separated rule names to run "
                        "(default: all)")
    p.add_argument("--fail-on", default="error",
                   choices=["error", "warning", "info"],
                   help="exit 1 if any diagnostic reaches this "
                        "severity (default: error)")
    p.add_argument("--verbose", "-v", action="store_true",
                   help="include info-level diagnostics in text "
                        "output")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--list-models", action="store_true")
    p.add_argument("--runtime", action="store_true",
                   help="run the runtime-code lint (locks, RPC verbs, "
                        "metric/flag catalog, shared state) instead "
                        "of the jaxpr zoo analyzer")
    p.add_argument("--root",
                   help="repository root to lint (--runtime only; "
                        "default: this checkout)")
    p.add_argument("--waivers",
                   help="waiver file for --runtime ('none' disables; "
                        "default: analysis/runtime/waivers.json)")
    args = p.parse_args(argv)

    if args.runtime:
        return _runtime_main(p, args)

    from . import registered_rules, zoo_names
    from .zoo import analyze_zoo

    if args.list_rules:
        for name, cls in sorted(registered_rules().items(),
                                key=lambda kv: kv[1].id):
            print("%-6s %-18s %s" % (cls.id, name, cls.doc))
        return 0
    if args.list_models:
        for name in zoo_names():
            print(name)
        return 0

    failures = import_check()
    for name, err in failures:
        print("import-check FAILED: %s (%s)" % (name, err),
              file=sys.stderr)
    if failures:
        return 1

    names = zoo_names() if args.all or not args.models else args.models
    unknown = set(names) - set(zoo_names())
    if unknown:
        p.error("unknown model(s) %s; --list-models for the zoo"
                % ", ".join(sorted(unknown)))
    rules = args.rules.split(",") if args.rules else None
    if rules:
        bad = set(rules) - set(registered_rules())
        if bad:
            p.error("unknown rule(s) %s; --list-rules for the catalog"
                    % ", ".join(sorted(bad)))

    def progress(name, report, dt):
        if not args.json:
            c = report.counts()
            print("analyzed %-18s %5.1fs  %d error(s) %d warning(s)"
                  % (name, dt, c["error"], c["warning"]),
                  file=sys.stderr)

    report = analyze_zoo(names, rules=rules, progress=progress)
    if args.json:
        print(report.to_json())
    else:
        print(report.render_text(verbose=args.verbose))
    return 1 if report.at_least(args.fail_on) else 0


def _runtime_main(p, args):
    from .runtime import (run_runtime, registered_runtime_rules,
                          WaiverError)

    if args.list_rules:
        for name, cls in sorted(registered_runtime_rules().items(),
                                key=lambda kv: kv[1].id):
            print("%-6s %-20s %s" % (cls.id, name, cls.doc))
        return 0
    rules = None
    if args.rules:
        table = registered_runtime_rules()
        names = args.rules.split(",")
        bad = set(names) - set(table)
        if bad:
            p.error("unknown runtime rule(s) %s; --runtime "
                    "--list-rules for the catalog"
                    % ", ".join(sorted(bad)))
        rules = [table[n]() for n in names]
    try:
        report = run_runtime(root=args.root, rules=rules,
                             waivers_path=(args.waivers
                                           if args.waivers is not None
                                           else ""))
    except WaiverError as e:
        p.error(str(e))                   # argparse exits 2
    if args.json:
        print(report.to_json())
    else:
        print(report.render_text())
    return 1 if report.at_least(args.fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
