"""RT01 lock-discipline: deadlock cycles + blocking calls under a lock.

Per class, the rule reconstructs the lock set (``self.X =
threading.Lock()/RLock()/Condition(...)``, with a Condition built over
an existing lock aliased to that lock — acquiring the condition IS
acquiring the lock) and walks every method with the held-lock context:

  * ``with self.A:`` inside ``with self.B:`` records the edge B->A in
    the class's lock-acquisition graph; a strongly connected component
    (two orders of the same pair, or any longer cycle) is a potential
    deadlock -> ERROR. A directly nested re-acquisition of one
    NON-reentrant lock is an immediate self-deadlock -> ERROR.
  * a blocking call while any lock is held -> ERROR. Blocking means:
    socket I/O (send/recv/connect/accept and the rpc framing helpers
    ``_send_msg``/``_recv_msg``/..., which are blocking wherever they
    are imported), ``time.sleep``, thread ``join``, ``Event.wait``,
    retry ``Policy.run`` (sleeps between attempts), and subprocess
    waits. ``Condition.wait`` on the HELD condition is exempt — it
    releases the lock while waiting, that is the correct pattern.
  * blocking-ness propagates one class deep: ``self.m()`` under a lock
    where ``m`` (transitively) blocks is flagged at the call site, and
    locks ``m`` acquires become edges from the held lock.

Module-level functions get the same treatment against module-level
``_LOCK = threading.Lock()`` style globals, and a module function that
blocks marks its bare-name callers within the module as blocking.
"""

import ast

from ..astscan import (dotted_name, class_methods, iter_lock_scopes)
from ..engine import (Finding, RuntimeRule, register_runtime_rule,
                      ERROR, WARNING)

__all__ = ["LockDisciplineRule"]

# rpc framing / reply helpers: blocking socket I/O wherever imported
KNOWN_BLOCKING = {
    "_send_msg": "rpc framing _send_msg()",
    "_recv_msg": "rpc framing _recv_msg()",
    "_recv_exact": "rpc framing _recv_exact()",
    "_recv_into": "rpc framing _recv_into()",
    "_recv_frame_head": "rpc framing _recv_frame_head()",
    "_sendall_parts": "rpc framing _sendall_parts()",
    "_clock_reply": "rpc reply _clock_reply()",
    "_metr_reply": "rpc reply _metr_reply()",
    "_hlth_reply": "rpc reply _hlth_reply()",
    "_dump_reply": "rpc reply _dump_reply()",
    "_clock_exchange": "rpc _clock_exchange()",
    "create_connection": "socket.create_connection()",
}

_SOCKET_TAILS = {"sendall", "recv", "recv_into", "accept", "connect",
                 "connect_ex", "sendmsg", "recvmsg"}
_SUBPROC_TAILS = {"run", "call", "check_call", "check_output"}
_LOCK_FACTORIES = {"Lock", "RLock"}


def _call_parts(call):
    """(tail, receiver_dotted_or_None) for a Call's func."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr, dotted_name(call.func.value)
    if isinstance(call.func, ast.Name):
        return call.func.id, None
    return None, None


def _factory_of(value):
    """'Lock'/'RLock'/'Condition'/'Event'/'Thread' for an assignment
    value like ``threading.Lock()``, else None."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if name is None:
        return None
    tail = name.split(".")[-1]
    if tail in ("Lock", "RLock", "Condition", "Event", "Thread",
                "Semaphore", "BoundedSemaphore"):
        return tail
    return None


class _ClassInfo:
    def __init__(self):
        self.locks = {}      # attr -> canonical lock attr (alias-resolved)
        self.rlocks = set()  # attrs that are reentrant
        self.events = set()
        self.threads = set()


def _collect_class_info(cls):
    info = _ClassInfo()
    aliases = {}             # condition attr -> underlying lock attr
    for fn in class_methods(cls).values():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            fac = _factory_of(node.value)
            if fac is None:
                continue
            for tgt in node.targets:
                name = dotted_name(tgt)
                if name is None or not name.startswith("self."):
                    continue
                attr = name.split(".", 1)[1]
                if "." in attr:
                    continue
                if fac in _LOCK_FACTORIES:
                    info.locks[attr] = attr
                    if fac == "RLock":
                        info.rlocks.add(attr)
                elif fac in ("Semaphore", "BoundedSemaphore"):
                    info.locks[attr] = attr
                elif fac == "Condition":
                    args = node.value.args
                    base = dotted_name(args[0]) if args else None
                    if base and base.startswith("self."):
                        aliases[attr] = base.split(".", 1)[1]
                    else:
                        info.locks[attr] = attr
                elif fac == "Event":
                    info.events.add(attr)
                elif fac == "Thread":
                    info.threads.add(attr)
    for attr, base in aliases.items():
        info.locks[attr] = info.locks.get(base, base)
        if base in info.rlocks:
            info.rlocks.add(attr)
    return info


def _local_threads(fn):
    """Local names bound to threading.Thread(...) in this function."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _factory_of(node.value) == "Thread":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _blocking_reason(call, info, module_blocking, local_threads):
    """Why this call blocks, or None. ``info`` may be None for
    module-level functions."""
    tail, recv = _call_parts(call)
    if tail is None:
        return None
    if recv == "time" and tail == "sleep":
        return "time.sleep()"
    if recv is None:
        if tail in module_blocking:
            return module_blocking[tail]
        if tail in KNOWN_BLOCKING:
            return KNOWN_BLOCKING[tail]
        return None
    if tail in _SOCKET_TAILS:
        return "socket .%s()" % tail
    if tail in KNOWN_BLOCKING and recv is not None:
        # e.g. rpc._send_msg(...) via a module alias
        if recv.split(".")[-1] in ("rpc", "_rpc"):
            return KNOWN_BLOCKING[tail]
    if tail == "join":
        attr = recv.split(".", 1)[1] if recv.startswith("self.") else None
        if (attr is not None and attr in (info.threads if info else ())) \
                or recv in local_threads or "thread" in recv.lower():
            return "thread .join()"
        return None
    if tail == "wait":
        attr = recv.split(".", 1)[1] if recv.startswith("self.") else None
        if info is not None and attr in info.events:
            return "Event .wait()"
        return None
    if tail == "communicate" or (recv.split(".")[-1] == "subprocess"
                                 and tail in _SUBPROC_TAILS):
        return "subprocess .%s()" % tail
    if tail == "run" and ("retry" in recv.lower()
                          or "policy" in recv.lower()):
        return "retry Policy.run()"
    return None


def _module_blocking_funcs(sf):
    """{bare function name -> reason} for this module's top-level
    functions that (transitively, within the module) block; seeded by
    KNOWN_BLOCKING so callers of framing helpers propagate."""
    funcs = {fn.name: fn for fn in sf.functions()}
    blocking = dict(KNOWN_BLOCKING)
    changed = True
    while changed:
        changed = False
        for name, fn in funcs.items():
            if name in blocking:
                continue
            locals_t = _local_threads(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                reason = _blocking_reason(node, None, blocking, locals_t)
                if reason is not None:
                    blocking[name] = ("%s() -> %s" % (name, reason))
                    changed = True
                    break
    return blocking


def _sccs(graph):
    """Strongly connected components with >1 node (iterative Tarjan
    would be overkill at this scale: simple DFS reachability)."""
    nodes = sorted(set(graph) | {w for vs in graph.values() for w, _ in vs})
    reach = {}
    for n in nodes:
        seen = set()
        stack = [w for w, _ in graph.get(n, ())]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(w for w, _ in graph.get(m, ()))
        reach[n] = seen
    comps, done = [], set()
    for n in nodes:
        if n in done:
            continue
        comp = {n} | {m for m in reach[n] if n in reach.get(m, ())}
        if len(comp) > 1:
            comps.append(sorted(comp))
        done |= comp
    return comps


@register_runtime_rule
class LockDisciplineRule(RuntimeRule):
    name = "lock-discipline"
    id = "RT01"
    doc = ("per-class lock graph: acquisition cycles (deadlock) and "
           "blocking calls (socket I/O, sleep, join, Policy.run, "
           "subprocess) while a lock is held")
    max_reports = 80

    def check(self, index):
        for sf in index.iter_files():
            module_blocking = _module_blocking_funcs(sf)
            # module-level locks + top-level functions
            mod_locks = {}
            for stmt in sf.tree.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Call) and \
                        _factory_of(stmt.value) in _LOCK_FACTORIES:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            mod_locks[tgt.id] = tgt.id
            if mod_locks:
                for fn in sf.functions():
                    for f in self._check_callable(
                            sf, fn, fn.name, None, mod_locks,
                            module_blocking, {}, {}, {}, {}):
                        yield f
            for cls_node in sf.classes():
                for f in self._check_class(sf, cls_node,
                                           module_blocking):
                    yield f

    # -- per-class ---------------------------------------------------------
    def _check_class(self, sf, cls, module_blocking):
        info = _collect_class_info(cls)
        methods = class_methods(cls)
        if not info.locks:
            return
        edges = {}           # lock -> [(lock2, line)]
        acquires = {}        # method -> set of locks acquired inside
        blocking_sites = {}  # method -> [(line, reason, held)]
        self_calls = {}      # method -> [(line, callee, held)]
        for mname, fn in methods.items():
            self._scan_method(sf, cls, fn, info, module_blocking,
                              edges, acquires.setdefault(mname, set()),
                              blocking_sites.setdefault(mname, []),
                              self_calls.setdefault(mname, []))
        # propagate blocking-ness through self.m() calls (fixed point)
        blocking_method = {}
        changed = True
        while changed:
            changed = False
            for mname in methods:
                if mname in blocking_method:
                    continue
                if blocking_sites[mname]:
                    blocking_method[mname] = blocking_sites[mname][0][1]
                    changed = True
                    continue
                for _ln, callee, _held in self_calls[mname]:
                    if callee in blocking_method:
                        blocking_method[mname] = ("self.%s() -> %s"
                                                  % (callee,
                                                     blocking_method[callee]))
                        changed = True
                        break
        # transitive acquires (one fixed point, same shape)
        changed = True
        while changed:
            changed = False
            for mname in methods:
                for _ln, callee, _held in self_calls[mname]:
                    extra = acquires.get(callee, set()) - acquires[mname]
                    if extra:
                        acquires[mname] |= extra
                        changed = True
        # findings: blocking under a held lock
        for mname in sorted(methods):
            where = "%s.%s" % (cls.name, mname)
            for ln, reason, held in blocking_sites[mname]:
                if held:
                    yield Finding(
                        self.name, ERROR, sf.path, ln,
                        "blocking call %s while holding lock '%s'"
                        % (reason, held[-1]), where=where,
                        hint="compute the reply under the lock, do the "
                             "I/O after releasing it")
            for ln, callee, held in self_calls[mname]:
                if held and callee in blocking_method:
                    yield Finding(
                        self.name, ERROR, sf.path, ln,
                        "call to self.%s() (%s) while holding lock '%s'"
                        % (callee, blocking_method[callee], held[-1]),
                        where=where,
                        hint="move the call after the lock release")
                if held:
                    for lk2 in sorted(acquires.get(callee, ())):
                        edges.setdefault(held[-1], []).append((lk2, ln))
        # findings: same-lock re-acquisition + cycles
        for lk, outs in sorted(edges.items()):
            for lk2, ln in outs:
                if lk2 == lk and lk not in info.rlocks:
                    yield Finding(
                        self.name, ERROR, sf.path, ln,
                        "nested re-acquisition of non-reentrant lock "
                        "'%s'" % lk, where=cls.name,
                        hint="use threading.RLock or split the method")
        graph = {lk: [(l2, ln) for l2, ln in outs if l2 != lk]
                 for lk, outs in edges.items()}
        for comp in _sccs(graph):
            first_line = min(ln for lk in comp
                             for l2, ln in graph.get(lk, ())
                             if l2 in comp)
            yield Finding(
                self.name, ERROR, sf.path, first_line,
                "lock-order cycle: %s" % " -> ".join(comp + [comp[0]]),
                where=cls.name,
                hint="pick one acquisition order and stick to it")

    def _scan_method(self, sf, cls, fn, info, module_blocking, edges,
                     acquires, blocking_sites, self_calls):
        locals_t = _local_threads(fn)
        methods = class_methods(cls)

        def lock_of(expr):
            name = dotted_name(expr)
            if name and name.startswith("self."):
                attr = name.split(".", 1)[1]
                return info.locks.get(attr)
            return None

        for kind, node, held, lk in iter_lock_scopes(fn.body, lock_of):
            if kind == "acquire":
                acquires.add(lk)
                if held:
                    edges.setdefault(held[-1], []).append(
                        (lk, node.lineno))
                continue
            if not isinstance(node, ast.Call):
                continue
            tail, recv = _call_parts(node)
            # Condition.wait on the held condition releases the lock
            reason = _blocking_reason(node, info, module_blocking,
                                      locals_t)
            if reason is not None:
                blocking_sites.append((node.lineno, reason, held))
            elif recv == "self" and tail in methods:
                self_calls.append((node.lineno, tail, held))
            elif tail == "acquire" and recv and recv.startswith("self."):
                attr = recv.split(".", 1)[1]
                lk2 = info.locks.get(attr)
                if lk2 is not None:
                    acquires.add(lk2)
                    if held:
                        edges.setdefault(held[-1], []).append(
                            (lk2, node.lineno))

    # -- module-level functions against module locks -----------------------
    def _check_callable(self, sf, fn, where, info, mod_locks,
                        module_blocking, edges, acquires,
                        blocking_sites, self_calls):
        locals_t = _local_threads(fn)

        def lock_of(expr):
            if isinstance(expr, ast.Name):
                return mod_locks.get(expr.id)
            return None

        for kind, node, held, lk in iter_lock_scopes(fn.body, lock_of):
            if kind == "acquire" or not isinstance(node, ast.Call):
                continue
            reason = _blocking_reason(node, info, module_blocking,
                                      locals_t)
            if reason is not None and held:
                yield Finding(
                    self.name, ERROR, sf.path, node.lineno,
                    "blocking call %s while holding lock '%s'"
                    % (reason, held[-1]), where=where,
                    hint="compute under the lock, do the I/O after "
                         "releasing it")
