"""Pattern fusion over the Program IR (ISSUE 15, ROADMAP direction 4).

The PR-9 passes were cleanup passes; this one moves op-path numbers: a
declarative peephole matcher rewrites multi-op chains into the fused
ops registered in ``ops/fused.py`` (whose lowerings replay the exact
component lowerings — bitwise identity by construction, see that
module), eliminates inverse transpose/transpose and reshape/reshape
chains outright, and folds adjacent scale/cast pairs into one op.

Patterns (per-pattern hit counters land in ``TransformResult.patterns``
and the ``ptpu_transform_patterns_total{pattern}`` metric):

  matmul_bias_act      mul/matmul/conv2d + elementwise_add + activation
                       -> ONE fused_matmul_bias_act op (3 -> 1)
  matmul_bias          the same chain without the activation (2 -> 1)
  transpose_transpose  inverse perms cancel to nothing (the pair is
                       dropped and consumers read the original name);
                       non-inverse perms compose into ONE transpose
  reshape_reshape      a reshape of a reshape is the outer reshape
  scale_cast           adjacent scale/cast ops fold into ONE
                       fused_scale_cast applying both in order

Safety discipline (the non-SSA IR rules of the PR-9 passes): only pure
ops participate; every intermediate must be single-def, single-consumer
and outside the keep/persistable/marker/sub-block protected set; every
chain input must be single-def (the replacement op evaluates at the
chain tail's position, so a redefinition in between would change what
it reads). RNG ops never match (stream-position pinning).
"""

import collections

from ..core.program import Operator
from ..ops.fused import FUSABLE_ANCHORS, fusable_act_types
from .passes import (Pass, is_side_effecting, op_inputs,
                     _marker_input_names, _subblock_needed,
                     _def_counts, _has_subblock)

_TRANSPOSES = ("transpose", "transpose2")
_RESHAPES = ("reshape", "reshape2")
_SCALE_CAST = ("scale", "cast")

PATTERN_NAMES = ("matmul_bias_act", "matmul_bias",
                 "transpose_transpose", "reshape_reshape", "scale_cast")


class _Ctx:
    """Shared match context for one rewrite sweep."""

    def __init__(self, gb, keep, program):
        self.gb = gb
        self.ops = list(gb.ops)
        self.persistable = {v.name for v in gb.vars.values()
                            if v.persistable}
        self.protected = (set(keep) | self.persistable
                          | _subblock_needed(program))
        for op in self.ops:
            self.protected.update(_marker_input_names(op))
        self.defs = _def_counts(gb)
        self.uses = collections.defaultdict(list)
        for idx, op in enumerate(self.ops):
            for n in op_inputs(op):
                self.uses[n].append(idx)
        self.taken = set()
        self.dropped = set()
        self.replaced = {}      # index -> replacement Operator
        self.rename = {}
        self.hits = collections.OrderedDict(
            (p, 0) for p in PATTERN_NAMES)
        self._removed = 0

    def pure(self, op):
        return not (is_side_effecting(op, self.persistable)
                    or _has_subblock(op))

    def single_out(self, op):
        outs = op.output_names
        if len(outs) == 1 and self.defs[outs[0]] == 1:
            return outs[0]
        return None

    def single_consumer(self, name, after):
        """Index of the one op consuming ``name`` (one occurrence
        total), or None."""
        idxs = self.uses.get(name, [])
        if len(idxs) != 1 or idxs[0] <= after:
            return None
        return idxs[0]

    def inputs_stable(self, names):
        """True when every input name is defined at most once — the
        replacement evaluates at the chain tail, so any name redefined
        between head and tail would be read at the wrong generation."""
        return all(self.defs[n] <= 1 for n in names)

    def claim(self, pattern, drop, replacement_at=None, replacement=None,
              removed=None):
        self.taken.update(drop)
        if replacement_at is None:
            self.dropped.update(drop)
        else:
            self.taken.add(replacement_at)
            self.replaced[replacement_at] = replacement
            self.dropped.update(i for i in drop if i != replacement_at)
        self.hits[pattern] += 1
        self._removed += removed if removed is not None else len(drop)


def _match_matmul_bias_act(ctx, i):
    op = ctx.ops[i]
    if op.type not in FUSABLE_ANCHORS or not ctx.pure(op):
        return False
    o0 = ctx.single_out(op)
    if o0 is None or o0 in ctx.protected:
        return False
    j = ctx.single_consumer(o0, i)
    if j is None or j in ctx.taken:
        return False
    add = ctx.ops[j]
    if add.type != "elementwise_add" or not ctx.pure(add):
        return False
    if add.input("X") != [o0] or o0 in add.input("Y"):
        return False
    o1 = ctx.single_out(add)
    if o1 is None:
        return False
    lhs_slot, rhs_slot, _ = FUSABLE_ANCHORS[op.type]
    chain_inputs = (op.input(lhs_slot) + op.input(rhs_slot)
                    + add.input("Y"))
    if len(op.input(lhs_slot)) != 1 or len(op.input(rhs_slot)) != 1 \
            or len(add.input("Y")) != 1:
        return False
    if not ctx.inputs_stable(chain_inputs):
        return False

    act_idx, act = None, None
    if o1 not in ctx.protected:
        k = ctx.single_consumer(o1, j)
        if k is not None and k not in ctx.taken:
            cand = ctx.ops[k]
            if cand.type in fusable_act_types() and ctx.pure(cand) \
                    and cand.input("X") == [o1]:
                o2 = ctx.single_out(cand)
                if o2 is not None:
                    act_idx, act = k, cand

    final = act.output("Out")[0] if act is not None else o1
    tail = act_idx if act is not None else j
    fused = Operator(
        ctx.gb, "fused_matmul_bias_act",
        {"X": op.input(lhs_slot), "Y": op.input(rhs_slot),
         "Bias": add.input("Y")},
        {"Out": [final]},
        {"mm_type": op.type, "mm_attrs": dict(op.attrs),
         "add_attrs": dict(add.attrs),
         "act_type": act.type if act is not None else "",
         "act_attrs": dict(act.attrs) if act is not None else {}})
    drop = {i, j} | ({act_idx} if act_idx is not None else set())
    ctx.claim("matmul_bias_act" if act is not None else "matmul_bias",
              drop, replacement_at=tail, replacement=fused,
              removed=len(drop) - 1)
    return True


def _pair_head(ctx, i, types):
    """Shared head of the two-op patterns: pure op of ``types`` whose
    single unprotected output feeds exactly one pure consumer of
    ``types``. Returns (op, o0, j, op2, o1) or None."""
    op = ctx.ops[i]
    if op.type not in types or not ctx.pure(op):
        return None
    o0 = ctx.single_out(op)
    if o0 is None or o0 in ctx.protected:
        return None
    j = ctx.single_consumer(o0, i)
    if j is None or j in ctx.taken:
        return None
    op2 = ctx.ops[j]
    if op2.type not in types or not ctx.pure(op2):
        return None
    if op2.input("X") != [o0]:
        return None
    o1 = ctx.single_out(op2)
    if o1 is None:
        return None
    x = op.input("X")
    if len(x) != 1 or not ctx.inputs_stable(x):
        return None
    return op, o0, j, op2, o1


def _match_transpose_transpose(ctx, i):
    m = _pair_head(ctx, i, _TRANSPOSES)
    if m is None:
        return False
    op, o0, j, op2, o1 = m
    p1, p2 = op.attr("axis"), op2.attr("axis")
    if not p1 or not p2 or len(p1) != len(p2):
        return False
    composed = [p1[p2[a]] for a in range(len(p2))]
    x = op.input("X")[0]
    if composed == list(range(len(composed))):
        if o1 in ctx.protected:
            # the name must still hold a value at fetch time: keep ONE
            # op (a passthrough assign) instead of the pair
            rep = Operator(ctx.gb, "assign", {"X": [x]}, {"Out": [o1]},
                           {})
            ctx.claim("transpose_transpose", {i, j}, replacement_at=j,
                      replacement=rep, removed=1)
        else:
            ctx.rename[o1] = ctx.rename.get(x, x)
            ctx.claim("transpose_transpose", {i, j}, removed=2)
        return True
    rep = Operator(ctx.gb, op2.type, {"X": [x]}, {"Out": [o1]},
                   {"axis": composed})
    ctx.claim("transpose_transpose", {i, j}, replacement_at=j,
              replacement=rep, removed=1)
    return True


def _match_reshape_reshape(ctx, i):
    m = _pair_head(ctx, i, _RESHAPES)
    if m is None:
        return False
    op, o0, j, op2, o1 = m
    shape = op2.attr("shape")
    # a 0 entry copies the INTERMEDIATE's dim at that position — it
    # would resolve differently against the original input
    if not shape or any(int(s) == 0 for s in shape):
        return False
    rep = Operator(ctx.gb, op2.type, {"X": op.input("X")},
                   {"Out": [o1]}, dict(op2.attrs))
    ctx.claim("reshape_reshape", {i, j}, replacement_at=j,
              replacement=rep, removed=1)
    return True


def _match_scale_cast(ctx, i):
    m = _pair_head(ctx, i, _SCALE_CAST)
    if m is None:
        return False
    op, o0, j, op2, o1 = m
    rep = Operator(
        ctx.gb, "fused_scale_cast", {"X": op.input("X")},
        {"Out": [o1]},
        {"ops": [[op.type, dict(op.attrs)],
                 [op2.type, dict(op2.attrs)]]})
    ctx.claim("scale_cast", {i, j}, replacement_at=j, replacement=rep,
              removed=1)
    return True


_MATCHERS = (_match_matmul_bias_act, _match_transpose_transpose,
             _match_reshape_reshape, _match_scale_cast)


class FusionPass(Pass):
    """Declarative pattern fusion. One linear sweep per rewrite call;
    the PassManager's fixed-point loop composes longer chains (e.g. a
    scale/cast triple folds over two rounds). ``last_patterns`` holds
    the per-pattern hit counts of the most recent rewrite."""

    name = "fusion"
    doc = ("pattern fusion: matmul+bias+act -> fused op, inverse "
           "transpose/reshape chains, scale/cast pairs")

    def __init__(self):
        self.last_patterns = collections.OrderedDict(
            (p, 0) for p in PATTERN_NAMES)

    def rewrite(self, program, keep):
        gb = program.global_block()
        ctx = _Ctx(gb, keep, program)
        for i in range(len(ctx.ops)):
            if i in ctx.taken:
                continue
            for match in _MATCHERS:
                if match(ctx, i):
                    break
        self.last_patterns = ctx.hits
        if not ctx._removed:
            return 0
        new_ops = []
        for idx, op in enumerate(ctx.ops):
            if idx in ctx.dropped:
                continue
            out = ctx.replaced.get(idx, op)
            if ctx.rename:
                for slot, names in out.inputs.items():
                    out.inputs[slot] = [ctx.rename.get(n, n)
                                        for n in names]
            new_ops.append(out)
        gb.ops = new_ops
        program._bump_version()
        return ctx._removed
