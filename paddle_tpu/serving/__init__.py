"""paddle_tpu.serving — continuous-batching inference engine.

The serving story of PERF.md round 5 in one number: bs1 greedy decode
sits at the XLA while-loop step floor while bs32 buys ~23x the tokens
for ~1.4x the step latency. The engine closes that gap for real traffic
by keeping a fixed-capacity slot batch full: requests are admitted at
STEP boundaries into retired slots (Orca's iteration-level scheduling),
prompts prefill chunk-by-chunk so a long admission cannot stall the
running batch, and the compiled step shape never changes while requests
of different lengths come and go.

Quickstart::

    from paddle_tpu import serving
    eng = serving.Engine(infer, slots=8)      # infer: TransformerLMInfer
    reqs = [eng.submit([1, 5, 9], max_new_tokens=32) for _ in range(64)]
    for r in reqs:
        tokens, score = r.result()
    eng.close()

or the synchronous convenience ``eng.generate_many(prompts, 32)``.
``sequential_generate`` is the one-at-a-time baseline the engine is
benchmarked (and token-identity-tested) against.

Serving fleet (ISSUE 8): ``serving.fleet`` puts a self-healing front
door ahead of N Engine replicas — each replica hosts the engine behind
SUBM/POLL/CANC/STAT verbs on the ``distributed/rpc.py`` frame protocol
and registers under a TTL lease; the ``Router`` dispatches
least-loaded with session affinity, applies backpressure (bounded
per-replica in-flight window) and load shedding (typed ``Overloaded``
fast-fail at the global queue bound), and guarantees EXACTLY-ONCE
completion under churn: journaled requests are re-submitted to a
survivor on replica lease expiry or stall eviction, deduped by durable
id, token-identical on re-execution (greedy decode). A ``Supervisor``
respawns dead/evicted replicas. Chaos-gated by tests/test_fleet.py the
way test_chaos.py gates training resilience.

Paged KV + prefix reuse + sampling (ISSUE 10): the engine's default
KV layout is a shared block pool (``kvpool.BlockPool``) addressed
through per-slot block tables, with a radix prefix cache
(``kvpool.RadixCache``) that lets admissions sharing a cached prompt
prefix skip those prefill chunks entirely, copy-on-write for shared
blocks, and preemption (lowest-priority request re-queued for
re-prefill, output unchanged) when the pool runs dry. Per-request
``SamplingParams`` (temperature / top-k / top-p / seed) execute inside
the compiled step; temperature-0 stays bitwise-greedy.

Speculative decode (ISSUE 13): with ``serving_speculative`` on, a
cheap drafter (``spec.NgramDrafter`` prompt/n-gram lookup over the
request's own chain + the prefix cache's published chains, or a
flag-gated truncated-layer pass over the same weights) proposes up to
``serving_spec_gamma`` tokens per live slot, and ONE multi-position
paged-attention dispatch verifies them all — each dispatch emits
1..γ+1 tokens whose values are exactly what sequential decode would
have produced (accept-longest-prefix against the model's own greedy /
counter-keyed-sampled tokens). Temperature-0 stays bitwise-identical
to the non-speculative engine; drafting quality only moves the
acceptance rate.

Elastic fleet (ISSUE 18): ``serving.autoscale.Autoscaler`` closes the
loop from the alerting plane's ``Signals.scale_hint()`` to the fleet —
scale-up cold-boots replicas from a PR-15 inference artifact,
scale-down gracefully drains the least-loaded replica (typed ``DRNG``
admission NACKs the router re-dispatches penalty-free, lease re-marked
``draining:<ep>``, in-flight results delivered AND acked before
retire), and ``roll(artifact_v2)`` replaces replicas one at a time
(boot v2 -> healthy STAT -> drain v1 -> retire) with exactly-once
preserved across the roll and an abort path that halts the roll — not
the fleet — if a v2 replica fails health. Chaos-gated by
tests/test_autoscale.py: kills mid-drain and mid-roll under seeded
frame faults must stay token-identical to sequential decode.

Request-level observability (ISSUE 6): every ``Request`` handle
carries its lifecycle attribution after retirement — ``queue_wait``,
``ttft``, ``tpot``, ``prefill_chunks``, ``latency()`` — mirrored into
``ptpu_serving_{ttft,tpot,queue_wait}_seconds`` histograms,
``serving_request`` flight-recorder rows and ``serving.request`` trace
spans. ``python -m paddle_tpu.slo`` gates a declarative SLO spec
against any of those surfaces; ``python -m paddle_tpu.monitor watch``
renders them live.
"""

from .engine import (Engine, Request,  # noqa: F401
                     sequential_generate)
from .fleet import (Overloaded, Replica, ReplicaClient,  # noqa: F401
                    ReplicaDraining, ReplicaServer, Router, Supervisor)
from .autoscale import Autoscaler  # noqa: F401
from .rollout import RolloutController  # noqa: F401
from .kvpool import (BlockPool, RadixCache,  # noqa: F401
                     bytes_per_block)
from .sampling import SamplingParams  # noqa: F401
from .spec import NgramDrafter  # noqa: F401
from .artifact import (engine_from_artifact,  # noqa: F401
                       model_from_artifact, save_lm_artifact)

__all__ = ["Engine", "Request", "sequential_generate", "Router",
           "Replica", "ReplicaServer", "ReplicaClient", "Supervisor",
           "Overloaded", "ReplicaDraining", "Autoscaler",
           "RolloutController", "BlockPool",
           "RadixCache", "bytes_per_block", "SamplingParams",
           "NgramDrafter", "engine_from_artifact",
           "model_from_artifact", "save_lm_artifact"]
