"""Metric ops — parity with operators/{accuracy,auc,precision_recall,
edit_distance}_op.cc. These run inside the compiled step (per-batch values);
streaming accumulation lives in paddle_tpu/metrics.py like the reference's
python-side fluid.metrics.
"""

import jax
import jax.numpy as jnp

from .common import I64
from ..core.registry import register


@register("accuracy")
def _accuracy(ctx, op):
    from .common import lod_valid_mask
    indices = ctx.in1(op, "Indices")      # [N, k]
    label = ctx.in1(op, "Label")          # [N, 1] or [N]
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label.reshape(-1)
    hit = jnp.any(indices == label[:, None].astype(indices.dtype), axis=1)
    # per-token accuracy over a bucketed LoD label: pad rows are neither
    # hits nor part of the total
    valid, n_valid = lod_valid_mask(ctx, op, slot="Label")
    if valid is None:
        valid, n_valid = lod_valid_mask(ctx, op, slot="Indices")
    if valid is not None:
        hit = hit & valid
        total = n_valid.astype(I64())
    else:
        total = jnp.asarray(label.shape[0], I64())
    correct = jnp.sum(hit.astype(I64()))
    ctx.set_out(op, "Accuracy",
                (correct.astype(jnp.float32) / total.astype(jnp.float32)
                 ).reshape(1))
    ctx.set_out(op, "Correct", correct.reshape(1))
    ctx.set_out(op, "Total", total.reshape(1))


@register("auc")
def _auc(ctx, op):
    """Batch AUC by threshold bucketing (operators/auc_op.cc semantics)."""
    preds = ctx.in1(op, "Out")            # [N, 2] probs or [N]
    label = ctx.in1(op, "Label")
    if preds.ndim == 2 and preds.shape[1] >= 2:
        pos_score = preds[:, 1]
    else:
        pos_score = preds.reshape(-1)
    label = label.reshape(-1).astype(jnp.float32)
    num_t = op.attr("num_thresholds", 200)
    thresholds = jnp.linspace(0.0, 1.0, num_t)
    pred_pos = pos_score[None, :] > thresholds[:, None]     # [T, N]
    tp = jnp.sum(pred_pos * label[None, :], axis=1)
    fp = jnp.sum(pred_pos * (1 - label[None, :]), axis=1)
    pos = jnp.sum(label)
    neg = label.shape[0] - pos
    tpr = tp / jnp.maximum(pos, 1.0)
    fpr = fp / jnp.maximum(neg, 1.0)
    # trapezoid over decreasing fpr
    auc = -jnp.trapezoid(tpr, fpr)
    ctx.set_out(op, "AUC", auc.reshape(1))


@register("precision_recall")
def _precision_recall(ctx, op):
    indices = ctx.in1(op, "Indices")
    label = ctx.in1(op, "Labels").reshape(-1)
    cls = op.attr("class_number")
    pred = indices.reshape(-1).astype(jnp.int32)
    label = label.astype(jnp.int32)
    oh_pred = jnp.eye(cls, dtype=jnp.float32)[pred]
    oh_lab = jnp.eye(cls, dtype=jnp.float32)[label]
    tp = jnp.sum(oh_pred * oh_lab, axis=0)
    fp = jnp.sum(oh_pred * (1 - oh_lab), axis=0)
    fn = jnp.sum((1 - oh_pred) * oh_lab, axis=0)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    recall = tp / jnp.maximum(tp + fn, 1.0)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-6)
    macro = jnp.stack([jnp.mean(precision), jnp.mean(recall), jnp.mean(f1)])
    tp_a, fp_a, fn_a = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    micro_p = tp_a / jnp.maximum(tp_a + fp_a, 1.0)
    micro_r = tp_a / jnp.maximum(tp_a + fn_a, 1.0)
    micro_f = 2 * micro_p * micro_r / jnp.maximum(micro_p + micro_r, 1e-6)
    micro = jnp.stack([micro_p, micro_r, micro_f])
    ctx.set_out(op, "BatchMetrics", jnp.concatenate([macro, micro]))
    ctx.set_out(op, "AccumStatesInfo",
                jnp.stack([tp, fp, fn], axis=1))


@register("edit_distance")
def _edit_distance(ctx, op):
    """Levenshtein distance between padded int sequences (operators/
    edit_distance_op.cc). Uses a scan over the DP table rows — static shapes
    keep it XLA-friendly."""
    import jax
    from jax import lax
    hyp = ctx.in1(op, "Hyps")
    ref = ctx.in1(op, "Refs")
    hyp_lod = ctx.maybe_get(op.input("Hyps")[0] + "@LOD")
    ref_lod = ctx.maybe_get(op.input("Refs")[0] + "@LOD")
    if hyp.ndim == 2 and hyp.shape[-1] == 1:
        hyp = hyp[..., 0][None, :] if hyp_lod is None else hyp[..., 0]
    if ref.ndim == 2 and ref.shape[-1] == 1:
        ref = ref[..., 0][None, :] if ref_lod is None else ref[..., 0]
    if hyp.ndim == 1:
        hyp = hyp[None, :]
    if ref.ndim == 1:
        ref = ref[None, :]

    def one_pair(h, r, hl, rl):
        m, n = h.shape[0], r.shape[0]
        row0 = jnp.arange(n + 1, dtype=jnp.float32)

        def step(prev_row, i):
            def inner(carry, j):
                left = carry
                diag = prev_row[j]
                up = prev_row[j + 1]
                cost = jnp.where(h[i] == r[j], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(up + 1, left + 1), diag + cost)
                val = jnp.where(j < rl, val, left)
                return val, val
            first = prev_row[0] + 1
            _, rest = lax.scan(inner, first, jnp.arange(n))
            row = jnp.concatenate([first[None], rest])
            row = jnp.where(i < hl, row, prev_row)
            return row, None

        final, _ = lax.scan(step, row0, jnp.arange(m))
        return final[rl]

    hls = (hyp_lod if hyp_lod is not None
           else jnp.full((hyp.shape[0],), hyp.shape[1]))
    rls = (ref_lod if ref_lod is not None
           else jnp.full((ref.shape[0],), ref.shape[1]))
    dists = jax.vmap(one_pair)(hyp, ref, hls, rls)
    if op.attr("normalized", True):
        dists = dists / jnp.maximum(rls.astype(jnp.float32), 1.0)
    ctx.set_out(op, "Out", dists.reshape(-1, 1))
    ctx.set_out(op, "SequenceNum", jnp.asarray([hyp.shape[0]], I64()))


@register("positive_negative_pair")
def _positive_negative_pair(ctx, op):
    """Ranking-pair metric (operators/positive_negative_pair_op.h): within
    each query, a pair with label_i > label_j is positive when
    score_i > score_j, negative when <, neutral when ==. Optional
    accumulator inputs carry totals across batches."""
    score = ctx.in1(op, "Score").reshape(-1)
    label = ctx.in1(op, "Label").reshape(-1)
    qid = ctx.in1(op, "QueryID").reshape(-1)
    col = int(op.attr("column", -1))
    s2 = ctx.in1(op, "Score")
    if s2.ndim == 2 and s2.shape[1] > 1:
        score = s2[:, col]

    # optional per-row weight; a pair weighs (w_i + w_j) / 2 (reference
    # positive_negative_pair_op.h). NB the reference also adds equal-score
    # pairs to the negative count alongside neutral (a double-count its
    # own tests don't pin down); here the three counts are disjoint.
    w = ctx.in1(op, "Weight")
    w = jnp.ones_like(score) if w is None else w.reshape(-1)

    n_rows = score.shape[0]

    def counts(rows, ok):
        """pair counts for row block `rows` (indices, validity `ok`) vs
        ALL rows — bounds pairwise memory at [chunk, N], not [N, N]."""
        s_i, l_i, q_i = (a[rows] for a in (score, label, qid))
        pair_w = (w[rows][:, None] + w[None, :]) * 0.5
        considered = ok[:, None] & \
            (q_i[:, None] == qid[None, :]) & \
            (l_i[:, None] > label[None, :])
        sc_d = s_i[:, None] - score[None, :]
        return jnp.stack([
            jnp.sum(jnp.where(considered & (sc_d > 0), pair_w, 0.0)),
            jnp.sum(jnp.where(considered & (sc_d < 0), pair_w, 0.0)),
            jnp.sum(jnp.where(considered & (sc_d == 0), pair_w, 0.0))])

    chunk = 2048
    if n_rows <= chunk:
        pos, neg, neu = counts(jnp.arange(n_rows),
                               jnp.ones((n_rows,), bool))
    else:
        pad = (-n_rows) % chunk
        idx = jnp.arange(n_rows + pad).reshape(-1, chunk)
        valid = idx < n_rows
        idx = jnp.clip(idx, 0, n_rows - 1)
        total, _ = jax.lax.scan(
            lambda acc, a: (acc + counts(a[0], a[1]), None),
            jnp.zeros(3), (idx, valid))
        pos, neg, neu = total

    acc_p = ctx.in1(op, "AccumulatePositivePair", jnp.zeros((1,)))
    acc_n = ctx.in1(op, "AccumulateNegativePair", jnp.zeros((1,)))
    acc_u = ctx.in1(op, "AccumulateNeutralPair", jnp.zeros((1,)))
    ctx.set_out(op, "PositivePair", pos.reshape(1) + acc_p.reshape(1))
    ctx.set_out(op, "NegativePair", neg.reshape(1) + acc_n.reshape(1))
    ctx.set_out(op, "NeutralPair", neu.reshape(1) + acc_u.reshape(1))
