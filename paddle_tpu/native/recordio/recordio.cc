// Chunked record file format — the durable on-disk story of the data
// plane. Reference capability: paddle/fluid/recordio/{chunk,header,
// scanner,writer} (chunk.h:26, header.h:39) — chunked, checksummed,
// optionally-compressed byte records. This is a fresh TPU-side design,
// not a port: little-endian fixed header, whole-chunk DEFLATE, CRC32 over
// the RAW payload so corruption is caught after decompression too.
//
// Layout:
//   file  := chunk*
//   chunk := magic(4)="PTRC" | version(u8)=1 | compressor(u8)
//          | num_records(u32) | raw_len(u64) | comp_len(u64)
//          | crc32(u32 over raw payload) | payload[comp_len]
//   raw payload := (rec_len(u32) | rec_bytes)*
// Compressors: 0 = none, 1 = zlib DEFLATE.
//
// C ABI for ctypes (no pybind11 in this image): every function returns
// 0/handle on success; rio_last_error() describes the latest failure.

#include <zlib.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace {

constexpr char kMagic[4] = {'P', 'T', 'R', 'C'};
constexpr uint8_t kVersion = 1;
constexpr size_t kHeaderSize = 4 + 1 + 1 + 4 + 8 + 8 + 4;

thread_local std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

void put_u32(std::string* out, uint32_t v) {
  for (int i = 0; i < 4; ++i) out->push_back(char((v >> (8 * i)) & 0xff));
}
void put_u64(std::string* out, uint64_t v) {
  for (int i = 0; i < 8; ++i) out->push_back(char((v >> (8 * i)) & 0xff));
}
uint32_t get_u32(const unsigned char* p) {
  return uint32_t(p[0]) | uint32_t(p[1]) << 8 | uint32_t(p[2]) << 16 |
         uint32_t(p[3]) << 24;
}
uint64_t get_u64(const unsigned char* p) {
  uint64_t v = 0;
  for (int i = 7; i >= 0; --i) v = (v << 8) | p[i];
  return v;
}

struct Writer {
  FILE* f = nullptr;
  int compressor = 1;          // default DEFLATE
  size_t max_chunk_bytes = 1 << 20;
  std::string raw;             // pending raw payload
  uint32_t num_records = 0;
  uint64_t total_records = 0;

  bool flush_chunk() {
    if (num_records == 0) return true;
    uint32_t crc =
        crc32(0L, reinterpret_cast<const Bytef*>(raw.data()), raw.size());
    std::string payload;
    int comp = compressor;
    if (comp == 1) {
      uLongf bound = compressBound(raw.size());
      payload.resize(bound);
      if (compress2(reinterpret_cast<Bytef*>(&payload[0]), &bound,
                    reinterpret_cast<const Bytef*>(raw.data()), raw.size(),
                    Z_DEFAULT_COMPRESSION) != Z_OK) {
        set_error("deflate failed");
        return false;
      }
      payload.resize(bound);
    } else {
      payload = raw;
    }
    std::string header;
    header.append(kMagic, 4);
    header.push_back(char(kVersion));
    header.push_back(char(comp));
    put_u32(&header, num_records);
    put_u64(&header, raw.size());
    put_u64(&header, payload.size());
    put_u32(&header, crc);
    if (fwrite(header.data(), 1, header.size(), f) != header.size() ||
        fwrite(payload.data(), 1, payload.size(), f) != payload.size()) {
      set_error("short write");
      return false;
    }
    raw.clear();
    num_records = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::string raw;           // current chunk raw payload
  size_t pos = 0;            // cursor into raw
  uint32_t remaining = 0;    // records left in current chunk
  std::string record;        // last record (returned pointer target)

  bool load_chunk() {
    unsigned char hdr[kHeaderSize];
    size_t n = fread(hdr, 1, kHeaderSize, f);
    if (n == 0) {
      if (ferror(f)) {
        set_error("read error in chunk header");
      }
      return false;  // clean EOF only when !ferror
    }
    if (n != kHeaderSize || memcmp(hdr, kMagic, 4) != 0) {
      set_error("corrupt chunk header");
      return false;
    }
    uint8_t version = hdr[4];
    uint8_t comp = hdr[5];
    if (version != kVersion) {
      set_error("unsupported version");
      return false;
    }
    uint32_t num = get_u32(hdr + 6);
    uint64_t raw_len = get_u64(hdr + 10);
    uint64_t comp_len = get_u64(hdr + 18);
    uint32_t crc = get_u32(hdr + 26);
    // corrupt length bytes must become errors, not multi-GB allocations
    // that throw through the C ABI and abort the process
    constexpr uint64_t kMaxChunk = 1ull << 31;
    if (raw_len > kMaxChunk || comp_len > kMaxChunk ||
        (comp == 0 && comp_len != raw_len)) {
      set_error("corrupt chunk header (implausible lengths)");
      return false;
    }
    std::string payload(comp_len, '\0');
    if (fread(&payload[0], 1, comp_len, f) != comp_len) {
      set_error("truncated chunk payload");
      return false;
    }
    if (comp == 1) {
      raw.resize(raw_len);
      uLongf out_len = raw_len;
      if (uncompress(reinterpret_cast<Bytef*>(&raw[0]), &out_len,
                     reinterpret_cast<const Bytef*>(payload.data()),
                     comp_len) != Z_OK ||
          out_len != raw_len) {
        set_error("inflate failed");
        return false;
      }
    } else {
      raw = std::move(payload);
      if (raw.size() != raw_len) {
        set_error("raw length mismatch");
        return false;
      }
    }
    uint32_t got =
        crc32(0L, reinterpret_cast<const Bytef*>(raw.data()), raw.size());
    if (got != crc) {
      set_error("chunk CRC mismatch");
      return false;
    }
    pos = 0;
    remaining = num;
    return true;
  }
};

}  // namespace

extern "C" {

const char* rio_last_error() { return g_error.c_str(); }

void* rio_writer_open(const char* path, int compressor,
                      uint64_t max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) {
    set_error(std::string("cannot open for write: ") + path);
    return nullptr;
  }
  Writer* w = new Writer();
  w->f = f;
  w->compressor = compressor;
  if (max_chunk_bytes) w->max_chunk_bytes = max_chunk_bytes;
  return w;
}

int rio_writer_write(void* handle, const char* buf, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  if (len > 0xffffffffull) {
    set_error("record too large (u32 length prefix)");
    return -1;
  }
  try {  // bad_alloc etc. must not unwind through the C ABI
    put_u32(&w->raw, uint32_t(len));
    w->raw.append(buf, len);
    w->num_records += 1;
    w->total_records += 1;
    if (w->raw.size() >= w->max_chunk_bytes) {
      if (!w->flush_chunk()) return -1;
    }
  } catch (const std::exception& e) {
    set_error(std::string("write failed: ") + e.what());
    return -1;
  }
  return 0;
}

uint64_t rio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  uint64_t total = w->total_records;
  bool ok = false;
  try {
    ok = w->flush_chunk();
  } catch (const std::exception& e) {
    set_error(std::string("flush failed: ") + e.what());
  }
  fclose(w->f);
  delete w;
  return ok ? total : uint64_t(-1);
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    set_error(std::string("cannot open for read: ") + path);
    return nullptr;
  }
  Scanner* s = new Scanner();
  s->f = f;
  return s;
}

// Returns pointer to the next record (valid until the next call) and sets
// *len; nullptr at EOF (*len = 0) or on error (*len = uint64 max).
const char* rio_scanner_next(void* handle, uint64_t* len) {
  Scanner* s = static_cast<Scanner*>(handle);
  if (s->remaining == 0) {
    // the header's num_records is outside the payload CRC: an understated
    // count would silently drop trailing records unless the cursor is
    // checked against the chunk end here
    if (!s->raw.empty() && s->pos != s->raw.size()) {
      set_error("trailing bytes in chunk (corrupt record count)");
      *len = uint64_t(-1);
      return nullptr;
    }
    g_error.clear();
    bool ok = false;
    try {
      ok = s->load_chunk();
    } catch (const std::exception& e) {  // never unwind through the C ABI
      set_error(std::string("chunk load failed: ") + e.what());
    }
    if (!ok) {
      *len = g_error.empty() ? 0 : uint64_t(-1);
      return nullptr;
    }
  }
  if (s->pos + 4 > s->raw.size()) {
    set_error("corrupt record length");
    *len = uint64_t(-1);
    return nullptr;
  }
  uint32_t rec_len =
      get_u32(reinterpret_cast<const unsigned char*>(s->raw.data()) + s->pos);
  s->pos += 4;
  if (s->pos + rec_len > s->raw.size()) {
    set_error("corrupt record payload");
    *len = uint64_t(-1);
    return nullptr;
  }
  s->record.assign(s->raw, s->pos, rec_len);
  s->pos += rec_len;
  s->remaining -= 1;
  *len = rec_len;
  return s->record.data();
}

void rio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
