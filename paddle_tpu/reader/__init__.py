"""Reader-decorator combinators + minibatching.

Reference parity: python/paddle/reader/decorator.py:29-236 (map_readers,
shuffle, chain, compose, buffered, firstn, xmap_readers) and
python/paddle/v2/minibatch.py (batch). A reader is a zero-arg callable
returning an iterator of samples.
"""

import itertools
import queue
import random as _random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "batch", "cache", "open_files",
           "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return data_reader


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])
    return reader


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(x) for x in outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(x) for x in outputs), ())
    return reader


def buffered(reader, size):
    """Prefetch up to `size` samples on a background thread (the host half of
    the reference's double_buffer reader op)."""
    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)

        def feed():
            try:
                for d in r:
                    q.put(d)
                q.put(_End)
            except BaseException as exc:   # propagate to the consumer
                q.put(exc)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            if isinstance(e, BaseException):
                raise e
            yield e
    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (decorator.py:236)."""
    end = object()

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def read_worker():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample) if order else sample)
            for _ in range(process_num):
                in_q.put(end)

        def map_worker():
            while True:
                sample = in_q.get()
                if sample is end:
                    out_q.put(end)
                    return
                if order:
                    i, s = sample
                    out_q.put((i, mapper(s)))
                else:
                    out_q.put(mapper(sample))

        threading.Thread(target=read_worker, daemon=True).start()
        workers = [threading.Thread(target=map_worker, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, s = item
                pending[i] = s
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item
    return data_reader


def cache(reader):
    all_data = []
    filled = []

    def data_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)
    return data_reader


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def open_files(filenames, thread_num=1, buffer_size=64, shard_id=None,
               num_shards=None, shuffle_files=False, seed=0,
               pass_num=1):
    """Multi-file recordio ingestion (reference layers/io.py:360
    open_files / operators/reader/open_files_op.cc parity, reshaped for
    the TPU data plane: a reader-creator instead of a graph reader op —
    feed it through paddle.batch / DataFeeder / DeviceLoader).

    * ``filenames``: list of recordio files (each written by
      recordio.convert_reader_to_recordio_file).
    * ``thread_num`` reader threads scan DISJOINT file subsets
      concurrently, decoding into one bounded queue (the reference's
      multi-threaded buffered reader). Samples interleave across files;
      order is nondeterministic when thread_num > 1, exactly like the
      reference's open_files without order preservation.
    * ``shard_id``/``num_shards``: keep only files [shard_id::num_shards]
      — the MULTI-HOST input story (each host reads its shard; defaults
      to jax.process_index()/process_count() when either is None and
      jax is multi-process).
    * ``shuffle_files``: shuffle the file order each pass (seeded).
    * ``pass_num``: repeat the whole file set that many times.
    """
    from ..recordio import reader as _file_reader
    filenames = list(filenames)
    if not filenames:
        raise ValueError("open_files: empty file list")
    if shard_id is None or num_shards is None:
        try:
            import jax
            if jax.process_count() > 1:
                shard_id = jax.process_index() \
                    if shard_id is None else shard_id
                num_shards = jax.process_count() \
                    if num_shards is None else num_shards
        except Exception:
            pass
    if (shard_id is None) != (num_shards is None):
        # half a sharding spec on a single-process host would silently
        # read ALL files — in a multi-host launch that DUPLICATES the
        # data instead of sharding it
        raise ValueError(
            "open_files: got %s without %s — pass both shard_id and "
            "num_shards (or neither, to default from the jax process "
            "layout)" % (("shard_id", "num_shards") if num_shards is None
                         else ("num_shards", "shard_id")))
    if shard_id is not None and not 0 <= int(shard_id) < int(num_shards):
        raise ValueError(
            "open_files: shard_id %s out of range for num_shards %s"
            % (shard_id, num_shards))
    if num_shards and num_shards > 1:
        mine = filenames[int(shard_id or 0)::int(num_shards)]
        if not mine:
            raise ValueError(
                "open_files: shard %s of %s gets no files out of %d"
                % (shard_id, num_shards, len(filenames)))
        filenames = mine

    end = object()
    invocation = [0]          # distinct shuffle order per epoch/call

    def data_reader():
        inv = invocation[0]
        invocation[0] += 1
        rng = _random.Random(seed + inv)
        for _ in range(max(1, int(pass_num))):
            files = list(filenames)
            if shuffle_files:
                rng.shuffle(files)
            n_thr = max(1, min(int(thread_num), len(files)))
            out_q = queue.Queue(buffer_size)
            stop = threading.Event()

            def _put(item):
                # bounded put that gives up when the consumer abandoned
                # the pass, so no worker blocks forever on a full queue
                while not stop.is_set():
                    try:
                        out_q.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False

            def scan_worker(my_files):
                try:
                    for f in my_files:
                        it = _file_reader(f)()
                        try:
                            for sample in it:
                                if not _put(sample):
                                    return      # pass abandoned
                        finally:
                            it.close()          # frees the scanner FILE*
                except BaseException as e:      # propagate, don't truncate
                    _put((end, e))
                    return
                _put((end, None))

            threads = [threading.Thread(
                target=scan_worker, args=(files[t::n_thr],), daemon=True)
                for t in range(n_thr)]
            for t in threads:
                t.start()
            try:
                done = 0
                while done < n_thr:
                    sample = out_q.get()
                    if isinstance(sample, tuple) and len(sample) == 2 \
                            and sample[0] is end:
                        if sample[1] is not None:
                            raise sample[1]     # a scan thread failed
                        done += 1
                    else:
                        yield sample
            finally:
                # early abandon (consumer break / error / .close()):
                # release blocked putters and reap the threads
                stop.set()
                try:
                    while True:
                        out_q.get_nowait()
                except queue.Empty:
                    pass
                for t in threads:
                    t.join(timeout=5.0)

    return data_reader


from .device_loader import DeviceLoader, repeat_feed  # noqa: F401,E402
__all__ += ["DeviceLoader", "repeat_feed"]
