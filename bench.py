"""Driver benchmark entry: prints ONE JSON line with the headline metric.

Flagship: ResNet-50 ImageNet training throughput, bf16, one TPU chip
(BASELINE.json north star metric #1: ResNet-50 images/sec/chip). The same
line carries the second north-star metric — Transformer LM tokens/sec/chip
(flash-attention fused path) — as extra fields.

vs_baseline anchor: the reference's only in-tree ResNet-50 *training*
number — 81.69 imgs/sec (Intel MKL-DNN, 2×Xeon 6148, bs=64,
benchmark/IntelOptimizedPaddle.md; BASELINE.md). The reference has no
single-GPU ResNet-50 number; its closest GPU figure is AlexNet at 383
imgs/sec on a K40m.

MFU methodology and the measured per-op ceilings backing these numbers:
PERF.md.
"""

import json
import os
import sys

# ResNet-50 train step ~3x fwd FLOPs (fwd 4.1 GFLOP/img @224); v5e peak
# 197 bf16 TFLOP/s — MFU printed alongside throughput per VERDICT r1 #2.
FLOPS_PER_IMG_TRAIN = 3 * 4.1e9
PEAK_BF16 = 197e12


def flops_per_token(L, D, FFN, T, V):
    """Train-step FLOPs per token of a decoder-only LM (3x forward)."""
    return 3 * (L * (8 * D * D + 4 * D * FFN + 4 * T * D) + 2 * D * V)


def _run(argv):
    sys.argv = [sys.argv[0]] + argv


def main():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmarks"))
    # median-of-5 timing windows: the sandbox tunnel's variance must not
    # be recorded as the chip's number (PERF.md "Measurement variance");
    # the median over >=5 windows carries its own error bar.
    os.environ.setdefault("PADDLE_TPU_BENCH_WINDOWS", "5")

    _run(["--batch_size", "256", "--iterations", "20",
          "--skip_batch_num", "3", "--device", "TPU",
          "--dtype", "bfloat16"])
    from resnet import main as resnet_main
    ips = resnet_main()
    baseline = 81.69
    mfu = ips * FLOPS_PER_IMG_TRAIN / PEAK_BF16
    print("ResNet-50 MFU %.1f%% (%.1f img/s)" % (mfu * 100, ips),
          file=sys.stderr)

    # fresh graph state for the second model (both mains build into the
    # default program)
    import paddle_tpu as fluid
    from paddle_tpu.core import scope as scope_mod
    fluid.switch_main_program(fluid.Program())
    fluid.switch_startup_program(fluid.Program())
    scope_mod._global_scope = scope_mod.Scope()
    fluid.amp.enable_amp(False)

    def _fresh():
        fluid.switch_main_program(fluid.Program())
        fluid.switch_startup_program(fluid.Program())
        scope_mod._global_scope = scope_mod.Scope()
        fluid.amp.enable_amp(False)

    import importlib

    def transformer_bench(label, bs, L=4, D=512, FFN=2048, T=256,
                          V=8192, heads=None):
        """One transformer config through benchmarks/transformer.py;
        returns (tok/s, mfu) or (None, None) — ResNet stays the
        headline even if a transformer config fails."""
        _fresh()
        argv = ["--batch_size", str(bs), "--iterations", "10",
                "--skip_batch_num", "3", "--device", "TPU",
                "--dtype", "bfloat16", "--n_layer", str(L),
                "--d_model", str(D), "--d_inner", str(FFN),
                "--max_len", str(T), "--vocab", str(V)]
        if heads:
            argv += ["--n_head", str(heads)]
        _run(argv)
        try:
            import transformer as tmod
            tps = float(importlib.reload(tmod).main())
        except Exception as e:
            print("%s bench failed: %s" % (label, e), file=sys.stderr)
            return None, None
        mfu = tps * flops_per_token(L, D, FFN, T, V) / PEAK_BF16
        print("%s MFU %.1f%% (%.0f tok/s)" % (label, mfu * 100, tps),
              file=sys.stderr)
        return tps, mfu

    def resnet_repeat():
        _fresh()
        _run(["--batch_size", "256", "--iterations", "20",
              "--skip_batch_num", "3", "--device", "TPU",
              "--dtype", "bfloat16"])
        import resnet as rmod
        try:
            return float(importlib.reload(rmod).main())
        except Exception as e:
            print("resnet repeat failed: %s" % e, file=sys.stderr)
            return None

    def lstm_repeat():
        """The reference's strongest published training line: stacked
        dynamic LSTM (benchmark/README.md 184 ms/batch, h=512 bs=64 on
        a K40m) — the LoD/bucketing path under perf, not just
        correctness. Returns ms/batch (lower is better)."""
        _fresh()
        _run(["--batch_size", "64", "--hidden_dim", "512",
              "--iterations", "12", "--skip_batch_num", "2",
              "--device", "TPU"])
        try:
            import stacked_dynamic_lstm as lmod
            return float(importlib.reload(lmod).main())
        except Exception as e:
            print("lstm repeat failed: %s" % e, file=sys.stderr)
            return None

    # INTERLEAVED repeats (VERDICT r4 #7): the tunnel drifts +-30%
    # across a session, so each config is measured K times spread across
    # the whole invocation and reported as median + spread — a
    # round-over-round delta smaller than the spread is noise.
    K = max(1, int(os.environ.get("PADDLE_TPU_BENCH_REPEATS", "3")))
    res_s, large_s, xl_s, lstm_s = [ips], [], [], []
    tps_small = None
    for r in range(K):
        if r > 0:
            res_s.append(resnet_repeat())
        if r == 0:
            # bs256: the throughput-saturating batch for the 4L/d512
            # config — bs32 is dispatch-latency-bound (PERF.md batch
            # sweep); one sample (secondary metric)
            tps_small, _ = transformer_bench("Transformer-small", bs=256)
        # the LARGE config (8L d1024 ffn4096 T1024): kept unchanged for
        # round-over-round comparability
        large_s.append(transformer_bench(
            "Transformer-large", bs=8, L=8, D=1024, FFN=4096, T=1024)[0])
        # the XL config — the best honest MFU this chip reaches (width
        # sweep, PERF.md round 4): 8L d2048 ffn8192 T1024, head dim 128
        xl_s.append(transformer_bench(
            "Transformer-XL", bs=8, L=8, D=2048, FFN=8192, T=1024,
            heads=16)[0])
        lstm_s.append(lstm_repeat())

    def monitor_probe():
        """One short MONITORED window (benchmarks/mnist.py shrunk):
        paddle_tpu.monitor armed with flight recorder + cost model, the
        summary stamped into the bench JSON. Kept separate from the
        headline timing windows because the monitor syncs every step
        for honest latency — on the sandbox tunnel that per-step sync
        costs ~90 ms and would corrupt the throughput protocol."""
        from paddle_tpu import monitor as mon
        _fresh()
        log = "/tmp/ptpu_bench_monitor.jsonl"
        try:
            os.remove(log)
        except OSError:
            pass
        # monitor.session(): respects an env-armed ambient config and
        # reports the PROBE's own counts as deltas, so the stamp never
        # aggregates the headline windows' steps
        try:
            with mon.session(log_path=log) as sess:
                _run(["--batch_size", "128", "--iterations", "10",
                      "--skip_batch_num", "2", "--device", "TPU"])
                import mnist as mmod
                importlib.reload(mmod).main()
        except Exception as e:
            print("monitor probe failed: %s" % e, file=sys.stderr)
            return None
        s = sess.summary()
        probe = {
            "steps": s["steps"],
            "p50_ms": round(1000 * s["p50_s"], 3) if s["p50_s"] else None,
            "p95_ms": round(1000 * s["p95_s"], 3) if s["p95_s"] else None,
            "recompiles": s["recompiles"],
            "tokens_per_sec": round(s["tokens_per_sec"], 1)
            if s["tokens_per_sec"] else None,
            "mfu_pct": round(100 * s["mfu"], 2) if s["mfu"] else None,
            "log": log,
        }
        print("monitor probe: %s" % probe, file=sys.stderr)
        return probe

    monitor_summary = monitor_probe()

    def serving_probe():
        """Continuous-batching serving smoke (benchmarks/serving_bench
        fast CPU mode): engine-vs-sequential aggregate tokens/s on a
        mixed-length request set, with token identity verified. Runs on
        the CPU backend — the engine's win is scheduling, measured
        without the tunnel's per-step sync tax — and is stamped into
        the bench JSON like the monitor probe."""
        _fresh()
        _run(["--device", "CPU", "--fast"])
        try:
            import serving_bench as smod
            return importlib.reload(smod).main()
        except Exception as e:
            print("serving probe failed: %s" % e, file=sys.stderr)
            return None

    serving_summary = serving_probe()

    import statistics

    def agg(samples):
        vals = sorted(v for v in samples if v)
        if not vals:
            return None, None, []
        med = statistics.median(vals)
        spread = 100.0 * (vals[-1] - vals[0]) / med if med else 0.0
        return med, round(spread, 1), [round(v, 1) for v in vals]

    ips, res_spread, res_samples = agg(res_s)
    mfu = ips * FLOPS_PER_IMG_TRAIN / PEAK_BF16
    large_flops_tok = flops_per_token(L=8, D=1024, FFN=4096, T=1024,
                                      V=8192)
    xl_flops_tok = flops_per_token(L=8, D=2048, FFN=8192, T=1024, V=8192)
    tps_large, large_spread, large_samples = agg(large_s)
    tps_xl, xl_spread, xl_samples = agg(xl_s)
    lstm_ms, lstm_spread, lstm_samples = agg(lstm_s)

    out = {
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(float(ips), 1),
        "unit": "imgs/sec",
        "vs_baseline": round(float(ips) / baseline, 2),
        "mfu_pct": round(mfu * 100, 1),
        "repeats": K,
        "spread_pct": res_spread,
        "samples": res_samples,
    }
    if tps_small is not None:
        out["transformer_tokens_per_sec_per_chip"] = round(tps_small, 0)
    if tps_large is not None:
        out["transformer_large_tokens_per_sec_per_chip"] = round(tps_large, 0)
        out["transformer_large_mfu_pct"] = round(
            tps_large * large_flops_tok / PEAK_BF16 * 100, 1)
        out["transformer_large_spread_pct"] = large_spread
        out["transformer_large_samples"] = large_samples
    if tps_xl is not None:
        out["transformer_xl_tokens_per_sec_per_chip"] = round(tps_xl, 0)
        out["transformer_xl_mfu_pct"] = round(
            tps_xl * xl_flops_tok / PEAK_BF16 * 100, 1)
        out["transformer_xl_spread_pct"] = xl_spread
        out["transformer_xl_samples"] = xl_samples
    if lstm_ms is not None:
        # reference anchor: 184 ms/batch (K40m, h=512 bs=64) — LOWER is
        # better, so vs_baseline > 1 means faster than the reference
        out["lstm_ms_per_batch"] = round(lstm_ms, 1)
        out["lstm_vs_baseline"] = round(184.0 / lstm_ms, 2)
        out["lstm_spread_pct"] = lstm_spread
        out["lstm_samples"] = lstm_samples
    if monitor_summary is not None:
        # runtime-telemetry stamp (paddle_tpu.monitor): per-step p50/p95,
        # recompile count and cost-model MFU of the monitored probe
        out["monitor"] = monitor_summary
    if serving_summary is not None:
        # continuous-batching stamp (paddle_tpu.serving): engine vs
        # sequential tokens/s, speedup, occupancy, token identity
        out["serving"] = serving_summary
    print(json.dumps(out))


if __name__ == "__main__":
    main()
