"""Segment compilation for host-op programs (core/executor.py
_run_segments).

Round-1 verdict weak #5: a single send/recv op used to drop the WHOLE
step into the op-by-op eager interpreter. Now the compute runs between
host ops are jit-compiled and cached per (program version, segment,
signature) — the reference also only left graph land for the RPC ops
themselves (listen_and_serv_op.cc). These tests pin:
 - numeric parity: segment-compiled == full-eager (flag off) on a
   trainer program with send/recv against a live VariableServer;
 - the cache actually holds segment executables and re-running the
   same program does not add new entries (no per-step retrace);
 - sparse path (prefetch before the grad marker) is ALSO segment
   compiled (round-2 verdict #3: the eager fallback is lifted) — the
   prefetched rows enter the compiled fwd+bwd segment as a concrete
   gradient leaf, and grads stay correct.
"""

import threading

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.distributed import ops as dist_ops
from paddle_tpu.distributed.rpc import RPCClient, VariableServer


def _run_send_recv_trainer(steps=4):
    """Trainer computing grads locally, pushing them to a VariableServer
    (plain SGD server-side), and pulling the updated param back — the
    transpiled pserver-mode trainer shape, built directly."""
    server = VariableServer(
        fan_in=1,
        optimize_fn=lambda store, grads: store.update(
            {"w": store["w"] - 0.1 * np.asarray(grads["w@GRAD"])})).start()
    ep = "127.0.0.1:%d" % server.port
    server.store["w"] = np.zeros((4, 1), np.float32)

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = fluid.layers.data("x", [4])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(
            x, 1, bias_attr=False,
            param_attr=fluid.ParamAttr(
                name="w", initializer=fluid.initializer.Constant(0.0)))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.backward.append_backward(loss)
        blk = main.global_block()
        blk.append_op("send", {"X": ["w@GRAD"]}, {},
                      {"epmap": [ep], "endpoints": [ep], "sync": True})
        blk.append_op("recv", {}, {"Out": ["w"]},
                      {"epmap": [ep], "endpoints": [ep]})

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        xv = rng.rand(16, 4).astype(np.float32)
        yv = (xv @ np.array([1., 2., 3., 4.], np.float32))[:, None]
        losses = []
        for _ in range(steps):
            l, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
        w = np.asarray(scope.find_var("w")).copy()
    try:
        cli = RPCClient(ep)
        cli.shutdown_server()
        cli.close()
    finally:
        dist_ops.reset_clients()
    return losses, w, exe


def test_segment_parity_with_full_eager_and_cache_reuse():
    losses_seg, w_seg, exe_seg = _run_send_recv_trainer()
    seg_entries = [k for k in exe_seg._cache if k[0] == "segment"]
    assert seg_entries, "segment compilation did not engage"
    # 4 identical steps must share the same compiled segments: entry
    # count bounded by the number of compute segments (2: fwd+bwd, and
    # the tail after recv if any), not by the step count
    assert len(seg_entries) <= 3

    flags.set_flag("segment_compile", False)
    try:
        losses_eager, w_eager, exe_eager = _run_send_recv_trainer()
        assert not [k for k in exe_eager._cache if k[0] == "segment"]
    finally:
        flags.set_flag("segment_compile", None)

    np.testing.assert_allclose(losses_seg, losses_eager, rtol=1e-5)
    np.testing.assert_allclose(w_seg, w_eager, rtol=1e-5, atol=1e-6)
    # and it actually trained
    assert losses_seg[-1] < losses_seg[0]


def test_prefetch_before_marker_is_segment_compiled():
    """A host op feeding the forward (sparse embedding prefetch) no
    longer drops the step to the interpreter: the fwd+bwd still runs as
    a compiled segment with the prefetched rows as a concrete input
    (executor._grad_leaves_concrete), and gradients are exact."""
    table = np.arange(12, dtype=np.float32).reshape(6, 2)
    server = VariableServer(fan_in=1).start()
    ep = "127.0.0.1:%d" % server.port
    server.store["emb"] = table

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        ids = fluid.layers.data("ids", [1], dtype="int64")
        blk = main.global_block()
        rows = blk.create_var(name="rows", shape=[2, 2], dtype="float32")
        blk.append_op("prefetch", {"X": ["ids"]}, {"Out": ["rows"]},
                      {"epmap": [ep], "endpoints": [ep],
                       "table_name": "emb"})
        pred = fluid.layers.fc(rows, 1, bias_attr=False,
                               param_attr=fluid.ParamAttr(
                                   name="w_pf",
                                   initializer=fluid.initializer.Constant(
                                       0.5)))
        loss = fluid.layers.mean(pred)
        fluid.append_backward(loss)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        idv = np.array([[1], [3]], np.int64)
        l, g = exe.run(main, feed={"ids": idv},
                       fetch_list=[loss, "w_pf@GRAD"])
        # loss = mean(rows @ w); grad wrt w = mean over requested rows
        np.testing.assert_allclose(
            np.asarray(g).ravel(), table[[1, 3]].mean(axis=0), rtol=1e-5)
        np.testing.assert_allclose(
            float(np.asarray(l)), float(table[[1, 3]].mean() * 2 * 0.5),
            rtol=1e-5)
        assert [k for k in exe._cache if k[0] == "segment"], \
            "prefetch-bearing program was not segment compiled"
    try:
        cli = RPCClient(ep)
        cli.shutdown_server()
        cli.close()
    finally:
        dist_ops.reset_clients()


def test_prefetch_wrt_leaf_interpreter_fallback_parity():
    """With segment compilation OFF, the interpreter path must run the
    wrt-producing host op (prefetch) eagerly BEFORE the grad trace and
    still produce the same loss and grads w.r.t. the prefetched rows."""
    table = np.arange(12, dtype=np.float32).reshape(6, 2)

    def run_once():
        server = VariableServer(fan_in=1).start()
        ep = "127.0.0.1:%d" % server.port
        server.store["emb"] = table
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.scope_guard(scope):
            ids = fluid.layers.data("ids", [1], dtype="int64")
            blk = main.global_block()
            blk.create_var(name="rows2", shape=[2, 2], dtype="float32")
            blk.append_op("prefetch", {"X": ["ids"]}, {"Out": ["rows2"]},
                          {"epmap": [ep], "endpoints": [ep],
                           "table_name": "emb"})
            rows_v = blk.var("rows2")
            pred = fluid.layers.fc(rows_v, 1, bias_attr=False,
                                   param_attr=fluid.ParamAttr(
                                       name="w_pf2",
                                       initializer=fluid.initializer
                                       .Constant(0.5)))
            loss = fluid.layers.mean(pred)
            fluid.append_backward(loss, parameter_list=["w_pf2", "rows2"])
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            l, g = exe.run(main, feed={"ids": np.array([[1], [3]],
                                                       np.int64)},
                           fetch_list=[loss, "rows2@GRAD"])
            out = float(np.asarray(l)), np.asarray(g).copy()
        try:
            cli = RPCClient(ep)
            cli.shutdown_server()
            cli.close()
        finally:
            dist_ops.reset_clients()
        return out

    l_seg, g_seg = run_once()
    flags.set_flag("segment_compile", False)
    try:
        l_eager, g_eager = run_once()
    finally:
        flags.set_flag("segment_compile", None)
    np.testing.assert_allclose(l_eager, l_seg, rtol=1e-6)
    np.testing.assert_allclose(g_eager, g_seg, rtol=1e-6)
    # grad of mean(rows @ w) wrt rows = w/N broadcast
    np.testing.assert_allclose(g_seg, np.full((2, 2), 0.5 / 2), rtol=1e-6)
