"""LR decay schedules built as graph ops over a step counter.

Reference parity: python/paddle/fluid/layers/learning_rate_scheduler.py
(noam/exponential/natural_exp/inverse_time/polynomial/piecewise decay).
Each returns a Variable usable as ``Optimizer(learning_rate=...)``; the step
counter is a persistable var incremented once per executed step, so the
schedule advances with training exactly like the reference's
``_decay_step_counter``.
"""

import math

from .layer_helper import LayerHelper
from .tensor import cast, fill_constant
from ..core import unique_name
from ..initializer import ConstantInitializer

__all__ = ["exponential_decay", "natural_exp_decay", "inverse_time_decay",
           "polynomial_decay", "piecewise_decay", "noam_decay"]


def _decay_step_counter(begin=0):
    helper = LayerHelper("global_step_counter")
    counter = helper.create_global_variable(
        name=unique_name.generate("@LR_DECAY_COUNTER@"), shape=[1],
        dtype="float32", persistable=True)
    helper.set_variable_initializer(
        counter, ConstantInitializer(float(begin - 1)))
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": 1.0})
    counter.stop_gradient = True
    return counter


def _unary(x, op_type, **attrs):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (Transformer schedule)."""
    from .ops import elementwise_min
    step = _decay_step_counter(begin=1)
    a = step ** -0.5
    b = (warmup_steps ** -1.5) * step
    return (d_model ** -0.5) * elementwise_min(a, b)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = _unary(div, "floor")
    return learning_rate * (float(decay_rate) ** div)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = _unary(div, "floor")
    return learning_rate * _unary(-1.0 * float(decay_rate) * div, "exp")


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = _unary(div, "floor")
    return learning_rate / (1.0 + float(decay_rate) * div)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from .ops import elementwise_max
    step = _decay_step_counter()
    if cycle:
        ratio = _unary(step / float(decay_steps), "ceil")
        # when step == 0, divisor must be 1 not 0
        one = fill_constant([1], "float32", 1.0)
        ratio = elementwise_max(ratio, one)
        decay_var = float(decay_steps) * ratio
        frac = step / decay_var
    else:
        # clip step to decay_steps
        cap = fill_constant([1], "float32", float(decay_steps))
        from .ops import elementwise_min
        step = elementwise_min(step, cap)
        frac = step / float(decay_steps)
    return (float(learning_rate) - float(end_learning_rate)) * \
        ((1.0 - frac) ** power) + float(end_learning_rate)


def piecewise_decay(boundaries, values):
    """Piecewise-constant schedule: lr = values[i] on
    [boundaries[i-1], boundaries[i])."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    step = _decay_step_counter()
    # sum of indicator-weighted segment values (compiles to pure XLA
    # select arithmetic; the reference used a Switch control-flow block)
    lr = None
    for i, v in enumerate(values):
        if i == 0:
            ind = cast(step < float(boundaries[0]), "float32")
        elif i == len(values) - 1:
            ind = cast(step >= float(boundaries[-1]), "float32")
        else:
            ind = cast(step >= float(boundaries[i - 1]), "float32") * \
                  cast(step < float(boundaries[i]), "float32")
        term = ind * float(v)
        lr = term if lr is None else lr + term
    return lr
