"""v2 optimizers (python/paddle/v2/optimizer.py parity): thin wrappers that
carry the config until the trainer appends the real fluid optimizer ops."""

from .. import optimizer as fluid_optimizer


class Optimizer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def _make(self):
        raise NotImplementedError

    def create_updater(self):
        return self._make()


class SGD(Optimizer):
    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(**kwargs)
        self.learning_rate = learning_rate

    def _make(self):
        return fluid_optimizer.SGD(learning_rate=self.learning_rate)


class Momentum(Optimizer):
    def __init__(self, momentum=0.9, learning_rate=0.01, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.learning_rate = learning_rate

    def _make(self):
        return fluid_optimizer.Momentum(learning_rate=self.learning_rate,
                                        momentum=self.momentum)


class Adam(Optimizer):
    def __init__(self, learning_rate=1e-3, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.learning_rate = learning_rate
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _make(self):
        return fluid_optimizer.Adam(learning_rate=self.learning_rate,
                                    beta1=self.beta1, beta2=self.beta2,
                                    epsilon=self.epsilon)


class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, epsilon=1e-6, **kwargs):
        super().__init__(**kwargs)
        self.learning_rate = learning_rate
        self.epsilon = epsilon

    def _make(self):
        return fluid_optimizer.Adagrad(learning_rate=self.learning_rate,
                                       epsilon=self.epsilon)


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.01, rho=0.95, epsilon=1e-6,
                 **kwargs):
        super().__init__(**kwargs)
        self.learning_rate = learning_rate
        self.rho, self.epsilon = rho, epsilon

    def _make(self):
        return fluid_optimizer.RMSProp(learning_rate=self.learning_rate,
                                       rho=self.rho, epsilon=self.epsilon)
