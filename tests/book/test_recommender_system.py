"""Book test: recommender_system (reference
python/paddle/fluid/tests/book/test_recommender_system.py) — two-tower
user/movie model over movielens: id/categorical embeddings + pooled
sequence features -> cos_sim -> scaled score regression."""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu as fluid
import paddle_tpu.dataset.movielens as movielens


def get_usr_combined_features():
    usr_id = fluid.layers.data("user_id", [1], dtype="int64")
    gender = fluid.layers.data("gender_id", [1], dtype="int64")
    age = fluid.layers.data("age_id", [1], dtype="int64")
    job = fluid.layers.data("job_id", [1], dtype="int64")
    emb = lambda x, n: fluid.layers.fc(
        fluid.layers.embedding(x, size=[n, 16]), 16)
    feats = [emb(usr_id, movielens.max_user_id() + 1),
             emb(gender, 2),
             emb(age, len(movielens.age_table()) + 1),
             emb(job, movielens.max_job_id() + 1)]
    concat = fluid.layers.concat(feats, axis=1)
    return fluid.layers.fc(concat, 32, act="tanh"), \
        [usr_id, gender, age, job]


def get_mov_combined_features():
    mov_id = fluid.layers.data("movie_id", [1], dtype="int64")
    category = fluid.layers.data("category_id", [1], dtype="int64",
                                 lod_level=1)
    title = fluid.layers.data("movie_title", [1], dtype="int64",
                              lod_level=1)
    mov_emb = fluid.layers.fc(
        fluid.layers.embedding(mov_id, size=[movielens.max_movie_id() + 1,
                                             16]), 16)
    cat_pool = fluid.layers.sequence_pool(
        fluid.layers.embedding(category,
                               size=[movielens.CATEGORIES, 16]), "sum")
    title_pool = fluid.layers.sequence_pool(
        fluid.layers.embedding(title, size=[movielens.TITLE_VOCAB + 1, 16]),
        "sum")
    concat = fluid.layers.concat([mov_emb, cat_pool, title_pool], axis=1)
    return fluid.layers.fc(concat, 32, act="tanh"), \
        [mov_id, category, title]


def test_recommender_system_trains():
    usr, usr_vars = get_usr_combined_features()
    mov, mov_vars = get_mov_combined_features()
    inference = fluid.layers.cos_sim(usr, mov)
    scale_infer = fluid.layers.scale(inference, scale=5.0)
    label = fluid.layers.data("score", [1])
    cost = fluid.layers.square_error_cost(scale_infer, label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    reader = paddle.batch(paddle.reader.shuffle(movielens.train(), 256),
                          batch_size=32)
    feed_vars = usr_vars + mov_vars + [label]
    feeder = fluid.DataFeeder(feed_vars, fluid.CPUPlace())

    first = last = None
    for epoch in range(8):
        for batch in reader():
            feed = feeder.feed(batch)
            for k in ("user_id", "gender_id", "age_id", "job_id",
                      "movie_id"):
                feed[k] = np.asarray(feed[k]).reshape(-1, 1)
            feed["score"] = np.asarray(feed["score"]).reshape(-1, 1)
            lv, = exe.run(feed=feed, fetch_list=[avg_cost])
            if first is None:
                first = float(lv)
            last = float(lv)
    # reference threshold: test cost < 6 (score scale 1-5); require a real
    # fit well under the variance of the score distribution
    assert last < first * 0.7, (first, last)
    assert last < 2.0, last
