"""Evaluator façade (reference python/paddle/fluid/evaluator.py).

The reference deprecated this module in favor of fluid.metrics; its
classes are kept for script parity. ChunkEvaluator and EditDistance are
the host-side metric accumulators from paddle_tpu.metrics. DetectionMAP
appends the ``detection_map`` op to the current program (evaluator.py:257
semantics) and averages the per-batch mAP host-side via update()."""

import numpy as np

from .metrics import ChunkEvaluator, EditDistance  # noqa: F401 (parity)

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


class DetectionMAP:
    """Builds the mAP computation over detection results.

    Args mirror the reference (input [M, 6] det results, gt label/box);
    `self.metrics` holds the per-batch mAP Variable to fetch, and
    update(map_value)/eval() accumulate the running mean across batches.
    """

    def __init__(self, input, gt_label, gt_box=None, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="11point"):
        from . import layers
        from .layers.layer_helper import LayerHelper

        # both AP versions are implemented ("11point" interpolated and
        # "integral" recall-delta); evaluate_difficult=False excludes
        # difficult ground truth VOC-style via the gt_difficult column;
        # class_num > 0 gives true per-class-averaged mAP (else AP is
        # class-pooled — see ops/detection_ops.py _detection_map)
        if ap_version not in ("11point", "integral"):
            raise ValueError(
                "DetectionMAP: ap_version must be '11point' or "
                "'integral', got %r" % (ap_version,))
        if not evaluate_difficult and gt_difficult is None:
            # same contract as layers.detection_map: excluding difficult
            # GT without the difficult flags would silently count them
            raise ValueError(
                "DetectionMAP: evaluate_difficult=False needs the "
                "gt_difficult ground-truth flag input")

        helper = LayerHelper("detection_map_eval")
        label = gt_label if gt_box is None else \
            layers.concat([gt_label, gt_box], axis=1)
        inputs = {"DetectRes": [input], "Label": [label]}
        if not evaluate_difficult and gt_difficult is not None:
            inputs["Difficult"] = [gt_difficult]
        m = helper.create_variable_for_type_inference("float32", shape=(1,))
        acc = helper.create_variable_for_type_inference("int64", shape=(1,))
        helper.append_op(
            type="detection_map",
            inputs=inputs,
            outputs={"MAP": [m], "AccumPosCount": [acc]},
            attrs={"overlap_threshold": overlap_threshold,
                   "ap_version": ap_version,
                   "class_num": int(class_num or 0),
                   "background_label": background_label,
                   "evaluate_difficult": evaluate_difficult})
        self.metrics = [m]
        self.reset()

    def reset(self, executor=None, reset_program=None):
        self._sum = 0.0
        self._n = 0

    def update(self, map_value):
        self._sum += float(np.asarray(map_value).reshape(-1)[0])
        self._n += 1

    def eval(self, executor=None, eval_program=None):
        if not self._n:
            raise ValueError("eval() before any update(); no batches seen")
        return np.array([self._sum / self._n], np.float32)
