"""Reference-parity layer wrappers (layers/compat.py): every wrapper
drives its op end-to-end through a user-style program."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _run(fetches, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed, fetch_list=list(fetches))


def test_mul_pad_sum_multiplex():
    x = fluid.layers.data("x", [3])
    y = fluid.layers.data("y", [3, 2], append_batch_size=False)
    m = fluid.layers.mul(x, y)
    p = fluid.layers.pad(x, [0, 0, 1, 1], pad_value=9.0)
    s = fluid.layers.sums([x, x])
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    yv = np.ones((3, 2), np.float32)
    mv, pv, sv = _run([m, p, s], {"x": xv, "y": yv})
    np.testing.assert_allclose(np.asarray(mv), xv @ yv)
    assert np.asarray(pv).shape == (2, 5)
    np.testing.assert_allclose(np.asarray(pv)[:, 0], 9.0)
    np.testing.assert_allclose(np.asarray(sv), 2 * xv)


def test_random_and_batch_size_like():
    u = fluid.layers.uniform_random([2000], min=-2.0, max=2.0)
    g = fluid.layers.gaussian_random([2000], mean=1.0, std=2.0)
    x = fluid.layers.data("x", [4])
    ub = fluid.layers.uniform_random_batch_size_like(x, [-1, 7])
    uv, gv, ubv = _run([u, g, ub], {"x": np.zeros((5, 4), np.float32)})
    assert -2.0 <= float(np.asarray(uv).min()) and \
        float(np.asarray(uv).max()) <= 2.0
    assert abs(float(np.asarray(gv).mean()) - 1.0) < 0.3
    assert np.asarray(ubv).shape == (5, 7)


def test_smooth_l1_and_lrn():
    x = fluid.layers.data("x", [4])
    y = fluid.layers.data("y", [4])
    l = fluid.layers.smooth_l1(x, y)
    img = fluid.layers.data("img", [4, 6, 6])
    n = fluid.layers.lrn(img)
    lv, nv = _run([l, n], {"x": np.zeros((2, 4), np.float32),
                           "y": np.ones((2, 4), np.float32),
                           "img": np.ones((2, 4, 6, 6), np.float32)})
    np.testing.assert_allclose(np.asarray(lv).reshape(-1), 2.0, rtol=1e-5)
    assert np.asarray(nv).shape == (2, 4, 6, 6)


def test_im2sequence_and_mulplex():
    img = fluid.layers.data("img", [1, 4, 4])
    seq = fluid.layers.im2sequence(img, filter_size=2, stride=2)
    a = fluid.layers.data("a", [2])
    b = fluid.layers.data("b", [2])
    idx = fluid.layers.data("idx", [1], dtype="int32")
    mx = fluid.layers.multiplex([a, b], idx)
    sv, mv = _run([seq, mx], {
        "img": np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4),
        "a": np.zeros((2, 2), np.float32),
        "b": np.ones((2, 2), np.float32),
        "idx": np.array([[0], [1]], np.int32)})
    assert np.asarray(sv).shape == (4, 4)
    np.testing.assert_allclose(np.asarray(mv), [[0, 0], [1, 1]])


def test_warpctc_and_greedy_decoder():
    lg = fluid.layers.data("lg", [5], lod_level=1)
    lb = fluid.layers.data("lb", [1], dtype="int64", lod_level=1)
    loss = fluid.layers.warpctc(lg, lb, blank=0)
    probs = fluid.layers.data("probs", [3], dtype="float32", lod_level=1)
    dec = fluid.layers.ctc_greedy_decoder(probs, blank=0)
    rng = np.random.RandomState(0)
    logits = rng.rand(6, 5).astype(np.float32)
    labels = np.array([[1], [2]], np.int64)
    pv = np.array([[0.1, 0.8, 0.1], [0.1, 0.8, 0.1], [0.8, 0.1, 0.1],
                   [0.1, 0.1, 0.8]], np.float32)
    lv, dv = _run([loss, dec], {
        "lg": fluid.create_lod_tensor(logits, [[6]]),
        "lb": fluid.create_lod_tensor(labels, [[2]]),
        "probs": fluid.create_lod_tensor(pv, [[4]])})
    assert np.isfinite(np.asarray(lv)).all()
    np.testing.assert_array_equal(np.asarray(dv).reshape(-1)[:2], [1, 2])


def test_edit_distance_chunk_eval():
    h = fluid.layers.data("h", [1], dtype="int64", lod_level=1)
    r = fluid.layers.data("r", [1], dtype="int64", lod_level=1)
    d, n = fluid.layers.edit_distance(h, r, normalized=False)
    iv = fluid.layers.data("iv", [1], dtype="int64", lod_level=1)
    lv = fluid.layers.data("lv", [1], dtype="int64", lod_level=1)
    outs = fluid.layers.chunk_eval(iv, lv, "IOB", 1)
    seq = np.array([[1], [2], [3]], np.int64)
    ref = np.array([[1], [3]], np.int64)
    lab = np.array([[0], [1], [2]], np.int64)
    vals = _run([d, n, outs[3], outs[4]], {
        "h": fluid.create_lod_tensor(seq, [[3]]),
        "r": fluid.create_lod_tensor(ref, [[2]]),
        "iv": fluid.create_lod_tensor(lab, [[3]]),
        "lv": fluid.create_lod_tensor(lab, [[3]])})
    assert int(np.asarray(vals[0]).reshape(-1)[0]) == 1   # one insertion
    assert int(np.asarray(vals[2]).reshape(-1)[0]) == 1   # one chunk each
    assert int(np.asarray(vals[3]).reshape(-1)[0]) == 1


def test_edit_distance_ignored_tokens_and_chunk_exclusion():
    h = fluid.layers.data("h", [1], dtype="int64", lod_level=1)
    r = fluid.layers.data("r", [1], dtype="int64", lod_level=1)
    d, _ = fluid.layers.edit_distance(h, r, normalized=False,
                                      ignored_tokens=[0])
    iv = fluid.layers.data("iv", [1], dtype="int64", lod_level=1)
    lv = fluid.layers.data("lv", [1], dtype="int64", lod_level=1)
    # IOB, 2 types; exclude type 0: only the type-1 chunk counts
    outs = fluid.layers.chunk_eval(iv, lv, "IOB", 2,
                                   excluded_chunk_types=[0])
    seq = np.array([[0], [1], [0], [2]], np.int64)   # 0s ignored -> [1,2]
    ref = np.array([[1], [2]], np.int64)
    lab = np.array([[0], [1], [2], [3]], np.int64)   # B0 I0 B1 I1
    vals = _run([d, outs[3], outs[4]], {
        "h": fluid.create_lod_tensor(seq, [[4]]),
        "r": fluid.create_lod_tensor(ref, [[2]]),
        "iv": fluid.create_lod_tensor(lab, [[4]]),
        "lv": fluid.create_lod_tensor(lab, [[4]])})
    assert int(np.asarray(vals[0]).reshape(-1)[0]) == 0   # identical
    assert int(np.asarray(vals[1]).reshape(-1)[0]) == 1   # type-0 excluded
    assert int(np.asarray(vals[2]).reshape(-1)[0]) == 1


def test_multiclass_nms_pixel_convention():
    # two 1-pixel-overlap boxes: IoU differs between normalized (area
    # w*h) and pixel (w+1)*(h+1) conventions; with normalized=False the
    # +1 offset pushes IoU over the threshold and suppresses box 2
    b = fluid.layers.data("b", [2, 4], append_batch_size=False)
    sc = fluid.layers.data("s", [1, 2, 2], append_batch_size=False)
    # normalized IoU = 3/15 = 0.20; pixel IoU = 8/24 = 0.33 — a 0.25
    # threshold separates the conventions
    keep_n = fluid.layers.multiclass_nms(b, sc, nms_threshold=0.25,
                                         background_label=-1, keep_top_k=4)
    keep_p = fluid.layers.multiclass_nms(b, sc, nms_threshold=0.25,
                                         background_label=-1, keep_top_k=4,
                                         normalized=False)
    boxes = np.array([[0, 0, 3, 3], [2, 0, 5, 3]], np.float32)
    scores = np.array([[[0.9, 0.8], [0.9, 0.8]]], np.float32)
    nv, pv = _run([keep_n, keep_p], {"b": boxes[None], "s": scores})
    n_kept = int((np.asarray(nv).reshape(-1, 6)[:, 1] > 0).sum())
    p_kept = int((np.asarray(pv).reshape(-1, 6)[:, 1] > 0).sum())
    assert n_kept == 4   # both boxes survive in both classes
    assert p_kept == 2   # pixel convention suppresses the second box


def test_detection_wrappers():
    feat = fluid.layers.data("feat", [2, 3, 3])
    img = fluid.layers.data("img", [3, 12, 12])
    boxes, variances = fluid.layers.prior_box(
        feat, img, min_sizes=[4.0], aspect_ratios=[1.0])
    dist = fluid.layers.data("dist", [3, 3], append_batch_size=False)
    midx, mdist = fluid.layers.bipartite_match(dist)
    x = fluid.layers.data("xx", [3, 4])
    tout, tw = fluid.layers.target_assign(x, midx)
    dv = np.array([[0.9, 0.1, 0.2], [0.1, 0.8, 0.3], [0.2, 0.1, 0.7]],
                  np.float32)
    vals = _run([boxes, midx, tout], {
        "feat": np.ones((1, 2, 3, 3), np.float32),
        "img": np.ones((1, 3, 12, 12), np.float32),
        "dist": dv,
        "xx": np.ones((1, 3, 4), np.float32)})
    assert np.asarray(vals[0]).shape[-1] == 4
    assert np.asarray(vals[1]).shape == (1, 3)
    assert np.asarray(vals[2]).shape == (1, 3, 4)


def test_detection_output_and_map():
    loc = fluid.layers.data("loc", [4, 4], append_batch_size=False)
    # reference contract: raw scores [N, M, C]; detection_output
    # softmaxes + transposes internally
    conf = fluid.layers.data("conf", [1, 4, 2], append_batch_size=False)
    pb = fluid.layers.data("pb", [4, 4], append_batch_size=False)
    pbv = fluid.layers.data("pbv", [4, 4], append_batch_size=False)
    out = fluid.layers.detection_output(loc, conf, pb, pbv)
    det = fluid.layers.data("det", [6])
    gt = fluid.layers.data("gt", [5])
    m = fluid.layers.detection_map(det, gt)
    rng = np.random.RandomState(0)
    vals = _run([out, m], {
        "loc": np.zeros((4, 4), np.float32),
        "conf": rng.rand(1, 4, 2).astype(np.float32),
        "pb": np.abs(rng.rand(4, 4)).astype(np.float32),
        "pbv": np.full((4, 4), 0.1, np.float32),
        "det": np.array([[0, 0.9, 0, 0, 10, 10]], np.float32),
        "gt": np.array([[0, 0, 0, 10, 10]], np.float32)})
    assert np.asarray(vals[0]).shape[-1] == 6
    assert 0.0 <= float(np.asarray(vals[1]).reshape(-1)[0]) <= 1.0 + 1e-6


def test_conditional_block_and_reader_aliases():
    x = fluid.layers.data("x", [2])
    flag = fluid.layers.data("flag", [1], append_batch_size=False)
    out = fluid.layers.fill_constant([2, 2], "float32", 0.0)
    cond = fluid.layers.ConditionalBlock([flag])
    with cond.block():
        doubled = fluid.layers.scale(x, 2.0)
        fluid.layers.assign(doubled, out)
    xv = np.ones((2, 2), np.float32)
    on, = _run([out], {"x": xv, "flag": np.ones((1,), np.float32)})
    np.testing.assert_allclose(np.asarray(on), 2 * xv)
    exe = fluid.Executor(fluid.CPUPlace())
    off, = exe.run(feed={"x": xv, "flag": np.zeros((1,), np.float32)},
                   fetch_list=[out])
    np.testing.assert_allclose(np.asarray(off), 0.0)

    # host-reader aliases
    def rdr():
        for i in range(7):
            yield [np.full((2,), i, np.float32)]

    batched = fluid.layers.batch(
        fluid.layers.shuffle(fluid.layers.double_buffer(rdr), 16), 2)
    chunks = list(batched())
    # 7 items, batch 2: partial final batch is KEPT, matching the
    # reference BatchReader (create_batch_reader_op.cc:70-79).
    assert len(chunks) == 4
    assert sum(len(c) for c in chunks) == 7


def test_create_parameter_counter_print_nce():
    w = fluid.layers.create_parameter([3, 2], "float32", name="cp_w")
    ctr = fluid.layers.autoincreased_step_counter()
    x = fluid.layers.data("x", [3])
    pr = fluid.layers.Print(x, message="compat")
    emb = fluid.layers.data("e", [8])
    lbl = fluid.layers.data("l", [1], dtype="int64")
    cost = fluid.layers.nce(emb, lbl, num_total_classes=6,
                            num_neg_samples=2)
    vals = _run([w, ctr, pr, cost], {
        "x": np.ones((2, 3), np.float32),
        "e": np.ones((2, 8), np.float32),
        "l": np.zeros((2, 1), np.int64)})
    assert np.asarray(vals[0]).shape == (3, 2)
    assert np.asarray(vals[3]).shape[0] == 2


def test_nce_sample_weight_scales_cost():
    """nce sample_weight (nce_op.cc:97): per-example weights scale each
    example's cost; weight 0 silences an example entirely."""
    emb = fluid.layers.data("e2", [8])
    lbl = fluid.layers.data("l2", [1], dtype="int64")
    swt = fluid.layers.data("sw", [1])
    prog = fluid.default_main_program()
    prog.random_seed = 7
    cost = fluid.layers.nce(emb, lbl, num_total_classes=6,
                            num_neg_samples=2, sample_weight=swt,
                            param_attr=fluid.ParamAttr(name="ncew"),
                            bias_attr=fluid.ParamAttr(name="nceb"))
    ev = np.ones((3, 8), np.float32)
    lv = np.zeros((3, 1), np.int64)

    def run_fresh(sw):
        # fresh executor+scope per run -> identical RNG stream, so the
        # drawn negatives match and only the weights differ
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            out, = exe.run(feed={"e2": ev, "l2": lv, "sw": sw},
                           fetch_list=[cost])
        return np.asarray(out)

    base = run_fresh(np.ones((3, 1), np.float32))
    scaled = run_fresh(np.array([[1.], [2.], [0.]], np.float32))
    assert base.shape == (3, 1)
    np.testing.assert_allclose(scaled[0], base[0], rtol=1e-6)
    np.testing.assert_allclose(scaled[1], 2 * base[1], rtol=1e-5)
    np.testing.assert_allclose(scaled[2], 0.0, atol=1e-7)
