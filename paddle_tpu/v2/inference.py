"""v2 inference (python/paddle/v2/inference.py parity):
paddle.v2.infer(output_layer=..., parameters=..., input=...)."""

import numpy as np

from ..core.executor import Executor
from ..core.places import CPUPlace
from ..core.scope import scope_guard
from ..data_feeder import DataFeeder
from .parameters import Parameters


def _feeds_some_op(program, name):
    return any(name in op.input_names
               for op in program.global_block().ops)


def infer(output_layer, parameters, input, feeding=None, field="value"):
    if not isinstance(parameters, Parameters):
        raise TypeError("parameters must be a paddle.v2 Parameters")
    # backward-slice to the output layer (framework Prune parity) so loss
    # labels and optimizer ops are neither required nor run at infer time
    program = output_layer.block.program.prune([output_layer])
    data_vars = [v for v in program.global_block().vars.values()
                 if getattr(v, "is_data", False)
                 and _feeds_some_op(program, v.name)]
    # drop label-style inputs the output does not depend on: keep feeds in
    # declaration order and feed only as many columns as the input rows have
    n_cols = len(input[0]) if input and isinstance(input[0],
                                                   (tuple, list)) else 1
    if input and not isinstance(input[0], (tuple, list)):
        input = [(x,) for x in input]
    if feeding:
        order = sorted(feeding, key=lambda n: feeding[n])
        by_name = {v.name: v for v in data_vars}
        data_vars = [by_name[n] for n in order]
    feeder = DataFeeder(data_vars[:n_cols], CPUPlace(), program=program)
    feed = feeder.feed(input)
    exe = Executor(CPUPlace())
    with scope_guard(parameters._scope):
        out, = exe.run(program, feed=feed, fetch_list=[output_layer])
    return np.asarray(out)
