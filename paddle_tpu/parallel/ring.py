"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context design (task requirement; beyond the 2018 reference, which
handled long sequences only by LoD batching — SURVEY.md §5.7): the sequence
axis is sharded across devices; each device holds a Q shard and passes its
K/V shard around the ring with ``ppermute`` while merging
flash-attention-style partial results (per-shard output + log-sum-exp
rows), so the full [T, T] score matrix never materializes and K/V
transfers overlap with the blockwise matmuls (Liu et al., Ring Attention
with Blockwise Transformers).

Each ring step computes attention of the local Q shard against the
currently-held K/V shard with the fused Pallas flash kernel
(ops/flash_attention.flash_attention_lse — dense math off-TPU), then
merges (out_i, lse_i) into the running accumulator by stable
log-sum-exp weighting. Because shards are contiguous sequence chunks,
the causal mask per step collapses to three cases: the diagonal shard is
plain causal attention, earlier shards are unmasked, later shards
contribute nothing.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from ._shard_map import shard_map
from jax.sharding import PartitionSpec as P

_NEG_BIG = -1e30   # finite "-inf": keeps exp()==0 without inf-inf NaNs


def _ring_attention_sharded(q, k, v, axis_name, causal, scale):
    """Per-shard body (inside shard_map). q/k/v: [B, H, T_local, D]."""
    from ..ops.flash_attention import flash_attention_lse

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, t_local, d = q.shape

    def diag_block(k_cur, v_cur):      # src == me: aligned causal mask
        return flash_attention_lse(q, k_cur, v_cur, causal=causal,
                                   scale=scale)

    def full_block(k_cur, v_cur):      # src strictly before me: no mask
        return flash_attention_lse(q, k_cur, v_cur, causal=False,
                                   scale=scale)

    def skip_block(k_cur, v_cur):      # src after me: fully masked out
        return (jnp.zeros(q.shape, q.dtype),
                jnp.full((b, h, t_local), _NEG_BIG, jnp.float32))

    # Deferred-normalization carry (one divide AFTER the loop, not per
    # step): num = Σ_seen o_j·e^{lse_j - m_run}, s = Σ_seen e^{lse_j -
    # m_run}, with m_run the running max of the seen shards' lse rows.
    num = jnp.zeros((b, h, t_local, d), jnp.float32)
    s = jnp.zeros((b, h, t_local), jnp.float32)
    m_run = jnp.full((b, h, t_local), _NEG_BIG, jnp.float32)

    def ring_step(i, carry):
        num, s, m_run, k_cur, v_cur = carry
        src_idx = (my_idx - i) % axis_size   # whose K/V shard we hold now
        if causal:
            case = jnp.where(src_idx == my_idx, 0,
                             jnp.where(src_idx < my_idx, 1, 2))
            o_i, lse_i = lax.switch(case, (diag_block, full_block,
                                           skip_block), k_cur, v_cur)
        else:
            o_i, lse_i = full_block(k_cur, v_cur)
        m_new = jnp.maximum(m_run, lse_i)
        alpha = jnp.exp(m_run - m_new)       # rescales the old partials
        w_i = jnp.exp(lse_i - m_new)         # this shard's weight
        num = num * alpha[..., None] \
            + o_i.astype(jnp.float32) * w_i[..., None]
        s = s * alpha + w_i
        # rotate K/V shards around the ring (overlaps with the next
        # step's matmuls after XLA latency-hiding scheduling)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return num, s, m_new, k_nxt, v_nxt

    num, s, m_run, _, _ = lax.fori_loop(0, axis_size, ring_step,
                                        (num, s, m_run, k, v))
    return (num / jnp.maximum(s, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False, scale=None,
                   batch_axis=None):
    """q,k,v: [B, H, T, D] with T sharded on `axis_name`. Returns [B,H,T,D]
    with the same sharding. Pass batch_axis="dp" when the mesh also data-
    parallelizes the batch dim, so shard_map doesn't gather it."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, None, axis_name, None)
    fn = shard_map(
        functools.partial(_ring_attention_sharded, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh, axis_name="sp", causal=False,
                      scale=None, batch_axis=None):
    """DeepSpeed-Ulysses style sequence parallelism: all-to-all swaps the
    sharded axis from sequence to heads, runs full local attention (the
    fused flash kernel on TPU), then swaps back. Better when H >=
    axis_size and T is moderate."""
    from ..ops.flash_attention import flash_attention

    if scale is None:
        scale = q.shape[-1] ** -0.5

    def body(q, k, v):
        # local shards [B, H, T/s, D] → a2a → [B, H/s, T, D]
        def a2a(x, split, concat):
            return lax.all_to_all(x, axis_name, split_axis=split,
                                  concat_axis=concat, tiled=True)
        q2, k2, v2 = (a2a(t, 1, 2) for t in (q, k, v))
        o = flash_attention(q2, k2, v2, causal=causal, scale=scale)
        return a2a(o, 2, 1)

    spec = P(batch_axis, None, axis_name, None)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)
