"""Gradient accumulation (DistributedStrategy.gradient_accumulation_steps
→ core/executor.py _lower_with_grad_accum).

The feed batch splits into k microbatches scanned in-graph; grads and
targets are means over microbatches. For a mean-reduced loss this equals
the full-batch gradient, so one accumulated step must match one
unaccumulated step on the same feeds — params, loss, everything.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import parallel


def _build_net(seed=3):
    x = fluid.layers.data("x", [8])
    y = fluid.layers.data("y", [1])
    h = fluid.layers.fc(x, 16, act="tanh")
    pred = fluid.layers.fc(h, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _train(accum_steps, steps=3):
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 8).astype(np.float32)
    yv = rng.rand(16, 1).astype(np.float32)

    from paddle_tpu.core import unique_name
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard("ga_"):
        loss = _build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        strategy = parallel.DistributedStrategy(
            gradient_accumulation_steps=accum_steps)
        pexe = fluid.ParallelExecutor(loss_name=loss.name,
                                      main_program=main, scope=scope,
                                      strategy=strategy)
        losses = [float(np.asarray(
            pexe.run([loss], feed={"x": xv, "y": yv})[0]))
            for _ in range(steps)]
        params = {v.name: np.asarray(scope.find_var(v.name)).copy()
                  for v in main.global_block().vars.values()
                  if v.persistable and scope.find_var(v.name) is not None}
    return losses, params


def test_accumulated_step_matches_full_batch():
    losses1, params1 = _train(accum_steps=1)
    losses4, params4 = _train(accum_steps=4)
    np.testing.assert_allclose(losses4, losses1, rtol=1e-5)
    assert params1.keys() == params4.keys()
    for n in params1:
        np.testing.assert_allclose(params4[n], params1[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)
    # and training moved the loss
    assert losses1[-1] < losses1[0]


def _train_sched(accum_steps, steps=4):
    """Computed learning rate (exponential decay) + accumulation: the LR
    chain is a forward intermediate read by the optimizer, and its step
    counter must tick once per STEP, not once per microbatch."""
    from paddle_tpu.core import unique_name
    rng = np.random.RandomState(1)
    xv = rng.rand(16, 8).astype(np.float32)
    yv = rng.rand(16, 1).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard("gs_"):
        x = fluid.layers.data("x", [8])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        lr = fluid.layers.exponential_decay(
            learning_rate=0.2, decay_steps=2, decay_rate=0.5,
            staircase=True)
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pexe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main, scope=scope,
            strategy=parallel.DistributedStrategy(
                gradient_accumulation_steps=accum_steps))
        losses = [float(np.asarray(
            pexe.run([loss], feed={"x": xv, "y": yv})[0]))
            for _ in range(steps)]
        params = {v.name: np.asarray(scope.find_var(v.name)).copy()
                  for v in main.global_block().vars.values()
                  if v.persistable and scope.find_var(v.name) is not None}
        counter_name, = [n for n in params if "@LR_DECAY_COUNTER@" in n]
        counter = params[counter_name]
    return losses, counter, params


def test_accumulation_with_lr_schedule_matches_and_ticks_once():
    l1, c1, p1 = _train_sched(accum_steps=1)
    l2, c2, p2 = _train_sched(accum_steps=2)
    np.testing.assert_allclose(l2, l1, rtol=1e-5)
    # the decay counter advanced once per STEP in both configurations —
    # under the per-microbatch-tick bug c2 would be ~2x c1
    np.testing.assert_array_equal(c1, c2)
    assert int(np.asarray(c1).ravel()[0]) > 0
    for n in p1:
        np.testing.assert_allclose(p2[n], p1[n], rtol=1e-5, atol=1e-6,
                                   err_msg=n)


def test_accumulation_rejects_non_scalar_loss():
    main2, startup2 = fluid.Program(), fluid.Program()
    scope2 = fluid.Scope()
    with fluid.program_guard(main2, startup2), fluid.scope_guard(scope2):
        x = fluid.layers.data("x", [8])
        y = fluid.layers.data("y", [1])
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.square_error_cost(pred, y)
        fluid.append_backward(loss)                      # non-scalar target
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup2)
        pexe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main2, scope=scope2,
            strategy=parallel.DistributedStrategy(
                gradient_accumulation_steps=2))
        b = pexe.device_count * 2
        with pytest.raises(ValueError, match="SCALAR"):
            pexe.run([loss], feed={"x": np.ones((b, 8), np.float32),
                                   "y": np.ones((b, 1), np.float32)})


def test_accumulation_requires_divisible_batch():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        loss = _build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pexe = fluid.ParallelExecutor(
            loss_name=loss.name, main_program=main, scope=scope,
            strategy=parallel.DistributedStrategy(
                gradient_accumulation_steps=3))
        x = np.zeros((16, 8), np.float32)
        y = np.zeros((16, 1), np.float32)
        with pytest.raises(ValueError, match="microbatch"):
            pexe.run([loss], feed={"x": x, "y": y})


def test_accumulation_requires_grad_marker():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        x = fluid.layers.data("x", [4])
        out = fluid.layers.fc(x, 2)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pexe = fluid.ParallelExecutor(
            loss_name=None, main_program=main, scope=scope,
            strategy=parallel.DistributedStrategy(
                gradient_accumulation_steps=2))
        b = pexe.device_count * 2
        with pytest.raises(ValueError, match="grad marker"):
            pexe.run([out], feed={"x": np.zeros((b, 4), np.float32)})


def _lod(arr, lengths):
    t = fluid.LoDTensor(np.asarray(arr))
    t.set_recursive_sequence_lengths([list(lengths)])
    return t


def _train_lstm(accum_steps, steps=3):
    """Stacked LSTM over LoD sequence feeds under accumulation (the
    round-3 restriction at core/executor.py lifted): each microbatch is
    a host-side ragged split padded to a shared bucket, scanned with its
    own per-sequence lengths."""
    from paddle_tpu.core import unique_name
    rng = np.random.RandomState(2)
    lengths = [3, 5, 2, 6, 4, 4, 3, 5]           # 8 sequences, total 32
    total = sum(lengths)
    xv = rng.rand(total, 6).astype(np.float32)
    yv = rng.randint(0, 2, (8, 1)).astype(np.int64)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 13
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard("gl_"):
        x = fluid.layers.data("x", [6], lod_level=1)
        label = fluid.layers.data("y", [1], dtype="int64")
        fc1 = fluid.layers.fc(x, 8)
        lstm1, _ = fluid.layers.dynamic_lstm(fc1, size=8)
        fc2 = fluid.layers.fc(lstm1, 8)
        lstm2, _ = fluid.layers.dynamic_lstm(fc2, size=8,
                                             is_reverse=True)
        pooled = fluid.layers.sequence_pool(lstm2, "max")
        pred = fluid.layers.fc(pooled, 2, act="softmax")
        loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        strategy = parallel.DistributedStrategy(
            gradient_accumulation_steps=accum_steps)
        pexe = fluid.ParallelExecutor(loss_name=loss.name,
                                      main_program=main, scope=scope,
                                      strategy=strategy)
        losses = [float(np.asarray(pexe.run(
            [loss], feed={"x": _lod(xv, lengths), "y": yv})[0]))
            for _ in range(steps)]
        params = {v.name: np.asarray(scope.find_var(v.name)).copy()
                  for v in main.global_block().vars.values()
                  if v.persistable and scope.find_var(v.name) is not None}
    return losses, params


def test_lod_sequence_accumulation_matches_full_batch():
    losses1, params1 = _train_lstm(accum_steps=1)
    losses2, params2 = _train_lstm(accum_steps=2)
    np.testing.assert_allclose(losses2, losses1, rtol=2e-5, atol=1e-6)
    assert params1.keys() == params2.keys()
    for n in params1:
        np.testing.assert_allclose(params2[n], params1[n], rtol=1e-4,
                                   atol=1e-5, err_msg=n)
    assert losses1[-1] < losses1[0]


def test_lod_accumulation_rejects_indivisible_sequences():
    from paddle_tpu.core.executor import _normalize_feeds
    t = _lod(np.random.rand(7, 2).astype(np.float32), [3, 2, 2])
    with pytest.raises(ValueError, match="not divisible"):
        _normalize_feeds({"x": t}, accum_steps=2)


def _train_token_norm(accum_steps, loss_norm=None, steps=3):
    """TOKEN-normalized loss (mean over tokens, not sequences) under a
    ragged split whose microbatch token totals are UNEQUAL. Equal
    microbatch weighting is wrong here; loss_norm='token' weights each
    microbatch by its true token count, which reproduces the full-batch
    token mean exactly."""
    from paddle_tpu.core import unique_name
    rng = np.random.RandomState(5)
    lengths = [1, 2, 3, 2, 5, 3, 7, 1]   # k=2 -> totals [8, 16]: unequal
    total = sum(lengths)
    xv = rng.rand(total, 4).astype(np.float32)
    wv = np.asarray(lengths, np.float32).reshape(-1, 1)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 7
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard("tn_"):
        x = fluid.layers.data("x", [4], lod_level=1)
        w = fluid.layers.data("w", [1])      # per-sequence token counts
        h = fluid.layers.fc(x, 8, act="tanh")
        per_seq = fluid.layers.sequence_pool(h, "sum")   # sum over tokens
        tok_sum = fluid.layers.reduce_sum(per_seq)
        n_tok = fluid.layers.reduce_sum(w)
        loss = fluid.layers.elementwise_div(tok_sum, n_tok)  # token mean
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        strategy = parallel.DistributedStrategy(
            gradient_accumulation_steps=accum_steps,
            gradient_accumulation_loss_norm=loss_norm)
        pexe = fluid.ParallelExecutor(loss_name=loss.name,
                                      main_program=main, scope=scope,
                                      strategy=strategy)
        losses = []
        for _ in range(steps):
            # w chunks with the batch dim, so microbatch i sees its own
            # sequences' lengths: loss_i = S_i / T_i, and the 'token'
            # weights T_i/T recover the full-batch S/T exactly
            losses.append(float(np.asarray(pexe.run(
                [loss], feed={"x": _lod(xv, lengths), "w": wv})[0])))
        params = {v.name: np.asarray(scope.find_var(v.name)).copy()
                  for v in main.global_block().vars.values()
                  if v.persistable and scope.find_var(v.name) is not None}
    return losses, params


def test_token_normalized_accumulation_matches_full_batch():
    losses1, params1 = _train_token_norm(accum_steps=1)
    losses2, params2 = _train_token_norm(accum_steps=2, loss_norm="token")
    np.testing.assert_allclose(losses2, losses1, rtol=2e-5, atol=1e-6)
    for n in params1:
        np.testing.assert_allclose(params2[n], params1[n], rtol=1e-4,
                                   atol=1e-5, err_msg=n)


def test_token_normalized_accumulation_sequence_weighting_differs():
    # sharpness check: equal ('sequence') weighting is NOT exact for a
    # token-normalized loss over an unequal split — if this ever starts
    # passing, the token test above lost its teeth
    losses1, _ = _train_token_norm(accum_steps=1)
    losses_seq, _ = _train_token_norm(accum_steps=2, loss_norm="sequence")
    assert abs(losses_seq[0] - losses1[0]) > 1e-4


def test_ragged_unequal_totals_require_explicit_loss_norm():
    with pytest.raises(ValueError, match="unequal"):
        _train_token_norm(accum_steps=2, loss_norm=None)


def test_accum_loss_norm_validated():
    with pytest.raises(ValueError, match="loss_norm"):
        _train_token_norm(accum_steps=2, loss_norm="bogus")
