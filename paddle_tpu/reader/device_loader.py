"""Device-prefetching data loader.

Reference parity: operators/reader/create_double_buffer_reader_op.cc:34,168
— a prefetch thread keeping a 2-slot device-side buffer so host→device
transfer overlaps compute. On TPU the host→device hop (through the axon
tunnel here) dominates naive per-step feeding, so this is the difference
between transfer-bound and compute-bound steps.
"""

import queue
import threading

import numpy as np
import jax

__all__ = ["DeviceLoader"]


class DeviceLoader:
    """Wrap an iterable of feed dicts; yields dicts of device-resident
    jax.Arrays, transferring `capacity` batches ahead on a worker thread."""

    def __init__(self, feed_iterable, capacity=2, device=None,
                 sharding=None):
        self._src = feed_iterable
        self._capacity = max(1, capacity)
        self._device = device
        self._sharding = sharding

    def _put(self, value):
        if self._sharding is not None:
            return jax.device_put(value, self._sharding)
        if self._device is not None:
            return jax.device_put(value, self._device)
        return jax.device_put(value)

    def __iter__(self):
        q = queue.Queue(maxsize=self._capacity)
        stop = object()
        err = []

        def worker():
            try:
                for feed in self._src:
                    dev = {k: self._put(np.asarray(v)
                                        if not isinstance(v, jax.Array)
                                        else v)
                           for k, v in feed.items()}
                    q.put(dev)
            except BaseException as e:   # propagate to consumer
                err.append(e)
            finally:
                q.put(stop)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            yield item
        if err:
            raise err[0]


def repeat_feed(feed, n):
    """Iterator yielding the same feed dict n times (benchmark helper)."""
    for _ in range(n):
        yield feed
