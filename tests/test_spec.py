"""paddle_tpu.serving speculative decode (ISSUE 13).

The contract pinned here is the ISSUE-13 acceptance story: a
speculative engine (γ drafted tokens per live slot verified in ONE
multi-position paged-attention dispatch, accept-longest-prefix against
the model's own tokens) emits output BITWISE-identical to the
non-speculative engine and the sequential one-at-a-time baseline —
at γ∈{0,2,4}, through multi-chunk prefill, EOS inside an accepted
draft, mid-flight admission, megastep composition, pool-dry
preemption/resume and seeded-sampling replay — while the drafting tier
(host n-gram lookup + the radix cache's published chains; flag-gated
truncated-layer pass) only ever moves the ACCEPTANCE RATE, never a
token. Telemetry (ptpu_spec_* counters, serving_step row fields, the
monitor-watch acceptance line) lands day one.

The LM and baseline are module-scoped like test_serving's: every
speculative engine carries an extra compiled scoring program per
(γ, sampled) pair, so engines are built once per γ where possible.
"""

import copy
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import serving
from paddle_tpu.models import transformer
from paddle_tpu.models.transformer_infer import TransformerLMInfer
from paddle_tpu.monitor import runtime as monrt
from paddle_tpu.serving.kvpool import BlockPool, RadixCache
from paddle_tpu.serving.spec import NgramDrafter

N_LAYER, N_HEAD, D_MODEL, MAX_LEN, VOCAB = 2, 2, 32, 64, 40


@pytest.fixture(scope="module")
def lm():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        transformer.transformer_lm(
            vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        return TransformerLMInfer(main, scope, N_LAYER, N_HEAD,
                                  D_MODEL, MAX_LEN)


@pytest.fixture(scope="module")
def spec4(lm):
    """The shared γ=4 speculative engine (one compile of the scoring
    program for most of the module). min_n=1 so even weak-evidence
    drafts fire — the identity pins want the REJECTION paths
    exercised, not a high acceptance rate."""
    eng = serving.Engine(lm, slots=4, prefill_chunk=4,
                         speculative=True, spec_gamma=4)
    eng._drafter = NgramDrafter(max_n=3, min_n=1)
    yield eng
    eng.close()


def _requests(rng, n, max_prompt=13, min_new=4, max_new=20):
    reqs = []
    for _ in range(n):
        plen = int(rng.randint(1, max_prompt + 1))
        prompt = [1] + rng.randint(3, VOCAB, plen - 1).tolist()
        reqs.append((prompt, int(rng.randint(min_new, max_new + 1))))
    return reqs


def _assert_identical(seq, eng):
    for i, ((st, ss), (et, es)) in enumerate(zip(seq, eng)):
        assert st == et, "request %d diverged: %r vs %r" % (i, st, et)
        np.testing.assert_allclose(es, ss, rtol=1e-5, atol=1e-5)


# -- drafting tier (pure host, device-free) --------------------------------

def test_ngram_drafter_self_chain():
    d = NgramDrafter(max_n=3, min_n=1)
    # period-2 cycle: the strongest (3-gram) suffix match proposes the
    # full continuation from inside the cycle
    assert d.propose([5, 9, 5, 9, 5, 9], 4) == [5, 9, 5, 9]
    # the rightmost match with a FULL γ continuation wins over a more
    # recent match that could only continue shorter
    assert d.propose([7, 1, 2, 3, 4, 1, 2, 3], 3) == [4, 1, 2]
    # no earlier occurrence at any n -> no draft
    assert d.propose([1, 2, 3, 4, 5], 4) == []
    # gamma cap + empty/zero requests
    assert d.propose([5, 5, 5, 5], 2) == [5, 5]
    assert d.propose([5, 5, 5], 0) == []
    assert d.propose([], 4) == []


def test_ngram_drafter_min_n_and_window():
    strict = NgramDrafter(max_n=3, min_n=3)
    # only a 1-gram repeats -> strict (strong-evidence) drafter stays
    # silent where the loose one proposes
    chain = [3, 9, 4, 9]
    assert NgramDrafter(max_n=3, min_n=1).propose(chain, 2) == [4, 9]
    assert strict.propose(chain, 2) == []
    # matches OUTSIDE the search window are invisible
    near = NgramDrafter(max_n=2, min_n=2, window=6)
    far = [1, 2, 8, 8, 8, 8, 8, 1, 2]
    assert near.propose(far, 2) == []
    assert NgramDrafter(max_n=2, min_n=2, window=64).propose(
        far, 2) == [8, 8]


def test_ngram_drafter_published_chains():
    d = NgramDrafter(max_n=3, min_n=2)
    # the request's own chain has no repeat, but a published radix
    # chain continues its suffix — cross-request drafting
    chain = [1, 6, 7]
    pub = [(9, 9, 6, 7, 5, 4, 3, 2)]
    assert d.propose(chain, 3, extra_chains=pub) == [5, 4, 3]
    # self-chain evidence wins when it can serve the full draft
    cyc = [6, 7, 8, 6, 7]
    assert d.propose(cyc, 1, extra_chains=pub) == [8]


def test_radix_cache_token_chains():
    pool = BlockPool(8, 2)
    cache = RadixCache(2, pool)
    b1 = pool.alloc(2)
    b2 = pool.alloc(1)
    cache.insert([1, 2, 3, 4], b1)
    cache.insert([1, 2, 9, 9], [b1[0], b2[0]])
    used0 = pool.used
    chains = cache.token_chains()
    # leaf root-paths, most recently used first; prefixes ride inside
    assert chains == [(1, 2, 9, 9), (1, 2, 3, 4)]
    assert cache.token_chains(limit=1) == [(1, 2, 9, 9)]
    # reading text takes NO pool refs
    assert pool.used == used0
    for b in b1 + b2:
        pool.free(b)


# -- bitwise-greedy identity ----------------------------------------------

def test_spec_identity_gamma_2_and_4(rng, lm, spec4):
    """The ISSUE-13 acceptance pin: speculative output (γ∈{2,4}) is
    token-identical to the sequential baseline across slot recycling
    and multi-chunk prefill, with drafting REAL (dispatches verified
    drafts, some accepted, some rejected)."""
    reqs = _requests(rng, 8)
    assert max(len(p) for p, _ in reqs) > 4   # multi-chunk prefill
    seq = serving.sequential_generate(lm, reqs)
    out = spec4.generate_many([p for p, _ in reqs],
                              [m for _, m in reqs])
    _assert_identical(seq, out)
    assert spec4.stats["spec_dispatches"] > 0
    assert spec4.stats["spec_drafted"] > 0
    # the tiny-LM greedy continuations cycle (seeded), so drafts are
    # verifiably accepted AND rejected — both acceptance branches ran
    assert 0 < spec4.stats["spec_accepted"] \
        < spec4.stats["spec_drafted"]
    with serving.Engine(lm, slots=2, prefill_chunk=4,
                        speculative=True, spec_gamma=2) as eng2:
        eng2._drafter = NgramDrafter(max_n=3, min_n=1)
        out2 = eng2.generate_many([p for p, _ in reqs],
                                  [m for _, m in reqs])
        assert eng2.stats["spec_dispatches"] > 0
    _assert_identical(seq, out2)


def test_spec_gamma0_disables_and_runs_existing_programs(rng, lm):
    """γ=0 (or speculative=False) must run the PR-10 engine
    cost-for-cost: no scoring program is even BUILT, no spec stats
    tick, and output identity holds — the regression the PR-10
    sampled-program tail was caught by."""
    reqs = _requests(rng, 4)
    seq = serving.sequential_generate(lm, reqs)
    with serving.Engine(lm, slots=2, prefill_chunk=4,
                        speculative=True, spec_gamma=0) as eng:
        assert eng._speculative is False
        assert eng._spec_fn is None and eng._draft_fn is None
        out = eng.generate_many([p for p, _ in reqs],
                                [m for _, m in reqs])
        assert eng.stats["spec_dispatches"] == 0
    _assert_identical(seq, out)
    # the default engine builds no speculative machinery either
    with serving.Engine(lm, slots=2) as dflt:
        assert dflt._spec_fn is None
    # and speculation REQUIRES the paged layout (ragged draft lengths
    # ride the block-table gather)
    with pytest.raises(ValueError, match="paged"):
        serving.Engine(lm, slots=2, paged=False, speculative=True)
    with pytest.raises(ValueError, match="drafter"):
        serving.Engine(lm, slots=2, speculative=True,
                       spec_drafter="nope")


def test_spec_mid_flight_admission(rng, lm, spec4):
    """Requests submitted WHILE the engine speculates join at an
    iteration boundary and decode identically — drafting for running
    slots must never leak into an admitted slot's tokens."""
    reqs = _requests(rng, 5, min_new=10, max_new=18)
    seq = serving.sequential_generate(lm, reqs)
    first = [spec4.submit(p, m) for p, m in reqs[:3]]
    time.sleep(0.03)
    rest = [spec4.submit(p, m) for p, m in reqs[3:]]
    out = [r.result(timeout=60) for r in first + rest]
    _assert_identical(seq, out)


def test_spec_eos_inside_accepted_draft(rng, lm):
    """EOS landing INSIDE an accepted draft truncates the emit right
    there (EOS included, nothing after) — pinned deterministically by
    drafting with the TRUNCATED tier at FULL depth (the drafter IS
    the scoring model, so every draft is accepted and the first
    dispatch covers the whole continuation incl. the EOS position).
    Uses the observed-token end_id trick of the PR-5 dense EOS pin."""
    probe = ([1, 5, 9], 12)
    [(toks, _)] = serving.sequential_generate(lm, [probe])
    lm_eos = copy.copy(lm)
    lm_eos.end_id = toks[2]     # 3rd emitted token = EOS
    reqs = [probe] + _requests(rng, 2, min_new=4, max_new=8)
    seq = serving.sequential_generate(lm_eos, reqs)
    assert len(seq[0][0]) == 3 and seq[0][0][-1] == lm_eos.end_id
    with serving.Engine(lm_eos, slots=2, prefill_chunk=4,
                        speculative=True, spec_gamma=4,
                        spec_drafter="truncated",
                        spec_layers=N_LAYER) as eng:
        out = eng.generate_many([p for p, _ in reqs],
                                [m for _, m in reqs])
        # full-depth drafts accept: the EOS really sat inside one
        assert eng.stats["spec_accepted"] > 0
    _assert_identical(seq, out)


def test_spec_truncated_drafter_identity(rng, lm):
    """Tier B at REDUCED depth (1 of 2 layers): draft quality drops,
    output must not — the truncated pass writes only layer rows the
    scoring dispatch overwrites, and rejected drafts cost nothing."""
    reqs = _requests(rng, 5)
    seq = serving.sequential_generate(lm, reqs)
    with serving.Engine(lm, slots=2, prefill_chunk=4,
                        speculative=True, spec_gamma=3,
                        spec_drafter="truncated",
                        spec_layers=1) as eng:
        assert eng._spec_layers == 1
        out = eng.generate_many([p for p, _ in reqs],
                                [m for _, m in reqs])
        assert eng.stats["spec_dispatches"] > 0
    _assert_identical(seq, out)


def test_spec_megastep_composition(rng, lm):
    """Megastep × speculation (the ISSUE-13 composition pin): drafted
    iterations take the scoring dispatch, draftless ones still fuse K
    steps — K→1 boundary rules unchanged — and output stays
    token-identical through a mid-flight admission."""
    reqs = _requests(rng, 6, min_new=8, max_new=16)
    seq = serving.sequential_generate(lm, reqs)
    with serving.Engine(lm, slots=2, prefill_chunk=4, megastep=4,
                        speculative=True, spec_gamma=2,
                        name="specmega") as eng:
        eng._drafter = NgramDrafter(max_n=3, min_n=1)
        eng.warmup()
        out = eng.generate_many([p for p, _ in reqs[:4]],
                                [m for _, m in reqs[:4]])
        first = [eng.submit(p, m) for p, m in reqs[4:5]]
        time.sleep(0.02)
        rest = [eng.submit(p, m) for p, m in reqs[5:]]
        out += [h.result(timeout=60) for h in first + rest]
        assert eng.stats["spec_dispatches"] > 0
    _assert_identical(seq, out)


def test_spec_warmup_precompiles_scoring_program(lm):
    """Engine.warmup() pre-compiles the speculative scoring program
    (γ is a static shape constant — without this the first drafted
    batch eats the XLA compile mid-traffic, the stall PR 7/10 killed
    twice) and the truncated draft program with tier B; sampled=True
    adds the sampling-tail variant."""
    with serving.Engine(lm, slots=2, prefill_chunk=4,
                        speculative=True, spec_gamma=2) as eng:
        assert eng._spec_fn._cache_size() == 0
        eng.warmup()
        assert eng._spec_fn._cache_size() == 1
        eng.warmup(sampled=True)
        assert eng._spec_fn._cache_size() == 2
    with serving.Engine(lm, slots=2, prefill_chunk=4,
                        speculative=True, spec_gamma=2,
                        spec_drafter="truncated",
                        spec_layers=1) as tr:
        tr.warmup()
        assert tr._spec_fn._cache_size() == 1
        assert tr._draft_fn._cache_size() == 1


# -- seeded sampling + preemption -----------------------------------------

def test_spec_sampled_reproducible_and_matches_nonspec(rng, lm, spec4):
    """Seeded sampling under speculation: the counter-keyed PRNG
    (fold_in(seed, tokens_generated + j), position-indexed inside the
    scoring dispatch) makes sampled output (a) identical to the
    NON-speculative engine's for the same seeds — acceptance verifies
    against the very tokens the plain path would draw — and (b)
    replay-identical on re-execution (the fleet's exactly-once
    resubmission contract for sampled traffic)."""
    reqs = _requests(rng, 4, min_new=8, max_new=14)
    samp = [dict(temperature=0.9, top_k=8, seed=31 + i)
            for i in range(len(reqs))]

    def run(engine):
        hs = [engine.submit(p, m, sampling=s)
              for (p, m), s in zip(reqs, samp)]
        return [h.result(timeout=60) for h in hs]

    a = run(spec4)
    assert spec4.stats["spec_dispatches"] > 0
    b = run(spec4)                       # replica re-execution replay
    with serving.Engine(lm, slots=2, prefill_chunk=4) as plain:
        c = run(plain)
    for (ta, _), (tb, _), (tc, _) in zip(a, b, c):
        assert ta == tb == tc


def test_spec_preemption_resume_identity_and_no_leak(lm):
    """Pool-dry preemption under speculation: mandatory write
    positions walk the SAME pressure ladder as the plain engine (the
    preempted request re-prefills and replays identically), while
    draft positions only grow best-effort — speculation can never
    preempt committed work for a guess. Greedy identity + seeded
    reproduction + zero block leak."""
    long_reqs = [([1] + list(range(3, 15)), 32),
                 ([2] + list(range(5, 17)), 32)]
    seq = serving.sequential_generate(lm, long_reqs)
    eng = serving.Engine(lm, slots=2, prefill_chunk=4, block_size=8,
                         num_blocks=9, prefix_cache=False,
                         speculative=True, spec_gamma=4,
                         name="spec-tiny-pool")
    eng._drafter = NgramDrafter(max_n=3, min_n=1)
    try:
        out = eng.generate_many([p for p, _ in long_reqs],
                                [m for _, m in long_reqs])
        _assert_identical(seq, out)
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["spec_dispatches"] > 0
        assert eng._pool.used == 0       # every block came back
        samp = [dict(temperature=0.8, top_k=6, seed=21 + i)
                for i in range(2)]

        def run():
            hs = [eng.submit(p, m, sampling=s)
                  for (p, m), s in zip(long_reqs, samp)]
            return [h.result(timeout=60) for h in hs]

        p0 = eng.stats["preemptions"]
        a, b = run(), run()
        assert eng.stats["preemptions"] > p0   # the sampled pass
        for (ta, _), (tb, _) in zip(a, b):     # itself preempted
            assert ta == tb
        assert eng._pool.used == 0
    finally:
        eng.close()


# -- telemetry -------------------------------------------------------------

def test_spec_telemetry_counters_rows_and_watch(rng, lm, tmp_path):
    """Day-one telemetry: ptpu_spec_* counters tick, serving_step
    rows carry CUMULATIVE spec_drafted/spec_accepted/spec_emitted/
    spec_dispatches, and monitor watch renders the acceptance-rate
    line (plain mode + --fleet merged counters)."""
    from paddle_tpu import monitor
    from paddle_tpu.monitor.watch import (WatchState, render_frame,
                                          fleet_lines)
    reqs = _requests(rng, 4, min_new=8, max_new=14)
    mlog = str(tmp_path / "spec.jsonl")
    d0 = monrt.SPEC_DISPATCHES.value()
    dr0 = monrt.SPEC_DRAFTED.value()
    ac0 = monrt.SPEC_ACCEPTED.value()
    monitor.enable(log_path=mlog)
    try:
        with serving.Engine(lm, slots=2, prefill_chunk=4,
                            speculative=True, spec_gamma=4,
                            name="spectel") as eng:
            eng._drafter = NgramDrafter(max_n=3, min_n=1)
            eng.generate_many([p for p, _ in reqs],
                              [m for _, m in reqs])
            stats = dict(eng.stats)
    finally:
        monitor.disable()
    assert monrt.SPEC_DISPATCHES.value() - d0 \
        == stats["spec_dispatches"] > 0
    assert monrt.SPEC_DRAFTED.value() - dr0 == stats["spec_drafted"]
    assert monrt.SPEC_ACCEPTED.value() - ac0 == stats["spec_accepted"]
    rows = [r for r in monitor.read_jsonl(mlog)
            if r["ev"] == "serving_step" and r["engine"] == "spectel"]
    assert rows
    last = rows[-1]
    assert last["spec_drafted"] == stats["spec_drafted"]
    assert last["spec_accepted"] == stats["spec_accepted"]
    assert last["spec_emitted"] == stats["spec_emitted"]
    assert last["spec_dispatches"] == stats["spec_dispatches"]
    # cumulative discipline: monotone across rows
    seqs = [r["spec_dispatches"] for r in rows]
    assert seqs == sorted(seqs)
    # watch (plain): the acceptance line renders from the last row
    st = WatchState()
    for r in rows:
        st.feed_event(r)
    frame = render_frame(st, mlog)
    assert "accept rate" in frame and "tok/dispatch" in frame
    # watch --fleet: merged ptpu_spec_* counters render the fleet line
    snap = {
        "ptpu_spec_drafted_tokens_total":
            {"kind": "counter", "series": {"": 10}},
        "ptpu_spec_accepted_tokens_total":
            {"kind": "counter", "series": {"": 4}},
        "ptpu_spec_dispatches_total":
            {"kind": "counter", "series": {"": 6}},
    }
    lines = "\n".join(fleet_lines(snap))
    assert "spec" in lines and "40%" in lines and "dispatches 6" in lines


@pytest.mark.slow
def test_spec_bench_fast_smoke(tmp_path):
    """serving_bench --speculative end-to-end (fast mode): the spec_*
    stamps land, both regimes verify token identity, and the
    SLO-visible accepted_tokens_per_dispatch figure clears the
    ISSUE-13 bar (>1.5 — tokens really multiplied per dispatch).
    Behind -m slow per the PR-11 durations audit (~17 s: a second
    jax process + three model builds); the tier-1 identity pins above
    gate the engine itself."""
    import subprocess
    import sys as _sys
    import json
    import os
    bdir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        [_sys.executable, "serving_bench.py", "--device", "CPU",
         "--fast", "--requests", "6", "--max_new", "48",
         "--speculative", "4"],
        cwd=bdir, env=env, capture_output=True, text=True,
        timeout=540)
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["spec_identical"] is True
    assert out["spec_gamma"] == 4
    for k in ("spec_shared_tok_s", "spec_natural_tok_s",
              "spec_shared_accept_rate", "spec_natural_accept_rate",
              "spec_shared_tokens_per_dispatch", "spec_bs1_speedup",
              "spec_bs1_tok_s"):
        assert k in out, k
    assert out["accepted_tokens_per_dispatch"] > 1.5


# -- soak (slow tier) ------------------------------------------------------

@pytest.mark.slow
def test_spec_soak_identity_and_replay(rng, lm):
    """Seeded soak: repeated mixed greedy+sampled workloads through
    fresh speculative engines at γ∈{2,4} stay identical to the
    baseline / replay-identical across engines."""
    for trial in range(3):
        reqs = _requests(rng, 10, max_prompt=13, min_new=4,
                         max_new=24)
        seq = serving.sequential_generate(lm, reqs)
        g = 2 if trial % 2 else 4
        with serving.Engine(lm, slots=4, prefill_chunk=4,
                            speculative=True, spec_gamma=g) as eng:
            eng._drafter = NgramDrafter(max_n=3, min_n=1)
            out = eng.generate_many([p for p, _ in reqs],
                                    [m for _, m in reqs])
        _assert_identical(seq, out)
        samp = [dict(temperature=1.1, top_k=6, top_p=0.9,
                     seed=100 * trial + i) for i in range(4)]
        outs = []
        for _ in range(2):
            with serving.Engine(lm, slots=2, prefill_chunk=4,
                                speculative=True, spec_gamma=4) as e2:
                hs = [e2.submit(p, m, sampling=s)
                      for (p, m), s in zip(reqs[:4], samp)]
                outs.append([h.result(timeout=120)[0] for h in hs])
        assert outs[0] == outs[1]
