"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

Beyond the 2018 reference (SURVEY.md §2.7: EP absent; the closest analog is
the distributed sparse lookup table). GShard-style design: top-k gating with
capacity, dispatch/combine as einsums against a one-hot dispatch tensor, and
expert weights stacked [E, ...] sharded on ``ep`` — XLA GSPMD turns the
dispatch einsum into the all-to-all over ICI, no manual comm code.
"""

import jax
import jax.numpy as jnp
from jax import lax


def top1_gating(logits, capacity, rng=None, noise_std=0.0):
    """logits [T, E] → (dispatch [T, E, C] one-hot, combine [T, E, C],
    aux_loss). Tokens beyond an expert's capacity are dropped (standard
    Switch-transformer behavior)."""
    t, e = logits.shape
    if noise_std and rng is not None:
        logits = logits + noise_std * jax.random.normal(rng, logits.shape)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                  # [T]
    expert_mask = jax.nn.one_hot(expert_idx, e)              # [T, E]
    # position of each token within its expert's queue
    pos_in_expert = (jnp.cumsum(expert_mask, axis=0) - 1.0) * expert_mask
    keep = (pos_in_expert < capacity) * expert_mask          # [T, E]
    pos = jnp.sum(pos_in_expert * keep, axis=-1)             # [T]
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity)  # [T, C]
    dispatch = keep[:, :, None] * pos_oh[:, None, :]         # [T, E, C]
    gate_prob = jnp.sum(probs * expert_mask, axis=-1)        # [T]
    combine = dispatch * gate_prob[:, None, None]
    # load-balancing aux loss (GShard eq. 4 / Switch aux)
    density = jnp.mean(expert_mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * (e ** 2) / e
    return dispatch, combine, aux


def topk_gating(logits, capacity, k=2, rng=None, noise_std=0.0):
    """GShard-style top-k gating (top-2 is the standard MoE training
    config). Combine weights are the k selected gate probabilities
    NORMALIZED to sum to 1 per token; rank-0 choices claim expert queue
    slots before rank-1 choices (GShard sec. 2.2). Tokens whose rank-r
    choice overflows the expert's capacity lose that branch (no
    renormalization after dropping, per the paper).

    logits [T, E] → (dispatch [T, E, C], combine [T, E, C], aux_loss,
    overflow_frac) where overflow_frac = dropped assignments / (T*k).
    """
    t, e = logits.shape
    if noise_std and rng is not None:
        logits = logits + noise_std * jax.random.normal(rng, logits.shape)
    # slot bookkeeping in float32 ALWAYS: a bf16 cumsum cannot represent
    # integers past 256 exactly, so positions would collide silently
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, idxs = jax.lax.top_k(probs, k)                # [T, k]
    weights = gate_vals / (jnp.sum(gate_vals, -1, keepdims=True) + 1e-9)

    counts = jnp.zeros((e,), jnp.float32)    # slots CLAIMED per expert
    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    kept_total = jnp.asarray(0.0, jnp.float32)
    for r in range(k):
        mask = jax.nn.one_hot(idxs[:, r], e)                 # [T, E] f32
        pos = (jnp.cumsum(mask, axis=0) - 1.0) * mask + counts * mask
        keep = (pos < capacity) * mask                       # [T, E]
        pos_tok = jnp.sum(pos * keep, axis=-1)               # [T]
        pos_oh = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity)
        slot = keep[:, :, None] * pos_oh[:, None, :]         # [T, E, C]
        dispatch = dispatch + slot
        combine = combine + slot * weights[:, r][:, None, None]
        # offset the next rank by slots actually CLAIMED (≤ capacity).
        # Equivalent gating to the raw-count offset — once an expert
        # overflows it is full under either bookkeeping — but counts
        # stays a true slot count.
        counts = counts + jnp.sum(keep, axis=0)
        kept_total = kept_total + jnp.sum(keep)
    overflow = jnp.clip(1.0 - kept_total / (t * k), 0.0, 1.0)
    # load-balancing aux loss on the rank-0 assignment (GShard eq. 4)
    density = jnp.mean(jax.nn.one_hot(idxs[:, 0], e), axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * (e ** 2) / e
    dtype = logits.dtype
    return dispatch.astype(dtype), combine.astype(dtype), aux, overflow


def moe_ffn(x, gate_w, w_up, w_down, capacity_factor=1.25, rng=None,
            mesh=None, ep_axis="ep", top_k=1, return_stats=False):
    """Switch-style (top_k=1) or GShard-style (top_k=2) MoE FFN.

    x       [T, D] tokens
    gate_w  [D, E]
    w_up    [E, D, H] stacked expert weights (shard on ep)
    w_down  [E, H, D]
    Returns ([T, D], aux_loss), plus a stats dict ({"overflow": frac of
    dropped token-expert assignments}) when return_stats=True.
    """
    t, d = x.shape
    e = gate_w.shape[1]
    capacity = max(1, int(capacity_factor * top_k * t / e))
    logits = x @ gate_w
    if top_k > 1:
        dispatch, combine, aux, overflow = topk_gating(
            logits, capacity, k=top_k, rng=rng)
    else:
        dispatch, combine, aux = top1_gating(logits, capacity, rng)
        overflow = jnp.clip(1.0 - jnp.sum(dispatch) / t, 0.0, 1.0)
    # dispatch tokens to experts: [E, C, D]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    if mesh is not None and ep_axis in mesh.axis_names:
        from jax.sharding import NamedSharding, PartitionSpec as P
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(ep_axis)))
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", expert_in, w_up))
    expert_out = jnp.einsum("ech,ehd->ecd", h, w_down)
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    if return_stats:
        return out, aux, {"overflow": overflow}
    return out, aux


def moe_ffn_pp_sharded(x, gate_w, w_up_local, w_down_local, ep_axis,
                       top_k=1, capacity_factor=1.25):
    """Per-DEVICE MoE FFN for use INSIDE shard_map — the pp x ep
    composition (the MoE all-to-all nested in the pipeline stage body).

    x             [T_loc, D]: THIS member's token slice (the stage
                  activations arrive batch-sharded over dp x ep)
    gate_w        [D, E] replicated (routing needs every expert's logit)
    w_up_local    [E/n_ep, D, H]: this member's expert shard (expert e's
                  owner is e // e_loc — the contiguous ep sharding of the
                  stacked [E, ...] weights)
    w_down_local  [E/n_ep, H, D]

    Routing is LOCAL (each member gates its own tokens with capacity
    cf*k*T_loc/E — the standard local-routing MoE deployment); the
    dispatched token queues ride ONE tiled lax.all_to_all to the expert
    owners ([E, C, D] -> [E/n, n*C, D]), the expert FFN runs on the
    local expert shard, and a second all_to_all brings the outputs back.
    Math per member is EXACTLY moe_ffn(mesh=None) on its token group, so
    a dense fallback that gates the same groups reproduces this bit-for-
    float (ops/parallel_ops pipeline_stack moe_gate_groups contract).

    Returns ([T_loc, D], aux_loss_local).
    """
    t, d = x.shape
    n_ep = lax.psum(1, ep_axis)
    e_loc = w_up_local.shape[0]
    e = e_loc * n_ep
    capacity = max(1, int(capacity_factor * top_k * t / e))
    logits = x @ gate_w
    if top_k > 1:
        dispatch, combine, aux, _ = topk_gating(logits, capacity, k=top_k)
    else:
        dispatch, combine, aux = top1_gating(logits, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)       # [E, C, D]
    # chunk j of the E axis (this member's queues for owner j's experts)
    # goes to member j; received chunks concatenate on the slot axis:
    # [E, C, D] -> [E/n, n*C, D] (slot block i = tokens from member i)
    expert_in = lax.all_to_all(expert_in, ep_axis, split_axis=0,
                               concat_axis=1, tiled=True)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", expert_in, w_up_local))
    expert_out = jnp.einsum("ech,ehd->ecd", h, w_down_local)
    # inverse movement: slot block i returns to member i, rebuilding the
    # full [E, C, D] expert-major layout for the local combine
    expert_out = lax.all_to_all(expert_out, ep_axis, split_axis=1,
                                concat_axis=0, tiled=True)
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, aux
