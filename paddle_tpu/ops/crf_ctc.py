"""Structured-prediction ops: linear-chain CRF, CTC, chunk evaluation.

Reference parity: operators/{linear_chain_crf,crf_decoding,warpctc,
ctc_align,chunk_eval,edit_distance}_op.cc.

TPU-first: the CRF forward recursion and Viterbi, and the CTC alpha
recursion, are lax.scan over padded [B, T, ...] batches (mask-frozen past
each sequence end) instead of the reference's per-sequence CPU loops /
warp-ctc CUDA kernels; everything is differentiable where the reference's
grad kernels were (CRF LL, CTC loss).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .common import I64
from ..core.registry import register


def _pad_batch(ctx, op, slot="Emission"):
    """rnn_ops' unique-indices pack (fast backward scatter; see
    _pad_from_lod) with the crf/ctc 3-tuple signature kept."""
    from .rnn_ops import _pad_from_lod
    padded, lens, total, _ = _pad_from_lod(ctx, op, slot)
    return padded, lens, total


def _unpad(padded, lens, total):
    from .rnn_ops import _unpad_to_lod
    return _unpad_to_lod(padded, lens, total)


@register("linear_chain_crf")
def _linear_chain_crf(ctx, op):
    """Negative log-likelihood of a linear-chain CRF
    (linear_chain_crf_op.cc). Transition [D+2, D]: row 0 = start weights,
    row 1 = end weights, rows 2.. = transition matrix."""
    emission, lens, total = _pad_batch(ctx, op, "Emission")   # [B,T,D]
    label_flat = ctx.in1(op, "Label")
    label_p, _, _ = _pad_batch(ctx, op, "Label") \
        if op.input("Label") else (None, None, None)
    label_p = label_p.reshape(label_p.shape[0], label_p.shape[1])
    trans = ctx.in1(op, "Transition")
    d = trans.shape[1]
    w_start, w_end, w = trans[0], trans[1], trans[2:]
    b, tmax = emission.shape[0], emission.shape[1]

    # log-partition via forward recursion
    alpha0 = w_start[None, :] + emission[:, 0]               # [B, D]

    def fwd(carry, t):
        alpha = carry
        # [B, D_prev, 1] + [D_prev, D] → logsumexp over prev
        scores = alpha[:, :, None] + w[None, :, :] + \
            emission[:, t][:, None, :]
        new = jax.scipy.special.logsumexp(scores, axis=1)
        alive = (t < lens)[:, None]
        return jnp.where(alive, new, alpha), None

    alpha, _ = lax.scan(fwd, alpha0, jnp.arange(1, tmax))
    log_z = jax.scipy.special.logsumexp(alpha + w_end[None, :], axis=1)

    # gold path score
    lbl = label_p.astype(jnp.int32)
    pos = jnp.arange(tmax)[None, :]
    alive = pos < lens[:, None]
    em_sc = jnp.take_along_axis(emission, lbl[:, :, None],
                                axis=2)[:, :, 0]
    em_score = jnp.sum(jnp.where(alive, em_sc, 0.0), axis=1)
    prev_l = lbl[:, :-1]
    next_l = lbl[:, 1:]
    tr_sc = w[prev_l, next_l]
    tr_alive = (pos[:, 1:] < lens[:, None])
    tr_score = jnp.sum(jnp.where(tr_alive, tr_sc, 0.0), axis=1)
    last = jnp.clip(lens - 1, 0)
    start_score = w_start[lbl[:, 0]]
    end_score = w_end[jnp.take_along_axis(lbl, last[:, None], axis=1)[:, 0]]
    gold = em_score + tr_score + start_score + end_score
    ll = log_z - gold                                         # NLL [B]
    ctx.set_out(op, "LogLikelihood", ll[:, None])
    ctx.set_out(op, "Alpha", _unpad(
        jnp.zeros_like(emission), lens, total))
    ctx.set_out(op, "EmissionExps", _unpad(jnp.exp(emission), lens, total))
    ctx.set_out(op, "TransitionExps", jnp.exp(trans))


@register("crf_decoding")
def _crf_decoding(ctx, op):
    """Viterbi decode (crf_decoding_op.cc)."""
    emission, lens, total = _pad_batch(ctx, op, "Emission")
    trans = ctx.in1(op, "Transition")
    d = trans.shape[1]
    w_start, w_end, w = trans[0], trans[1], trans[2:]
    b, tmax = emission.shape[0], emission.shape[1]

    delta0 = w_start[None, :] + emission[:, 0]

    def fwd(carry, t):
        delta = carry
        scores = delta[:, :, None] + w[None, :, :] + \
            emission[:, t][:, None, :]
        best_prev = jnp.argmax(scores, axis=1)               # [B, D]
        new = jnp.max(scores, axis=1)
        alive = (t < lens)[:, None]
        return jnp.where(alive, new, delta), \
            jnp.where(alive, best_prev, -1)

    delta, backptrs = lax.scan(fwd, delta0, jnp.arange(1, tmax))
    # include end weights at each sequence's true last step
    final = delta + w_end[None, :]
    last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)    # [B]

    # backtrack (backptrs [T-1, B, D]); -1 rows are frozen (past end)
    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        tag_new = jnp.where(prev >= 0, prev, tag)
        return tag_new.astype(jnp.int32), tag_new.astype(jnp.int32)

    _, path_rev = lax.scan(back, last_tag, backptrs, reverse=True)
    # path_rev[t] = tag at step t (for t = 0..T-2); last step tag = last_tag
    path = jnp.concatenate([path_rev, last_tag[None, :]], axis=0)  # [T,B]
    path = jnp.transpose(path)                                     # [B,T]
    # but frozen steps gave propagated tags; true last position differs per
    # sequence. Reconstruct: for each b, the decode of position t is valid
    # for t < len.
    out = _unpad(path[:, :, None], lens, total)
    ctx.set_out(op, "ViterbiPath", out.astype(I64()))


@register("warpctc")
def _warpctc(ctx, op):
    """CTC loss (warpctc_op.cc) via the log-domain alpha recursion."""
    logits, in_lens, total = _pad_batch(ctx, op, "Logits")   # [B,T,C]
    labels, lab_lens, lab_total = _pad_batch(ctx, op, "Label")
    labels = labels.reshape(labels.shape[0], labels.shape[1])
    blank = int(op.attr("blank", 0))
    norm_by_times = op.attr("norm_by_times", False)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    b, tmax, c = log_probs.shape
    l = labels.shape[1]
    s = 2 * l + 1
    neg_inf = -1e30

    # extended label seq: blank l1 blank l2 ... blank
    ext = jnp.full((b, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    # alpha[0]
    a0 = jnp.full((b, s), neg_inf)
    a0 = a0.at[:, 0].set(log_probs[:, 0, blank])
    first_lab = jnp.take_along_axis(log_probs[:, 0], ext[:, 1:2],
                                    axis=1)[:, 0]
    a0 = a0.at[:, 1].set(jnp.where(lab_lens > 0, first_lab, neg_inf))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((b, 2), bool),
         ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, t):
        p = jnp.take_along_axis(log_probs[:, t], ext, axis=1)  # [B, S]
        a_shift1 = jnp.concatenate(
            [jnp.full((b, 1), neg_inf), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate(
            [jnp.full((b, 2), neg_inf), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
        merged = jnp.logaddexp(alpha, a_shift1)
        merged = jnp.logaddexp(merged, a_shift2)
        new = merged + p
        alive = (t < in_lens)[:, None]
        return jnp.where(alive, new, alpha), None

    alpha, _ = lax.scan(step, a0, jnp.arange(1, tmax))
    # final: sum of last two valid ext positions (2*lab_len-1, 2*lab_len)
    end_idx = 2 * lab_lens
    a_last = jnp.take_along_axis(alpha, end_idx[:, None].astype(jnp.int32),
                                 axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.clip(end_idx - 1, 0)[:, None].astype(jnp.int32),
        axis=1)[:, 0]
    loss = -jnp.logaddexp(a_last, a_prev)                     # [B]
    if norm_by_times:
        loss = loss / jnp.maximum(in_lens, 1)
    ctx.set_out(op, "Loss", loss[:, None])
    ctx.set_out(op, "WarpCTCGrad", jnp.zeros_like(logits))


@register("ctc_align")
def _ctc_align(ctx, op):
    """CTC greedy decode post-process (ctc_align_op.cc): merge repeats,
    strip blanks. Output keeps static shape, compacted + -1 padded, with
    @LOD carrying decoded lengths."""
    x = ctx.in1(op, "Input")
    lens = ctx.maybe_get(op.input("Input")[0] + "@LOD")
    blank = int(op.attr("blank", 0))
    merge = op.attr("merge_repeated", True)
    flat = x.reshape(-1).astype(jnp.int32)
    t = flat.shape[0]
    if lens is None:
        lens = jnp.asarray([t], jnp.int32)
    ends = jnp.cumsum(lens)
    seg = jnp.searchsorted(ends, jnp.arange(t), side="right")
    starts = ends - lens
    pos = jnp.arange(t) - starts[seg]
    prev = jnp.where(pos > 0, jnp.roll(flat, 1), -1)
    keep = flat != blank
    if merge:
        keep = keep & (flat != prev)
    order = jnp.argsort(~keep, stable=True)
    out = jnp.where(jnp.arange(t) < jnp.sum(keep), flat[order], -1)
    new_lens = jax.ops.segment_sum(keep.astype(jnp.int32), seg,
                                   num_segments=lens.shape[0])
    name = ctx.out_name(op, "Output")
    ctx.env[name] = out[:, None].astype(I64())
    ctx.env[name + "@LOD"] = new_lens


@register("chunk_eval")
def _chunk_eval(ctx, op):
    """Chunk detection metrics for IOB tagging (chunk_eval_op.cc).
    num_chunk_types T with scheme IOB: tag = type*2 (B) / type*2+1 (I)."""
    inf = ctx.in1(op, "Inference").reshape(-1).astype(jnp.int32)
    lab = ctx.in1(op, "Label").reshape(-1).astype(jnp.int32)
    lens = ctx.maybe_get(op.input("Inference")[0] + "@LOD")
    num_types = int(op.attr("num_chunk_types", 1))
    scheme = op.attr("chunk_scheme", "IOB")
    excluded = [int(e) for e in (op.attr("excluded_chunk_types") or [])]
    t = inf.shape[0]
    if lens is None:
        lens = jnp.asarray([t], jnp.int32)
    ends = jnp.cumsum(lens)
    seg = jnp.searchsorted(ends, jnp.arange(t), side="right")
    starts_ = ends - lens
    pos = jnp.arange(t) - starts_[seg]
    # bucket-pad rows past the true total carry tag 0 (= B of type 0);
    # force them to an out-of-range tag so no scheme counts them as chunks
    valid = jnp.arange(t) < ends[-1]
    sentinel = 2 * num_types + 7
    inf = jnp.where(valid, inf, sentinel)
    lab = jnp.where(valid, lab, sentinel)

    def _exclude(start, typ):
        # excluded chunk types do not count as chunks (chunk_eval_op.h
        # isExcludedChunkType): their positions become non-chunk
        for et in excluded:
            start = start & (typ != et)
            typ = jnp.where(typ == et, -1, typ)
        return start, typ

    def chunk_starts(tags):
        if scheme == "plain":
            typ = tags
            prev = jnp.where(pos > 0, jnp.roll(tags, 1), -1)
            start = (typ >= 0) & (typ < num_types) & (typ != prev)
            return _exclude(start, typ)
        # IOB: B tag starts; I starts a chunk if prev is different type/O
        is_b = (tags % 2 == 0) & (tags < 2 * num_types)
        is_i = (tags % 2 == 1) & (tags < 2 * num_types)
        typ = jnp.where(is_b | is_i, tags // 2, -1)
        prev_typ = jnp.where(pos > 0, jnp.roll(typ, 1), -2)
        start = is_b | (is_i & (typ != prev_typ))
        return _exclude(start, typ)

    # a label chunk is correct iff an inference chunk has the SAME start,
    # SAME end, and SAME type (chunk_eval_op.h exact-span semantics)
    def spans(tags):
        start, typ = chunk_starts(tags)
        in_chunk = typ >= 0
        cid = jnp.cumsum(start.astype(jnp.int32)) * in_chunk
        return start, typ, cid, in_chunk

    s_i, t_i, c_i, in_i = spans(inf)
    s_l, t_l, c_l, in_l = spans(lab)
    num_inf = jnp.sum(s_i)
    num_lab = jnp.sum(s_l)
    # per-position agreement: membership and starts coincide, types match
    # inside chunks
    ok = (in_i == in_l) & (s_i == s_l) & \
        jnp.where(in_l, t_i == t_l, True)
    bad = (~ok).astype(jnp.int32)
    cum_bad = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(bad)])
    n_chunks = int(t)
    pos_arr = jnp.arange(t)
    start_pos = jax.ops.segment_min(
        jnp.where(in_l, pos_arr, t), c_l, num_segments=n_chunks + 1)
    end_pos = jax.ops.segment_max(
        jnp.where(in_l, pos_arr, -1), c_l, num_segments=n_chunks + 1)
    exists = end_pos >= 0
    sp = jnp.clip(start_pos, 0, t - 1)
    ep = jnp.clip(end_pos, 0, t - 1)
    bad_in_span = cum_bad[ep + 1] - cum_bad[sp]
    # the inference chunk must END with the label chunk: position end+1
    # must not continue an inference chunk
    cont = (in_i & ~s_i)
    cont_pad = jnp.concatenate([cont, jnp.zeros((1,), bool)])
    extends = cont_pad[ep + 1]
    correct_chunk = exists & (bad_in_span == 0) & ~extends
    correct = jnp.sum(correct_chunk[1:].astype(jnp.int32))
    precision = jnp.where(num_inf > 0, correct / num_inf, 0.0)
    recall = jnp.where(num_lab > 0, correct / num_lab, 0.0)
    f1 = jnp.where(correct > 0,
                   2 * precision * recall / (precision + recall), 0.0)
    ctx.set_out(op, "Precision", precision.reshape(1))
    ctx.set_out(op, "Recall", recall.reshape(1))
    ctx.set_out(op, "F1-Score", f1.reshape(1))
    ctx.set_out(op, "NumInferChunks",
                num_inf.reshape(1).astype(I64()))
    ctx.set_out(op, "NumLabelChunks",
                num_lab.reshape(1).astype(I64()))
    ctx.set_out(op, "NumCorrectChunks",
                correct.reshape(1).astype(I64()))
