"""RecordIO-equivalent data format: native C++ chunk/scanner round-trips,
CRC corruption detection, compression, reader-pipeline + DeviceLoader
integration, and a train-from-file end-to-end run.

Reference parity: paddle/fluid/recordio/ (chunk_test.cc, scanner),
recordio_writer.py, operators/reader/create_recordio_file_reader_op.cc."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu as fluid
from paddle_tpu import recordio


def test_bytes_roundtrip_multiple_chunks(tmp_path):
    path = str(tmp_path / "r.rio")
    records = [os.urandom(np.random.RandomState(i).randint(1, 4000))
               for i in range(200)]
    with recordio.Writer(path, max_chunk_bytes=8192) as w:
        for r in records:
            w.write(r)
    got = list(recordio.Scanner(path))
    assert got == records
    # multiple chunks were actually written (8KB cap, ~400KB of data)
    assert os.path.getsize(path) > 8192


def test_compression_none_vs_deflate(tmp_path):
    comp = str(tmp_path / "c.rio")
    raw = str(tmp_path / "n.rio")
    rec = (b"abc" * 1000,)
    data = [rec[0]] * 50
    for path, compressor in ((comp, recordio.COMPRESSOR_DEFLATE),
                             (raw, recordio.COMPRESSOR_NONE)):
        with recordio.Writer(path, compressor=compressor) as w:
            for r in data:
                w.write(r)
    assert list(recordio.Scanner(comp)) == data
    assert list(recordio.Scanner(raw)) == data
    # highly repetitive payload must compress well
    assert os.path.getsize(comp) < os.path.getsize(raw) / 5


def test_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "x.rio")
    with recordio.Writer(path) as w:
        w.write(b"hello world" * 100)
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0xFF                     # flip a payload byte
    open(path, "wb").write(bytes(blob))
    with pytest.raises(IOError):
        list(recordio.Scanner(path))


def test_truncated_file_errors(tmp_path):
    path = str(tmp_path / "t.rio")
    with recordio.Writer(path) as w:
        w.write(b"x" * 500)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:len(blob) // 2])
    with pytest.raises(IOError):
        list(recordio.Scanner(path))


def test_sample_codec_numpy_and_scalars():
    sample = (np.arange(6, dtype=np.float32).reshape(2, 3),
              np.array([1, 2, 3], np.int64), 7, 2.5)
    back = recordio.decode_sample(recordio.encode_sample(sample))
    np.testing.assert_array_equal(back[0], sample[0])
    np.testing.assert_array_equal(back[1], sample[1])
    assert back[2] == 7 and abs(back[3] - 2.5) < 1e-12
    assert isinstance(back[2], int)


def test_convert_reader_and_read_back(tmp_path):
    path = str(tmp_path / "ds.rio")
    rng = np.random.RandomState(0)
    xs = rng.rand(37, 4).astype(np.float32)
    ys = rng.randint(0, 3, 37).astype(np.int64)

    def creator():
        for i in range(37):
            yield xs[i], int(ys[i])

    n = recordio.convert_reader_to_recordio_file(path, creator)
    assert n == 37
    back = list(recordio.reader(path)())
    assert len(back) == 37
    np.testing.assert_allclose(back[5][0], xs[5])
    assert back[5][1] == ys[5]

    # composes with the reader-decorator pipeline
    batches = list(paddle.batch(
        paddle.reader.shuffle(recordio.reader(path), 37),
        batch_size=10)())
    assert sum(len(b) for b in batches) == 37


def test_train_from_recordio_file(tmp_path):
    # the data-plane integration the VERDICT asked for: file -> reader ->
    # DataFeeder -> compiled step, loss converges
    path = str(tmp_path / "train.rio")
    rng = np.random.RandomState(0)
    w_true = rng.rand(4, 1).astype(np.float32)

    def creator():
        for _ in range(64):
            x = rng.rand(4).astype(np.float32)
            yield x, float((x @ w_true).item() + 0.5)

    recordio.convert_reader_to_recordio_file(path, creator)

    x = fluid.layers.data("x", [4])
    y = fluid.layers.data("y", [1])
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feeder = fluid.DataFeeder([x, y], fluid.CPUPlace())

    first = last = None
    for epoch in range(30):
        for batch in paddle.batch(recordio.reader(path), batch_size=16)():
            lv, = exe.run(feed=feeder.feed(batch), fetch_list=[loss])
            if first is None:
                first = float(lv)
            last = float(lv)
    assert last < first * 0.05, (first, last)


def test_device_loader_prefetch_from_recordio(tmp_path):
    from paddle_tpu.reader.device_loader import DeviceLoader
    path = str(tmp_path / "dl.rio")

    def creator():
        for i in range(20):
            yield (np.full((2, 2), i, np.float32),)

    recordio.convert_reader_to_recordio_file(path, creator)
    feed_dicts = ({"x": np.stack([s[0] for s in b])}
                  for b in paddle.batch(recordio.reader(path),
                                        batch_size=4)())
    loader = DeviceLoader(feed_dicts, capacity=2)
    seen = list(loader)
    assert len(seen) == 5
    import jax
    assert isinstance(seen[0]["x"], jax.Array)
    np.testing.assert_allclose(np.asarray(seen[-1]["x"])[-1],
                               np.full((2, 2), 19.0))


def test_scanner_safe_after_exhaustion(tmp_path):
    path = str(tmp_path / "s.rio")
    with recordio.Writer(path) as w:
        w.write(b"one")
    s = recordio.Scanner(path)
    assert list(s) == [b"one"]
    # re-iterating an exhausted scanner must raise StopIteration, not
    # touch the freed native handle
    assert list(s) == []
    with pytest.raises(StopIteration):
        next(s)


def test_corrupt_header_lengths_raise_ioerror(tmp_path):
    # corruption in the LENGTH bytes of the header (not payload) must be
    # an IOError, not a multi-GB allocation/abort
    path = str(tmp_path / "h.rio")
    with recordio.Writer(path) as w:
        w.write(b"payload" * 50)
    blob = bytearray(open(path, "rb").read())
    blob[12] = 0xFF   # raw_len high byte
    blob[20] = 0xFF   # comp_len high byte
    open(path, "wb").write(bytes(blob))
    with pytest.raises(IOError):
        list(recordio.Scanner(path))


def test_reader_early_abandon_does_not_leak_fds(tmp_path):
    import gc
    path = str(tmp_path / "fd.rio")
    recordio.convert_reader_to_recordio_file(
        path, lambda: ((np.zeros(2, np.float32),) for _ in range(50)))
    n0 = len(os.listdir("/proc/self/fd"))
    for _ in range(20):
        it = recordio.reader(path)()
        next(it)          # read one record, abandon the pass
        it.close()        # generator close triggers the finally
    gc.collect()
    assert len(os.listdir("/proc/self/fd")) <= n0 + 1


def test_understated_record_count_detected(tmp_path):
    # num_records is outside the payload CRC; an understated count must
    # raise instead of silently dropping trailing records
    path = str(tmp_path / "cnt.rio")
    with recordio.Writer(path, compressor=recordio.COMPRESSOR_NONE) as w:
        for i in range(5):
            w.write(b"rec%d" % i)
    blob = bytearray(open(path, "rb").read())
    assert blob[6] == 5            # num_records low byte
    blob[6] = 3
    open(path, "wb").write(bytes(blob))
    s = recordio.Scanner(path)
    with pytest.raises(IOError):
        list(s)
