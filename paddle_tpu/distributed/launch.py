"""Multi-host bootstrap for mesh-mode training.

Reference parity: the env-driven trainer bootstrap of the pserver world
(distribute_transpiler.py trainer_id/trainers envs; paddle.init) mapped to
the TPU-native path — jax.distributed.initialize builds the cross-host
process group, after which a Mesh spanning all hosts' devices gives DCN+ICI
collectives through the same GSPMD programs (SURVEY §5.8: jax.distributed
+ coordination service replace etcd rendezvous for mesh mode; the
pserver/elastic tier remains the explicitly-managed alternative).

Env contract (PADDLE_* names kept for reference-script compatibility):
  PADDLE_COORDINATOR   host:port of process 0 (jax coordination service)
  PADDLE_TRAINERS_NUM  total process count
  PADDLE_TRAINER_ID    this process's rank
"""

import os

import jax

_initialized = False


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None, local_device_ids=None):
    """Idempotent process-group init. With no arguments and no PADDLE_*
    env, single-process mode is a no-op (matching paddle.init locally)."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or \
        os.environ.get("PADDLE_COORDINATOR")
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    process_id = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if num_processes <= 1 and coordinator_address is None:
        _initialized = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True


def global_mesh(axes):
    """Mesh over ALL processes' devices (call after init_parallel_env).
    `axes`: dict name -> size, like parallel.make_mesh but global."""
    from ..parallel.mesh import make_mesh
    return make_mesh(axes, devices=jax.devices())


def trainer_id():
    return jax.process_index()


def trainer_count():
    return jax.process_count()
