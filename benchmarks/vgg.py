"""VGG-16 benchmark — parity with reference benchmark/fluid/vgg.py."""

import numpy as np

from common import parse_args, get_place, time_loop  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import vgg  # noqa: E402


def main():
    args = parse_args(
        "vgg", batch_size=32, iterations=20,
        extra=lambda p: p.add_argument("--image_size", type=int,
                                       default=32))
    shape = (3, args.image_size, args.image_size)
    image, label, avg_cost, acc = vgg.build_train_net(
        image_shape=shape, num_classes=10, learning_rate=1e-3)
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    # feeds committed to the DEVICE once: re-uploading the same numpy
    # batch every step would measure the sandbox tunnel's measured
    # 4-8 MB/s upload path, not the chip (at 224^2 bs64 that is ~5-9
    # s/step of pure transfer — PERF.md round-5 bandwidth probe). Real
    # input overlap is benchmarks/input_pipeline.py's job (DeviceLoader
    # prefetch).
    import jax
    dev = get_place(args).jax_device()    # honor --device CPU/TPU
    xs = jax.device_put(rng.rand(args.batch_size,
                                 *shape).astype(np.float32), dev)
    ys = jax.device_put(
        rng.randint(0, 10, (args.batch_size, 1)).astype(np.int64), dev)

    last = []

    def step(i):
        lv, = exe.run(feed={"data": xs, "label": ys},
                      fetch_list=[avg_cost], return_numpy=False)
        last[:] = [lv]

    def sync():
        # one blocking fetch per timing window (per-step fetches would
        # measure the sandbox tunnel's ~90ms sync, not the chip)
        if last:
            print("loss %.4f" % float(np.asarray(last[0])))

    return time_loop(step, args, args.batch_size, "imgs", sync=sync)


if __name__ == "__main__":
    main()
