"""ResNet benchmark — parity with reference benchmark/fluid/resnet.py
(north star: ResNet-50 images/sec/chip)."""

import numpy as np

from common import parse_args, get_place, time_loop, synthetic_feeds  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import resnet  # noqa: E402


def main():
    args = parse_args(
        "resnet", batch_size=32, iterations=30,
        extra=lambda p: (
            p.add_argument("--model", default="resnet_imagenet",
                           choices=["resnet_imagenet", "resnet_cifar10"]),
            p.add_argument("--depth", type=int, default=50),
            p.add_argument("--image_size", type=int, default=224)))
    shape = ((3, args.image_size, args.image_size)
             if args.model == "resnet_imagenet" else (3, 32, 32))
    classes = 1000 if args.model == "resnet_imagenet" else 10
    # in-graph synthetic data (create_random_data_generator parity) so the
    # steady-state step measures compute, not the host->device tunnel
    synth = synthetic_feeds({
        "data": ((args.batch_size,) + shape, "float32", 1.0),
        "label": ((args.batch_size, 1), "int64", classes)})
    image, label, avg_cost, acc = resnet.build_train_net(
        model=args.model, depth=args.depth, image_shape=shape,
        num_classes=classes, learning_rate=0.01,
        image=synth["data"], label=synth["label"])
    if args.dtype == "bfloat16":
        fluid.amp.enable_amp()
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())

    last = []

    def step(i):
        loss, = exe.run(feed={}, fetch_list=[avg_cost],
                        return_numpy=False)
        last[:] = [loss]

    def sync():
        # one blocking fetch per timing window (not per step: the sandbox
        # tunnel charges ~90ms per sync)
        print("loss %.4f" % float(np.asarray(last[0])))

    return time_loop(step, args, args.batch_size, "imgs", sync=sync)


if __name__ == "__main__":
    main()
