"""DeepFM CTR model (models/deepfm.py) — the sparse-embedding workload of
SURVEY M5: trains through the lookup_table is_sparse path, learns a
synthetic click rule, and its FM second-order term matches the explicit
pairwise-interaction computation."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import deepfm


def _batch(rng, batch, num_fields, vocab):
    ids = rng.randint(0, vocab, (batch, num_fields)).astype(np.int64)
    # click iff field0 id is even AND field1 id < vocab/2 (learnable from
    # the embeddings alone)
    click = ((ids[:, 0] % 2 == 0) & (ids[:, 1] < vocab // 2))
    return ids, click.astype(np.float32).reshape(-1, 1)


def test_deepfm_learns_synthetic_ctr():
    num_fields, vocab = 6, 64
    fields, label, prob, loss = deepfm.build_train_net(
        num_fields=num_fields, vocab_size=vocab, embed_dim=8,
        learning_rate=2e-2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    first = last = None
    for _ in range(150):
        ids, click = _batch(rng, 64, num_fields, vocab)
        feed = {f.name: ids[:, i:i + 1] for i, f in enumerate(fields)}
        feed["click"] = click
        lv, = exe.run(feed=feed, fetch_list=[loss])
        if first is None:
            first = float(lv)
        last = float(lv)
    # below ln2 (chance) and well below the start
    assert last < 0.45, (first, last)
    assert last < first * 0.7, (first, last)

    # predicted probabilities separate clicks from non-clicks
    ids, click = _batch(rng, 256, num_fields, vocab)
    feed = {f.name: ids[:, i:i + 1] for i, f in enumerate(fields)}
    feed["click"] = click
    p, = exe.run(feed=feed, fetch_list=[prob])
    p = np.asarray(p).ravel()
    assert p[click.ravel() > 0].mean() > p[click.ravel() == 0].mean() + 0.2


def test_fm_second_order_identity():
    # the sum-square/square-sum trick == explicit pairwise dot products
    num_fields, vocab, k = 4, 20, 5
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        fields = [fluid.layers.data("f%d" % i, [1], dtype="int64")
                  for i in range(num_fields)]
        _, logit = deepfm.deepfm(fields, vocab, embed_dim=k,
                                 dnn_dims=(4,))
        # the model's second-order term is the (only) reduce_sum output
        second_name = [op.outputs["Out"][0]
                       for op in prog.global_block().ops
                       if op.type == "reduce_sum"][0]
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            rng = np.random.RandomState(1)
            ids = rng.randint(0, vocab, (3, num_fields)).astype(np.int64)
            feed = {f.name: ids[:, i:i + 1]
                    for i, f in enumerate(fields)}
            out, second = exe.run(prog, feed=feed,
                                  fetch_list=[logit, second_name])
            v = np.asarray(scope.find_var("fm_second_w"))

    # the FRAMEWORK's fetched second-order term must equal the explicit
    # numpy pairwise-interaction sum
    emb = v[ids]                                     # [B, F, k]
    pairwise = np.zeros(3)
    for b in range(3):
        for i in range(num_fields):
            for j in range(i + 1, num_fields):
                pairwise[b] += emb[b, i] @ emb[b, j]
    np.testing.assert_allclose(np.asarray(second).ravel(), pairwise,
                               rtol=1e-4, atol=1e-5)
    assert np.asarray(out).shape == (3, 1)
