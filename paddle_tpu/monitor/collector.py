"""Fleet telemetry plane: live metrics scrape over RPC.

PRs 2/4/6 made each *process* observable — a metrics registry, a
flight recorder, request SLOs — but the multi-replica fleet of PRs
8–10 was only observable by hand-collecting per-process JSONL files
after the fact. This module is the central half the survey's legacy
stack built a master runtime for (the pserver/master tier *tracks*
fleet state centrally): a ``Collector`` discovers every serving
process from the membership lease registry, scrapes each one's
metrics registry + flight-recorder delta over the shared RPC frame
protocol (the new ``METR`` / ``HLTH`` verbs every dispatch loop
serves), and re-exports ONE fleet registry — Prometheus text or a
JSON snapshot the SLO engine evaluates directly.

Merge semantics (the part a naive scraper gets wrong):

  * counters merge by EXACT SUM across processes,
  * histograms merge bucket-wise (every snapshot embeds its bucket
    boundaries since PR 6; mismatched boundaries raise loudly),
  * gauges sum across the processes live at the last scrape,
  * a process RESTART (new incarnation, uptime reset) re-bases that
    process's contribution instead of producing a negative delta —
    fleet counters stay monotonic across respawns,
  * two endpoints served by the SAME process (a master + pserver
    hosted in one process share one registry) are deduped by
    incarnation — the registry is counted once, not once per port.

``TelemetryServer`` is the lightweight scrape-only endpoint for
processes that do not already host a dispatch loop (a trainer, a
bare engine): arm it with ``PADDLE_TPU_TELEMETRY=1`` (and
``PADDLE_TPU_TELEMETRY_KV=<host:port>`` to self-register in the
lease registry so collectors find it).

CLI surfaces: ``python -m paddle_tpu.monitor watch --fleet
<kv-endpoint>`` renders the live scraped dashboard (replacing PR 8's
multi-file log tailing), and ``python -m paddle_tpu.slo spec.json
--metrics fleet.json`` gates the whole fleet with one spec.
"""

import copy
import json
import socket
import socketserver
import threading
import time

from . import metrics as _metrics
from .metrics import (META_KEY, bucket_percentile, merge_snapshots,
                      render_prometheus_snapshot)

__all__ = ["TelemetryServer", "TelemetryClient", "Collector",
           "render_prometheus_snapshot", "maybe_arm_from_flags",
           "TELEMETRY_ROLE", "AUTOSCALER_ROLE", "ROLLOUT_ROLE"]

TELEMETRY_ROLE = "telemetry"
# the serving.autoscale control loop lease-registers under this role so
# collectors scrape its fleet metrics (desired replicas, scale events,
# rolls) without configuration — string lives here so the monitor tier
# needs no import of the serving tier
AUTOSCALER_ROLE = "autoscaler"
# serving.rollout's canary-analysis controller (ISSUE 19): same
# contract — lease-registered, scrapeable, black-box-dumpable
ROLLOUT_ROLE = "rollout"


def _valid_endpoint(ep):
    """Scrapeable 'host:port'? Registry slots may carry arbitrary
    values and operators typo statics — a malformed one is skipped,
    never allowed to crash the scrape loop with a parse error."""
    if not isinstance(ep, str):
        return False
    host, _, port = ep.rpartition(":")
    return bool(host) and port.isdigit()



class TelemetryServer:
    """Scrape-only endpoint (METR / HLTH / DUMP / CLKS / EXIT on the shared
    frame protocol) for processes without a dispatch loop of their
    own. Serves the process-wide registry by default; tests may pin a
    private ``Registry`` (and swap it to model a restart)."""

    def __init__(self, host="127.0.0.1", port=0, role=TELEMETRY_ROLE,
                 registry=None, port_file=None):
        # late imports: monitor must stay importable before the
        # distributed tier exists (paddle_tpu/__init__ import order)
        from ..distributed.rpc import (_recv_msg, _send_msg,
                                       _clock_reply, _metr_reply,
                                       _hlth_reply, _dump_reply)
        from ..trace import runtime as _trace
        self.role = role
        self.registry = registry         # None -> global at call time
        outer = self

        def _serve(request, op, payload):
            if op == "METR":
                _metr_reply(request, payload, role=outer.role,
                            registry=outer.registry)
            elif op == "HLTH":
                _hlth_reply(request, role=outer.role,
                            registry=outer.registry)
            elif op == "DUMP":
                _dump_reply(request, payload, role=outer.role,
                            registry=outer.registry)
            elif op == "CLKS":
                _clock_reply(request)
            elif op == "EXIT":
                _send_msg(request, "OK")
                outer.stop()
                return False
            else:
                _send_msg(request, "ERR", "unknown op %s" % op)
            return True

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # same trace-header path as every other dispatch loop
                # (master/kv/replica): a traced scrape nests under the
                # collector's client span
                try:
                    while True:
                        op, name, payload, tctx = _recv_msg(
                            self.request, want_ctx=True)
                        trc = _trace._TRACER
                        if trc is not None and tctx is not None \
                                and op != "CLKS":
                            with trc.server_span("telemetry." + op,
                                                 tctx, op=op):
                                cont = _serve(self.request, op,
                                              payload)
                        else:
                            cont = _serve(self.request, op, payload)
                        if not cont:
                            break
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self.endpoint = "%s:%d" % (host, self.port)
        if port_file:
            with open(port_file, "w") as f:
                f.write(str(self.port))
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ptpu-telemetry")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()


class TelemetryClient:
    """One scrape connection (collector side). Verbs are pure reads —
    safe to re-issue; a failed scrape drops the connection and the
    next call reconnects lazily."""

    def __init__(self, endpoint, timeout=2.0):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = float(timeout)
        self._sock = None

    def _call(self, op, body=None):
        from ..distributed.rpc import _recv_msg, _send_msg
        if self._sock is None:
            s = socket.create_connection(self._addr,
                                         timeout=self._timeout)
            s.settimeout(self._timeout)
            self._sock = s
        try:
            _send_msg(self._sock, op, "",
                      json.dumps(body).encode() if body is not None
                      else b"")
            rop, _, payload = _recv_msg(self._sock)
        except (ConnectionError, OSError):
            self.close()
            raise
        if rop != "VAL":
            self.close()
            raise ConnectionError("%s reply %s" % (op, rop))
        return json.loads(bytes(payload).decode())

    def metr(self, cursor=None, events=True, ring=None):
        return self._call("METR", {"cursor": cursor, "events": events,
                                   "ring": ring})

    def hlth(self):
        return self._call("HLTH")

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _zeroed(ent):
    """Deep-copied snapshot entry with every series zeroed — the
    accumulator skeleton for a metric first seen on a restart base."""
    out = copy.deepcopy(ent)
    out["series"] = {}
    return out


def _delta_snapshot(cur, prev):
    """cur - prev for the CUMULATIVE kinds (counters, histograms) of
    two snapshots of the SAME registry incarnation; gauges are
    point-in-time and excluded (the collector reads them live). prev
    None = everything is new (first scrape / fresh incarnation)."""
    out = {}
    for name, ent in cur.items():
        if name == META_KEY or ent.get("kind") not in ("counter",
                                                       "histogram"):
            continue
        pent = (prev or {}).get(name)
        if pent is None or pent.get("kind") != ent.get("kind"):
            out[name] = copy.deepcopy(ent)
            continue
        d = _zeroed(ent)
        if ent["kind"] == "counter":
            for key, v in ent["series"].items():
                pv = pent["series"].get(key, 0)
                # a shrinking counter under one incarnation is a
                # registry reset the meta missed — re-base, never
                # emit a negative delta
                d["series"][key] = v - pv if v >= pv else v
        else:
            if list(ent.get("buckets", ())) != \
                    list(pent.get("buckets", ())):
                raise ValueError(
                    "histogram %r changed bucket boundaries "
                    "mid-incarnation" % name)
            for key, s in ent["series"].items():
                ps = pent["series"].get(
                    key, {"counts": [0] * len(s["counts"]),
                          "sum": 0.0, "count": 0})
                if s["count"] >= ps["count"]:
                    d["series"][key] = {
                        "counts": [c - pc for c, pc in
                                   zip(s["counts"], ps["counts"])],
                        "sum": s["sum"] - ps["sum"],
                        "count": s["count"] - ps["count"]}
                else:
                    d["series"][key] = copy.deepcopy(s)
        # drop all-zero series so the accumulator stays sparse
        if ent["kind"] == "counter":
            d["series"] = {k: v for k, v in d["series"].items() if v}
        else:
            d["series"] = {k: v for k, v in d["series"].items()
                           if v["count"]}
        if d["series"]:
            out[name] = d
    return out


class Collector:
    """Scrape-and-merge loop over a fleet's METR endpoints.

    Discovery: the membership lease registry (``kv_endpoint``) is
    listed for every role in ``roles`` — serving replicas, pservers,
    flag-armed telemetry endpoints — plus any ``static`` endpoints
    passed as ``(role, "host:port")`` pairs (the KV server and master
    are not themselves lease-registered). A slot whose lease expired
    simply stops appearing; its already-accumulated counter
    contributions remain (fleet counters are monotonic).

    ``scrape_once()`` performs one round and returns the merged NEW
    flight-recorder events (ts-sorted across processes — the live
    feed ``watch --fleet`` renders); ``fleet_snapshot()`` returns the
    merged registry snapshot (same schema as ``Registry.snapshot``,
    so ``python -m paddle_tpu.slo --metrics`` gates it unchanged);
    ``render_prometheus()`` the text exposition of the same."""

    def __init__(self, kv_endpoint=None, roles=("ps", "replica",
                                                TELEMETRY_ROLE,
                                                AUTOSCALER_ROLE,
                                                ROLLOUT_ROLE),
                 static=(), timeout=2.0):
        self._kv_endpoint = kv_endpoint
        self._roles = tuple(roles)
        self._static = []
        for r, ep in static:
            if _valid_endpoint(ep):
                self._static.append((r, ep))
            else:
                import sys
                print("monitor.collector: ignoring malformed static "
                      "endpoint %s=%r (want host:port)" % (r, ep),
                      file=sys.stderr)
        self._timeout = float(timeout)
        self._kv = None
        self._lock = threading.Lock()
        # per-endpoint scrape state: {"role", "client", "incarnation",
        #  "uptime_s", "ok", "error", "last_ok_ts", "last_event_ts"}
        self._endpoints = {}
        # per-incarnation merge state: {"base": last snapshot,
        #  "gauges": last snapshot (for live gauge read), "cursor",
        #  "primary": endpoint, "last_seen": ts, "role"}
        self._incarnations = {}
        # the fleet accumulator: cumulative counter/histogram deltas
        self._acc = {}
        self.scrapes = 0
        self.events_lost = 0

    # -- discovery ---------------------------------------------------------
    def _discover(self):
        found = list(self._static)
        if self._kv_endpoint:
            if not _valid_endpoint(self._kv_endpoint):
                # same courtesy the statics get: a typo'd --fleet
                # value must degrade loudly, not traceback the loop
                import sys
                print("monitor.collector: malformed KV endpoint %r "
                      "(want host:port) — registry discovery "
                      "disabled" % self._kv_endpoint, file=sys.stderr)
                self._kv_endpoint = None
                return found
            from ..distributed import membership as _membership
            if self._kv is None:
                try:
                    # KVClient connects eagerly: a registry that is
                    # down (or not up YET — a dashboard may start
                    # first) must degrade to the static endpoints,
                    # not crash the scrape loop
                    self._kv = _membership.KVClient(
                        self._kv_endpoint, timeout=self._timeout)
                except (ConnectionError, OSError):
                    return found
            # the KV server itself serves METR too
            found.append(("kv", self._kv_endpoint))
            for role in self._roles:
                try:
                    live = _membership.live_endpoints(self._kv, role)
                except (ConnectionError, OSError):
                    # the KV server may have RESTARTED (its socket is
                    # dead but the registry will be healthy again):
                    # drop the client so next round reconnects —
                    # otherwise discovery silently degrades to the
                    # statics for the dashboard's whole life
                    self._kv.close()
                    self._kv = None
                    break
                for slot, ep in sorted(live.items()):
                    # a tombstoned slot (fleet eviction) is registry
                    # bookkeeping, not a process; any other
                    # non-endpoint value a registry slot may carry
                    # (live_endpoints: readers filter) is skipped —
                    # one garbage value must not poison the scrape
                    if ep.startswith(_membership.EVICTED_PREFIX):
                        continue
                    if ep.startswith(_membership.DRAINING_PREFIX):
                        # a gracefully draining replica is alive and
                        # MUST stay scrapeable — the drain itself is
                        # the telemetry story; strip the mark to
                        # recover the endpoint
                        ep = ep[len(_membership.DRAINING_PREFIX):]
                    if not _valid_endpoint(ep):
                        continue
                    found.append((role, ep))
        return found

    # -- scrape ------------------------------------------------------------
    def scrape_once(self):
        """One scrape round over every discovered endpoint. Returns
        the list of NEW flight-recorder events across the fleet,
        ts-sorted (each row gains ``proc`` = the serving role/endpoint
        so downstream consumers can attribute per process).

        One scraper drives a Collector (the watch loop shape) — the
        lock protects exporters (``fleet_snapshot`` / renderers)
        reading concurrently, and the network I/O runs OUTSIDE it so
        a dead endpoint's connect timeout never blocks an export for
        the whole round."""
        found = self._discover()
        now = time.time()
        new_events = []
        with self._lock:
            known = set(self._endpoints)
            for role, ep in found:
                st = self._endpoints.get(ep)
                if st is None:
                    st = self._endpoints[ep] = {
                        "role": role,
                        "client": TelemetryClient(
                            ep, timeout=self._timeout),
                        "incarnation": None, "uptime_s": None,
                        "ok": False, "error": None, "missing": 0,
                        "last_ok_ts": None, "last_event_ts": None}
                st["role"] = role
                st["missing"] = 0
                known.discard(ep)
            # endpoints that vanished from the registry: RETAIN their
            # state for a grace window instead of dropping it — a
            # transient registry flap (lease hiccup, KV error) would
            # otherwise destroy the endpoint->incarnation link, and
            # the next round's cursor-less scrape would replay the
            # whole recorder ring as "new" events (double-counted
            # totals/verdicts). Accumulated contributions always
            # survive either way.
            for ep in known:
                st = self._endpoints[ep]
                st["missing"] += 1
                if st["missing"] > self._MISSING_ROUNDS_MAX:
                    self._endpoints.pop(ep)["client"].close()
            round_eps = [(ep, st) for ep, st in
                         sorted(self._endpoints.items())]
        seen_incs = set()

        def scrape_endpoint(ep, st):
            with self._lock:
                inc_state = self._incarnations.get(st["incarnation"])
                pep = inc_state.get("primary") if inc_state else None
                # this endpoint fetches the event delta when it IS
                # the primary — or when the primary is gone OR its
                # last scrape failed (a dead-but-still-listed primary
                # must not freeze the process's event stream; the
                # apply phase reassigns and dedups, so a transition
                # round can never double-deliver)
                primary = (inc_state is None or pep in (None, ep)
                           or pep not in self._endpoints
                           or not self._endpoints[pep]["ok"])
                cursor = inc_state["cursor"] if (primary and inc_state)\
                    else None
                ring = inc_state.get("ring") if (primary and inc_state)\
                    else None
            try:
                rep = st["client"].metr(cursor=cursor, events=primary,
                                        ring=ring)
            except (ConnectionError, OSError, ValueError) as e:
                with self._lock:
                    st["ok"] = False
                    st["error"] = repr(e)
                return
            inc = rep.get("incarnation")
            # a RESPAWNED process (new incarnation) needs no special
            # event handling here: a stored cursor always travels
            # with its ring id, and the fresh recorder's ring id
            # mismatches — _metr_reply already restarted the delta
            # from the beginning server-side
            with self._lock:
                st["ok"] = True
                st["error"] = None
                # an answering process IS alive: registry absence
                # alone (KV down for minutes while replicas stay
                # healthy) must never age out its ring-cursor link —
                # only absence AND scrape failure does
                st["missing"] = 0
                st["last_ok_ts"] = now
                st["incarnation"] = inc
                st["uptime_s"] = rep.get("uptime_s")
                ist = self._incarnations.get(inc)
                if ist is None:
                    ist = self._incarnations[inc] = {
                        "base": None, "gauges": None, "cursor": None,
                        "ring": None, "primary": ep, "last_seen": None,
                        "role": st["role"]}
                if ist["primary"] not in self._endpoints or \
                        not self._endpoints[ist["primary"]]["ok"]:
                    # failover: first healthy endpoint of the
                    # incarnation to apply this round takes over the
                    # ring cursor (this one just answered, so its own
                    # ok is already True)
                    ist["primary"] = ep
                snap = rep.get("snapshot") or {}
                if inc not in seen_incs:
                    # merge once per PROCESS per round, however many
                    # of its ports we scraped. A schema violation
                    # (mixed-version fleet: same metric, different
                    # kind/buckets) marks THIS endpoint bad and skips
                    # its merge — merge_snapshots validates before
                    # mutating, so the accumulator stays consistent
                    # and the dashboard keeps rendering the rest.
                    seen_incs.add(inc)
                    try:
                        merge_snapshots(
                            self._acc,
                            _delta_snapshot(snap, ist["base"]))
                    except ValueError as e:
                        st["ok"] = False
                        st["error"] = repr(e)
                        return
                    ist["base"] = snap
                    ist["gauges"] = snap
                    ist["last_seen"] = now
                if ist["primary"] == ep:
                    rows = rep.get("events") or []
                    for r in rows:
                        r = dict(r)
                        r.setdefault("proc", "%s@%s"
                                     % (st["role"], ep))
                        new_events.append(r)
                    if rows:
                        st["last_event_ts"] = max(
                            [r.get("ts") or 0 for r in rows]
                            + [st["last_event_ts"] or 0])
                    if rep.get("ring") is not None:
                        ist["cursor"] = rep.get("cursor")
                        ist["ring"] = rep.get("ring")
                        self.events_lost += int(rep.get("lost") or 0)
                    else:
                        # recorder DISARMED (no ring in the reply):
                        # drop the saved cursor — a later re-arm is a
                        # fresh ring whose rows a stale cursor would
                        # silently filter out, the exact loss ring_id
                        # exists to prevent
                        ist["cursor"] = None
                        ist["ring"] = None

        # scrape CONCURRENTLY (bounded pool): a round over a fleet
        # with several wedged-but-leased replicas must cost ~one
        # timeout, not one per wedge — the lock-phased worker keeps
        # all state mutation serialized while only the socket waits
        # overlap. Dead AND delisted endpoints age out without
        # burning a connect timeout at all; a mere registry flap
        # (still answering) keeps being scraped normally.
        live_eps = [(ep, st) for ep, st in round_eps
                    if not (st["missing"] and not st["ok"])]
        if len(live_eps) > 1:
            import concurrent.futures as _cf
            with _cf.ThreadPoolExecutor(
                    max_workers=min(8, len(live_eps))) as pool:
                list(pool.map(lambda p: scrape_endpoint(*p),
                              live_eps))
        elif live_eps:
            scrape_endpoint(*live_eps[0])
        with self._lock:
            self.scrapes += 1
            self._prune_incarnations_locked()
        new_events.sort(key=lambda e: (e.get("ts") is None,
                                       e.get("ts") or 0.0))
        return new_events

    # how many consecutive rounds a registry-vanished endpoint's state
    # (the endpoint->incarnation link holding its ring cursor) is
    # retained before being dropped
    _MISSING_ROUNDS_MAX = 30

    # dead incarnations (supervisor respawns under chaos) each pin a
    # full snapshot dict in "base"/"gauges"; keep a bounded number so
    # a long-lived dashboard's memory doesn't grow with churn. The
    # bound is deliberately generous, not zero: a lease FLAP (same
    # process vanishes from the registry and returns) must find its
    # baseline again, or its counters would merge twice.
    _DEAD_INCARNATIONS_MAX = 256

    def _prune_incarnations_locked(self):
        live = {st["incarnation"] for st in self._endpoints.values()}
        dead = [(ist.get("last_seen") or 0, inc)
                for inc, ist in self._incarnations.items()
                if inc not in live]
        excess = len(dead) - self._DEAD_INCARNATIONS_MAX
        if excess > 0:
            for _, inc in sorted(dead)[:excess]:
                del self._incarnations[inc]

    # -- export ------------------------------------------------------------
    def fleet_snapshot(self):
        """Merged fleet registry snapshot: accumulated counter /
        histogram sums plus the LIVE processes' gauges, in the exact
        ``Registry.snapshot`` schema (histogram buckets embedded) so
        the SLO engine's ``--metrics`` surface evaluates it unchanged.
        The ``__meta__`` entry describes the fleet instead of one
        process."""
        with self._lock:
            out = copy.deepcopy(self._acc)
            live = {inc: ist for inc, ist in
                    self._incarnations.items()
                    if ist.get("gauges") is not None
                    and any(st["incarnation"] == inc and st["ok"]
                            for st in self._endpoints.values())}
            for ist in live.values():
                gauges = {name: ent for name, ent in
                          ist["gauges"].items()
                          if name != META_KEY
                          and ent.get("kind") == "gauge"}
                try:
                    merge_snapshots(out, gauges)
                except ValueError:
                    # mixed-version kind collision (this process
                    # exports a name another exports as a counter):
                    # skip ITS gauges — validate-then-apply keeps the
                    # export atomic, and the dashboard/exporters keep
                    # rendering everyone else
                    continue
            now = time.time()
            out[META_KEY] = {
                "fleet": True,
                "processes": len(live),
                "scrapes": self.scrapes,
                "events_lost": self.events_lost,
                "ts": now,
                "endpoints": [
                    {"endpoint": ep, "role": st["role"],
                     "incarnation": st["incarnation"],
                     "uptime_s": st["uptime_s"], "ok": st["ok"],
                     "error": st["error"],
                     "age_s": (now - st["last_ok_ts"])
                     if st["last_ok_ts"] else None,
                     "last_event_age_s":
                         (now - st["last_event_ts"])
                         if st["last_event_ts"] else None}
                    for ep, st in sorted(self._endpoints.items())],
            }
        return out

    def render_prometheus(self):
        return render_prometheus_snapshot(self.fleet_snapshot())

    def dump_json(self, path):
        with open(path, "w") as f:
            json.dump(self.fleet_snapshot(), f, indent=1,
                      sort_keys=True)

    def fleet_percentile(self, hist_name, q):
        """Bucket-interpolated q-quantile of a merged fleet histogram
        (all label series pooled); None when absent/empty."""
        snap = self.fleet_snapshot()
        ent = snap.get(hist_name)
        if not ent or ent.get("kind") != "histogram":
            return None
        buckets = [float(b) for b in ent.get("buckets", ())]
        counts = [0] * (len(buckets) + 1)
        for s in ent["series"].values():
            for i, c in enumerate(s.get("counts", ())):
                if i < len(counts):
                    counts[i] += int(c)
        if not sum(counts):
            return None
        return bucket_percentile(buckets, counts, q)

    def close(self):
        with self._lock:
            for st in self._endpoints.values():
                st["client"].close()
            self._endpoints = {}
            if self._kv is not None:
                self._kv.close()
                self._kv = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- flag-driven arming ------------------------------------------------------

_ARMED = None


def maybe_arm_from_flags():
    """PADDLE_TPU_TELEMETRY=1 starts the scrape-only TelemetryServer
    for this process (PADDLE_TPU_TELEMETRY_PORT pins the port; 0 =
    ephemeral). With PADDLE_TPU_TELEMETRY_KV=<host:port> the server
    self-registers in the membership lease registry under role
    ``telemetry`` so collectors discover it without configuration."""
    global _ARMED
    from .. import flags
    try:
        if not flags.get_flag("telemetry") or _ARMED is not None:
            return _ARMED
    except KeyError:
        return None
    try:
        srv = TelemetryServer(
            port=int(flags.get_flag("telemetry_port"))).start()
    except OSError as e:
        # telemetry must never take the process down: a pinned port
        # already bound (two workers sharing one env) degrades to
        # disarmed, same discipline as the KV-registration fallback
        import sys
        print("paddle_tpu.monitor.collector: telemetry server "
              "failed to bind (%r); telemetry disarmed" % e,
              file=sys.stderr)
        return None
    lease = None
    kv_ep = flags.get_flag("telemetry_kv")
    if kv_ep:
        try:
            from ..distributed import membership as _membership
            kv = _membership.KVClient(kv_ep, timeout=5.0)
            # this runs at `import paddle_tpu` time: a short bounded
            # timeout (not register_endpoint's default 30 s) so a
            # full slot table or unreachable KV cannot stall every
            # worker's interpreter startup — the fallback is serving
            # unregistered, loudly
            _, lease = _membership.register_endpoint(
                kv, TELEMETRY_ROLE,
                int(flags.get_flag("telemetry_slots")),
                srv.endpoint, ttl=2.0, timeout=5.0)
        except Exception as e:
            import sys
            print("paddle_tpu.monitor.collector: telemetry KV "
                  "registration failed (%r); serving unregistered on "
                  "%s" % (e, srv.endpoint), file=sys.stderr)
            try:
                # on success the lease keeps the client; on failure
                # nothing else would ever close it
                kv.close()
            except Exception:
                pass
    _ARMED = (srv, lease)
    return _ARMED
