"""Collective communication ops as Program ops.

Reference parity: operators/nccl/nccl_op.cc:24 (NCCLInit/AllReduce/Reduce/
Bcast as graph ops) and the allreduce op-handles. On TPU these lower to XLA
collectives over the ICI mesh. Outside shard_map (normal jit SPMD), sharding
propagation already inserts collectives, so these ops lower to identity /
psum-style reductions only when an explicit mesh axis context exists
(ctx.mesh set by shard_map-based runners); otherwise they are sharding
constraints or no-ops — semantically the value is already global-view.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register


def _axis(op, default="dp"):
    return op.attr("ring_id_axis", op.attr("axis_name", default))


@register("c_allreduce_sum")
def _c_allreduce_sum(ctx, op):
    x = ctx.in1(op, "X")
    if ctx.mesh is not None:
        x = lax.psum(x, _axis(op))
    ctx.set_out(op, "Out", x)


@register("c_allreduce_max")
def _c_allreduce_max(ctx, op):
    x = ctx.in1(op, "X")
    if ctx.mesh is not None:
        x = lax.pmax(x, _axis(op))
    ctx.set_out(op, "Out", x)


@register("c_allgather")
def _c_allgather(ctx, op):
    x = ctx.in1(op, "X")
    if ctx.mesh is not None:
        x = lax.all_gather(x, _axis(op), tiled=True)
    ctx.set_out(op, "Out", x)


@register("c_reducescatter")
def _c_reducescatter(ctx, op):
    x = ctx.in1(op, "X")
    if ctx.mesh is not None:
        x = lax.psum_scatter(x, _axis(op), tiled=True)
    ctx.set_out(op, "Out", x)


@register("c_broadcast")
def _c_broadcast(ctx, op):
    # root's value everywhere; in global-view SPMD the value is already
    # consistent, so this is an identity (parity with ncclBcast of params,
    # parallel_executor.cc:115)
    ctx.set_out(op, "Out", ctx.in1(op, "X"))


@register("all_to_all")
def _all_to_all(ctx, op):
    x = ctx.in1(op, "X")
    if ctx.mesh is not None:
        split_axis = int(op.attr("split_axis", 0))
        concat_axis = int(op.attr("concat_axis", 0))
        x = lax.all_to_all(x, _axis(op), split_axis, concat_axis,
                           tiled=True)
    ctx.set_out(op, "Out", x)


@register("c_sync_comm_stream")
def _c_sync(ctx, op):
    # stream sync is meaningless under XLA's single-program schedule
    for name, out in zip(op.input("X"), op.output("Out")):
        ctx.env[out] = ctx.get(name)


def allreduce(x, axis_name="dp"):
    """Functional helper for shard_map code."""
    return lax.psum(x, axis_name)


def barrier(mesh):
    """Host-side barrier: tiny psum across the mesh (send_barrier parity)."""
    from ._shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    f = shard_map(lambda x: lax.psum(x, mesh.axis_names),
                  mesh=mesh,
                  in_specs=P(*([None] * 0)), out_specs=P())
    jax.block_until_ready(f(jnp.zeros(())))
