"""Seq2seq inference benchmark: beam-search translate tokens/sec.

Reference parity: the decode path of test_machine_translation.py — but as
ONE jitted XLA while-loop (models/transformer_infer + models/decoding), so
generation needs no host round-trip per token."""

import time

import numpy as np

from common import parse_args, get_place, time_loop  # noqa: E402

import jax
import jax.numpy as jnp

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import transformer as T  # noqa: E402
from paddle_tpu.models.transformer_infer import TransformerInfer  # noqa: E402


def main():
    args = parse_args(
        "translate_infer", batch_size=32, iterations=20,
        extra=lambda p: (
            p.add_argument("--max_len", type=int, default=64),
            p.add_argument("--out_len", type=int, default=48),
            p.add_argument("--n_layer", type=int, default=2),
            p.add_argument("--n_head", type=int, default=8),
            p.add_argument("--d_model", type=int, default=256),
            p.add_argument("--beam", type=int, default=4),
            p.add_argument("--vocab", type=int, default=8192)))
    avg_cost, _ = T.transformer(
        src_vocab_size=args.vocab, trg_vocab_size=args.vocab,
        max_len=args.max_len, n_layer=args.n_layer, n_head=args.n_head,
        d_model=args.d_model, d_inner=args.d_model * 4)
    exe = fluid.Executor(get_place(args))
    exe.run(fluid.default_startup_program())
    # bf16 serving mode: CPU-verified; the one real-TPU validation
    # attempt coincided with a sandbox tunnel outage (round 5) — the
    # LM twin (lm_decode.py --dtype bfloat16) is TPU-measured (+37%)
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else None
    infer = TransformerInfer(fluid.default_main_program(),
                             fluid.global_scope(), args.n_layer,
                             args.n_head, args.d_model, args.max_len,
                             dtype=dtype)

    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randint(3, args.vocab,
                                  (args.batch_size, args.max_len)),
                      dtype=jnp.int32)
    mask = jnp.ones((args.batch_size, args.max_len), jnp.float32)

    translate = jax.jit(lambda s, m: infer.translate(
        s, m, beam_size=args.beam, max_out_len=args.out_len))
    out = [translate(src, mask)]

    def step(i):
        out[:] = [translate(src, mask)]

    def sync():
        # block_until_ready is a NO-OP on the axon tunnel (PERF.md
        # "Measurement variance"); only a device->host VALUE fetch
        # orders the timeline — pull one element of the decode result
        leaf = jax.tree_util.tree_leaves(out[0])[0]
        np.asarray(leaf).ravel()[:1]

    # tokens/sec = generated tokens (batch * out_len), beams explored in
    # parallel are the speedup mechanism, not the deliverable
    tps = time_loop(step, args, args.batch_size * args.out_len, "tokens",
                    sync=sync)
    # per-decode-step latency at this batch (the deployment metric):
    # batch_time / out_len = bs / tps
    print("=> %.2f ms/token (bs=%d beam=%d)"
          % (1000.0 * args.batch_size / tps, args.batch_size, args.beam))
    return tps


if __name__ == "__main__":
    main()
