"""paddle_tpu.serving.autoscale: the elastic fleet, chaos-gated
(ISSUE 18).

Tiers:

  * Drain protocol units (no control loop): the membership drain mark
    (``_Lease.mark`` re-values a live lease in place — it must KEEP
    beating under the new value), the replica-side drain asymmetry
    (SUBM → typed ``DRNG`` NACK while duplicate-SUBM/POLL/CANC/STAT
    keep serving), and the router's penalty-free re-dispatch on a DRNG
    it learns about only from the wire.
  * Scale-hint plumbing: ``Signals.evaluate()`` feeds its
    ``scale_hint()`` into the controller's ``offer_hint`` (the
    capture-hook pattern), which moves ``desired`` under bounds +
    cooldown and refuses during a roll.
  * Roll ABORT: a v2 that cannot boot, and a v2 that boots but fails
    its health gate, each halt the ROLL — never the fleet; the
    surviving v1 keeps serving.
  * THE CHAOS GATE (tier-1 smoke + ``-m slow`` soak, seeded like
    test_fleet.py): one fleet scales 2→4→2 under frame faults with a
    replica KILLED mid-scale-down, then rolls v1→v2 under live traffic
    with a replica KILLED mid-roll — every accepted request completes
    exactly once, token-identical to the fault-free sequential
    baseline; zero requests shed during the roll; the final fleet
    serves only v2, observable in STAT, the controller's status, the
    version-mix gauge, and the recorder's scale_event/drain/roll rows.
"""

import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving
from paddle_tpu.distributed import membership
from paddle_tpu.distributed.membership import (KVServer, KVClient,
                                               live_endpoints)
from paddle_tpu.models import transformer
from paddle_tpu.models.transformer_infer import TransformerLMInfer
from paddle_tpu.monitor import runtime as monrt
from paddle_tpu.monitor import signals as msignals
from paddle_tpu.resilience import faults
from paddle_tpu.serving import fleet
from paddle_tpu.serving.autoscale import Autoscaler
from paddle_tpu.serving.fleet import (ReplicaClient, ReplicaDraining,
                                      Router)

N_LAYER, N_HEAD, D_MODEL, MAX_LEN, VOCAB = 1, 2, 32, 48, 40


@pytest.fixture(scope="module")
def arts(tmp_path_factory):
    """One tiny LM, saved as TWO artifact versions (same weights — the
    roll's token-identity gate is the point; version labels derive
    from the directory basenames v1/v2)."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope):
        avg_cost, logits = transformer.transformer_lm(
            vocab_size=VOCAB, max_len=MAX_LEN, n_layer=N_LAYER,
            n_head=N_HEAD, d_model=D_MODEL, d_inner=64)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        lm = TransformerLMInfer(main, scope, N_LAYER, N_HEAD,
                                D_MODEL, MAX_LEN)
    base = tmp_path_factory.mktemp("elastic")
    v1, v2 = str(base / "v1"), str(base / "v2")
    for d in (v1, v2):
        serving.save_lm_artifact(d, main, scope, [logits], N_LAYER,
                                 N_HEAD, D_MODEL, MAX_LEN)
    return {"lm": lm, "v1": v1, "v2": v2}


def _requests(rng, n, max_prompt=8, min_new=4, max_new=10):
    reqs = []
    for _ in range(n):
        plen = int(rng.randint(1, max_prompt + 1))
        prompt = [1] + rng.randint(3, VOCAB, plen - 1).tolist()
        reqs.append((prompt, int(rng.randint(min_new, max_new + 1))))
    return reqs


def _kv_pair():
    kvs = KVServer(sweep_interval=0.05).start()
    return kvs, KVClient(kvs.endpoint)


# -- drain protocol units ---------------------------------------------------

def test_lease_mark_keeps_beating_then_revokes():
    """The drain mark re-values a LIVE lease: after ``mark`` the value
    reads ``draining:<ep>``, the heartbeat keeps renewing it well past
    the TTL (unlike an eviction tombstone the holder is still alive),
    and ``revoke`` still frees the slot via CAD on the marked value."""
    kvs, kv = _kv_pair()
    try:
        slot, lease = membership.register_endpoint(
            kv, "replica", 2, "h:1", ttl=0.3)
        marked = membership.DRAINING_PREFIX + "h:1"
        assert lease.mark(marked) is True
        assert lease.value == marked
        assert live_endpoints(kv, "replica") == {slot: marked}
        time.sleep(1.0)                   # > 3x TTL
        assert not lease.lost
        assert live_endpoints(kv, "replica") == {slot: marked}
        lease.revoke()
        assert live_endpoints(kv, "replica") == {}
        # a revoked lease's slot is gone: a late mark cannot re-create
        # it (CAS against the old value has nothing to hit)
        assert lease.mark("late-mark") is False
        assert live_endpoints(kv, "replica") == {}
    finally:
        kv.shutdown_server()
        kv.close()


def test_replica_drain_asymmetry(arts):
    """Satellite 5: a draining replica NACKs new SUBM with the typed
    DRNG reply while duplicate-SUBM dedup, POLL delivery, CANC ack and
    STAT keep serving — and its lease stays registered under the drain
    mark so the router keeps polling for the in-flight results."""
    kvs, kv = _kv_pair()
    cell, cli = None, None
    try:
        cell = fleet.Replica(kv, arts["lm"], desired=1, slots=2,
                             prefill_chunk=4, ttl=0.4, version="v1")
        cli = ReplicaClient(cell.endpoint, timeout=2.0)
        cli.submit("r1", [1, 5], 4)
        cell.drain()
        vals = list(live_endpoints(kv, fleet.REPLICA_ROLE).values())
        assert fleet.DRAINING_PREFIX + cell.endpoint in vals
        with pytest.raises(ReplicaDraining):
            cli.submit("r2", [1, 6], 4)
        cli.submit("r1", [1, 5], 4)       # duplicate of admitted id: OK
        st = cli.stat()
        assert st["draining"] is True and st["version"] == "v1"
        done, deadline = [], time.time() + 30
        while time.time() < deadline and not done:
            done = cli.poll(wait=0.2)
        assert done and done[0]["id"] == "r1" and done[0]["tokens"]
        cli.cancel("r1")
        # delivered AND acked -> the journal empties: the CANC-safe
        # retire condition the autoscaler's drain loop waits for
        deadline = time.time() + 5
        while time.time() < deadline and cell.server._jobs:
            time.sleep(0.02)
        assert not cell.server._jobs
        assert not cell.lease.lost        # still beating, post-drain
    finally:
        if cli is not None:
            cli.close()
        if cell is not None:
            cell.shutdown()
        kv.shutdown_server()
        kv.close()


def test_router_redispatches_on_drng(arts):
    """The router side of satellite 5: admissions closed on one
    replica WITHOUT a registry mark — the router learns only from the
    typed DRNG NACK, re-queues without burning the attempt budget, and
    completes everything on the survivor."""
    kvs, kv = _kv_pair()
    cells, router = [], None
    try:
        cells = [fleet.Replica(kv, arts["lm"], desired=2, slots=2,
                               prefill_chunk=4, ttl=0.4)
                 for _ in range(2)]
        lo = min(cells, key=lambda c: c.slot)
        lo.server.drain()                 # server-side only: no mark
        router = Router(kvs.endpoint, window=3, max_queue=32,
                        stall_timeout=2.0, refresh_interval=0.05,
                        client_timeout=1.0, name="drng")
        router.wait_for_replicas(2, timeout=10)
        # first dispatch tie-breaks to the LOWEST slot = the draining
        # one, so at least one DRNG NACK is deterministic
        hs = [router.submit([1, 4 + i], 4) for i in range(4)]
        outs = [h.result(timeout=60) for h in hs]
        assert len(outs) == 4 and all(t for t, _ in outs)
        assert router.stats["drain_nacks"] >= 1
        assert router.stats["failed"] == 0
        assert lo.slot in router.draining()
    finally:
        if router is not None:
            router.close()
        for c in cells:
            c.shutdown()
        kv.shutdown_server()
        kv.close()


# -- scale-hint plumbing ----------------------------------------------------

def test_scale_hook_moves_desired():
    """Tentpole wiring: ``Signals.evaluate()`` calls the installed
    scale hook with its hint; the controller clamps to bounds,
    respects the cooldown, and refuses to move during a roll."""
    kvs, kv = _kv_pair()
    auto = None
    try:
        # not .start()ed: no replica ever spawns — the hint plumbing
        # is pure controller state
        auto = Autoscaler(kvs.endpoint, "unused", desired=2,
                          min_replicas=1, max_replicas=3,
                          cooldown=0.0, register=False)
        sig = msignals.Signals()
        auto.attach(sig)
        assert sig.scale_hook == auto.offer_hint
        sig.evaluate(now=time.time())     # hold hint: desired unmoved
        assert auto.last_hint is not None
        assert auto.last_hint[0] == "hold" and auto.desired == 2

        assert auto.offer_hint(("up", 1, "queue pressure")) is True
        assert auto.desired == 3
        assert auto.offer_hint(("up", 2, "more")) is False  # at max
        assert auto.desired == 3
        assert auto.offer_hint(("down", 1, "idle")) is True
        assert auto.desired == 2
        assert auto.last_scale["reason"] == "idle"
        # cooldown: a fresh controller-side gate, not hint spam
        auto._cooldown = 60.0
        assert auto.offer_hint(("down", 1, "idle")) is False
        assert auto.desired == 2
        auto._cooldown = 0.0
        # a roll in progress holds elasticity
        auto.roll("unused2", version="v2")
        assert auto.offer_hint(("up", 1, "pressure")) is False
        assert auto.desired == 2
        with pytest.raises(RuntimeError, match="in progress"):
            auto.roll("unused3")
    finally:
        if auto is not None:
            auto.close()
        kv.shutdown_server()
        kv.close()


def test_autoscale_in_analysis_import_check():
    from paddle_tpu.analysis.__main__ import IMPORT_CHECK_PACKAGES
    assert "paddle_tpu.serving.autoscale" in IMPORT_CHECK_PACKAGES


# -- roll abort -------------------------------------------------------------

def test_roll_abort_halts_roll_not_fleet(arts, tmp_path):
    """A v2 that cannot boot, then a v2 that boots but never passes
    the health gate: both abort the ROLL; the surviving v1 fleet keeps
    serving and the controller returns to steady."""
    kvs, kv = _kv_pair()
    auto = None
    try:
        auto = Autoscaler(kvs.endpoint, arts["v1"], desired=1,
                          min_replicas=1, max_replicas=3, slots=2,
                          ttl=0.4, interval=0.05, cooldown=0.0,
                          health_timeout=0.8, register=False,
                          prefill_chunk=4).start()
        auto.wait_steady(timeout=30)

        auto.roll(str(tmp_path / "nope"), version="broken")
        last = auto.wait_roll(timeout=30)
        assert last["aborted"] is True and "boot" in last["reason"]

        auto._healthy = lambda cell, version: False
        auto.roll(arts["v2"])
        last = auto.wait_roll(timeout=30)
        assert last["aborted"] is True and "health" in last["reason"]
        del auto._healthy

        st = auto.wait_steady(timeout=30)
        assert st["live"] == 1 and st["version"] == "v1"
        assert st["version_mix"].get("v1") == 1
        assert not st["version_mix"].get("v2")
        assert auto.aborted_rolls == 2 and auto.rolls == 0
        # the surviving fleet still serves
        cell = auto._active[0]
        cli = ReplicaClient(cell.endpoint, timeout=2.0)
        try:
            cli.submit("alive", [1, 7], 4)
            done, deadline = [], time.time() + 30
            while time.time() < deadline and not done:
                done = cli.poll(wait=0.2)
            assert done and done[0]["tokens"]
            cli.cancel("alive")
        finally:
            cli.close()
    finally:
        if auto is not None:
            auto.close()
        kv.shutdown_server()
        kv.close()


# -- the chaos gate ---------------------------------------------------------

ELASTIC_SPEC = {
    "rpc": {"drop": 0.03, "duplicate": 0.03, "close_mid_frame": 0.02,
            "delay": 0.05, "delay_s": 0.003, "max": 6},
    "kill": [{"target": "drain", "after": 0},
             {"target": "roll", "after": 0}],
}


def _run_elastic_chaos(arts, reqs, seq, seed, tmp_path, tag):
    """Stand up KV + autoscaler (2 replicas cold-booted from the v1
    artifact) + router, arm the seeded plan, then: traffic while
    scaling 2→4, traffic while scaling 4→2 (first drain KILLED
    mid-drain), traffic while rolling v1→v2 (first roll drain KILLED
    mid-roll). Asserts the ISSUE-18 acceptance invariants."""
    kvs, kv = _kv_pair()
    auto, router, plan = None, None, None

    def burst(batch, off):
        return [router.submit(p, m, session="s%d" % ((off + i) % 4))
                for i, (p, m) in enumerate(batch)]

    try:
        auto = Autoscaler(kvs.endpoint, arts["v1"], desired=2,
                          min_replicas=1, max_replicas=5, slots=2,
                          ttl=0.4, interval=0.05, cooldown=0.0,
                          drain_timeout=15.0, health_timeout=15.0,
                          prefill_chunk=4).start()
        auto.wait_steady(timeout=30)
        spec = dict(ELASTIC_SPEC)
        rpc_spec = dict(spec["rpc"])
        # frame faults on the v1 cells' ports (later spawns get fresh
        # ports; the kill targets are port-independent)
        rpc_spec["ports"] = [c.server.port for c in auto.cells]
        spec["rpc"] = rpc_spec
        plan = faults.arm(spec, seed=seed)
        router = Router(kvs.endpoint, window=3, max_queue=64,
                        stall_timeout=1.0, refresh_interval=0.05,
                        client_timeout=0.8, name="auto-" + tag)
        router.wait_for_replicas(2, timeout=15)

        out = []
        # scale UP mid-traffic: 2 -> 4
        hs = burst(reqs[:8], 0)
        assert auto.set_desired(4, reason="pressure",
                                detail="test burst") == 4
        out += [h.result(timeout=120) for h in hs]
        auto.wait_steady(timeout=30)
        assert auto.status()["live"] == 4
        router.wait_for_replicas(4, timeout=15)

        # scale DOWN mid-traffic: 4 -> 2; the armed plan kills the
        # first drained cell the moment its drain begins
        hs = burst(reqs[8:16], 8)
        assert auto.set_desired(2, reason="idle") == 2
        out += [h.result(timeout=120) for h in hs]
        auto.wait_steady(timeout=45)
        assert auto.status()["live"] == 2
        assert ("kill", "drain") in plan.trips, plan.trips

        # rolling weight update v1 -> v2 under live traffic; the plan
        # kills the first rolled-out cell mid-drain
        shed0 = router.stats["shed"]
        hs = burst(reqs[16:], 16)
        auto.roll(arts["v2"])
        last = auto.wait_roll(timeout=90)
        out += [h.result(timeout=120) for h in hs]
        assert last["aborted"] is False, last
        assert last["from"] == "v1" and last["to"] == "v2"
        assert last["shed_during"] == 0
        assert last["convergence_s"] > 0
        assert router.stats["shed"] == shed0
        assert ("kill", "roll") in plan.trips, plan.trips

        # EXACTLY ONCE, TOKEN-IDENTICAL across all three phases
        assert len(out) == len(reqs)
        for i, ((bt, bs), (et, es)) in enumerate(zip(seq, out)):
            assert bt == et, "request %d diverged: %r vs %r" % (i, bt,
                                                                et)
            np.testing.assert_allclose(es, bs, rtol=1e-4, atol=1e-4)
        rst = router.stats
        assert rst["completed"] == rst["requests"] == len(reqs)
        assert rst["failed"] == 0

        # the fleet converged to v2-only, observable everywhere:
        st = auto.wait_steady(timeout=30)
        assert st["live"] == 2 and st["version"] == "v2"
        assert st["version_mix"].get("v2") == 2
        assert not st["version_mix"].get("v1")
        for cell in list(auto._active):   # ...at the wire (STAT)
            cli = ReplicaClient(cell.endpoint, timeout=2.0)
            try:
                assert cli.stat()["version"] == "v2"
            finally:
                cli.close()
        mix = {k[0]: int(v) for k, v in   # ...and in telemetry
               monrt.FLEET_VERSION_REPLICAS.snapshot().items()}
        assert mix.get("v2") == 2 and not mix.get("v1")
        kinds = {k for k, _ in plan.trips}
        assert kinds & {"drop", "duplicate", "close_mid_frame",
                        "delay"}, plan.trips
        return auto
    finally:
        faults.disarm()
        if router is not None:
            router.close()
        if auto is not None:
            auto.close()
        try:
            kv.shutdown_server()
            kv.close()
        except OSError:
            pass


def test_autoscale_chaos_smoke(rng, arts, tmp_path):
    """Tier-1 gate: 2→4→2 elasticity + a v1→v2 roll, kills mid-drain
    AND mid-roll under seeded frame faults — exactly once,
    token-identical, zero shed during the roll, fleet all-v2."""
    reqs = _requests(rng, 24, min_new=4, max_new=10)
    seq = serving.sequential_generate(arts["lm"], reqs)
    mlog = str(tmp_path / "autoscale-mon.jsonl")
    with monitor.session(log_path=mlog):
        _run_elastic_chaos(arts, reqs, seq, seed=1807,
                           tmp_path=tmp_path, tag="smoke")
    # the recorder rows tell the same story, in the shape the SLO's
    # version_convergence_s / roll_shed objectives and the watch
    # dashboard's autoscale line consume
    rows = monitor.read_jsonl(mlog)
    scale = [r for r in rows if r["ev"] == "scale_event"]
    assert {e["direction"] for e in scale} >= {"up", "down"}
    assert all(e["reason"] in ("pressure", "idle", "roll", "manual")
               for e in scale)
    assert any(r["ev"] == "drain" for r in rows)
    rolls = [r for r in rows if r["ev"] == "roll"]
    assert rolls and rolls[-1]["aborted"] is False
    assert rolls[-1]["from_version"] == "v1"
    assert rolls[-1]["to_version"] == "v2"
    assert rolls[-1]["shed_during"] == 0
    assert rolls[-1]["convergence_s"] > 0
    from paddle_tpu import slo
    samples = slo.samples_from_events(rows, compute_goodput=False)
    assert samples["version_convergence_s"]
    assert samples["roll_shed"] == [0.0]
    verdict = slo.evaluate(
        {"objectives": [
            {"metric": "version_convergence_s", "percentile": 1.0,
             "max_seconds": 120.0},
            {"metric": "roll_shed", "max_value": 0}]},
        samples)
    assert verdict["pass"], verdict


@pytest.mark.slow
def test_autoscale_chaos_soak_deterministic_three_runs(rng, arts,
                                                       tmp_path):
    """The acceptance soak: the seeded elastic-chaos scenario passes 3
    consecutive times (fresh fleet each time)."""
    reqs = _requests(rng, 32, min_new=4, max_new=12)
    seq = serving.sequential_generate(arts["lm"], reqs)
    for attempt in range(3):
        _run_elastic_chaos(arts, reqs, seq, seed=9090,
                           tmp_path=tmp_path, tag="soak%d" % attempt)
