"""Pipeline (GPipe over pp axis) and MoE (ep axis) tests on the virtual
8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import parallel


def test_gpipe_matches_sequential():
    mesh = parallel.make_mesh({"pp": 4})
    rng = np.random.RandomState(0)
    s, d = 4, 8
    ws = rng.randn(s, d, d).astype(np.float32) * 0.3
    bs = rng.randn(s, d).astype(np.float32) * 0.1
    params = {"w": jnp.asarray(ws), "b": jnp.asarray(bs)}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    m, mb = 6, 4
    xs = rng.randn(m, mb, d).astype(np.float32)
    got = np.asarray(parallel.gpipe(stage_fn, params, jnp.asarray(xs),
                                    mesh, axis_name="pp"))
    # sequential reference
    want = xs.copy()
    for i in range(s):
        want = np.tanh(want @ ws[i] + bs[i])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_gpipe_differentiable():
    mesh = parallel.make_mesh({"pp": 2})
    rng = np.random.RandomState(1)
    s, d = 2, 4
    params = {"w": jnp.asarray(rng.randn(s, d, d).astype(np.float32) * 0.3)}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    xs = jnp.asarray(rng.randn(3, 2, d).astype(np.float32))

    def loss(params):
        return jnp.sum(parallel.gpipe(stage_fn, params, xs, mesh) ** 2)

    g = jax.grad(loss)(params)
    arr = np.asarray(g["w"])
    assert np.isfinite(arr).all()
    assert np.abs(arr).max() > 0
    # both stages' params must receive gradient
    assert np.abs(arr[0]).max() > 0 and np.abs(arr[1]).max() > 0


def test_moe_routing_and_shapes():
    rng = np.random.RandomState(2)
    t, d, e, h = 32, 8, 4, 16
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    w_up = jnp.asarray(rng.randn(e, d, h).astype(np.float32) * 0.2)
    w_down = jnp.asarray(rng.randn(e, h, d).astype(np.float32) * 0.2)
    out, aux = parallel.moe_ffn(x, gate_w, w_up, w_down,
                                capacity_factor=2.0)
    assert out.shape == (t, d)
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) > 0

    # with generous capacity, each kept token must equal its top-1 expert's
    # FFN output scaled by the gate prob
    probs = np.asarray(jax.nn.softmax(x @ gate_w, axis=-1))
    top = probs.argmax(-1)
    xn = np.asarray(x)
    for i in range(5):
        ei = int(top[i])
        hi = np.maximum(xn[i] @ np.asarray(w_up)[ei], 0)
        want = (hi @ np.asarray(w_down)[ei]) * probs[i, ei]
        np.testing.assert_allclose(np.asarray(out)[i], want, rtol=1e-3,
                                   atol=1e-4)


def test_moe_capacity_drops_overflow():
    rng = np.random.RandomState(3)
    t, e = 16, 2
    # force all tokens to expert 0
    logits = jnp.asarray(np.tile([10.0, -10.0], (t, 1)).astype(np.float32))
    dispatch, combine, aux = parallel.top1_gating(logits, capacity=4)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == 4          # only capacity tokens kept
    assert d[:, 1].sum() == 0


def test_moe_under_ep_mesh():
    mesh = parallel.make_mesh({"ep": 4})
    rng = np.random.RandomState(4)
    t, d, e, h = 16, 8, 4, 8
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    w_up = jnp.asarray(rng.randn(e, d, h).astype(np.float32) * 0.2)
    w_down = jnp.asarray(rng.randn(e, h, d).astype(np.float32) * 0.2)

    with mesh:
        jit_moe = jax.jit(lambda *a: parallel.moe_ffn(*a, mesh=mesh))
        out, aux = jit_moe(x, gate_w, w_up, w_down)
    base, _ = parallel.moe_ffn(x, gate_w, w_up, w_down)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=2e-4, atol=2e-5)
