"""CLI: merge per-process span logs / report fleet latency stats.

    python -m paddle_tpu.trace merge trainer.jsonl ps.jsonl -o t.json
    python -m paddle_tpu.trace stats *.jsonl [--root round] [--json]

``merge`` writes one skew-corrected Perfetto/Chrome timeline (load it
at ui.perfetto.dev or chrome://tracing) with per-process lanes and
cross-process flow arrows. ``stats`` prints per-verb p50/p95, the
per-round critical-path breakdown, and straggler attribution.
"""

import argparse
import json
import sys

from .merge import merge_files, render_stats, stats_files, write_timeline


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.trace",
        description="paddle_tpu distributed-trace span-log tools")
    sub = p.add_subparsers(dest="cmd", required=True)

    pm = sub.add_parser("merge",
                        help="merge span logs into one skew-corrected "
                             "Perfetto timeline")
    pm.add_argument("logs", nargs="+", help="per-process span .jsonl")
    pm.add_argument("-o", "--out", default="timeline.json",
                    help="output Chrome/Perfetto JSON path")
    pm.add_argument("--json", action="store_true",
                    help="print the merge info summary as JSON")

    ps = sub.add_parser("stats",
                        help="per-verb p50/p95, per-round critical "
                             "path, straggler attribution")
    ps.add_argument("logs", nargs="+", help="per-process span .jsonl")
    ps.add_argument("--root", default=None,
                    help="only count roots with this span name as "
                         "rounds (default: every root span)")
    ps.add_argument("--json", action="store_true",
                    help="emit the stats as one JSON object")

    args = p.parse_args(argv)
    if args.cmd == "merge":
        info = write_timeline(args.logs, args.out)
        if args.json:
            print(json.dumps(info))
        else:
            print("merged %d spans from %d process(es) -> %s "
                  "(reference pid %s)"
                  % (info["spans"], info["processes"], args.out,
                     info["reference_pid"]))
            for pid, off in sorted(info["clock_offsets"].items()):
                print("  pid %-8d clock offset %+.6fs" % (pid, off))
        return 0
    s = stats_files(args.logs, root_name=args.root)
    print(json.dumps(s) if args.json else render_stats(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
