"""DistributeTranspiler: rewrite a Program for distributed training.

Reference parity: python/paddle/fluid/distribute_transpiler.py:138-1128.

Two modes:
  * ``mode="mesh"`` (default, TPU-idiomatic): no program surgery. The
    transpiler annotates sharding hints — dense params replicated over
    ``dp`` (gradient psum comes from GSPMD), ``is_distributed`` embedding
    tables row-sharded — and every trainer runs the SAME program under
    ParallelExecutor. This is the §7 mapping: pserver rounds become ICI
    collectives compiled into the step.
  * ``mode="pserver"`` (reference-compat): real program surgery. The
    trainer program gets send/send_barrier/recv ops; get_pserver_program
    builds a listen_and_serv program whose optimize sub-block applies the
    merged gradients — served by distributed/rpc.VariableServer over TCP
    (the DCN tier). Used for sparse-embedding service and the reference's
    localhost multi-process test pattern (test_dist_train.py).
"""

from ..core.program import (default_main_program, default_startup_program,
                            Program)
from ..core import unique_name

__all__ = ["DistributeTranspiler"]


def _clone_op_vars(src_block, dst_block, op, shape_map=None,
                   fallback_block=None):
    """Declare every var an op references into dst_block (persistable) so
    the cloned op can resolve them — shared by pserver/startup builders.
    shape_map overrides per-var shapes (sharded-table local shapes)."""
    shape_map = shape_map or {}
    for name in op.input_names + op.output_names:
        v = src_block.vars.get(name)
        if v is None and fallback_block is not None:
            v = fallback_block.vars.get(name)
        if v is not None and not dst_block.has_var(name):
            dst_block.create_var(name=name,
                                 shape=shape_map.get(name, v.shape),
                                 dtype=v.dtype, persistable=True)


class DistributeTranspiler:
    def __init__(self, mode="pserver"):
        self.mode = mode
        self._trainer_id = 0
        self._trainers = 1
        self._eps = []
        self._program = None
        self._startup = None
        self._param_grads = []
        self._dist_tables = {}
        self._table_opt = {}
        self._table_init_ops = []

    # ------------------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None):
        program = program or default_main_program()
        self._program = program
        self._startup = startup_program or default_startup_program()
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._eps = [e for e in pservers.split(",") if e]
        self._sync = sync_mode

        # find (param, grad) pairs from optimizer ops
        self._opt_ops = []
        self._param_grads = []
        for op in list(program.global_block().ops):
            if op.type in ("sgd", "momentum", "adam", "adagrad", "rmsprop",
                           "adamax", "adadelta", "ftrl", "decayed_adagrad"):
                p = op.input("Param")[0]
                g = op.input("Grad")[0]
                self._param_grads.append((p, g))
                self._opt_ops.append(op)

        # distributed lookup tables (lookup_table_op.cc `is_distributed`,
        # distribute_transpiler.py:201-255): table row-sharded over ALL
        # pservers, trainer replaces the lookup with a prefetch of just
        # the needed rows and sends SelectedRows grads per shard
        self._dist_tables = {}
        gb = program.global_block()
        for op in list(gb.ops):
            if op.type == "lookup_table" and op.attr("is_distributed"):
                w = op.input("W")[0]
                v = gb.vars[w]
                meta = self._dist_tables.setdefault(
                    w, {"height": int(v.shape[0]),
                        "dim": int(v.shape[-1]), "lookups": []})
                meta["lookups"].append(op)

        if self.mode == "mesh":
            for p, _ in self._param_grads:
                program._sharding_hints.setdefault(p, None)
            for v in program.list_vars():
                if getattr(v, "is_distributed", False):
                    program._sharding_hints[v.name] = ("mp", None)
            return self

        # pserver mode: strip optimizer ops from the trainer program and
        # append send/barrier/recv (distribute_transpiler.py:257ff).
        # Distributed tables leave the dense path entirely: their
        # optimizer ops go to EVERY pserver (each owns a row shard), the
        # trainer's lookups become prefetches, and the grads ride the
        # wire as SelectedRows.
        self._table_opt = {}      # table -> its optimizer op
        if self._dist_tables:
            kept_pg, kept_ops = [], []
            for (p, g), op in zip(self._param_grads, self._opt_ops):
                if p in self._dist_tables:
                    self._table_opt[p] = op
                else:
                    kept_pg.append((p, g))
                    kept_ops.append(op)
            self._param_grads, self._opt_ops = kept_pg, kept_ops

        for op in self._opt_ops + list(self._table_opt.values()):
            gb.ops.remove(op)
        self._rewrite_dist_lookups(gb)
        params = [p for p, _ in self._param_grads]
        grads = [g for _, g in self._param_grads]
        n = max(1, len(self._eps))
        for w, meta in self._dist_tables.items():
            gb.append_op(
                type="send_sparse",
                inputs={"Ids": meta["id_names"],
                        "Grads": [o + "@GRAD" for o in meta["out_names"]]},
                outputs={},
                attrs={"grad_name": w + "@GRAD", "epmap": self._eps,
                       "endpoints": self._eps, "height": meta["height"],
                       "trainer_id": self._trainer_id})
        gb.append_op(type="send", inputs={"X": grads}, outputs={},
                     attrs={"epmap": [self._eps[i % n]
                                      for i in range(len(grads))],
                            "sync": self._sync,
                            "endpoints": self._eps,
                            "trainer_id": self._trainer_id})
        gb.append_op(type="recv", inputs={},
                     outputs={"Out": params},
                     attrs={"epmap": [self._eps[i % n]
                                      for i in range(len(params))],
                            "recv_names": params,
                            "endpoints": self._eps})
        self._program._bump_version()
        return self

    def _rewrite_dist_lookups(self, gb):
        """Trainer-side table rewrite: each ``lookup_table`` on a
        distributed table becomes a ``prefetch`` (ids → rows from the
        sharded servers), the prefetched rows join the backward marker's
        wrt list (they are the gradient LEAF the sparse send reads), and
        the table/its accumulators drop out of the trainer's programs
        entirely — the trainer never materializes the [V, D] table."""
        # prefetches are HOISTED to the program head (after any producer
        # of their ids): the executor then sees one host block, then one
        # compute block holding every consumer of the prefetched rows up
        # to the grad marker — the shape _grad_leaves_concrete can
        # segment-compile with the rows as gradient leaves
        n_inserted = 0
        for w, meta in self._dist_tables.items():
            meta["id_names"] = []
            meta["out_names"] = []
            for op in meta["lookups"]:
                ids = op.input("Ids")[0]
                out = op.output("Out")[0]
                gb.ops.remove(op)
                prod = max((i for i, o in enumerate(gb.ops)
                            if any(ids in ns
                                   for ns in o.outputs.values())),
                           default=-1)
                newop = gb.append_op(
                    type="prefetch", inputs={"X": [ids]},
                    outputs={"Out": [out]},
                    attrs={"table_name": w, "epmap": self._eps,
                           "endpoints": self._eps})
                gb.ops.remove(newop)
                pos = max(n_inserted, prod + 1)
                gb.ops.insert(pos, newop)
                n_inserted = pos + 1
                meta["id_names"].append(ids)
                meta["out_names"].append(out)

        # rewrite the backward marker: grads w.r.t. prefetched rows, not
        # the (absent) table param
        table_names = set(self._dist_tables)
        for op in gb.ops:
            if op.type != "backward_marker":
                continue
            pnames = [p for p in (op.attr("param_names") or [])
                      if p not in table_names]
            new_wrt = [o for meta in self._dist_tables.values()
                       for o in meta["out_names"]]
            op.attrs["param_names"] = pnames + new_wrt
            gvars = [g for g in op.outputs.get("Grads", [])
                     if g.replace("@GRAD", "") not in table_names]
            for o in new_wrt:
                v = gb.vars.get(o)
                g = gb.create_var(name=o + "@GRAD",
                                  shape=v.shape if v is not None else None,
                                  dtype=v.dtype if v is not None
                                  else "float32",
                                  persistable=False, stop_gradient=True)
                gvars.append(g.name)
            op.outputs["Grads"] = gvars

        # drop trainer-side init ops for the table and its accumulators
        table_state = set(table_names)
        for op in self._table_opt.values():
            for ns in op.inputs.values():
                for nm in ns:
                    v = self._program.global_block().vars.get(nm)
                    if v is not None and getattr(v, "persistable", False) \
                            and nm not in ("@EMPTY@",):
                        if tuple(v.shape or ())[:1] == \
                                (self._dist_tables[op.input("Param")[0]]
                                 ["height"],):
                            table_state.add(nm)
        if self._startup is not None:
            sb = self._startup.global_block()
            removed = [o for o in sb.ops
                       if any(nm in table_state
                              for ns in o.outputs.values() for nm in ns)]
            # keep the removed init ops: get_startup_program clones them
            # (at shard shape) into each server's startup
            self._table_init_ops = removed
            for op in removed:
                sb.ops.remove(op)
            self._startup._bump_version()

    # ------------------------------------------------------------------
    def get_trainer_program(self):
        return self._program

    def _table_local_shapes(self):
        """For every dist table: {var_name: local_shape} covering the
        table itself and each same-height accumulator of its optimizer op
        — every shard holds rows {g : g % n == shard}, stored compactly
        as ceil(V/n) rows (same local shape on every server)."""
        n = max(1, len(self._eps))
        out = {}
        src_gb = self._program.global_block()
        for w, meta in self._dist_tables.items():
            local_h = -(-meta["height"] // n)
            opt = self._table_opt.get(w)
            names = [w] + ([nm for ns in opt.inputs.values() for nm in ns]
                           if opt is not None else [])
            for nm in names:
                v = src_gb.vars.get(nm)
                if v is not None and tuple(v.shape or ())[:1] == \
                        (meta["height"],):
                    out[nm] = (local_h,) + tuple(v.shape[1:])
        return out

    def get_pserver_program(self, endpoint, port_file=None):
        """Build the server program: one listen_and_serv op whose
        sub-block holds the optimizer ops for the params this endpoint
        owns (round-robin placement like distributed_splitter), plus —
        when distributed tables exist — the sparse optimizer op for this
        server's row shard of EVERY table (each server owns one shard,
        distribute_transpiler.py pserver-side table blocks)."""
        prog = Program()
        gb = prog.global_block()
        my = self._owned(endpoint)
        local_shapes = self._table_local_shapes()
        src_gb = self._program.global_block()

        opt_block = prog.create_block()
        for i, (p, g) in my:
            op = self._opt_ops[i]
            _clone_op_vars(src_gb, gb, op, shape_map=local_shapes)
            opt_block.append_op(op.type, dict(op.inputs), dict(op.outputs),
                                dict(op.attrs))
        table_params, table_grads = [], []
        for w in self._dist_tables:
            op = self._table_opt.get(w)
            if op is None:
                continue
            _clone_op_vars(src_gb, gb, op, shape_map=local_shapes)
            opt_block.append_op(op.type, dict(op.inputs), dict(op.outputs),
                                dict(op.attrs))
            table_params.append(op.input("Param")[0])
            table_grads.append(op.input("Grad")[0])
        prog.rollback()
        n = max(1, len(self._eps))
        shard = self._eps.index(endpoint) if endpoint in self._eps else 0
        sparse_tables = {w: {"shard": shard, "num_shards": n,
                             "height": meta["height"]}
                         for w, meta in self._dist_tables.items()}
        gb.append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint,
                   "Fanin": self._trainers,
                   "sync_mode": self._sync,
                   "param_names": [p for _, (p, g) in my] + table_params,
                   "grad_names": [g for _, (p, g) in my] + table_grads,
                   "sparse_tables": sparse_tables,
                   "optimize_blocks": [opt_block],
                   "port_file": port_file,
                   "blocking": True})
        return prog

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """Server startup: a Program that initializes exactly the params
        this endpoint owns, by cloning the matching initializer ops out of
        the trainer's startup program (distribute_transpiler.py
        get_startup_program per-endpoint init parity). Distributed-table
        state (the shard + its optimizer accumulators) is initialized at
        the LOCAL shard shape ceil(V/n) — the [V, D] table never exists
        on any single process."""
        owned = set(self._owned_param_names(endpoint))
        # plus the optimizer STATE of the owned params (accumulators,
        # beta pows, learning rate) — the server applies the update, so
        # it must initialize the update's state
        for i, _pg in self._owned(endpoint):
            op = self._opt_ops[i]
            owned.update(nm for ns in op.inputs.values() for nm in ns)
        local_shapes = self._table_local_shapes()
        # table shard + every optimizer-state var of the table opt ops
        # (moments at local shape; scalar state like beta pows as-is)
        table_state = set(local_shapes)
        for op in getattr(self, "_table_opt", {}).values():
            table_state.update(nm for ns in op.inputs.values()
                               for nm in ns if nm not in owned)
        prog = Program()
        gb = prog.global_block()
        if self._startup is None:
            return prog
        src = self._startup.global_block()
        # trainer-side table init ops were dropped by transpile(); the
        # pre-transpile startup kept clones in _table_init_ops
        src_ops = list(src.ops) + list(getattr(self, "_table_init_ops", []))
        main_gb = self._program.global_block()
        for op in src_ops:
            out_names = [n for ns in op.outputs.values() for n in ns]
            if not any(n in owned or n in table_state for n in out_names):
                continue
            _clone_op_vars(src, gb, op, shape_map=local_shapes,
                           fallback_block=main_gb)
            attrs = dict(op.attrs)
            outs = [n for ns in op.outputs.values() for n in ns]
            patched = [n for n in outs if n in local_shapes]
            if patched and "shape" in attrs:
                attrs["shape"] = list(local_shapes[patched[0]])
            gb.append_op(op.type, dict(op.inputs), dict(op.outputs), attrs)
        return prog

    def _owned(self, endpoint=None):
        """Round-robin param placement (distributed_splitter parity):
        [(index, (param, grad))] owned by `endpoint`. The single source of
        truth for placement — get_pserver_program and get_startup_program
        must agree or a server would init a shard it doesn't serve."""
        n = max(1, len(self._eps))
        if endpoint is None:
            if n > 1:
                raise ValueError(
                    "endpoint is required when transpiling for %d pservers"
                    " %r — each server owns a different param shard"
                    % (n, self._eps))
            my_idx = 0
        else:
            try:
                my_idx = self._eps.index(endpoint)
            except ValueError:
                raise ValueError(
                    "endpoint %r is not one of the transpiled pserver "
                    "endpoints %r" % (endpoint, self._eps))
        return [(i, pg) for i, pg in enumerate(self._param_grads)
                if i % n == my_idx]

    def _owned_param_names(self, endpoint=None):
        return [p for _, (p, g) in self._owned(endpoint)]
