"""Automatic mixed precision (bf16 compute, fp32 accumulate/state).

The reference era used fp16 kernels selected by OpKernelType
(data_type_transform.cc fp16↔fp32); the TPU-native equivalent is bf16 on
the MXU: matmul/conv INPUTS are cast to bfloat16 while accumulation stays
fp32 (preferred_element_type) and all state (params, optimizer moments,
batch-norm stats) remains fp32. Enable per-process with ``enable_amp()`` or
scoped with ``amp_guard()``; the matmul/conv lowerings consult this flag.
"""

import contextlib

_AMP = {"enabled": False}


def enable_amp(flag=True):
    _AMP["enabled"] = bool(flag)


def amp_enabled():
    return _AMP["enabled"]


@contextlib.contextmanager
def amp_guard(enable=True):
    old = _AMP["enabled"]
    _AMP["enabled"] = bool(enable)
    try:
        yield
    finally:
        _AMP["enabled"] = old


def maybe_bf16(*arrays):
    """Cast fp32 arrays to bf16 when AMP is on (inputs to MXU ops)."""
    import jax.numpy as jnp
    if not _AMP["enabled"]:
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(a.astype(jnp.bfloat16)
                if a is not None and a.dtype == jnp.float32 else a
                for a in arrays)
    return out if len(out) > 1 else out[0]


def amp_out(out, orig_dtype):
    """Result-dtype policy for MXU ops (conv/mul/matmul).

    Without AMP: cast back to the op's input dtype. With AMP: KEEP the
    activation in bf16 instead of round-tripping to fp32 — the profiler
    showed the ResNet-50 step 82% HBM-bound with fp32 materialization of
    every conv output doubling the traffic. Params stay fp32 (master
    weights); the cast's vjp upcasts their grads back to fp32."""
    import jax.numpy as jnp
    if _AMP["enabled"] and jnp.dtype(orig_dtype) == jnp.float32:
        return out.astype(jnp.bfloat16)
    return out.astype(orig_dtype)
