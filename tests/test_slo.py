"""paddle_tpu.slo + monitor watch: the request-level SLO gate tier.

Golden-fixture contract (ISSUE 6): `tests/fixtures/serving_requests.jsonl`
is a checked-in flight-recorder log (20 retired requests with exact
hand-computable percentiles + 1 failed request + 40 serving_step rows);
`slo_pass.json` / `slo_fail.json` are spec fixtures that must evaluate
to PASS (exit 0) and FAIL (exit 1) against it — the CI/chaos gate
primitive ROADMAP direction 2 builds on. Everything here is pure host
JSON work: milliseconds, no jax.
"""

import io
import json
import os

import pytest

from paddle_tpu import slo

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "fixtures")
LOG = os.path.join(FIX, "serving_requests.jsonl")
PASS_SPEC = os.path.join(FIX, "slo_pass.json")
FAIL_SPEC = os.path.join(FIX, "slo_fail.json")


# -- sample extraction + evaluation against the golden log -----------------

def test_monitor_log_samples_exact():
    s = slo.samples_from_monitor_log(LOG)
    assert s["requests"] == 21 and s["errors"] == 1
    assert len(s["ttft"]) == 20 and len(s["tpot"]) == 20
    # the errored request carries queue_wait=0.0004 in its row, but a
    # failed request is the error budget's business ONLY — its
    # failure-time latencies must not enter percentile samples
    assert len(s["queue_wait"]) == 20
    assert 0.0004 not in s["queue_wait"]
    assert len(s["step_latency"]) == 40
    assert s["skipped"] == 0


def test_evaluate_golden_pass_measured_percentiles():
    v = slo.evaluate(json.load(open(PASS_SPEC)),
                     slo.samples_from_monitor_log(LOG))
    assert v["pass"] is True
    by = {r["metric"]: r for r in v["objectives"]}
    # nearest-rank over the fixture's arithmetic series — exact values
    assert by["ttft"]["measured"] == pytest.approx(0.046)
    assert by["tpot"]["measured"] == pytest.approx(0.0029)
    assert by["queue_wait"]["measured"] == pytest.approx(0.009)
    assert by["step_latency"]["measured"] == pytest.approx(0.00285)
    assert by["error_rate"]["measured"] == pytest.approx(1 / 21)
    assert all(r["pass"] for r in v["objectives"])
    assert not any(r["approximate"] for r in v["objectives"])


def test_evaluate_golden_fail():
    v = slo.evaluate(json.load(open(FAIL_SPEC)),
                     slo.samples_from_monitor_log(LOG))
    assert v["pass"] is False
    by = {r["metric"]: r for r in v["objectives"]}
    assert by["ttft"]["pass"] is False          # 46ms > 20ms
    assert by["tpot"]["pass"] is True
    assert by["error_rate"]["pass"] is False    # 4.76% > 1%


def test_no_samples_objective_fails():
    v = slo.evaluate(
        {"objectives": [{"metric": "ttft", "percentile": 0.5,
                         "max_seconds": 1.0}]},
        slo.samples_from_monitor_log(os.devnull))
    assert v["pass"] is False
    assert v["objectives"][0]["reason"] == "no samples observed"


def test_spec_validation_is_loud():
    with pytest.raises(ValueError, match="unknown metric"):
        slo.load_spec({"objectives": [{"metric": "latency",
                                       "max_seconds": 1}]})
    with pytest.raises(ValueError, match="max_seconds"):
        slo.load_spec({"objectives": [{"metric": "ttft"}]})
    with pytest.raises(ValueError, match="percentile"):
        slo.load_spec({"objectives": [{"metric": "ttft",
                                       "percentile": 1.5,
                                       "max_seconds": 1}]})
    with pytest.raises(ValueError, match="objectives"):
        slo.load_spec({})


# -- the tier-1 gate: CLI exit codes on the checked-in fixtures ------------

def test_slo_cli_gate_pass_and_fail_exit_codes(capsys):
    """THE gate smoke: `python -m paddle_tpu.slo` returns 0 on the
    golden pass spec and 1 on the fail spec, with a machine-readable
    verdict under --json."""
    assert slo.main([PASS_SPEC, "--log", LOG]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out and "serving-golden-pass" in out
    assert slo.main([FAIL_SPEC, "--log", LOG]) == 1
    assert "FAIL" in capsys.readouterr().out
    assert slo.main([PASS_SPEC, "--log", LOG, "--json"]) == 0
    v = json.loads(capsys.readouterr().out)
    assert v["pass"] is True and len(v["objectives"]) == 5
    assert v["requests"] == 21


def test_slo_cli_bad_spec_exits_2(tmp_path, capsys):
    bad = tmp_path / "bad.json"
    bad.write_text('{"objectives": []}')
    assert slo.main([str(bad), "--log", LOG]) == 2
    with pytest.raises(SystemExit) as ei:     # no source given
        slo.main([PASS_SPEC])
    assert ei.value.code == 2


def test_slo_in_analysis_import_check():
    from paddle_tpu.analysis.__main__ import IMPORT_CHECK_PACKAGES
    assert "paddle_tpu.slo" in IMPORT_CHECK_PACKAGES
    assert "paddle_tpu.monitor.watch" in IMPORT_CHECK_PACKAGES


# -- the other two evaluation surfaces -------------------------------------

def test_span_log_source(tmp_path):
    """serving.request spans (close-time attrs) + engine.step durations
    are a full evaluation surface — the merged-fleet-timeline path."""
    log = tmp_path / "spans.jsonl"
    rows = []
    for i in range(10):
        rows.append({"ts": 1.0 + i, "ev": "span", "trace": "t%d" % i,
                     "span": "s%d" % i, "parent": None,
                     "name": "serving.request", "t0": 1.0 + i,
                     "dur": 0.5, "pid": 1, "proc": "eng", "tid": 1,
                     "attrs": {"ttft": 0.01 * (i + 1),
                               "tpot": 0.001, "queue_wait": 0.002}})
        rows.append({"ts": 1.0 + i, "ev": "span", "trace": "t%d" % i,
                     "span": "e%d" % i, "parent": None,
                     "name": "engine.step", "t0": 1.0 + i,
                     "dur": 0.004, "pid": 1, "proc": "eng", "tid": 1})
    rows.append({"ts": 20.0, "ev": "span", "trace": "tx", "span": "sx",
                 "parent": None, "name": "serving.request", "t0": 20.0,
                 "dur": 0.1, "pid": 1, "proc": "eng", "tid": 1,
                 "attrs": {"error": "RuntimeError('boom')"}})
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    s = slo.samples_from_span_logs([str(log)])
    assert s["requests"] == 11 and s["errors"] == 1
    assert len(s["step_latency"]) == 10
    v = slo.evaluate(
        {"objectives": [
            {"metric": "ttft", "percentile": 0.95, "max_seconds": 0.2},
            {"metric": "step_latency", "percentile": 0.95,
             "max_seconds": 0.005},
            {"metric": "error_rate", "max_ratio": 0.5}]}, s)
    assert v["pass"] is True


def test_metrics_snapshot_source(tmp_path):
    """A registry snapshot (dump_metrics .json shape, now carrying
    histogram bucket boundaries) evaluates with bucket-interpolated
    percentiles flagged approximate."""
    from paddle_tpu.monitor.metrics import Registry
    reg = Registry()
    h = reg.histogram("ptpu_serving_ttft_seconds", "t", ("engine",))
    hs = reg.histogram("ptpu_serving_step_seconds", "s", ("engine",))
    for _ in range(100):
        h.observe(0.03, engine="e")       # inside the (0.025, 0.05]
        hs.observe(0.002, engine="e")     # inside the (0.001, 0.0025]
    fails = reg.counter("ptpu_serving_request_failures_total", "f")
    rets = reg.counter("ptpu_serving_retirements_total", "r")
    rets.inc(99)
    fails.inc(1)
    snap = tmp_path / "metrics.json"
    reg.dump_json(str(snap))
    s = slo.samples_from_metrics(str(snap))
    assert s["requests"] == 100 and s["errors"] == 1
    # step_latency reads the SERVING engine-iteration histogram — the
    # same quantity the --log and --spans surfaces measure
    assert "step_latency" in s["histograms"]
    v = slo.evaluate(
        {"objectives": [
            {"metric": "ttft", "percentile": 0.95, "max_seconds": 0.05},
            {"metric": "step_latency", "percentile": 0.95,
             "max_seconds": 0.0025},
            {"metric": "error_rate", "max_ratio": 0.05}]}, s)
    by = {r["metric"]: r for r in v["objectives"]}
    assert v["pass"] is True
    assert by["ttft"]["approximate"] is True
    assert 0.025 < by["ttft"]["measured"] <= 0.05
    # tighter than the bucket floor must fail — approx never flatters
    v2 = slo.evaluate(
        {"objectives": [{"metric": "ttft", "percentile": 0.95,
                         "max_seconds": 0.02}]}, s)
    assert v2["pass"] is False


# -- fleet: union across per-replica logs (ISSUE 8 satellite) --------------

def _replica_log(path, n, ttft0, errors=0):
    rows = []
    for i in range(n):
        rows.append({"ts": 1.0 + i, "ev": "serving_request",
                     "engine": os.path.basename(str(path)),
                     "queue_wait": 0.001, "ttft": ttft0 + 0.001 * i,
                     "tpot": 0.002, "tokens": 8, "prefill_chunks": 1,
                     "prompt_len": 4})
        rows.append({"ts": 1.0 + i, "ev": "serving_step", "active": 2,
                     "slots": 2, "queue_depth": 0, "emitted": 2,
                     "admitted": 0, "retired": 0, "dt": 0.003})
    for _ in range(errors):
        rows.append({"ts": 99.0, "ev": "serving_request",
                     "engine": os.path.basename(str(path)),
                     "error": "Overloaded(...)", "tokens": 0})
    with open(path, "w") as f:
        f.write("\n".join(json.dumps(r) for r in rows) + "\n")


def test_slo_log_union_across_replica_logs(tmp_path, capsys):
    """Fleet-wide percentiles come from the UNION of per-replica logs,
    not a single process's view: the p95 over both replicas' TTFT
    samples differs from either log alone, and the error budget counts
    every replica's failures."""
    a, b = str(tmp_path / "rep0.jsonl"), str(tmp_path / "rep1.jsonl")
    _replica_log(a, 10, ttft0=0.010)            # 10..19 ms
    _replica_log(b, 10, ttft0=0.050, errors=1)  # 50..59 ms + 1 error
    sa = slo.samples_from_monitor_log(a)
    su = slo.samples_from_monitor_log([a, b])
    assert sa["requests"] == 10 and su["requests"] == 21
    assert su["errors"] == 1
    assert len(su["ttft"]) == 20 and len(su["step_latency"]) == 20
    spec = {"objectives": [
        {"metric": "ttft", "percentile": 0.95, "max_seconds": 0.030},
        {"metric": "error_rate", "max_ratio": 0.10}]}
    # replica 0 alone passes 30ms; the union must NOT (p95 ~59ms) —
    # a single-log verdict would flatter the fleet
    assert slo.evaluate(spec, sa)["pass"] is True
    vu = slo.evaluate(spec, su)
    assert vu["pass"] is False
    by = {r["metric"]: r for r in vu["objectives"]}
    assert by["ttft"]["measured"] == pytest.approx(0.058)
    assert by["error_rate"]["measured"] == pytest.approx(1 / 21)
    # the CLI takes several --log paths
    s = json.dumps(spec)
    assert slo.main([s, "--log", a]) == 0
    capsys.readouterr()
    assert slo.main([s, "--log", a, b]) == 1
    assert "FAIL" in capsys.readouterr().out


def test_watch_once_over_multiple_replica_logs(tmp_path):
    from paddle_tpu.monitor.watch import watch
    a, b = str(tmp_path / "rep0.jsonl"), str(tmp_path / "rep1.jsonl")
    _replica_log(a, 5, ttft0=0.010)
    _replica_log(b, 7, ttft0=0.020, errors=1)
    buf = io.StringIO()
    frame = watch([a, b], once=True, out=buf)
    assert frame is not None
    assert "n 13" in frame          # 5 + 7 + 1 failed, unioned
    assert "errors 1" in frame
    assert "steps 12" in frame      # serving_step rows across both
    from paddle_tpu.monitor.__main__ import main as mon_main
    assert mon_main(["watch", a, b, "--once"]) == 0


# -- the live dashboard -----------------------------------------------------

def test_watch_renders_once_on_static_log():
    from paddle_tpu.monitor.watch import watch
    buf = io.StringIO()
    frame = watch(LOG, once=True, out=buf, slo_spec=PASS_SPEC)
    assert frame is not None and frame in buf.getvalue()
    assert "serving" in frame and "requests" in frame
    assert "TTFT" in frame and "TPOT" in frame
    assert "queue_wait" in frame
    assert "slo" in frame and "PASS" in frame
    # totals from the fixture: 40 engine steps, 21 requests, 1 error
    assert "steps 40" in frame
    assert "n 21" in frame
    assert "errors 1" in frame


def test_watch_cli_once(capsys):
    from paddle_tpu.monitor.__main__ import main as mon_main
    assert mon_main(["watch", LOG, "--once"]) == 0
    out = capsys.readouterr().out
    assert "TTFT" in out and "tokens/s" in out
    # --once on a not-yet-created log: clean exit 1, no traceback (the
    # LIVE loop would instead wait for the file to appear)
    assert mon_main(["watch", "/tmp/ptpu_no_such_log.jsonl",
                     "--once"]) == 1
    assert "does not exist" in capsys.readouterr().out
    # a typo'd --slo spec: clean exit 2 like the slo CLI
    assert mon_main(["watch", LOG, "--once",
                     "--slo", "/tmp/ptpu_no_such_spec.json"]) == 2


def test_monitor_cli_summarizes_serving_rows(capsys):
    """ISSUE-6 satellite: one command reports BOTH workloads — the
    summary now carries a serving block with step latency, occupancy
    and TTFT/TPOT percentiles."""
    from paddle_tpu.monitor.__main__ import main as mon_main
    from paddle_tpu.monitor.__main__ import summarize_log
    s = summarize_log(LOG)
    sv = s["serving"]
    assert sv["steps"] == 40 and sv["requests"] == 21
    assert sv["errors"] == 1
    assert sv["ttft_p95_s"] == pytest.approx(0.046)
    assert sv["tpot_p95_s"] is not None
    assert sv["step_p95_s"] == pytest.approx(0.00285)
    assert sv["max_queue_depth"] == 12
    assert 0.0 < sv["mean_occupancy"] <= 1.0
    assert mon_main([LOG]) == 0
    out = capsys.readouterr().out
    assert "serving" in out and "TTFT" in out and "ERRORS 1" in out
    # a pure training log keeps serving == None (shape unchanged)
    assert mon_main([LOG, "--json"]) == 0
    j = json.loads(capsys.readouterr().out)
    assert j["serving"]["requests"] == 21
