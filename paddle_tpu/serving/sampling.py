"""Per-request sampling: temperature / top-k / top-p with per-slot
PRNG state, executed INSIDE the compiled slot-batched decode step.

The engine's sampling contract (pinned in tests/test_kvpool.py and by
every existing greedy-identity test):

  * ``temperature == 0`` (the default) is BITWISE-greedy: the argmax
    path is computed exactly as the PR-5 engine computed it and
    selected per slot with an elementwise ``where`` — so every
    token-identity pin (engine vs ``sequential_generate``, megastep
    fusion, fleet exactly-once re-execution) survives unchanged.
  * stochastic slots draw through a counter-based per-slot key:
    ``fold_in(PRNGKey(seed), tokens_generated_so_far)``. No entropy
    enters the step, which buys three properties at once — the same
    ``seed`` reproduces the same tokens, a fused K-step megastep draws
    the same sequence as K single steps (the count rides the scan
    carry), and a PREEMPTED request re-decoded from its prompt
    regenerates its exact output (the count restarts with it), keeping
    the fleet's exactly-once dedup valid for sampled traffic.
  * ``top_k`` masks to the k highest logits (ties at the k-th logit
    are all kept); ``top_p`` masks to the smallest cumulative-p head
    of the top-k-filtered distribution (the top-1 token is always
    kept). Both run as fixed-shape sorts so the compiled step never
    re-traces as per-request parameters vary.
"""

import jax
import jax.numpy as jnp

__all__ = ["SamplingParams", "sample", "step_keys"]


class SamplingParams:
    """Validated per-request sampling knobs, wire-serializable (the
    fleet's SUBM frames carry ``to_dict()``; resubmission to a survivor
    replica re-executes with the SAME params + seed, so sampled
    requests stay deterministic under churn).

    temperature: 0 = greedy (bitwise; the default). > 0 scales logits.
    top_k:       0 = off; else sample among the k highest logits.
    top_p:       1.0 = off; else nucleus sampling inside top-k.
    seed:        per-request PRNG seed (default 0 — reproducibility,
                 not entropy, is the contract; pass your own for
                 independent streams)."""

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature=0.0, top_k=0, top_p=1.0, seed=0):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0, got %r"
                             % (temperature,))
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0, got %r" % (top_k,))
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError("top_p must be in (0, 1], got %r"
                             % (top_p,))
        if not (0 <= self.seed < 2 ** 32):
            raise ValueError("seed must fit uint32, got %r" % (seed,))

    @property
    def greedy(self):
        return self.temperature <= 0.0

    def to_dict(self):
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}

    @classmethod
    def from_dict(cls, d):
        if d is None:
            return cls()
        if isinstance(d, cls):
            return d
        if not isinstance(d, dict):
            # ValueError, not AttributeError: Engine.submit promises
            # the fleet's BADR typed-reject covers invalid sampling —
            # a non-dict wire payload must not tear the connection and
            # get retried into every replica as a transport failure
            raise ValueError(
                "sampling must be a SamplingParams or its dict form, "
                "got %r" % (type(d).__name__,))
        unknown = set(d) - {"temperature", "top_k", "top_p", "seed"}
        if unknown:
            # a misspelled knob ("temp", "topK") must not silently run
            # greedy — the caller asked for sampling and would get
            # deterministic unsampled output with no error anywhere
            raise ValueError(
                "unknown sampling field(s) %s (known: temperature, "
                "top_k, top_p, seed)" % sorted(unknown))
        return cls(temperature=d.get("temperature", 0.0),
                   top_k=d.get("top_k", 0),
                   top_p=d.get("top_p", 1.0),
                   seed=d.get("seed", 0))

    def __repr__(self):
        return ("SamplingParams(temperature=%g, top_k=%d, top_p=%g, "
                "seed=%d)" % (self.temperature, self.top_k, self.top_p,
                              self.seed))


def step_keys(seeds, counts):
    """Per-slot PRNG keys for one decode step: ``seeds`` [S] uint32,
    ``counts`` [S] int32 (tokens generated so far). Counter-based so a
    restart (preemption re-prefill) regenerates the same stream."""
    return jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c)
    )(seeds, counts)


def sample(logits, temperature, top_k, top_p, keys):
    """Draw one token per slot: ``logits`` [S, V] float32,
    ``temperature`` [S] (rows <= 0 are computed at temperature 1 and
    DISCARDED by the caller's greedy ``where`` — never select them
    from here), ``top_k`` [S] int32 (0 = off), ``top_p`` [S] (1 = off),
    ``keys`` [S] PRNG keys. Returns int32 [S] token ids."""
    v = logits.shape[-1]
    t = jnp.where(temperature > 0.0, temperature, 1.0)
    scaled = logits / t[:, None]
    # top-k: keep scores >= the k-th largest (ties at the boundary all
    # kept — fixed-shape, no dynamic gather)
    srt = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.clip(jnp.where(top_k > 0, top_k, v), 1, v)
    kth = jnp.take_along_axis(srt, (k - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= kth, scaled, -jnp.inf)
    # top-p over the top-k-filtered distribution: keep the smallest
    # prefix of descending-prob tokens whose cumulative mass BEFORE
    # each token is < p (top-1 always kept)
    lp = jax.nn.log_softmax(masked, axis=-1)
    probs = jnp.exp(lp)
    ps = jnp.sort(probs, axis=-1)[:, ::-1]
    csum = jnp.cumsum(ps, axis=-1)
    keep = (csum - ps) < top_p[:, None]
    minkeep = jnp.min(jnp.where(keep, ps, jnp.inf), axis=-1)
    final = jnp.where(probs >= minkeep[:, None], lp, -jnp.inf)
    return jax.vmap(jax.random.categorical)(keys, final).astype(
        jnp.int32)
