"""DataFeeder: minibatch rows → feed dict.

Reference parity: python/paddle/fluid/data_feeder.py:69 — converts a list of
sample tuples (one element per feed var) into arrays/LoDTensors keyed by var
name. LoD-level>0 vars become padded arrays + `<name>@LOD` length vectors
(the TPU static-shape representation, see core/lod.py).
"""

import numpy as np

from .core.lod import LoDTensor, pack_sequences
from .core.program import Variable, convert_dtype, default_main_program


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.place = place
        program = program or default_main_program()
        self.feed_vars = []
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_vars.append(v)

    def feed(self, iterable):
        """iterable: list of sample tuples. Returns {var name: array|LoDTensor}."""
        columns = list(zip(*iterable)) if iterable else \
            [[] for _ in self.feed_vars]
        out = {}
        for var, col in zip(self.feed_vars, columns):
            dtype = np.dtype(convert_dtype(var.dtype))
            if var.lod_level and var.lod_level > 0:
                seqs = [np.asarray(s, dtype=dtype) for s in col]
                # reference shape convention: sequence features often [Ti] ids
                # or [Ti, D]; pad to [B, Tmax, ...] and attach lengths
                if seqs and seqs[0].ndim == 0:
                    seqs = [s.reshape(1) for s in seqs]
                padded, lengths = pack_sequences(seqs, dtype=dtype)
                t = LoDTensor(padded)
                t.set_recursive_sequence_lengths([list(map(int, lengths))])
                out[var.name] = t
            else:
                arr = np.asarray(col, dtype=dtype)
                shape = var.shape
                if shape is not None:
                    want = [len(col)] + [int(s) for s in shape[1:]]
                    if -1 not in want and list(arr.shape) != want:
                        arr = arr.reshape(want)
                    elif arr.ndim == 1 and len(shape) > 1:
                        arr = arr.reshape(len(col), -1)
                out[var.name] = arr
        return out

    def feed_parallel(self, iterable, num_places):
        """Split one batch into per-device sub-batches (SplitLoDTensor
        equivalent for the data-parallel executor)."""
        full = self.feed(iterable)
        outs = [dict() for _ in range(num_places)]
        for name, val in full.items():
            arr = val.data if isinstance(val, LoDTensor) else val
            chunks = np.array_split(arr, num_places)
            for i, c in enumerate(chunks):
                outs[i][name] = c
        return outs
