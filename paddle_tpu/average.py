"""Pure-python running averages (reference python/paddle/fluid/average.py).

Host-side accumulators over fetched values — they never touch the
Program. Kept for API parity with reference user scripts; new code
should prefer paddle_tpu.metrics.
"""

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number(v):
    return isinstance(v, (int, float)) or (
        isinstance(v, np.ndarray) and v.shape == (1,))


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not (_is_number(value) or isinstance(value, np.ndarray)):
            raise ValueError("'value' must be a number or numpy ndarray")
        if not _is_number(weight):
            raise ValueError("'weight' must be a number")
        if self.numerator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator == 0:
            raise ValueError("eval() before any add(); no data to average")
        return self.numerator / self.denominator
