"""Model-zoo smoke tests: the "book"-test pattern (SURVEY.md §4.3) —
train a few steps, assert loss decreases / shapes hold."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import resnet, vgg, mlp


def _train_steps(image, label, avg_cost, batch=8, shape=(3, 16, 16),
                 classes=10, steps=6, rng=None):
    rng = rng or np.random.RandomState(0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x = rng.rand(batch, *shape).astype(np.float32)
    y = rng.randint(0, classes, (batch, 1)).astype(np.int64)
    losses = []
    for _ in range(steps):
        lv, = exe.run(feed={"data": x, "label": y}, fetch_list=[avg_cost])
        losses.append(float(np.asarray(lv)))
    return losses


def test_resnet_cifar10_trains():
    image, label, avg_cost, acc = resnet.build_train_net(
        model="resnet_cifar10", depth=8, image_shape=(3, 16, 16),
        learning_rate=0.05)
    losses = _train_steps(image, label, avg_cost)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_resnet50_imagenet_builds_and_runs():
    image, label, avg_cost, acc = resnet.build_train_net(
        model="resnet_imagenet", depth=50, image_shape=(3, 64, 64),
        num_classes=100)
    losses = _train_steps(image, label, avg_cost, batch=2,
                          shape=(3, 64, 64), classes=100, steps=2)
    assert np.isfinite(losses).all()


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_vgg16_trains():
    image, label, avg_cost, acc = vgg.build_train_net(
        image_shape=(3, 32, 32), learning_rate=1e-3)
    losses = _train_steps(image, label, avg_cost, batch=4,
                          shape=(3, 32, 32), steps=3)
    assert np.isfinite(losses).all()


def test_mnist_cnn_trains():
    img = fluid.layers.data("img", [1, 28, 28])
    label = fluid.layers.data("label", [1], dtype="int64")
    _, avg_cost, acc = mlp.cnn(img, label)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    x = rng.rand(8, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, (8, 1)).astype(np.int64)
    losses = []
    for _ in range(8):
        lv, = exe.run(feed={"img": x, "label": y}, fetch_list=[avg_cost])
        losses.append(float(np.asarray(lv)))
    assert losses[-1] < losses[0]
