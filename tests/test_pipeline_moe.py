"""Pipeline (GPipe over pp axis) and MoE (ep axis) tests on the virtual
8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import parallel


def test_gpipe_matches_sequential():
    mesh = parallel.make_mesh({"pp": 4})
    rng = np.random.RandomState(0)
    s, d = 4, 8
    ws = rng.randn(s, d, d).astype(np.float32) * 0.3
    bs = rng.randn(s, d).astype(np.float32) * 0.1
    params = {"w": jnp.asarray(ws), "b": jnp.asarray(bs)}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    m, mb = 6, 4
    xs = rng.randn(m, mb, d).astype(np.float32)
    got = np.asarray(parallel.gpipe(stage_fn, params, jnp.asarray(xs),
                                    mesh, axis_name="pp"))
    # sequential reference
    want = xs.copy()
    for i in range(s):
        want = np.tanh(want @ ws[i] + bs[i])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_gpipe_differentiable():
    mesh = parallel.make_mesh({"pp": 2})
    rng = np.random.RandomState(1)
    s, d = 2, 4
    params = {"w": jnp.asarray(rng.randn(s, d, d).astype(np.float32) * 0.3)}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    xs = jnp.asarray(rng.randn(3, 2, d).astype(np.float32))

    def loss(params):
        return jnp.sum(parallel.gpipe(stage_fn, params, xs, mesh) ** 2)

    g = jax.grad(loss)(params)
    arr = np.asarray(g["w"])
    assert np.isfinite(arr).all()
    assert np.abs(arr).max() > 0
    # both stages' params must receive gradient
    assert np.abs(arr[0]).max() > 0 and np.abs(arr[1]).max() > 0


def test_moe_routing_and_shapes():
    rng = np.random.RandomState(2)
    t, d, e, h = 32, 8, 4, 16
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    w_up = jnp.asarray(rng.randn(e, d, h).astype(np.float32) * 0.2)
    w_down = jnp.asarray(rng.randn(e, h, d).astype(np.float32) * 0.2)
    out, aux = parallel.moe_ffn(x, gate_w, w_up, w_down,
                                capacity_factor=2.0)
    assert out.shape == (t, d)
    assert np.isfinite(np.asarray(out)).all()
    assert float(aux) > 0

    # with generous capacity, each kept token must equal its top-1 expert's
    # FFN output scaled by the gate prob
    probs = np.asarray(jax.nn.softmax(x @ gate_w, axis=-1))
    top = probs.argmax(-1)
    xn = np.asarray(x)
    for i in range(5):
        ei = int(top[i])
        hi = np.maximum(xn[i] @ np.asarray(w_up)[ei], 0)
        want = (hi @ np.asarray(w_down)[ei]) * probs[i, ei]
        np.testing.assert_allclose(np.asarray(out)[i], want, rtol=1e-3,
                                   atol=1e-4)


def test_moe_capacity_drops_overflow():
    rng = np.random.RandomState(3)
    t, e = 16, 2
    # force all tokens to expert 0
    logits = jnp.asarray(np.tile([10.0, -10.0], (t, 1)).astype(np.float32))
    dispatch, combine, aux = parallel.top1_gating(logits, capacity=4)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == 4          # only capacity tokens kept
    assert d[:, 1].sum() == 0


def test_moe_under_ep_mesh():
    mesh = parallel.make_mesh({"ep": 4})
    rng = np.random.RandomState(4)
    t, d, e, h = 16, 8, 4, 8
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    w_up = jnp.asarray(rng.randn(e, d, h).astype(np.float32) * 0.2)
    w_down = jnp.asarray(rng.randn(e, h, d).astype(np.float32) * 0.2)

    with mesh:
        jit_moe = jax.jit(lambda *a: parallel.moe_ffn(*a, mesh=mesh))
        out, aux = jit_moe(x, gate_w, w_up, w_down)
    base, _ = parallel.moe_ffn(x, gate_w, w_up, w_down)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=2e-4, atol=2e-5)


def test_moe_top2_identical_experts_equals_dense():
    # with normalized top-2 combine weights and all experts equal, the MoE
    # output must equal the single dense FFN exactly (weights sum to 1)
    rng = np.random.RandomState(5)
    t, d, e, h = 16, 8, 4, 16
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    wu = rng.randn(d, h).astype(np.float32) * 0.2
    wd = rng.randn(h, d).astype(np.float32) * 0.2
    w_up = jnp.asarray(np.tile(wu, (e, 1, 1)))
    w_down = jnp.asarray(np.tile(wd, (e, 1, 1)))
    out, aux = parallel.moe_ffn(x, gate_w, w_up, w_down, top_k=2,
                                capacity_factor=4.0)
    want = np.maximum(np.asarray(x) @ wu, 0) @ wd
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)


def test_moe_top2_overflow_metric():
    t, e, cap = 16, 2, 4
    # all tokens pick expert 0 first (logit 10), expert 1 second (logit 5):
    # rank-0 keeps cap of 16, rank-1 keeps cap of 16 → dropped 24/32
    logits = jnp.asarray(np.tile([10.0, 5.0], (t, 1)).astype(np.float32))
    dispatch, combine, aux, overflow = parallel.topk_gating(
        logits, capacity=cap, k=2)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == cap and d[:, 1].sum() == cap
    np.testing.assert_allclose(float(overflow), 24.0 / 32.0)
    # kept combine weights normalized over the two selected gates
    c = np.asarray(combine)
    probs = np.asarray(jax.nn.softmax(logits, -1))[0]
    np.testing.assert_allclose(c[0, 0].sum(),
                               probs[0] / (probs[0] + probs[1]), rtol=1e-5)


def test_moe_top2_under_ep_mesh_matches_local():
    mesh = parallel.make_mesh({"ep": 4})
    rng = np.random.RandomState(6)
    t, d, e, h = 32, 8, 4, 8
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    gate_w = jnp.asarray(rng.randn(d, e).astype(np.float32))
    w_up = jnp.asarray(rng.randn(e, d, h).astype(np.float32) * 0.2)
    w_down = jnp.asarray(rng.randn(e, h, d).astype(np.float32) * 0.2)
    with mesh:
        jit_moe = jax.jit(lambda *a: parallel.moe_ffn(*a, mesh=mesh,
                                                      top_k=2))
        out, aux = jit_moe(x, gate_w, w_up, w_down)
    base, _ = parallel.moe_ffn(x, gate_w, w_up, w_down, top_k=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=2e-4, atol=2e-5)


def test_moe_top2_grads_reach_gate_and_experts():
    rng = np.random.RandomState(7)
    t, d, e, h = 16, 8, 4, 8
    x = jnp.asarray(rng.randn(t, d).astype(np.float32))
    params = {
        "g": jnp.asarray(rng.randn(d, e).astype(np.float32)),
        "u": jnp.asarray(rng.randn(e, d, h).astype(np.float32) * 0.2),
        "d": jnp.asarray(rng.randn(e, h, d).astype(np.float32) * 0.2),
    }

    def loss(p):
        out, aux = parallel.moe_ffn(x, p["g"], p["u"], p["d"], top_k=2)
        return jnp.sum(out ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for k in ("g", "u", "d"):
        arr = np.asarray(g[k])
        assert np.isfinite(arr).all() and np.abs(arr).max() > 0, k


def test_sparse_moe_layer_top2_overflow_fetchable():
    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6, 16])
        out, aux, ovf = fluid.layers.sparse_moe(
            x, num_experts=4, d_inner=32, top_k=2, return_overflow=True)
        loss = fluid.layers.mean(out) + fluid.layers.scale(aux, 0.01)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.random.RandomState(8).randn(4, 6, 16).astype(np.float32)
        l1, o1 = exe.run(feed={"x": xv}, fetch_list=[loss, ovf])
    assert np.isfinite(np.asarray(l1)).all()
    o1 = float(np.asarray(o1))
    assert 0.0 <= o1 <= 1.0


def test_gpipe_heterogeneous_stage_params():
    """Per-stage parameter SHAPES differ (list-of-pytrees form): stage 0
    is a dense tanh layer, stage 1 an affine scale — same activation
    shape, different param shapes, selected by stage index."""
    mesh = parallel.make_mesh({"pp": 2})
    rng = np.random.RandomState(9)
    d = 6
    w = rng.randn(d, d).astype(np.float32) * 0.4
    s = rng.rand(d).astype(np.float32) + 0.5
    b = rng.randn(d).astype(np.float32) * 0.1
    params = [{"w": jnp.asarray(w)},
              {"s": jnp.asarray(s), "b": jnp.asarray(b)}]

    def stage_fn(p, x):
        if "w" in p:
            return jnp.tanh(x @ p["w"])
        return x * p["s"] + p["b"]

    xs = rng.randn(4, 3, d).astype(np.float32)
    got = np.asarray(parallel.gpipe(stage_fn, params, jnp.asarray(xs),
                                    mesh, axis_name="pp"))
    want = np.tanh(xs @ w) * s + b
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    # differentiable through both heterogeneous stages
    def loss(ps):
        return jnp.sum(parallel.gpipe(stage_fn, ps, jnp.asarray(xs),
                                      mesh, axis_name="pp") ** 2)

    g = jax.grad(loss)(params)
    assert np.abs(np.asarray(g[0]["w"])).max() > 0
    assert np.abs(np.asarray(g[1]["s"])).max() > 0


def test_gpipe_interleaved_matches_sequential():
    """Interleaved virtual stages (V chunks per device, Megatron
    assignment {d, d+S, ...}): same math as the sequential stack, with
    the bubble cut to (S-1)/V chunk-times (pipeline.gpipe_interleaved)."""
    mesh = parallel.make_mesh({"pp": 4})
    rng = np.random.RandomState(0)
    s, v, d = 4, 2, 8
    L = s * v                              # one layer per chunk
    ws = rng.randn(L, d, d).astype(np.float32) * 0.3
    bs = rng.randn(L, d).astype(np.float32) * 0.1
    # device dd holds global chunks {dd, dd+S}: [L,...] -> [V,S,...] ->
    # [S,V,...] (the op lowering's interleave reshape, per=1 folded in)
    params = {
        "w": jnp.asarray(ws).reshape(v, s, d, d).swapaxes(0, 1),
        "b": jnp.asarray(bs).reshape(v, s, d).swapaxes(0, 1)}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    m, mb = 4, 2                           # M <= S regime
    xs = rng.randn(m, mb, d).astype(np.float32)
    got = np.asarray(parallel.gpipe_interleaved(
        stage_fn, params, jnp.asarray(xs), mesh, n_chunks=v,
        axis_name="pp"))
    want = xs.copy()
    for i in range(L):
        want = np.tanh(want @ ws[i] + bs[i])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    # differentiable, every chunk's params receive gradient
    def loss(ps):
        return jnp.sum(parallel.gpipe_interleaved(
            stage_fn, ps, jnp.asarray(xs), mesh, n_chunks=v,
            axis_name="pp") ** 2)

    g = np.asarray(jax.grad(loss)(params)["w"])
    assert np.isfinite(g).all()
    assert (np.abs(g).reshape(s * v, -1).max(axis=1) > 0).all()

    # M > S is a different schedule regime: refused loudly
    with pytest.raises(ValueError, match="interleaved"):
        parallel.gpipe_interleaved(
            stage_fn, params, jnp.asarray(rng.randn(6, 2, d)), mesh,
            n_chunks=v, axis_name="pp")


def _lm_parallel_loss(strategy, mesh_axes, prefix, num_experts=0):
    """Build transformer_lm_parallel under `strategy`, run ONE step on
    the given mesh, return (loss, updated first pipeline weight)."""
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name
    from paddle_tpu.models import transformer as T

    mesh = parallel.make_mesh(mesh_axes) if mesh_axes else None
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard(prefix):
        avg, _ = T.transformer_lm_parallel(
            vocab_size=64, max_len=16, n_layer=4, n_head=4, d_model=32,
            d_inner=64, strategy=strategy, num_experts=num_experts)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(5)
        feeds = T.make_lm_batch(rng, 8, 16, 64)
        if mesh is None:
            l, = exe.run(feed=feeds, fetch_list=[avg])
        else:
            pexe = fluid.ParallelExecutor(loss_name=avg.name,
                                          main_program=main, mesh=mesh,
                                          scope=scope)
            l, = pexe.run([avg], feed=feeds)
        wname = prefix + "pipeline_stack_0.wq"
        w = scope.find_var(wname)
        return float(np.asarray(l)), (np.asarray(w) if w is not None
                                      else None)


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_pipeline_composes_with_tp_and_sp():
    """pp x tp (Megatron shards + psum inside the stage) and pp x sp
    (ring attention inside the stage) match the pp-only run, which
    matches dense single-device math (lifting the round-3 refusal at
    models/transformer.py)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    st_pp = parallel.DistributedStrategy(dp=1, pp=2)
    l_pp, w_pp = _lm_parallel_loss(st_pp, {"dp": 1, "pp": 2}, "pa_")
    st_tp = parallel.DistributedStrategy(dp=1, pp=2, tp=2)
    l_tp, w_tp = _lm_parallel_loss(st_tp, {"dp": 1, "pp": 2, "tp": 2},
                                   "pb_")
    st_sp = parallel.DistributedStrategy(dp=1, pp=2, sp=2)
    l_sp, w_sp = _lm_parallel_loss(st_sp, {"dp": 1, "pp": 2, "sp": 2},
                                   "pc_")
    np.testing.assert_allclose(l_tp, l_pp, rtol=2e-4)
    np.testing.assert_allclose(l_sp, l_pp, rtol=2e-4)
    # updated WEIGHTS match too, not just the loss
    np.testing.assert_allclose(w_tp, w_pp, rtol=2e-3, atol=2e-5)
    np.testing.assert_allclose(w_sp, w_pp, rtol=2e-3, atol=2e-5)


def test_pipeline_interleaved_schedule_parity():
    """The interleaved schedule through the layer DSL trains the same
    model as gpipe (same loss + updated weights)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    st_g = parallel.DistributedStrategy(dp=2, pp=2)
    l_g, w_g = _lm_parallel_loss(st_g, {"dp": 2, "pp": 2}, "qa_")
    st_i = parallel.DistributedStrategy(dp=2, pp=2,
                                        pp_schedule="interleaved")
    l_i, w_i = _lm_parallel_loss(st_i, {"dp": 2, "pp": 2}, "qb_")
    np.testing.assert_allclose(l_i, l_g, rtol=2e-4)
    np.testing.assert_allclose(w_i, w_g, rtol=2e-3, atol=2e-5)


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_pipeline_full_composition_pp_tp_sp():
    """pp x tp x sp in ONE stage body: Megatron-sharded weights with
    per-sublayer psum AND ring attention over the sequence shard, inside
    the pipeline shard_map — the deepest composition the stage supports."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    st_pp = parallel.DistributedStrategy(dp=1, pp=2)
    l_pp, w_pp = _lm_parallel_loss(st_pp, {"dp": 1, "pp": 2}, "fa_")
    st_all = parallel.DistributedStrategy(dp=1, pp=2, tp=2, sp=2)
    l_all, w_all = _lm_parallel_loss(
        st_all, {"dp": 1, "pp": 2, "tp": 2, "sp": 2}, "fb_")
    np.testing.assert_allclose(l_all, l_pp, rtol=2e-4)
    np.testing.assert_allclose(w_all, w_pp, rtol=2e-3, atol=2e-5)


def test_pipeline_interleaved_with_recompute():
    """Interleaved virtual stages compose with per-layer recompute
    (jax.checkpoint inside the chunk body): same trained model as plain
    gpipe."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name

    def run(schedule, recompute, prefix):
        mesh = parallel.make_mesh({"dp": 2, "pp": 2})
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 23
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard(prefix):
            x = fluid.layers.data("x", [8, 16])
            y = fluid.layers.pipelined_decoder_stack(
                x, n_layer=4, n_head=2, d_inner=32,
                schedule=schedule, recompute=recompute)
            loss = fluid.layers.mean(fluid.layers.square(y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            pexe = fluid.ParallelExecutor(loss_name=loss.name,
                                          main_program=main, mesh=mesh,
                                          scope=scope)
            xv = np.random.RandomState(6).rand(8, 8, 16).astype(
                np.float32)
            l, = pexe.run([loss], feed={"x": xv})
            wname = prefix + "pipeline_stack_0.wq"
            return (float(np.asarray(l)),
                    np.asarray(scope.find_var(wname)))

    l_g, w_g = run("gpipe", False, "ra_")
    l_ir, w_ir = run("interleaved", True, "rb_")
    np.testing.assert_allclose(l_ir, l_g, rtol=1e-5)
    np.testing.assert_allclose(w_ir, w_g, rtol=1e-4, atol=1e-6)


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_pipeline_composes_with_ep_moe():
    """pp x ep — the last composition refusal, lifted: MoE FFN inside
    the pipeline stage body, expert stacks sharded over ep with the
    dispatch all-to-all nested in the stage (moe_ffn_pp_sharded), must
    match the dense fallback's group-wise routing exactly (the
    moe_gate_groups static-granularity contract)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    st = parallel.DistributedStrategy(dp=2, pp=2, ep=2)
    l_dense, w_dense = _lm_parallel_loss(st, None, "pe_", num_experts=4)
    l_ep, w_ep = _lm_parallel_loss(st, {"dp": 2, "pp": 2, "ep": 2},
                                   "pe_", num_experts=4)
    np.testing.assert_allclose(l_ep, l_dense, rtol=2e-4)
    np.testing.assert_allclose(w_ep, w_dense, rtol=2e-3, atol=2e-5)


@pytest.mark.slow  # ISSUE-11 durations audit: >10 s on tier-1
def test_pipeline_moe_interleaved_schedule():
    """pp x ep under the interleaved virtual-stage schedule (aux loss
    rides the live-tick mask through the V-lap tick loop)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    st = parallel.DistributedStrategy(dp=2, pp=2, ep=2,
                                      pp_schedule="interleaved",
                                      pp_virtual_stages=2)
    l_dense, w_dense = _lm_parallel_loss(st, None, "pi_", num_experts=4)
    l_ep, w_ep = _lm_parallel_loss(st, {"dp": 2, "pp": 2, "ep": 2},
                                   "pi_", num_experts=4)
    np.testing.assert_allclose(l_ep, l_dense, rtol=2e-4)
    np.testing.assert_allclose(w_ep, w_dense, rtol=2e-3, atol=2e-5)


def test_pipeline_moe_rejects_sp():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    st = parallel.DistributedStrategy(dp=1, pp=2, sp=2, ep=2)
    with pytest.raises(Exception, match="sequence"):
        _lm_parallel_loss(st, {"dp": 1, "pp": 2, "sp": 2, "ep": 2},
                          "ps_", num_experts=4)


def test_pipeline_moe_gate_groups_must_match_mesh():
    """The static routing granularity (dp*ep baked into the program)
    must equal the mesh's actual token split — a mismatched mesh would
    silently route differently than the program's fallback."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    st = parallel.DistributedStrategy(dp=2, pp=2, ep=2)  # groups = 4
    with pytest.raises(Exception, match="moe_gate_groups"):
        # run on a mesh whose dp*ep = 2
        _lm_parallel_loss(st, {"dp": 1, "pp": 2, "ep": 2}, "pg_",
                          num_experts=4)


def test_pipeline_moe_top2_parity():
    """pp x ep with GShard top-2 routing (normalized combine weights)
    through the pipelined stage body matches the dense fallback — the
    layer-level knob (moe_top_k) the flagship builder defaults away."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name

    def run(mesh_axes, prefix):
        mesh = parallel.make_mesh(mesh_axes) if mesh_axes else None
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 23
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard(prefix):
            x = fluid.layers.data("x", [16, 32])
            y = fluid.layers.data("y", [16, 32])
            out, aux = fluid.layers.pipelined_decoder_stack(
                x, n_layer=2, n_head=4, d_inner=64, num_experts=4,
                moe_top_k=2, num_microbatches=2, moe_gate_groups=4)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(out, y)) \
                + fluid.layers.scale(aux, 0.01)
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            rng = np.random.RandomState(9)
            feeds = {"x": rng.rand(8, 16, 32).astype(np.float32),
                     "y": rng.rand(8, 16, 32).astype(np.float32)}
            if mesh is None:
                l, = exe.run(feed=feeds, fetch_list=[loss])
            else:
                pexe = fluid.ParallelExecutor(loss_name=loss.name,
                                              main_program=main,
                                              mesh=mesh, scope=scope)
                l, = pexe.run([loss], feed=feeds)
            # POST-step expert weight: proves the top-2 combine's
            # cotangent split survives the sharded stage body, not just
            # the (pre-update) loss value
            w = np.asarray(scope.find_var(
                prefix + "pipeline_stack_0.w_up"))
        return float(np.asarray(l)), w

    dense, w_dense = run(None, "t2_")
    sharded, w_sharded = run({"dp": 2, "pp": 2, "ep": 2}, "t2_")
    np.testing.assert_allclose(sharded, dense, rtol=2e-4)
    np.testing.assert_allclose(w_sharded, w_dense, rtol=2e-3, atol=2e-5)
