"""Model zoo: Program-building functions for the reference's benchmark
models (benchmark/fluid/{mnist,resnet,vgg,machine_translation,
stacked_dynamic_lstm}.py + tests/unittests/transformer_model.py), built
TPU-first with the paddle_tpu layers DSL."""

from . import mlp, resnet, ssd, vgg  # noqa: F401
