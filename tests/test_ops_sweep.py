"""Systematic op sweep, part 1: activations, elementwise, reductions, math,
tensor manipulation, random, loss, and nn ops.

Reference parity: the ~200 test_*_op.py files under
python/paddle/fluid/tests/unittests/, driven by op_test.py:212 (output
checks vs numpy) and op_test.py:378 (finite-difference gradient checks).
Part 2 (optimizers, metrics, rnn cells, detection, 3-D conv/pool) is
tests/test_ops_sweep2.py; the registry-completeness check lives there too.
"""

import numpy as np
import pytest

from op_test import check_output, check_grad, run_op


def _r(*shape, lo=0.0, hi=1.0, seed=0, dtype=np.float32):
    rng = np.random.RandomState(abs(hash((shape, lo, hi, seed))) % (2**31))
    return (rng.uniform(lo, hi, size=shape)).astype(dtype)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _softplus(x):
    return np.log1p(np.exp(x))


def _erf(x):
    import math
    return np.vectorize(math.erf)(x).astype(x.dtype)


# --------------------------------------------------------------------------
# activations (operators/activation_op.cc — 30 activations)
# entry: (attrs, ref(x, attrs), domain (lo, hi), check_grad?)
UNARY = {
    "sigmoid": ({}, lambda x, a: _sigmoid(x), (-2, 2), True),
    "logsigmoid": ({}, lambda x, a: -_softplus(-x), (-2, 2), True),
    "exp": ({}, lambda x, a: np.exp(x), (-2, 2), True),
    "relu": ({}, lambda x, a: np.maximum(x, 0), (0.1, 2), True),
    "tanh": ({}, lambda x, a: np.tanh(x), (-2, 2), True),
    "tanh_shrink": ({}, lambda x, a: x - np.tanh(x), (-2, 2), True),
    "sqrt": ({}, lambda x, a: np.sqrt(x), (0.5, 4), True),
    "rsqrt": ({}, lambda x, a: 1.0 / np.sqrt(x), (0.5, 4), True),
    "abs": ({}, lambda x, a: np.abs(x), (0.3, 2), True),
    "ceil": ({}, lambda x, a: np.ceil(x), (-2, 2), False),
    "floor": ({}, lambda x, a: np.floor(x), (-2, 2), False),
    "cos": ({}, lambda x, a: np.cos(x), (-2, 2), True),
    "sin": ({}, lambda x, a: np.sin(x), (-2, 2), True),
    "round": ({}, lambda x, a: np.round(x), (-2, 2), False),
    "reciprocal": ({}, lambda x, a: 1.0 / x, (0.5, 3), True),
    "log": ({}, lambda x, a: np.log(x), (0.5, 4), True),
    "square": ({}, lambda x, a: x * x, (-2, 2), True),
    "softplus": ({}, lambda x, a: _softplus(x), (-2, 2), True),
    "softsign": ({}, lambda x, a: x / (1 + np.abs(x)), (0.2, 2), True),
    "sign": ({}, lambda x, a: np.sign(x), (0.3, 2), False),
    "gelu": ({}, lambda x, a: x * 0.5 * (1 + _erf(x / np.sqrt(2.0))),
             (-2, 2), True),
    "erf": ({}, lambda x, a: _erf(x), (-2, 2), True),
    "silu": ({}, lambda x, a: x * _sigmoid(x), (-2, 2), True),
    "brelu": ({"t_min": -0.5, "t_max": 0.8},
              lambda x, a: np.clip(x, -0.5, 0.8), (-2, 2), False),
    "leaky_relu": ({"alpha": 0.1},
                   lambda x, a: np.where(x > 0, x, 0.1 * x), (0.2, 2), True),
    "soft_relu": ({"threshold": 40.0},
                  lambda x, a: np.log1p(np.exp(np.clip(x, -40, 40))),
                  (-2, 2), True),
    "elu": ({"alpha": 1.5},
            lambda x, a: np.where(x > 0, x, 1.5 * (np.exp(x) - 1)),
            (0.2, 2), True),
    "relu6": ({"threshold": 6.0}, lambda x, a: np.clip(x, 0, 6.0),
              (0.2, 2), True),
    "pow": ({"factor": 3.0}, lambda x, a: x ** 3.0, (0.5, 2), True),
    "stanh": ({"scale_a": 0.67, "scale_b": 1.7159},
              lambda x, a: 1.7159 * np.tanh(0.67 * x), (-2, 2), True),
    "hard_shrink": ({"threshold": 0.5},
                    lambda x, a: np.where(np.abs(x) > 0.5, x, 0.0),
                    (0.8, 2), False),
    "softshrink": ({"lambda": 0.5},
                   lambda x, a: np.sign(x) * np.maximum(np.abs(x) - 0.5, 0),
                   (0.8, 2), True),
    "thresholded_relu": ({"threshold": 1.0},
                         lambda x, a: np.where(x > 1.0, x, 0.0),
                         (1.2, 2), True),
    "hard_sigmoid": ({"slope": 0.2, "offset": 0.5},
                     lambda x, a: np.clip(0.2 * x + 0.5, 0, 1),
                     (-1.5, 1.5), False),
    "swish": ({"beta": 1.5}, lambda x, a: x * _sigmoid(1.5 * x),
              (-2, 2), True),
    "mish": ({}, lambda x, a: x * np.tanh(_softplus(x)), (-2, 2), True),
}


@pytest.mark.parametrize("op", sorted(UNARY))
def test_unary_output(op):
    attrs, ref, (lo, hi), _ = UNARY[op]
    x = _r(3, 4, lo=lo, hi=hi, seed=1)
    check_output(op, {"X": x}, attrs, {"Out": ref(x, attrs)},
                 rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize(
    "op", sorted(k for k, v in UNARY.items() if v[3]))
def test_unary_grad(op):
    attrs, _, (lo, hi), _ = UNARY[op]
    x = _r(2, 3, lo=lo, hi=hi, seed=2).astype(np.float32)
    check_grad(op, {"X": x}, attrs, wrt=["X"])


def test_prelu():
    x = _r(2, 4, lo=-2, hi=2, seed=3)
    alpha = np.asarray([0.25], np.float32)
    check_output("prelu", {"X": x, "Alpha": alpha}, {"mode": "all"},
                 {"Out": np.where(x > 0, x, 0.25 * x)})


# --------------------------------------------------------------------------
# elementwise binary / compare / logical (operators/elementwise_*.cc)
BINARY = {
    "elementwise_add": (np.add, (-2, 2), True),
    "elementwise_sub": (np.subtract, (-2, 2), True),
    "elementwise_mul": (np.multiply, (-2, 2), True),
    "elementwise_div": (np.divide, (0.5, 2), True),
    "elementwise_max": (np.maximum, (-2, 2), False),
    "elementwise_min": (np.minimum, (-2, 2), False),
    "elementwise_pow": (np.power, (0.5, 2), True),
}


@pytest.mark.parametrize("op", sorted(BINARY))
def test_binary_output(op):
    fn, (lo, hi), _ = BINARY[op]
    x = _r(3, 4, lo=lo, hi=hi, seed=4)
    y = _r(3, 4, lo=lo, hi=hi, seed=5)
    check_output(op, {"X": x, "Y": y}, {}, {"Out": fn(x, y)}, rtol=1e-4)


@pytest.mark.parametrize("op", sorted(k for k, v in BINARY.items() if v[2]))
def test_binary_grad(op):
    _, (lo, hi), _ = BINARY[op]
    x = _r(2, 3, lo=lo, hi=hi, seed=6)
    y = _r(2, 3, lo=lo, hi=hi, seed=7)
    check_grad(op, {"X": x, "Y": y}, {}, wrt=["X", "Y"])


def test_elementwise_axis_broadcast():
    # reference mid-dimension broadcast: Y [3] aligned to X [2,3,4] at axis=1
    x = _r(2, 3, 4, seed=8)
    y = _r(3, seed=9)
    check_output("elementwise_add", {"X": x, "Y": y}, {"axis": 1},
                 {"Out": x + y.reshape(1, 3, 1)})


def test_elementwise_int_ops():
    x = np.array([[7, 9], [4, 5]], np.int32)
    y = np.array([[2, 4], [3, 2]], np.int32)
    check_output("elementwise_mod", {"X": x, "Y": y}, {}, {"Out": x % y})
    check_output("elementwise_floordiv", {"X": x, "Y": y}, {},
                 {"Out": x // y})


COMPARE = {
    "less_than": np.less, "less_equal": np.less_equal,
    "greater_than": np.greater, "greater_equal": np.greater_equal,
    "equal": np.equal, "not_equal": np.not_equal,
}


@pytest.mark.parametrize("op", sorted(COMPARE))
def test_compare_output(op):
    x = np.array([[1, 2, 3], [4, 5, 6]], np.int32)
    y = np.array([[1, 3, 2], [4, 4, 7]], np.int32)
    check_output(op, {"X": x, "Y": y}, {}, {"Out": COMPARE[op](x, y)})


LOGICAL = {"logical_and": np.logical_and, "logical_or": np.logical_or,
           "logical_xor": np.logical_xor}


@pytest.mark.parametrize("op", sorted(LOGICAL))
def test_logical_output(op):
    x = np.array([True, True, False, False])
    y = np.array([True, False, True, False])
    check_output(op, {"X": x, "Y": y}, {}, {"Out": LOGICAL[op](x, y)})


def test_logical_not():
    x = np.array([True, False])
    check_output("logical_not", {"X": x}, {}, {"Out": ~x})


# --------------------------------------------------------------------------
# reductions (operators/reduce_op.cc)
REDUCE = {
    "reduce_sum": (np.sum, True), "reduce_mean": (np.mean, True),
    "reduce_max": (np.max, True), "reduce_min": (np.min, False),
    "reduce_prod": (np.prod, True),
}


@pytest.mark.parametrize("op", sorted(REDUCE))
def test_reduce_output(op):
    fn, _ = REDUCE[op]
    x = _r(2, 3, 4, lo=0.5, hi=2, seed=10)
    check_output(op, {"X": x}, {"dim": [1]}, {"Out": fn(x, axis=1)},
                 rtol=1e-4)
    check_output(op, {"X": x}, {"dim": [1], "keep_dim": True},
                 {"Out": fn(x, axis=1, keepdims=True)}, rtol=1e-4)
    check_output(op, {"X": x}, {"reduce_all": True},
                 {"Out": np.asarray(fn(x))}, rtol=1e-4)
    check_output(op, {"X": x}, {"dim": [-1]}, {"Out": fn(x, axis=-1)},
                 rtol=1e-4)


@pytest.mark.parametrize("op", sorted(k for k, v in REDUCE.items() if v[1]))
def test_reduce_grad(op):
    # distinct values keep max/min grads unambiguous
    x = (np.arange(12, dtype=np.float32).reshape(3, 4) / 7.0) + 0.5
    check_grad(op, {"X": x}, {"dim": [1]}, wrt=["X"])


# --------------------------------------------------------------------------
# core math (operators/{mul,matmul,sum,mean,scale,clip,...}_op.cc)
def test_mul():
    x, y = _r(3, 4, seed=11), _r(4, 5, seed=12)
    check_output("mul", {"X": x, "Y": y}, {}, {"Out": x @ y}, rtol=1e-4)
    check_grad("mul", {"X": x, "Y": y}, {}, wrt=["X", "Y"])


def test_mul_num_col_dims():
    x = _r(2, 3, 4, seed=13)   # x_num_col_dims=2 -> [6, 4]
    y = _r(4, 5, seed=14)
    want = (x.reshape(6, 4) @ y).reshape(2, 3, 5)
    check_output("mul", {"X": x, "Y": y}, {"x_num_col_dims": 2},
                 {"Out": want}, rtol=1e-4)


def test_matmul_flags():
    x, y = _r(3, 4, seed=15), _r(5, 4, seed=16)
    check_output("matmul", {"X": x, "Y": y}, {"transpose_Y": True},
                 {"Out": x @ y.T}, rtol=1e-4)
    x2, y2 = _r(4, 3, seed=17), _r(4, 5, seed=18)
    check_output("matmul", {"X": x2, "Y": y2}, {"transpose_X": True},
                 {"Out": x2.T @ y2}, rtol=1e-4)
    # batched + alpha
    xb, yb = _r(2, 3, 4, seed=19), _r(2, 4, 5, seed=20)
    check_output("matmul", {"X": xb, "Y": yb}, {"alpha": 2.0},
                 {"Out": 2.0 * np.einsum("bij,bjk->bik", xb, yb)}, rtol=1e-4)


def test_sum_multi_input():
    xs = [_r(2, 3, seed=s) for s in (21, 22, 23)]
    check_output("sum", {"X": xs}, {}, {"Out": xs[0] + xs[1] + xs[2]})


def test_mean():
    x = _r(3, 4, seed=24)
    check_output("mean", {"X": x}, {}, {"Out": np.asarray(np.mean(x))})
    check_grad("mean", {"X": x}, {}, wrt=["X"])


def test_scale():
    x = _r(3, 4, seed=25)
    check_output("scale", {"X": x}, {"scale": 2.0, "bias": 1.0},
                 {"Out": 2 * x + 1})
    check_output("scale", {"X": x},
                 {"scale": 2.0, "bias": 1.0, "bias_after_scale": False},
                 {"Out": 2 * (x + 1)})


def test_clip():
    x = _r(3, 4, lo=-3, hi=3, seed=26)
    check_output("clip", {"X": x}, {"min": -1.0, "max": 1.5},
                 {"Out": np.clip(x, -1, 1.5)})


def test_clip_by_norm():
    x = _r(3, 4, lo=1, hi=2, seed=27)
    n = np.sqrt((x ** 2).sum())
    check_output("clip_by_norm", {"X": x}, {"max_norm": 1.0},
                 {"Out": x / n}, rtol=1e-4)
    check_output("clip_by_norm", {"X": x}, {"max_norm": float(n + 5)},
                 {"Out": x})


def test_cumsum():
    x = _r(3, 4, seed=28)
    check_output("cumsum", {"X": x}, {"axis": 1},
                 {"Out": np.cumsum(x, axis=1)}, rtol=1e-4)
    rev = np.flip(np.cumsum(np.flip(x, 1), axis=1), 1)
    check_output("cumsum", {"X": x}, {"axis": 1, "reverse": True},
                 {"Out": rev}, rtol=1e-4)


def test_norm_ops():
    x = _r(3, 4, lo=0.5, hi=2, seed=29)
    check_output("l1_norm", {"X": x}, {},
                 {"Out": np.asarray(np.abs(x).sum())}, rtol=1e-4)
    check_output("squared_l2_norm", {"X": x}, {},
                 {"Out": np.asarray((x ** 2).sum())}, rtol=1e-4)
    check_grad("squared_l2_norm", {"X": x}, {}, wrt=["X"])
    nrm = np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    check_output("norm", {"X": x}, {"axis": 1, "epsilon": 1e-10},
                 {"Out": x / nrm, "Norm": nrm}, rtol=1e-4)


def test_squared_l2_distance():
    x, y = _r(3, 4, seed=30), _r(3, 4, seed=31)
    d = x - y
    check_output("squared_l2_distance", {"X": x, "Y": y}, {},
                 {"Out": (d ** 2).sum(1, keepdims=True), "sub_result": d},
                 rtol=1e-4)
    check_grad("squared_l2_distance", {"X": x, "Y": y}, {}, wrt=["X", "Y"])


def test_cos_sim():
    x, y = _r(3, 4, lo=0.5, hi=2, seed=32), _r(3, 4, lo=0.5, hi=2, seed=33)
    xn = np.sqrt((x ** 2).sum(1, keepdims=True))
    yn = np.sqrt((y ** 2).sum(1, keepdims=True))
    want = (x * y).sum(1, keepdims=True) / (xn * yn + 1e-12)
    check_output("cos_sim", {"X": x, "Y": y}, {},
                 {"Out": want, "XNorm": xn, "YNorm": yn}, rtol=1e-4)
    check_grad("cos_sim", {"X": x, "Y": y}, {}, wrt=["X", "Y"])


def test_bilinear_tensor_product():
    x, y = _r(3, 4, seed=34), _r(3, 5, seed=35)
    w = _r(2, 4, 5, seed=36)
    want = np.einsum("bm,omn,bn->bo", x, w, y)
    check_output("bilinear_tensor_product",
                 {"X": x, "Y": y, "Weight": w}, {}, {"Out": want}, rtol=1e-4)


def test_top_k():
    x = np.array([[0.1, 0.9, 0.5], [0.7, 0.2, 0.8]], np.float32)
    got = run_op("top_k", {"X": x}, {"k": 2}, ["Out", "Indices"])
    np.testing.assert_allclose(np.asarray(got["Out"]),
                               [[0.9, 0.5], [0.8, 0.7]])
    np.testing.assert_array_equal(np.asarray(got["Indices"]),
                                  [[1, 2], [2, 0]])


def test_arg_max_min():
    x = _r(3, 5, seed=37)
    check_output("arg_max", {"X": x}, {"axis": 1},
                 {"Out": np.argmax(x, 1).astype(np.int32)})
    check_output("arg_min", {"X": x}, {"axis": 0},
                 {"Out": np.argmin(x, 0).astype(np.int32)})


def test_minus():
    x, y = _r(3, 4, seed=38), _r(3, 4, seed=39)
    check_output("minus", {"X": x, "Y": y}, {}, {"Out": x - y})


def test_conv_shift():
    x, y = _r(2, 7, seed=40), _r(2, 3, seed=41)
    m, n = 7, 3
    half = n // 2
    want = np.zeros_like(x)
    for b in range(2):
        for i in range(m):
            for j in range(n):
                want[b, i] += x[b, (i + j - half) % m] * y[b, j]
    check_output("conv_shift", {"X": x, "Y": y}, {}, {"Out": want},
                 rtol=1e-4)


# --------------------------------------------------------------------------
# tensor manipulation (operators/{concat,split,reshape,...}_op.cc)
def test_fill_constant():
    check_output("fill_constant", {}, {"shape": [2, 3], "value": 1.5},
                 {"Out": np.full((2, 3), 1.5, np.float32)})
    check_output("fill_constant", {},
                 {"shape": [2], "value": 3, "dtype": "int32"},
                 {"Out": np.full((2,), 3, np.int32)})


def test_fill_constant_batch_size_like():
    ref = _r(5, 2, seed=42)
    check_output("fill_constant_batch_size_like", {"Input": ref},
                 {"shape": [3, 4], "value": 0.5},
                 {"Out": np.full((5, 4), 0.5, np.float32)})


def test_fill_like_ops():
    x = _r(2, 3, seed=43)
    check_output("fill_zeros_like", {"X": x}, {}, {"Out": np.zeros_like(x)})
    check_output("fill_any_like", {"X": x}, {"value": 2.5},
                 {"Out": np.full_like(x, 2.5)})


def test_assign_ops():
    x = _r(2, 3, seed=44)
    check_output("assign", {"X": x}, {}, {"Out": x})
    check_output("assign_value", {},
                 {"shape": [2, 2], "values": [1.0, 2.0, 3.0, 4.0]},
                 {"Out": np.array([[1, 2], [3, 4]], np.float32)})


def test_cast():
    x = _r(2, 3, lo=0, hi=5, seed=45)
    check_output("cast", {"X": x}, {"out_dtype": "int32"},
                 {"Out": x.astype(np.int32)})


def test_concat():
    xs = [_r(2, s, seed=46 + s) for s in (2, 3, 4)]
    check_output("concat", {"X": xs}, {"axis": 1},
                 {"Out": np.concatenate(xs, axis=1)})


def test_split():
    x = _r(2, 6, seed=50)
    check_output("split", {"X": x}, {"axis": 1, "sections": [1, 2, 3]},
                 {"Out": [x[:, :1], x[:, 1:3], x[:, 3:]]})
    check_output("split", {"X": x}, {"axis": 1, "num": 3},
                 {"Out": [x[:, :2], x[:, 2:4], x[:, 4:]]})


def test_reshape_ops():
    x = _r(2, 6, seed=51)
    for op in ("reshape", "reshape2"):
        check_output(op, {"X": x}, {"shape": [3, 4]},
                     {"Out": x.reshape(3, 4)})
        check_output(op, {"X": x}, {"shape": [-1, 2]},
                     {"Out": x.reshape(6, 2)})


def test_squeeze_unsqueeze():
    x = _r(2, 1, 3, seed=52)
    check_output("squeeze", {"X": x}, {"axes": [1]},
                 {"Out": x.reshape(2, 3)})
    y = _r(2, 3, seed=53)
    check_output("unsqueeze", {"X": y}, {"axes": [1]},
                 {"Out": y.reshape(2, 1, 3)})


def test_transpose_ops():
    x = _r(2, 3, 4, seed=54)
    for op in ("transpose", "transpose2"):
        check_output(op, {"X": x}, {"axis": [2, 0, 1]},
                     {"Out": np.transpose(x, (2, 0, 1))})


def test_expand():
    x = _r(2, 3, seed=55)
    check_output("expand", {"X": x}, {"expand_times": [2, 3]},
                 {"Out": np.tile(x, (2, 3))})


def test_stack_unstack():
    xs = [_r(2, 3, seed=56 + i) for i in range(3)]
    check_output("stack", {"X": xs}, {"axis": 1},
                 {"Y": np.stack(xs, axis=1)})
    x = np.stack(xs, axis=0)
    check_output("unstack", {"X": x}, {"axis": 0}, {"Y": xs})


def test_gather_scatter():
    x = _r(5, 3, seed=60)
    idx = np.array([0, 3, 1], np.int32)
    check_output("gather", {"X": x, "Index": idx}, {}, {"Out": x[idx]})
    check_grad("gather", {"X": x, "Index": idx}, {}, wrt=["X"])

    upd = _r(2, 3, seed=61)
    ids = np.array([1, 4], np.int32)
    want = x.copy()
    want[ids] = upd
    check_output("scatter", {"X": x, "Ids": ids, "Updates": upd},
                 {"overwrite": True}, {"Out": want})
    want2 = x.copy()
    want2[1] += upd[0]
    want2[4] += upd[1]
    check_output("scatter", {"X": x, "Ids": ids, "Updates": upd},
                 {"overwrite": False}, {"Out": want2}, rtol=1e-5)


def test_one_hot():
    x = np.array([0, 2, 1], np.int32)
    want = np.eye(4, dtype=np.float32)[x]
    check_output("one_hot", {"X": x}, {"depth": 4}, {"Out": want})


def test_pad_ops():
    x = _r(2, 3, seed=62)
    check_output("pad", {"X": x},
                 {"paddings": [1, 0, 0, 2], "pad_value": 9.0},
                 {"Out": np.pad(x, ((1, 0), (0, 2)), constant_values=9.0)})
    big = _r(4, 5, seed=63)
    small = _r(2, 3, seed=64)
    check_output("pad_constant_like", {"X": big, "Y": small},
                 {"pad_value": 0.0},
                 {"Out": np.pad(small, ((0, 2), (0, 2)))})


def test_crop():
    x = _r(4, 5, seed=65)
    check_output("crop", {"X": x}, {"offsets": [1, 2], "shape": [2, 2]},
                 {"Out": x[1:3, 2:4]})


def test_slice():
    x = _r(4, 5, seed=66)
    check_output("slice", {"Input": x},
                 {"axes": [0, 1], "starts": [1, 0], "ends": [3, 4]},
                 {"Out": x[1:3, 0:4]})


def test_shape_op():
    x = _r(3, 7, seed=67)
    check_output("shape", {"Input": x}, {},
                 {"Out": np.array([3, 7], np.int32)})


def test_increment():
    x = np.array([3], np.int32)
    check_output("increment", {"X": x}, {"step": 2.0},
                 {"Out": np.array([5], np.int32)})


def test_multiplex():
    xs = [_r(3, 4, seed=70 + i) for i in range(2)]
    ids = np.array([[1], [0], [1]], np.int32)
    want = np.stack([xs[1][0], xs[0][1], xs[1][2]])
    check_output("multiplex", {"X": xs, "Ids": ids}, {}, {"Out": want})


def test_label_smooth():
    x = np.eye(4, dtype=np.float32)[np.array([0, 2])]
    eps = 0.1
    check_output("label_smooth", {"X": x}, {"epsilon": eps},
                 {"Out": (1 - eps) * x + eps / 4})


def test_is_empty():
    check_output("is_empty", {"X": np.zeros((0, 3), np.float32)}, {},
                 {"Out": np.asarray(True)})
    check_output("is_empty", {"X": np.zeros((2, 3), np.float32)}, {},
                 {"Out": np.asarray(False)})


def test_linspace():
    check_output("linspace", {}, {"start": 0.0, "stop": 1.0, "num": 5},
                 {"Out": np.linspace(0, 1, 5).astype(np.float32)})


def test_sequence_mask_op():
    x = np.array([2, 4, 1], np.int32)
    want = (np.arange(5)[None, :] < x[:, None]).astype(np.float32)
    check_output("sequence_mask", {"X": x}, {"maxlen": 5}, {"Y": want})


def test_lookup_table():
    w = _r(6, 4, seed=72)
    ids = np.array([[1], [4], [2]], np.int64)
    check_output("lookup_table", {"W": w, "Ids": ids}, {},
                 {"Out": w[ids.reshape(-1)]})
    # padding_idx rows read as zero
    want = w[ids.reshape(-1)].copy()
    want[1] = 0
    check_output("lookup_table", {"W": w, "Ids": ids}, {"padding_idx": 4},
                 {"Out": want})


# --------------------------------------------------------------------------
# random ops — distribution moments, not exact values
def test_uniform_random():
    got = run_op("uniform_random", {},
                 {"shape": [4000], "min": -2.0, "max": 2.0}, ["Out"])
    v = np.asarray(got["Out"])
    assert v.shape == (4000,) and v.dtype == np.float32
    assert v.min() >= -2 and v.max() <= 2
    assert abs(v.mean()) < 0.15


def test_gaussian_random():
    got = run_op("gaussian_random", {},
                 {"shape": [4000], "mean": 1.0, "std": 2.0}, ["Out"])
    v = np.asarray(got["Out"])
    assert abs(v.mean() - 1.0) < 0.2 and abs(v.std() - 2.0) < 0.2


def test_truncated_gaussian_random():
    got = run_op("truncated_gaussian_random", {},
                 {"shape": [2000], "mean": 0.0, "std": 1.0}, ["Out"])
    v = np.asarray(got["Out"])
    assert np.abs(v).max() <= 2.0 + 1e-5


def test_random_batch_size_like():
    ref = _r(7, 2, seed=73)
    for op in ("uniform_random_batch_size_like",
               "gaussian_random_batch_size_like"):
        got = run_op(op, {"Input": ref}, {"shape": [3, 5]}, ["Out"])
        assert np.asarray(got["Out"]).shape == (7, 5)


# --------------------------------------------------------------------------
# losses (operators/*_loss_op.cc, cross_entropy, nce)
def test_cross_entropy_hard():
    p = _r(3, 4, lo=0.1, hi=1, seed=74)
    p = p / p.sum(1, keepdims=True)
    label = np.array([[0], [2], [1]], np.int64)
    want = -np.log(p[np.arange(3), label.reshape(-1)]).reshape(3, 1)
    check_output("cross_entropy", {"X": p, "Label": label}, {}, {"Y": want},
                 rtol=1e-4)


def test_cross_entropy_soft():
    p = _r(3, 4, lo=0.1, hi=1, seed=75)
    p = p / p.sum(1, keepdims=True)
    lab = _r(3, 4, lo=0.1, hi=1, seed=76)
    lab = lab / lab.sum(1, keepdims=True)
    want = -(lab * np.log(p)).sum(1, keepdims=True)
    check_output("cross_entropy", {"X": p, "Label": lab},
                 {"soft_label": True}, {"Y": want}, rtol=1e-4)


def test_softmax_with_cross_entropy():
    logits = _r(3, 5, lo=-2, hi=2, seed=77)
    label = np.array([[1], [0], [4]], np.int64)
    e = np.exp(logits - logits.max(1, keepdims=True))
    sm = e / e.sum(1, keepdims=True)
    want = -np.log(sm[np.arange(3), label.reshape(-1)]).reshape(3, 1)
    check_output("softmax_with_cross_entropy",
                 {"Logits": logits, "Label": label}, {},
                 {"Loss": want, "Softmax": sm}, rtol=1e-4)
    check_grad("softmax_with_cross_entropy",
               {"Logits": logits, "Label": label}, {}, wrt=["Logits"],
               out="Loss", out_slots=["Loss", "Softmax"])


def test_sigmoid_cross_entropy_with_logits():
    x = _r(3, 4, lo=-2, hi=2, seed=78)
    z = (_r(3, 4, seed=79) > 0.5).astype(np.float32)
    want = np.maximum(x, 0) - x * z + np.log1p(np.exp(-np.abs(x)))
    check_output("sigmoid_cross_entropy_with_logits",
                 {"X": x, "Label": z}, {}, {"Out": want}, rtol=1e-4)
    check_grad("sigmoid_cross_entropy_with_logits",
               {"X": x, "Label": z}, {}, wrt=["X"])


def test_hinge_loss():
    logits = _r(4, 1, lo=-2, hi=2, seed=80)
    labels = (_r(4, 1, seed=81) > 0.5).astype(np.float32)
    want = np.maximum(0, 1 - (2 * labels - 1) * logits)
    check_output("hinge_loss", {"Logits": logits, "Labels": labels}, {},
                 {"Loss": want})


def test_huber_loss():
    x, y = _r(4, 1, seed=82), _r(4, 1, lo=0, hi=3, seed=83)
    d = 1.0
    r = y - x
    want = np.where(np.abs(r) <= d, 0.5 * r * r, d * (np.abs(r) - 0.5 * d))
    check_output("huber_loss", {"X": x, "Y": y}, {"delta": d},
                 {"Out": want, "Residual": r}, rtol=1e-4)


def test_log_loss():
    p = _r(4, 1, lo=0.1, hi=0.9, seed=84)
    lab = (_r(4, 1, seed=85) > 0.5).astype(np.float32)
    eps = 1e-4
    want = -lab * np.log(p + eps) - (1 - lab) * np.log(1 - p + eps)
    check_output("log_loss", {"Predicted": p, "Labels": lab},
                 {"epsilon": eps}, {"Loss": want}, rtol=1e-4)


def test_smooth_l1_loss():
    x, y = _r(3, 4, seed=86), _r(3, 4, seed=87)
    d = x - y
    elem = np.where(np.abs(d) < 1.0, 0.5 * d * d, np.abs(d) - 0.5)
    want = elem.sum(1, keepdims=True)
    check_output("smooth_l1_loss", {"X": x, "Y": y}, {"sigma": 1.0},
                 {"Out": want, "Diff": d}, rtol=1e-4)


def test_rank_loss():
    lab = (_r(4, 1, seed=88) > 0.5).astype(np.float32)
    left, right = _r(4, 1, seed=89), _r(4, 1, seed=90)
    d = left - right
    want = np.maximum(d, 0) - d * lab + np.log1p(np.exp(-np.abs(d)))
    check_output("rank_loss",
                 {"Label": lab, "Left": left, "Right": right}, {},
                 {"Out": want}, rtol=1e-4)


def test_margin_rank_loss():
    lab = np.sign(_r(4, 1, lo=-1, hi=1, seed=91)).astype(np.float32)
    x1, x2 = _r(4, 1, seed=92), _r(4, 1, seed=93)
    want = np.maximum(0, -lab * (x1 - x2) + 0.1)
    check_output("margin_rank_loss",
                 {"Label": lab, "X1": x1, "X2": x2}, {"margin": 0.1},
                 {"Out": want}, rtol=1e-4)


def test_modified_huber_loss():
    x = _r(4, 1, lo=-2, hi=2, seed=94)
    y = (_r(4, 1, seed=95) > 0.5).astype(np.float32)
    z = (2 * y - 1) * x
    want = np.where(z < -1, -4 * z, np.maximum(0, 1 - z) ** 2)
    check_output("modified_huber_loss", {"X": x, "Y": y}, {},
                 {"Out": want, "IntermediateVal": z}, rtol=1e-4)


def test_nce_shapes():
    # stochastic negatives: check shape + positivity, not exact values
    x = _r(4, 3, seed=96)
    label = np.array([[1], [0], [2], [1]], np.int64)
    w, b = _r(5, 3, seed=97), _r(5, seed=98)
    got = run_op("nce", {"Input": x, "Label": label, "Weight": w, "Bias": b},
                 {"num_neg_samples": 3, "num_total_classes": 5},
                 ["Cost", "SampleLogits", "SampleLabels"])
    cost = np.asarray(got["Cost"])
    assert cost.shape == (4, 1) and (cost > 0).all()
    assert np.asarray(got["SampleLogits"]).shape == (4, 4)


# --------------------------------------------------------------------------
# nn ops (softmax/dropout/batch_norm/layer_norm/lrn/maxout)
def test_softmax_ops():
    x = _r(3, 5, lo=-2, hi=2, seed=99)
    e = np.exp(x - x.max(1, keepdims=True))
    sm = e / e.sum(1, keepdims=True)
    check_output("softmax", {"X": x}, {}, {"Out": sm}, rtol=1e-4)
    check_output("log_softmax", {"X": x}, {}, {"Out": np.log(sm)},
                 rtol=1e-4)
    check_grad("softmax", {"X": x}, {}, wrt=["X"])


def test_dropout():
    x = _r(4, 5, lo=1, hi=2, seed=100)
    # is_test -> identity under upscale_in_train
    check_output("dropout", {"X": x},
                 {"dropout_prob": 0.5,
                  "dropout_implementation": "upscale_in_train"},
                 {"Out": x}, is_test=True)
    # train mode: Out = X * Mask / keep; Mask in {0,1}
    got = run_op("dropout", {"X": _r(100, 10, lo=1, hi=2, seed=101)},
                 {"dropout_prob": 0.3,
                  "dropout_implementation": "upscale_in_train"},
                 ["Out", "Mask"])
    mask = np.asarray(got["Mask"])
    assert set(np.unique(mask)).issubset({0.0, 1.0})
    assert abs(mask.mean() - 0.7) < 0.05


def test_batch_norm_inference():
    x = _r(2, 3, 4, 4, seed=102)
    scale, bias = _r(3, seed=103), _r(3, seed=104)
    mean, var = _r(3, seed=105), _r(3, lo=0.5, hi=1.5, seed=106)
    eps = 1e-5
    want = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + eps)
    want = want * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
    check_output("batch_norm",
                 {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                  "Variance": var},
                 {"epsilon": eps, "data_layout": "NCHW"},
                 {"Y": want}, rtol=1e-4, atol=1e-5, is_test=True)


def test_layer_norm():
    x = _r(3, 8, seed=107)
    scale, bias = _r(8, seed=108), _r(8, seed=109)
    mu = x.mean(1, keepdims=True)
    var = x.var(1, keepdims=True)
    want = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
    check_output("layer_norm", {"X": x, "Scale": scale, "Bias": bias},
                 {"epsilon": 1e-5, "begin_norm_axis": 1},
                 {"Y": want}, rtol=1e-4, atol=1e-5)


def test_lrn():
    x = _r(2, 6, 3, 3, seed=110)
    n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
    sq = x ** 2
    mid = np.full_like(x, k)
    half = n // 2
    for c in range(6):
        lo, hi = max(0, c - half), min(6, c + half + 1)
        mid[:, c] += alpha * sq[:, lo:hi].sum(axis=1)
    check_output("lrn", {"X": x},
                 {"n": n, "k": k, "alpha": alpha, "beta": beta},
                 {"Out": x / mid ** beta, "MidOut": mid}, rtol=1e-4)


def test_maxout():
    x = _r(2, 6, 3, 3, seed=111)
    want = x.reshape(2, 3, 2, 3, 3).max(axis=2)
    check_output("maxout", {"X": x}, {"groups": 2}, {"Out": want})
