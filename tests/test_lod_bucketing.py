"""Flat-total LoD bucketing (core/executor._normalize_feeds): compile
signatures stay stable across batches with different token totals, while
reductions, NaN guards, and fetches see only the REAL rows."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _lod(arr, lengths):
    t = fluid.LoDTensor(np.asarray(arr))
    t.set_recursive_sequence_lengths([list(lengths)])
    return t


def test_bucketing_keeps_compile_signature_stable():
    x = fluid.layers.data("x", [2], lod_level=1)
    y = fluid.layers.fc(x, 3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    # totals 5, 6, 7 all bucket to 8 -> ONE compiled entry for all three
    n0 = len(exe._cache)
    for total, lens in ((5, [2, 3]), (6, [3, 3]), (7, [3, 4])):
        arr = np.random.rand(total, 2).astype(np.float32)
        out, = exe.run(feed={"x": _lod(arr, lens)}, fetch_list=[y])
        assert np.asarray(out).shape == (total, 3)   # trimmed to real rows
    assert len(exe._cache) == n0 + 1


def test_mean_over_bucketed_rows_is_exact():
    x = fluid.layers.data("x", [1], lod_level=1)
    m = fluid.layers.mean(x)
    exe = fluid.Executor(fluid.CPUPlace())
    arr = np.arange(1, 7, dtype=np.float32).reshape(6, 1)  # bucket pads to 8
    got, = exe.run(feed={"x": _lod(arr, [2, 4])}, fetch_list=[m])
    np.testing.assert_allclose(float(np.asarray(got)), arr.mean(),
                               rtol=1e-6)


def test_reduce_ops_mask_bucket_pad_rows():
    x = fluid.layers.data("x", [1], lod_level=1)
    s = fluid.layers.reduce_sum(x, dim=None, keep_dim=False) \
        if hasattr(fluid.layers, "reduce_sum") else None
    mx = fluid.layers.reduce_max(x)
    mn = fluid.layers.reduce_min(x)
    exe = fluid.Executor(fluid.CPUPlace())
    arr = -np.arange(1, 7, dtype=np.float32).reshape(6, 1)  # all negative
    feed = {"x": _lod(arr, [3, 3])}
    got_max, got_min = exe.run(feed=feed, fetch_list=[mx, mn])
    # zero pad rows must not win the max (all real values are negative)
    np.testing.assert_allclose(float(np.asarray(got_max).ravel()[0]), -1.0)
    np.testing.assert_allclose(float(np.asarray(got_min).ravel()[0]), -6.0)


def test_token_loss_pipeline_exact_under_bucketing():
    # the review scenario: mean(cross_entropy(...)) straight over flat rows
    x = fluid.layers.data("emb", [4], lod_level=1)
    label = fluid.layers.data("lbl", [1], dtype="int64", lod_level=1)
    pred = fluid.layers.fc(x, 5, act="softmax",
                           param_attr=fluid.ParamAttr(
                               initializer=fluid.initializer.Constant(0.1)))
    loss = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    arr = rng.rand(6, 4).astype(np.float32)        # pads to 8
    lbl = rng.randint(0, 5, (6, 1)).astype(np.int64)
    got, = exe.run(feed={"emb": _lod(arr, [2, 4]),
                         "lbl": _lod(lbl, [2, 4])}, fetch_list=[loss])
    # numpy reference over the REAL 6 rows only
    z = arr @ (np.full((4, 5), 0.1, np.float32))
    e = np.exp(z - z.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    want = float(np.mean(-np.log(p[np.arange(6), lbl.ravel()])))
    np.testing.assert_allclose(float(np.asarray(got)), want, rtol=1e-5)


def test_nan_guard_ignores_pad_rows(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CHECK_NAN_INF", "1")
    x = fluid.layers.data("x", [1], lod_level=1)
    # log of bucket-pad zeros is -inf but those rows are filler; real rows
    # are strictly positive -> must NOT raise
    out = fluid.layers.mean(
        fluid.default_main_program().current_block().var(x.name))
    prog = fluid.default_main_program()
    blk = prog.current_block()
    logv = blk.create_var(name="logx")
    blk.append_op("log", {"X": [x]}, {"Out": ["logx"]}, {})
    exe = fluid.Executor(fluid.CPUPlace())
    arr = np.ones((6, 1), np.float32)              # pads to 8 with zeros
    got = exe.run(feed={"x": _lod(arr, [3, 3])}, fetch_list=["logx", out])
    assert np.isfinite(np.asarray(got[0])).all()


def test_feed_parallel_splits_whole_sequences():
    x = fluid.layers.data("x", [1], dtype="int64", lod_level=1)
    d = fluid.layers.data("d", [2])
    feeder = fluid.DataFeeder([x, d], fluid.CPUPlace())
    batch = [([1, 2, 3], [0.0, 0.0]), ([4], [1.0, 1.0]),
             ([5, 6], [2.0, 2.0]), ([7, 8, 9], [3.0, 3.0])]
    outs = feeder.feed_parallel(batch, 2)
    assert len(outs) == 2
    p0, p1 = outs[0]["x"], outs[1]["x"]
    assert isinstance(p0, fluid.LoDTensor)
    assert p0.recursive_sequence_lengths() == [[3, 1]]
    assert p1.recursive_sequence_lengths() == [[2, 3]]
    np.testing.assert_array_equal(np.asarray(p0.data).ravel(),
                                  [1, 2, 3, 4])
    np.testing.assert_array_equal(np.asarray(p1.data).ravel(),
                                  [5, 6, 7, 8, 9])
    assert outs[0]["d"].shape == (2, 2) and outs[1]["d"].shape == (2, 2)


def test_accuracy_masks_bucket_pad_rows():
    pred = fluid.layers.data("pred", [3], lod_level=1)
    lbl = fluid.layers.data("lbl", [1], dtype="int64", lod_level=1)
    acc = fluid.layers.accuracy(pred, lbl)
    exe = fluid.Executor(fluid.CPUPlace())
    # 6 rows pad to 8; pad labels are 0 and pad argmax could hit class 0 —
    # they must count neither as correct nor in the total
    p = np.zeros((6, 3), np.float32)
    p[np.arange(6), [0, 1, 2, 0, 1, 2]] = 1.0        # argmax = pattern
    lab = np.array([[0], [1], [0], [0], [2], [2]], np.int64)  # 4 hits
    got, = exe.run(feed={"pred": _lod(p, [3, 3]), "lbl": _lod(lab, [3, 3])},
                   fetch_list=[acc])
    np.testing.assert_allclose(float(np.asarray(got).ravel()[0]), 4 / 6,
                               rtol=1e-6)


def test_reduce_max_keeps_integer_dtype_under_bucketing():
    x = fluid.layers.data("x", [1], dtype="int64", lod_level=1)
    mx = fluid.layers.reduce_max(x)
    exe = fluid.Executor(fluid.CPUPlace())
    arr = np.array([[-5], [-2], [-9], [-1], [-7], [-3]], np.int64)
    got, = exe.run(feed={"x": _lod(arr, [3, 3])}, fetch_list=[mx])
    got = np.asarray(got)
    assert got.dtype.kind == "i", got.dtype   # no silent float promotion
    assert int(got.ravel()[0]) == -1          # pad zeros must not win
