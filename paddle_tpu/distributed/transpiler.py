"""DistributeTranspiler: rewrite a Program for distributed training.

Reference parity: python/paddle/fluid/distribute_transpiler.py:138-1128.

Two modes:
  * ``mode="mesh"`` (default, TPU-idiomatic): no program surgery. The
    transpiler annotates sharding hints — dense params replicated over
    ``dp`` (gradient psum comes from GSPMD), ``is_distributed`` embedding
    tables row-sharded — and every trainer runs the SAME program under
    ParallelExecutor. This is the §7 mapping: pserver rounds become ICI
    collectives compiled into the step.
  * ``mode="pserver"`` (reference-compat): real program surgery. The
    trainer program gets send/send_barrier/recv ops; get_pserver_program
    builds a listen_and_serv program whose optimize sub-block applies the
    merged gradients — served by distributed/rpc.VariableServer over TCP
    (the DCN tier). Used for sparse-embedding service and the reference's
    localhost multi-process test pattern (test_dist_train.py).
"""

from ..core.program import (default_main_program, default_startup_program,
                            Program)
from ..core import unique_name

__all__ = ["DistributeTranspiler"]


def _clone_op_vars(src_block, dst_block, op):
    """Declare every var an op references into dst_block (persistable) so
    the cloned op can resolve them — shared by pserver/startup builders."""
    for name in op.input_names + op.output_names:
        v = src_block.vars.get(name)
        if v is not None and not dst_block.has_var(name):
            dst_block.create_var(name=name, shape=v.shape, dtype=v.dtype,
                                 persistable=True)


class DistributeTranspiler:
    def __init__(self, mode="pserver"):
        self.mode = mode
        self._trainer_id = 0
        self._trainers = 1
        self._eps = []
        self._program = None
        self._startup = None
        self._param_grads = []

    # ------------------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None):
        program = program or default_main_program()
        self._program = program
        self._startup = startup_program or default_startup_program()
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._eps = [e for e in pservers.split(",") if e]
        self._sync = sync_mode

        # find (param, grad) pairs from optimizer ops
        self._opt_ops = []
        self._param_grads = []
        for op in list(program.global_block().ops):
            if op.type in ("sgd", "momentum", "adam", "adagrad", "rmsprop",
                           "adamax", "adadelta", "ftrl", "decayed_adagrad"):
                p = op.input("Param")[0]
                g = op.input("Grad")[0]
                self._param_grads.append((p, g))
                self._opt_ops.append(op)

        if self.mode == "mesh":
            for p, _ in self._param_grads:
                program._sharding_hints.setdefault(p, None)
            for v in program.list_vars():
                if getattr(v, "is_distributed", False):
                    program._sharding_hints[v.name] = ("mp", None)
            return self

        # pserver mode: strip optimizer ops from the trainer program and
        # append send/barrier/recv (distribute_transpiler.py:257ff)
        gb = self._program.global_block()
        for op in self._opt_ops:
            gb.ops.remove(op)
        params = [p for p, _ in self._param_grads]
        grads = [g for _, g in self._param_grads]
        n = max(1, len(self._eps))
        epmap_g = [self._eps[i % n] for i in range(len(grads))]
        gb.append_op(type="send", inputs={"X": grads}, outputs={},
                     attrs={"epmap": epmap_g, "sync": self._sync,
                            "endpoints": self._eps})
        gb.append_op(type="recv", inputs={},
                     outputs={"Out": params},
                     attrs={"epmap": [self._eps[i % n]
                                      for i in range(len(params))],
                            "recv_names": params,
                            "endpoints": self._eps})
        self._program._bump_version()
        return self

    # ------------------------------------------------------------------
    def get_trainer_program(self):
        return self._program

    def get_pserver_program(self, endpoint, port_file=None):
        """Build the server program: one listen_and_serv op whose
        sub-block holds the optimizer ops for the params this endpoint
        owns (round-robin placement like distributed_splitter)."""
        prog = Program()
        gb = prog.global_block()
        my = self._owned(endpoint)

        opt_block = prog.create_block()
        src_gb = self._program.global_block()
        for i, (p, g) in my:
            op = self._opt_ops[i]
            _clone_op_vars(src_gb, gb, op)
            opt_block.append_op(op.type, dict(op.inputs), dict(op.outputs),
                                dict(op.attrs))
        prog.rollback()
        gb.append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint,
                   "Fanin": self._trainers,
                   "sync_mode": self._sync,
                   "param_names": [p for _, (p, g) in my],
                   "grad_names": [g for _, (p, g) in my],
                   "optimize_blocks": [opt_block],
                   "port_file": port_file,
                   "blocking": True})
        return prog

    def get_startup_program(self, endpoint=None, pserver_program=None):
        """Server startup: a Program that initializes exactly the params
        this endpoint owns, by cloning the matching initializer ops out of
        the trainer's startup program (distribute_transpiler.py
        get_startup_program per-endpoint init parity)."""
        owned = set(self._owned_param_names(endpoint))
        prog = Program()
        gb = prog.global_block()
        if self._startup is None:
            return prog
        src = self._startup.global_block()
        for op in src.ops:
            out_names = [n for ns in op.outputs.values() for n in ns]
            if not any(n in owned for n in out_names):
                continue
            _clone_op_vars(src, gb, op)
            gb.append_op(op.type, dict(op.inputs), dict(op.outputs),
                         dict(op.attrs))
        return prog

    def _owned(self, endpoint=None):
        """Round-robin param placement (distributed_splitter parity):
        [(index, (param, grad))] owned by `endpoint`. The single source of
        truth for placement — get_pserver_program and get_startup_program
        must agree or a server would init a shard it doesn't serve."""
        n = max(1, len(self._eps))
        if endpoint is None:
            if n > 1:
                raise ValueError(
                    "endpoint is required when transpiling for %d pservers"
                    " %r — each server owns a different param shard"
                    % (n, self._eps))
            my_idx = 0
        else:
            try:
                my_idx = self._eps.index(endpoint)
            except ValueError:
                raise ValueError(
                    "endpoint %r is not one of the transpiled pserver "
                    "endpoints %r" % (endpoint, self._eps))
        return [(i, pg) for i, pg in enumerate(self._param_grads)
                if i % n == my_idx]

    def _owned_param_names(self, endpoint=None):
        return [p for _, (p, g) in self._owned(endpoint)]
