"""Pipeline (GPipe) throughput + bubble-fraction benchmark.

Runs the pipelined decoder stack on a virtual pp mesh (CPU devices) and
measures tokens/sec as the microbatch count M grows, comparing the
throughput ratio against the GPipe theory: useful fraction
U(M) = M / (S + M - 1), so throughput(M) ≈ throughput(∞) · U(M).
Run with:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python benchmarks/pipeline_bench.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from paddle_tpu import parallel  # noqa: E402


def main(pp=4, d=256, d_inner=1024, t=64, mb=2, layers_per_stage=2,
         ms=(1, 2, 4, 8, 16)):
    mesh = parallel.make_mesh({"pp": pp})
    rng = np.random.RandomState(0)

    def mk(shape, scale):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)

    params = {
        "w1": mk((pp, layers_per_stage, d, d_inner), d ** -0.5),
        "w2": mk((pp, layers_per_stage, d_inner, d), d_inner ** -0.5),
    }

    def stage_fn(p, x):
        def body(carry, lp):
            h = jnp.maximum(carry @ lp["w1"], 0.0)
            return carry + h @ lp["w2"], None

        out, _ = lax.scan(body, x, p)
        return out

    results = {}
    for m in ms:
        xs = jnp.asarray(rng.randn(m, mb, t, d).astype(np.float32))

        def run(xs=xs):
            return parallel.gpipe(stage_fn, params, xs, mesh,
                                  axis_name="pp")

        jit_run = jax.jit(run)
        jax.block_until_ready(jit_run())          # compile
        n_rep = 3
        t0 = time.perf_counter()
        for _ in range(n_rep):
            jax.block_until_ready(jit_run())
        dt = (time.perf_counter() - t0) / n_rep
        toks = m * mb * t
        results[m] = toks / dt
        print("M=%2d  %8.0f tok/s  (%.1f ms/step)"
              % (m, toks / dt, dt * 1000))

    # bubble analysis: throughput(M) / throughput(M_max) vs U(M)/U(M_max)
    m_max = max(ms)
    print("\nGPipe bubble check (S=%d): measured vs theory U(M)=M/(S+M-1)"
          % pp)
    for m in ms:
        meas = results[m] / results[m_max]
        theo = (m / (pp + m - 1)) / (m_max / (pp + m_max - 1))
        print("M=%2d  measured ratio %.2f   theory %.2f" % (m, meas, theo))

    # interleaved virtual stages (the small-M 1F1B regime): V chunks per
    # device cut the fill to (S-1)/V chunk-times — theory
    # U_int(M) = M / (M + (S-1)/V) vs GPipe M / (M + S - 1)
    v = layers_per_stage                   # one layer per chunk
    L = pp * layers_per_stage

    def interleave(p):
        # stage-stacked [S, per, ...] -> global layer order [L, ...] ->
        # device d holds chunks {d, d+S, ...}: [V, S, 1, ...] -> [S, V, 1,
        # ...]
        flat = p.reshape((L,) + p.shape[2:])
        return flat.reshape((v, pp, 1) + p.shape[2:]).swapaxes(0, 1)

    inter = {"w1": interleave(params["w1"]),
             "w2": interleave(params["w2"])}
    print("\nInterleaved (V=%d chunks/device) vs GPipe at small M:" % v)
    for m in [mm for mm in ms if mm <= pp]:
        xs = jnp.asarray(rng.randn(m, mb, t, d).astype(np.float32))

        def run_i(xs=xs):
            return parallel.gpipe_interleaved(
                stage_fn, inter, xs, mesh, n_chunks=v, axis_name="pp")

        jit_i = jax.jit(run_i)
        jax.block_until_ready(jit_i())
        n_rep = 3
        t0 = time.perf_counter()
        for _ in range(n_rep):
            jax.block_until_ready(jit_i())
        dt = (time.perf_counter() - t0) / n_rep
        toks = m * mb * t
        speedup = (toks / dt) / results[m]
        theo = (m + pp - 1) / (m + (pp - 1) / v)
        print("M=%2d  %8.0f tok/s  %.2fx over GPipe  (theory %.2fx)"
              % (m, toks / dt, speedup, theo))
    return results


if __name__ == "__main__":
    main()
