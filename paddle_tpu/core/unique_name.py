"""Global unique-name generator.

Capability parity with the reference's python/paddle/fluid/unique_name.py
(UniqueNameGenerator + guard): every auto-created variable/op gets a
process-unique dotted name so Programs can be merged and cloned safely.
"""

import contextlib
import itertools
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.prefix = prefix
        self.ids = defaultdict(itertools.count)

    def __call__(self, key):
        return "%s%s_%d" % (self.prefix, key, next(self.ids[key]))


_generator = UniqueNameGenerator()


def generate(key):
    return _generator(key)


@contextlib.contextmanager
def guard(new_prefix=None):
    """Swap in a fresh generator (optionally prefixed) for a scope of code."""
    global _generator
    old = _generator
    _generator = UniqueNameGenerator(new_prefix or "")
    try:
        yield
    finally:
        _generator = old
