"""Perf regression gate: compare a bench.py JSON against a baseline.

The BENCH_r01..r06.json records checked into the repo are a perf
HISTORY; this module makes them a GATE — ``python -m
paddle_tpu.perfgate current.json`` compares the current round's
probes against the newest baseline round with an explicit noise band
per probe, and exits 0 (pass) / 1 (regression) / 2 (bad input) per
the analysis/slo CLI convention, so CI or a chip-round driver can
fail a build on a real throughput loss without flapping on host
noise. ``bench.py`` stamps the same verdict vs the previous round
into its own output.

Comparison rules (the part a naive differ gets wrong):

  * every probe carries a DIRECTION (tokens/s regress when they
    FALL; ms/batch when they RISE) and an explicit default noise
    band (%%) sized from the measured round-to-round spreads in
    PERF.md — the sandbox tunnel drifts ±30%% on some probes,
  * when either side stamped a measured spread (``*_spread_pct``
    from the interleaved A/B protocol), the band widens to it —
    a delta smaller than the run's own spread is noise by
    definition,
  * some probes are percentage-POINT values around zero (router
    overhead); those use an absolute band, not a relative one,
  * a probe missing or null on either side is SKIPPED with a reason
    (a config that failed its repeats must not read as a
    regression), and rounds from different PLATFORMS never compare
    (a CPU rehearsal round vs a chip round would scream regression
    on every probe),
  * an UNSTAMPED round (no ``platform`` field — the pre-r06 records)
    is the one platform-AMBIGUOUS pairing: the mismatch guard cannot
    fire, so a chip round could silently compare against a CPU
    baseline. The CLI warns loudly whenever either side lacks the
    stamp, and ``--require-platform-stamp`` turns that warning into
    exit 1 — the chip round's CI should pass it.

CLI::

    python -m paddle_tpu.perfgate current.json baseline.json [--json]
    python -m paddle_tpu.perfgate current.json --baseline-dir .
                          # newest BENCH_r*.json in the dir
    python -m paddle_tpu.perfgate current.json current.json
                          # self-compare: always exit 0 (sanity)
"""

import argparse
import glob
import json
import os
import re
import sys

__all__ = ["PROBES", "Probe", "load_result", "latest_baseline",
           "compare", "render", "main"]


class Probe:
    """One gated figure: where it lives in the bench JSON, which way
    is better, and how much round-over-round movement is noise."""

    def __init__(self, name, path, direction="higher", band_pct=15.0,
                 spread_path=None, band_abs=None):
        assert direction in ("higher", "lower")
        self.name = name
        self.path = tuple(path)
        self.direction = direction
        self.band_pct = float(band_pct)
        self.spread_path = tuple(spread_path) if spread_path else None
        self.band_abs = band_abs      # absolute units (pct-point probes)

    def get(self, result, path=None):
        cur = result
        for k in (path if path is not None else self.path):
            if not isinstance(cur, dict):
                return None
            cur = cur.get(k)
        return cur if isinstance(cur, (int, float)) else None


# Default bands come from the measured interleaved-window spreads of
# BENCH_r04..r06 / PERF.md: chip-headline configs sit well under 10%,
# CPU-pinned host probes drift 10-30% on this 1-core container.
PROBES = (
    Probe("resnet_imgs_per_sec", ("value",), "higher", 10.0,
          ("spread_pct",)),
    Probe("transformer_small_tok_s",
          ("transformer_tokens_per_sec_per_chip",), "higher", 15.0),
    Probe("transformer_large_tok_s",
          ("transformer_large_tokens_per_sec_per_chip",), "higher",
          10.0, ("transformer_large_spread_pct",)),
    Probe("transformer_xl_tok_s",
          ("transformer_xl_tokens_per_sec_per_chip",), "higher",
          10.0, ("transformer_xl_spread_pct",)),
    Probe("lstm_ms_per_batch", ("lstm_ms_per_batch",), "lower",
          10.0, ("lstm_spread_pct",)),
    Probe("monitor_step_p50_ms", ("monitor", "p50_ms"), "lower",
          30.0),
    Probe("serving_tok_s", ("serving", "value"), "higher", 30.0),
    Probe("serving_speedup", ("serving", "speedup"), "higher", 20.0),
    Probe("serving_megastep_bs1_speedup",
          ("serving", "megastep_bs1_speedup"), "higher", 25.0),
    Probe("serving_prefix_speedup", ("serving", "prefix_speedup"),
          "higher", 25.0),
    # speculative-decode probes (ISSUE 13): the verified-tokens-per-
    # scoring-dispatch multiplication (the figure a chip converts to
    # wall time at the dispatch floor), the acceptance rates of the
    # two drafting regimes, and the bs1-floor wall A/B (on THIS CPU
    # container the γ+1-position scoring compute is not free, so the
    # wall ratio sits below 1 — the gate holds it from regressing and
    # the chip round is where it flips; missing-on-baseline skips
    # keep rounds r01-r06 comparable)
    Probe("serving_spec_tok_per_dispatch",
          ("serving", "accepted_tokens_per_dispatch"), "higher",
          25.0),
    Probe("serving_spec_bs1_speedup",
          ("serving", "spec_bs1_speedup"), "higher", 25.0,
          ("serving", "spec_bs1_spread_pct")),
    Probe("serving_spec_shared_accept_rate",
          ("serving", "spec_shared_accept_rate"), "higher", 30.0),
    Probe("serving_spec_natural_accept_rate",
          ("serving", "spec_natural_accept_rate"), "higher", 30.0),
    Probe("megastep_k1_tok_s", ("megastep", "k1_tok_s"), "higher",
          20.0, ("megastep", "k1_spread_pct")),
    Probe("megastep_k8_tok_s", ("megastep", "k8_tok_s"), "higher",
          20.0, ("megastep", "k8_spread_pct")),
    Probe("megastep_speedup", ("megastep", "speedup"), "higher",
          15.0),
    Probe("fleet_router_overhead_pct",
          ("fleet", "router_overhead_pct"), "lower", 15.0,
          band_abs=10.0),
    # recsys sparse-serving probe (ISSUE 12): warm-cache scoring
    # throughput + the warm/cold ratio the hot-ID cache buys, plus
    # the routed-vs-direct front-door overhead (pct points around
    # zero -> absolute band, like the fleet probe)
    Probe("recsys_warm_rps", ("recsys", "warm_rps"), "higher", 30.0,
          ("recsys", "warm_spread_pct")),
    Probe("recsys_warm_over_cold", ("recsys", "warm_over_cold"),
          "higher", 25.0),
    Probe("recsys_router_overhead_pct",
          ("recsys", "router_overhead_pct"), "lower", 15.0,
          band_abs=10.0),
    # inference-specialization probes (ISSUE 15): the artifact-booted
    # engine's serving tok/s must not regress vs prior rounds (the
    # source-engine A/B rides the same stamp), the artifact cold-boot
    # wall must stay bounded (the direction-2 replica-respawn cost),
    # and the zoo-wide fusion hit count is a deterministic coverage
    # floor — fewer hits means a pattern stopped matching. Missing on
    # pre-15 baselines -> skip, like the spec/recsys probes
    Probe("specialize_art_tok_s", ("specialize", "artifact_tok_s"),
          "higher", 30.0, ("specialize", "artifact_spread_pct")),
    Probe("specialize_boot_s", ("specialize", "artifact_boot_s"),
          "lower", 50.0),
    Probe("specialize_zoo_fused", ("specialize", "zoo_fused_total"),
          "higher", 5.0),
    # elastic-fleet probes (ISSUE 18): the autoscale control loop's
    # serving-path overhead is pct points around zero -> absolute
    # band like the router-overhead probes; the roll wall clock and
    # the shed-during-roll count guard the rolling-update contract
    # (shed band 0: ANY shed during a roll is a regression, not
    # noise). Missing on pre-18 baselines -> skip
    Probe("autoscale_overhead_pct",
          ("autoscale", "overhead_pct"), "lower", 15.0,
          band_abs=10.0),
    Probe("autoscale_roll_s", ("autoscale", "roll_s"), "lower",
          50.0),
    Probe("autoscale_roll_shed", ("autoscale", "roll_shed"),
          "lower", 0.0, band_abs=0.0),
    # block-kernel probes (ISSUE 20): the large-capacity step-time
    # speedup of the chain-walk kernel over the dense gather, the
    # capacity-scaling flatness ratio (how much faster gather grows
    # with pool capacity than the block kernel — the acceptance
    # figure), and the int8-KV arm's speedup. Missing on pre-20
    # baselines -> skip, like every probe introduced mid-history
    Probe("serving_block_kernel_speedup",
          ("serving", "block_kernel_speedup"), "higher", 25.0,
          ("serving", "block_kernel_spread_pct")),
    Probe("serving_block_scale_ratio",
          ("serving", "block_kernel_scale_ratio"), "higher", 25.0),
    Probe("serving_block_quant_speedup",
          ("serving", "block_kernel_quant_speedup"), "higher", 30.0),
)


def load_result(source):
    """Bench record -> result dict. Accepts a path or a dict; a
    checked-in round file (``{"n", "cmd", "result": {...}}``) is
    unwrapped, a raw bench.py line passes through. Raises ValueError
    on anything that is not a bench result (no ``metric`` stamp)."""
    if isinstance(source, dict):
        rec = source
    else:
        with open(source) as f:
            rec = json.load(f)
    if not isinstance(rec, dict):
        raise ValueError("bench record is not a JSON object")
    # round-file shapes across the history: r06+ wrap the result dict
    # under "result"; r04 parsed it into "parsed"; r01-r03 only carry
    # the driver "tail" whose last JSON-looking line IS the result
    for key in ("result", "parsed"):
        if isinstance(rec.get(key), dict) and "metric" in rec[key]:
            rec = rec[key]
            break
    else:
        if "metric" not in rec and isinstance(rec.get("tail"), str):
            for line in reversed(rec["tail"].splitlines()):
                line = line.strip()
                if line.startswith("{") and '"metric"' in line:
                    try:
                        rec = json.loads(line)
                        break
                    except json.JSONDecodeError:
                        continue     # torn tail line: scan earlier
    if "metric" not in rec:
        raise ValueError(
            "not a bench.py result (no 'metric' stamp): %s"
            % (source if not isinstance(source, dict) else "<dict>"))
    return rec


def latest_baseline(dirpath, exclude=None):
    """Newest checked-in round (highest NN in BENCH_rNN.json) whose
    result actually LOADS (an aborted round — the r05 shape — is
    skipped, not compared against); None when the directory has no
    usable round. ``exclude``: a path to skip (the round being
    stamped must not baseline against itself)."""
    rounds = []
    for path in glob.glob(os.path.join(dirpath, "BENCH_r*.json")):
        if exclude and os.path.abspath(path) == os.path.abspath(
                exclude):
            continue
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    for _, path in sorted(rounds, reverse=True):
        try:
            load_result(path)
            return path
        except (OSError, ValueError, json.JSONDecodeError):
            continue
    return None


def compare(current, baseline, band_scale=1.0):
    """-> verdict dict {"pass", "compared", "regressions",
    "improvements", "platform", "baseline_platform", "probes":
    [{name, current, baseline, delta_pct?, delta?, band, status,
    reason?}]}. Pure function of the two result dicts (the CLI and
    bench.py's stamp share it)."""
    cur = load_result(current)
    base = load_result(baseline)
    plat_c = cur.get("platform")
    plat_b = base.get("platform")
    mismatch = (plat_c is not None and plat_b is not None
                and plat_c != plat_b)
    probes = []
    for p in PROBES:
        ent = {"name": p.name, "direction": p.direction,
               "current": p.get(cur), "baseline": p.get(base)}
        if mismatch:
            ent.update({"status": "skipped",
                        "reason": "platform mismatch (%s vs %s)"
                        % (plat_c, plat_b)})
            probes.append(ent)
            continue
        if ent["current"] is None or ent["baseline"] is None:
            ent.update({"status": "skipped",
                        "reason": "missing on %s side" % (
                            "current" if ent["current"] is None
                            else "baseline")})
            probes.append(ent)
            continue
        c, b = float(ent["current"]), float(ent["baseline"])
        if p.band_abs is not None:
            band = p.band_abs * band_scale
            delta = c - b
            ent["delta"] = round(delta, 3)
            ent["band"] = band
            worse = delta > band if p.direction == "lower" \
                else delta < -band
            better = delta < -band if p.direction == "lower" \
                else delta > band
        else:
            spreads = [p.band_pct]
            if p.spread_path:
                for side in (cur, base):
                    s = p.get(side, p.spread_path)
                    if s is not None:
                        spreads.append(float(s))
            band = max(spreads) * band_scale
            if b == 0:
                ent.update({"status": "skipped",
                            "reason": "baseline is zero"})
                probes.append(ent)
                continue
            delta_pct = 100.0 * (c - b) / abs(b)
            ent["delta_pct"] = round(delta_pct, 2)
            ent["band"] = round(band, 2)
            worse = delta_pct > band if p.direction == "lower" \
                else delta_pct < -band
            better = delta_pct < -band if p.direction == "lower" \
                else delta_pct > band
        ent["status"] = ("regression" if worse
                         else "improved" if better else "pass")
        probes.append(ent)
    regressions = [e["name"] for e in probes
                   if e["status"] == "regression"]
    return {"pass": not regressions,
            "compared": sum(1 for e in probes
                            if e["status"] != "skipped"),
            "regressions": regressions,
            "improvements": [e["name"] for e in probes
                             if e["status"] == "improved"],
            "platform": plat_c, "baseline_platform": plat_b,
            "probes": probes}


def render(verdict):
    head = "perfgate: %s  (%d probe(s) compared, %d regression(s))" \
        % ("PASS" if verdict["pass"] else "REGRESSION",
           verdict["compared"], len(verdict["regressions"]))
    lines = [head]
    for e in verdict["probes"]:
        if e["status"] == "skipped":
            lines.append("  SKIP %-28s %s" % (e["name"], e["reason"]))
            continue
        if "delta_pct" in e:
            delta = "%+.1f%%" % e["delta_pct"]
            band = "band ±%.0f%%" % e["band"]
        else:
            delta = "%+.3f" % e["delta"]
            band = "band ±%g" % e["band"]
        lines.append(
            "  %-4s %-28s %12g -> %-12g %8s (%s, %s better)"
            % (e["status"].upper()[:4], e["name"], e["baseline"],
               e["current"], delta, band, e["direction"]))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.perfgate",
        description="Gate a bench.py JSON against a baseline round; "
                    "exit 0 pass / 1 regression / 2 bad input")
    p.add_argument("current", help="current bench.py JSON (or round "
                                   "file with a 'result' key)")
    p.add_argument("baseline", nargs="?", default=None,
                   help="baseline JSON (default: newest BENCH_r*.json "
                        "in --baseline-dir)")
    p.add_argument("--baseline-dir", default=".",
                   help="where to look for BENCH_r*.json when no "
                        "baseline is named (default: cwd)")
    p.add_argument("--band-scale", type=float, default=1.0,
                   help="multiply every noise band (e.g. 2.0 on a "
                        "known-noisy host)")
    p.add_argument("--min-compared", type=int, default=0,
                   help="fail (exit 1) unless at least this many "
                        "probes actually compared — guards a CI gate "
                        "against going silently INERT when every "
                        "probe skips (platform mismatch, failed "
                        "configs). Default 0: a fully-skipped round "
                        "passes with a loud stderr warning, since a "
                        "CPU rehearsal gated against a chip baseline "
                        "is legitimate")
    p.add_argument("--require-platform-stamp", action="store_true",
                   help="fail (exit 1) unless BOTH sides carry a "
                        "'platform' stamp. An unstamped pre-r06 "
                        "baseline is the one platform-AMBIGUOUS "
                        "pairing (the mismatch guard cannot fire), "
                        "so a chip round could silently gate against "
                        "a CPU record — chip-round CI should pass "
                        "this")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict as one JSON object")
    args = p.parse_args(argv)

    baseline = args.baseline
    if baseline is None:
        baseline = latest_baseline(args.baseline_dir,
                                   exclude=args.current)
        if baseline is None:
            print("perfgate: no BENCH_r*.json baseline in %s"
                  % args.baseline_dir, file=sys.stderr)
            return 2
    try:
        verdict = compare(args.current, baseline,
                          band_scale=args.band_scale)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("perfgate: bad input: %s" % e, file=sys.stderr)
        return 2
    verdict["baseline"] = str(baseline)
    unstamped = [side for side, plat in
                 (("current", verdict["platform"]),
                  ("baseline", verdict["baseline_platform"]))
                 if plat is None]
    print(json.dumps(verdict) if args.json else
          render(verdict) + "\n  baseline: %s" % baseline)
    if unstamped:
        print("perfgate: WARNING — %s side(s) carry no 'platform' "
              "stamp (pre-r06 round?): this comparison is "
              "platform-AMBIGUOUS — the CPU-vs-chip mismatch guard "
              "cannot fire, so these deltas may compare different "
              "hardware. Re-stamp the round (bench.py stamps "
              "platform since r06) or pass an explicit stamped "
              "baseline." % " and ".join(unstamped), file=sys.stderr)
        if args.require_platform_stamp:
            print("perfgate: --require-platform-stamp set — gate "
                  "FAILED on the ambiguous pairing", file=sys.stderr)
            return 1
    if verdict["compared"] < args.min_compared:
        print("perfgate: only %d probe(s) compared < --min-compared "
              "%d — gate FAILED" % (verdict["compared"],
                                    args.min_compared),
              file=sys.stderr)
        return 1
    if verdict["pass"] and verdict["compared"] == 0:
        print("perfgate: WARNING — 0 probes compared (every probe "
              "skipped); this gate verdict is INERT, not a clean "
              "bill of health", file=sys.stderr)
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
