"""Device-prefetching data loader.

Reference parity: operators/reader/create_double_buffer_reader_op.cc:34,168
— a prefetch thread keeping a 2-slot device-side buffer so host→device
transfer overlaps compute. On TPU the host→device hop (through the axon
tunnel here) dominates naive per-step feeding, so this is the difference
between transfer-bound and compute-bound steps.

The prefetch path rides the core executor's feed-plan cache
(core/executor.FeedPlanCache): repeated same-shape batches skip the
per-batch normalization derivation, and feeds the caller froze
(``arr.flags.writeable = False`` — constant masks, position ids) are
committed to a device buffer ONCE and reused zero-copy every batch
instead of re-uploading.
"""

import queue
import threading

import numpy as np
import jax

__all__ = ["DeviceLoader"]


class DeviceLoader:
    """Wrap an iterable of feed dicts; yields dicts of device-resident
    jax.Arrays, transferring `capacity` batches ahead on a worker thread.

    ``plan_cache=None`` (default) builds a private feed-plan cache so
    repeated same-shape batches skip re-normalization; pass an existing
    core/executor FeedPlanCache to share plans (e.g. the consuming
    Executor's ``_feed_plans``), or ``plan_cache=False`` to disable."""

    def __init__(self, feed_iterable, capacity=2, device=None,
                 sharding=None, plan_cache=None):
        self._src = feed_iterable
        self._capacity = max(1, capacity)
        self._device = device
        self._sharding = sharding
        if plan_cache is None:
            from ..core.executor import FeedPlanCache
            # commit only when placement is a single device the cache
            # can reproduce; sharded puts stay on the loader's path
            dev_fn = (lambda: self._resolve_device()) \
                if sharding is None else None
            plan_cache = FeedPlanCache(device_fn=dev_fn)
        self._plans = plan_cache or None

    def _resolve_device(self):
        """The device committed buffers land on — must agree with what
        a bare device_put would pick, or one batch could mix devices
        (jax_default_device is process-wide and e.g. serving_bench
        sets it)."""
        if self._device is not None:
            return self._device
        return jax.config.jax_default_device or jax.local_devices()[0]

    def _put(self, value):
        # explicit placement always re-puts (device_put is a no-op for
        # a value already living there), matching the pre-plan-cache
        # contract that yielded arrays honor sharding=/device=
        if self._sharding is not None:
            return jax.device_put(value, self._sharding)
        if self._device is not None:
            return jax.device_put(value, self._device)
        if isinstance(value, jax.Array):
            return value            # committed / already resident
        return jax.device_put(value)

    def _normalize(self, feed):
        """Plan-cached dense normalization on the worker thread. LoD
        feeds pass through untouched — their flat/bucketed form carries
        trace-time static_info only the executor's own normalization
        pass can deliver, so pre-splitting them here would change what
        the compiled step sees."""
        if self._plans is None:
            return feed
        from ..core.lod import LoDTensor
        if any(isinstance(v, LoDTensor) for v in feed.values()):
            return feed
        from ..core.executor import _normalize_feeds
        arrays, _ = _normalize_feeds(feed, plan_cache=self._plans)
        return arrays

    def __iter__(self):
        q = queue.Queue(maxsize=self._capacity)
        stop = object()
        err = []

        def worker():
            try:
                for feed in self._src:
                    feed = self._normalize(feed)
                    dev = {k: self._put(np.asarray(v)
                                        if not isinstance(v, jax.Array)
                                        else v)
                           for k, v in feed.items()}
                    q.put(dev)
            except BaseException as e:   # propagate to consumer
                err.append(e)
            finally:
                q.put(stop)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            yield item
        if err:
            raise err[0]


def repeat_feed(feed, n):
    """Iterator yielding the same feed dict n times (benchmark helper)."""
    for _ in range(n):
        yield feed
