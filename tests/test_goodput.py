"""Goodput/badput ledger (ISSUE 11): golden attribution over a
checked-in chaos-run fixture, the exact sum-to-wall contract, the CLI,
the SLO goodput_fraction objective, and a LIVE armed run whose
injected stall shows up as badput."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, slo
from paddle_tpu.monitor import goodput as gp

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "goodput_chaos.jsonl")


def test_ledger_golden_over_chaos_fixture():
    """Hand-computed attribution of the checked-in chaos timeline:
    every second of the 9 s wall is named (see the fixture rows —
    compile [0,1], steps, a fused serving megastep, a 1.5 s stall,
    retry/reconnect and resume gaps, an async checkpoint gap, a
    preemption gap)."""
    events, skipped = monitor.read_jsonl_tolerant(FIXTURE)
    assert skipped == 0
    led = gp.ledger_from_events(events)
    cats = led["categories"]
    assert led["wall_s"] == pytest.approx(9.0)
    assert cats["compile"] == pytest.approx(1.0)
    assert cats["productive"] == pytest.approx(4.0)
    assert cats["stall"] == pytest.approx(1.5)
    assert cats["fault_recovery"] == pytest.approx(1.4)
    assert cats["checkpoint"] == pytest.approx(0.5)
    assert cats["preemption"] == pytest.approx(0.1)
    assert cats["idle"] == pytest.approx(0.5)
    assert led["goodput_fraction"] == pytest.approx(4.0 / 9.0)
    # the attribution contract: categories sum to wall EXACTLY
    assert sum(cats.values()) == pytest.approx(led["wall_s"])
    assert led["counts"]["steps"] == 4
    assert led["counts"]["serving_steps"] == 6
    assert led["counts"]["tokens"] == 10
    assert led["counts"]["preemptions"] == 1


def test_ledger_degenerate_inputs():
    assert gp.ledger_from_events([])["goodput_fraction"] is None
    one = gp.ledger_from_events([{"ts": 5.0, "ev": "step",
                                  "dt": 1.0}])
    assert one["wall_s"] == 0.0 and one["goodput_fraction"] is None


def test_goodput_cli_single_and_fleet_rollup(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.monitor", "goodput",
         FIXTURE, "--json"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["processes"][FIXTURE]["wall_s"] == pytest.approx(9.0)
    # fleet rollup: two processes = the fixture + a copy of it
    twin = tmp_path / "replica1.jsonl"
    twin.write_text(open(FIXTURE).read())
    rep2 = gp.ledger([FIXTURE, str(twin)])
    assert rep2["fleet"]["wall_s"] == pytest.approx(18.0)
    assert rep2["fleet"]["categories"]["productive"] == \
        pytest.approx(8.0)
    assert rep2["fleet"]["goodput_fraction"] == \
        pytest.approx(4.0 / 9.0)
    text = gp.render(rep2)
    assert "FLEET" in text and "goodput 44.4%" in text


def test_slo_goodput_fraction_objective(tmp_path):
    spec_pass = {"name": "g", "objectives": [
        {"metric": "goodput_fraction", "min_ratio": 0.40}]}
    spec_fail = {"name": "g", "objectives": [
        {"metric": "goodput_fraction", "min_ratio": 0.60}]}
    samples = slo.samples_from_monitor_log(FIXTURE)
    assert samples["goodput"]["goodput_fraction"] == \
        pytest.approx(4.0 / 9.0)
    assert slo.evaluate(spec_pass, samples)["pass"]
    v = slo.evaluate(spec_fail, samples)
    assert not v["pass"]
    obj = v["objectives"][0]
    assert obj["measured"] == pytest.approx(4.0 / 9.0)
    assert ">=" in slo.render(v)
    # multi-log: per-process rollup, NOT a union timeline
    twin = tmp_path / "replica1.jsonl"
    twin.write_text(open(FIXTURE).read())
    samples2 = slo.samples_from_monitor_log([FIXTURE, str(twin)])
    assert samples2["goodput"]["wall_s"] == pytest.approx(18.0)
    assert samples2["goodput"]["goodput_fraction"] == \
        pytest.approx(4.0 / 9.0)
    # spec validation: min_ratio is mandatory
    with pytest.raises(ValueError, match="min_ratio"):
        slo.load_spec({"objectives": [
            {"metric": "goodput_fraction"}]})
    # CLI exit codes over the same fixture
    for spec, want in ((spec_pass, 0), (spec_fail, 1)):
        p = tmp_path / ("spec%d.json" % want)
        p.write_text(json.dumps(spec))
        rc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.slo", str(p),
             "--log", FIXTURE],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu")).returncode
        assert rc == want


def test_live_armed_run_attributes_injected_stall(tmp_path):
    """ISSUE-11 acceptance (armed run): a monitored run with a real
    stall in the middle — the ledger attributes the full wall to
    named categories (sum == wall, the >=95%% bar by construction)
    with productive step time AND the stall visible as badput."""
    log = str(tmp_path / "armed.jsonl")
    monitor.enable(log_path=log, stall_timeout=0.2)
    try:
        x = fluid.layers.data("x", [8])
        loss = fluid.layers.mean(fluid.layers.fc(x, 4))
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        xv = np.random.rand(4, 8).astype(np.float32)
        for _ in range(5):
            exe.run(feed={"x": xv}, fetch_list=[loss])
        time.sleep(0.8)                  # the injected stall
        for _ in range(5):
            exe.run(feed={"x": xv}, fetch_list=[loss])
    finally:
        monitor.disable()
    events, _ = monitor.read_jsonl_tolerant(log)
    led = gp.ledger_from_events(events)
    cats = led["categories"]
    assert led["wall_s"] > 0.8
    # every second named: the attribution never leaks or double counts
    assert sum(cats.values()) == pytest.approx(led["wall_s"],
                                               rel=1e-6)
    assert cats["productive"] > 0
    assert cats["stall"] >= 0.2          # the injected badput, visible
    assert led["goodput_fraction"] is not None
    # and the SLO gate sees the same figure
    v = slo.evaluate(
        {"objectives": [{"metric": "goodput_fraction",
                         "min_ratio": 0.999}]},
        slo.samples_from_monitor_log(log))
    assert not v["pass"]                 # the stall burned the budget
