"""Monitor runtime: the glue between the metrics registry, the flight
recorder, the stall watchdog, and the executors' hot paths.

`enable()` arms the subsystem; until then every executor hook is one
boolean check (`enabled()`), so an unmonitored run pays nothing. Armed,
each `Executor.run` / `ParallelExecutor.run` reports:

  * a step-latency observation (histogram + flight-recorder `step` line),
  * compile-cache hit/miss and RECOMPILE counters classified against the
    executor's own cache key — a recompile names which key component
    moved (feed signature / program version / options), the #1 silent
    TPU throughput killer the static analyzer can only warn about,
  * feed-upload bytes (host arrays crossing to the device),
  * derived gauges: achieved MFU and tokens/s (static FLOPs per step
    from the paddle_tpu.analysis cost model, priced once per compile),
    and device live/peak memory via profiler.device_memory.

XLA compile wall time is captured from jax.monitoring's duration events
(the `/jax/.../compile...` family) — the same numbers a fleet-level
dashboard scrapes, here landing in the local registry.
"""

import collections
import os
import sys
import threading
import time
import weakref

from . import metrics as _metrics
from .recorder import FlightRecorder
from .watchdog import Watchdog

__all__ = [
    "enable", "disable", "enabled", "recorder", "set_peak_flops",
    "set_tokens_per_step", "on_compile", "on_step", "on_nan_trip",
    "on_retry", "on_reconnect", "on_fault", "on_rollback", "on_resume",
    "on_checkpoint", "on_serving_step", "on_serving_request",
    "on_spec", "on_alert",
    "on_feed_plan", "on_megastep", "on_transform", "on_sparse_lookup",
    "on_sparse_evictions", "on_sparse_prefetch", "on_sparse_staleness",
    "summary", "session", "prometheus_text", "dump_metrics",
]

_REG = _metrics.registry()

# -- metric declarations (import-time, cheap, shared) ----------------------
STEPS = _REG.counter("ptpu_steps_total",
                     "completed executor steps", ("executor",))
STEP_SECONDS = _REG.histogram("ptpu_step_seconds",
                              "wall time of one executor step",
                              ("executor",))
CACHE_HITS = _REG.counter("ptpu_compile_cache_hits_total",
                          "compiled-step cache hits")
CACHE_MISSES = _REG.counter("ptpu_compile_cache_misses_total",
                            "compiled-step cache misses (traces+compiles)")
COMPILES = _REG.counter("ptpu_compiles_total",
                        "program compiles by cause", ("reason",))
RECOMPILES = _REG.counter(
    "ptpu_recompiles_total",
    "compiles of a program ALREADY compiled under another key — each one "
    "burned compile time that better feed bucketing could have saved")
FEED_BYTES = _REG.counter("ptpu_feed_bytes_total",
                          "host feed bytes uploaded to the device")
NAN_TRIPS = _REG.counter("ptpu_nan_guard_trips_total",
                         "NaN/Inf guard trips", ("where",))
XLA_COMPILE_SECONDS = _REG.histogram(
    "ptpu_xla_compile_seconds",
    "XLA compile wall time (jax.monitoring duration events)", ("what",))
HBM_LIVE = _REG.gauge("ptpu_device_bytes_in_use", "device bytes live")
HBM_PEAK = _REG.gauge("ptpu_device_bytes_peak", "device bytes peak")
MFU = _REG.gauge("ptpu_mfu",
                 "achieved fraction of peak FLOP/s for the last step")
TOKENS_PER_SEC = _REG.gauge("ptpu_tokens_per_sec",
                            "tokens processed per second, last step")
STEP_FLOPS = _REG.gauge("ptpu_step_flops",
                        "static cost-model FLOPs of the cached step")
STALLS = _REG.counter("ptpu_stalls_total", "watchdog stall reports")
# resilience tier (paddle_tpu.resilience): like the distributed-runtime
# counters these record unconditionally — a retry storm must be visible
# even when nobody armed the monitor beforehand
RETRIES = _REG.counter("ptpu_retries_total",
                       "retry-policy re-issues of idempotent client "
                       "verbs", ("what",))
RECONNECTS = _REG.counter("ptpu_reconnects_total",
                          "client transparent reconnects", ("what",))
FAULTS = _REG.counter("ptpu_fault_injections_total",
                      "armed fault-plan injections", ("kind",))
ROLLBACKS = _REG.counter("ptpu_rollbacks_total",
                         "resilient_loop rollback-and-skip recoveries",
                         ("reason",))
RESUMES = _REG.counter("ptpu_resumes_total",
                       "resilient_loop auto-resumes from checkpoint")
CHECKPOINTS = _REG.counter("ptpu_checkpoints_total",
                           "resilient_loop checkpoints by mode",
                           ("mode",))
# distributed-tracing tier (paddle_tpu.trace): spans land in the span
# log; these counters make span volume and log-cap losses scrapeable
TRACE_SPANS = _REG.counter("ptpu_trace_spans_total",
                           "distributed-trace spans recorded", ("proc",))
TRACE_DROPPED = _REG.counter(
    "ptpu_trace_dropped_total",
    "distributed-trace spans lost (span log capped or absent)")
TRACE_RETAINED = _REG.counter(
    "ptpu_trace_retained_total",
    "traces retroactively promoted to the span log by tail-based "
    "retention (root error / slow root / incident offender)",
    ("reason",))
# serving tier (paddle_tpu.serving): continuous-batching engine health.
# Counters tick unconditionally (sub-microsecond next to a decode step);
# the gauges make queue pressure and batch utilization scrapeable live
SERVING_QUEUE_DEPTH = _REG.gauge(
    "ptpu_serving_queue_depth",
    "requests waiting for a decode slot")
SERVING_SLOT_OCCUPANCY = _REG.gauge(
    "ptpu_serving_slot_occupancy",
    "fraction of decode slots active in the last engine step")
SERVING_TOKENS = _REG.counter(
    "ptpu_serving_tokens_total",
    "tokens emitted by the continuous-batching engine")
SERVING_ADMISSIONS = _REG.counter(
    "ptpu_serving_admissions_total",
    "requests admitted into a decode slot")
SERVING_RETIREMENTS = _REG.counter(
    "ptpu_serving_retirements_total",
    "requests retired from a decode slot (EOS or max_new)")
SERVING_FAILURES = _REG.counter(
    "ptpu_serving_request_failures_total",
    "requests failed (engine closed or loop death) — the SLO error "
    "budget numerator")
# request-level latency attribution (ISSUE 6): the three figures a
# serving SLO is written against, observed once per request retirement
SERVING_TTFT = _REG.histogram(
    "ptpu_serving_ttft_seconds",
    "request time-to-first-token (submit -> first decoded token)",
    ("engine",))
SERVING_TPOT = _REG.histogram(
    "ptpu_serving_tpot_seconds",
    "mean per-token decode latency after the first token", ("engine",))
SERVING_QUEUE_WAIT = _REG.histogram(
    "ptpu_serving_queue_wait_seconds",
    "request wait from submit to decode-slot admission", ("engine",))
# paged-KV / prefix-cache tier (ISSUE 10): pool pressure and reuse.
# Gauges reflect the engine's last iteration; counters accumulate
KV_BLOCKS_TOTAL = _REG.gauge(
    "ptpu_kv_blocks_total",
    "physical blocks in the paged KV pool")
KV_BLOCKS_USED = _REG.gauge(
    "ptpu_kv_blocks_used",
    "paged KV blocks referenced by live requests or the prefix cache")
# effective-bytes companions (ISSUE 20): block counts x the engine's
# quantization-aware bytes_per_block, so watch/SLO read real HBM — a
# quantized pool reports its smaller footprint day one
KV_BYTES_TOTAL = _REG.gauge(
    "ptpu_kv_bytes_total",
    "HBM bytes the paged KV pool reserves (quantization-aware)")
KV_BYTES_USED = _REG.gauge(
    "ptpu_kv_bytes_used",
    "HBM bytes of paged KV blocks currently referenced "
    "(quantization-aware)")
PREFIX_HITS = _REG.counter(
    "ptpu_prefix_cache_hits_total",
    "admissions whose prompt matched a cached prefix chain (those "
    "prefill chunks are skipped)")
PREFIX_MISSES = _REG.counter(
    "ptpu_prefix_cache_misses_total",
    "admissions with no cached prefix (cold prefill)")
PREFIX_EVICTIONS = _REG.counter(
    "ptpu_prefix_cache_evictions_total",
    "prefix-cache blocks LRU-freed under pool pressure")
SERVING_PREEMPTIONS = _REG.counter(
    "ptpu_serving_preemptions_total",
    "requests preempted (blocks freed, re-queued for re-prefill) "
    "when the KV pool ran dry")
# speculative decode tier (ISSUE 13): tokens drafted vs accepted and
# the dispatches that verified them — acceptance rate is
# accepted/drafted, accepted tokens per dispatch the bs1-floor lever
SPEC_DRAFTED = _REG.counter(
    "ptpu_spec_drafted_tokens_total",
    "draft tokens proposed to speculative scoring dispatches")
SPEC_ACCEPTED = _REG.counter(
    "ptpu_spec_accepted_tokens_total",
    "draft tokens accepted by the model's own (greedy/seeded-sampled) "
    "tokens — each one is a decode step the dispatch floor never saw")
SPEC_DISPATCHES = _REG.counter(
    "ptpu_spec_dispatches_total",
    "speculative scoring dispatches (each verifies gamma+1 positions "
    "per live slot and emits 1..gamma+1 tokens per slot)")
SERVING_STEP_SECONDS = _REG.histogram(
    "ptpu_serving_step_seconds",
    "wall time of one engine iteration (prefill chunk + decode step; "
    "the wait-for-batch admission window is policy, not latency, and "
    "is excluded) — the serving analogue of ptpu_step_seconds, so an "
    "SLO step_latency objective gates the SAME quantity from a "
    "metrics snapshot as from the recorder rows", ("engine",))
# megastep execution (ISSUE 7): K logical steps fused into ONE device
# dispatch (Executor.run_steps / ParallelExecutor.run_steps /
# serving.Engine megastep). Latency/MFU/tokens-s figures stay PER
# LOGICAL STEP (the megastep wall time divided by K) so dashboards and
# SLO step_latency gates read the same quantity at any K; these two
# counters make the fusion itself scrapeable (dispatch tax saved =
# steps_total - dispatches_total host round-trips)
MEGASTEP_DISPATCHES = _REG.counter(
    "ptpu_megastep_dispatches_total",
    "fused K-step device dispatches (K > 1)", ("executor",))
MEGASTEP_STEPS = _REG.counter(
    "ptpu_megastep_steps_total",
    "logical steps advanced inside fused K-step dispatches",
    ("executor",))
# feed-plan cache (core/executor): a normalization is the full per-call
# feed re-marshal PERF.md round 5 measured; a plan hit skipped it
FEED_NORMALIZATIONS = _REG.counter(
    "ptpu_feed_normalizations_total",
    "full _normalize_feeds derivations (feed-plan cache misses or "
    "uncached callers)")
FEED_PLAN_HITS = _REG.counter(
    "ptpu_feed_plan_hits_total",
    "feed-plan cache hits (per-call feed normalization skipped)")
# program-transform tier (paddle_tpu.transform): optimizing-pass
# activity. Counters tick unconditionally (transforms run per compile,
# not per step); the flight-recorder row lands only when armed
TRANSFORM_PASSES = _REG.counter(
    "ptpu_transform_passes_total",
    "optimizing-pass rewrite phases executed over a Program",
    ("pass",))
TRANSFORM_OPS_REMOVED = _REG.counter(
    "ptpu_transform_ops_removed_total",
    "ops removed or rewritten by an optimizing pass", ("pass",))
TRANSFORM_PATTERNS = _REG.counter(
    "ptpu_transform_patterns_total",
    "fusion-pattern hits by pattern name (transform/fusion.py)",
    ("pattern",))
# sparse serving tier (paddle_tpu.serving.sparse, ISSUE 12): the hot-ID
# embedding cache in front of the live pserver shards, and the online-
# learning loop's read-your-writes staleness. Counters tick
# unconditionally (a dict probe is nothing next to a PRFT round trip);
# the staleness histogram backs the SLO `staleness_s` objective from a
# metrics snapshot the same way the latency histograms back TTFT
SPARSE_CACHE_HITS = _REG.counter(
    "ptpu_sparse_cache_hits_total",
    "embedding rows served from the hot-ID cache (no wire)")
SPARSE_CACHE_MISSES = _REG.counter(
    "ptpu_sparse_cache_misses_total",
    "embedding rows fetched from a pserver shard (cache cold)")
SPARSE_CACHE_STALE = _REG.counter(
    "ptpu_sparse_cache_stale_total",
    "cached rows re-fetched because they aged past the staleness "
    "bound or their shard's version/incarnation moved")
SPARSE_CACHE_EVICTIONS = _REG.counter(
    "ptpu_sparse_cache_evictions_total",
    "hot-ID cache rows evicted (LRU capacity or shard invalidation)")
SPARSE_PREFETCH_ROWS = _REG.counter(
    "ptpu_sparse_prefetch_rows_total",
    "embedding rows pulled over the PRFT wire (deduplicated, batched)")
SPARSE_PREFETCH_BYTES = _REG.counter(
    "ptpu_sparse_prefetch_bytes_total",
    "embedding row payload bytes pulled over the PRFT wire")
SPARSE_STALENESS = _REG.histogram(
    "ptpu_sparse_staleness_seconds",
    "read-your-writes staleness: an online update landing on the "
    "pservers -> the first serve whose rows reflect it", ("table",))
# alerting tier (paddle_tpu.monitor.signals, ISSUE 14): exactly-once
# FIRING/RESOLVED edges from the streaming rule engine. The counter
# ticks unconditionally (transitions are rare by hysteresis
# construction); the gauge is the evaluating process's live count
ALERT_TRANSITIONS = _REG.counter(
    "ptpu_alert_transitions_total",
    "alert state transitions emitted by the monitor.signals rule "
    "engine", ("rule", "severity", "state"))
ALERTS_ACTIVE = _REG.gauge(
    "ptpu_alerts_active",
    "alerts currently FIRING in this process's signals evaluator")
# elastic fleet tier (serving.autoscale, ISSUE 18): the control loop's
# desired count, scale events, graceful drains and rolling weight
# updates. Counters tick unconditionally (scale events are rare);
# convergence is a histogram so fleet merges stay bucket-wise
FLEET_DESIRED = _REG.gauge(
    "ptpu_fleet_desired_replicas",
    "replica count the autoscale control loop is converging toward")
FLEET_VERSION_REPLICAS = _REG.gauge(
    "ptpu_fleet_version_replicas",
    "live replicas per serving artifact version (the fleet's version "
    "mix during a rolling update)", ("version",))
FLEET_SCALE_EVENTS = _REG.counter(
    "ptpu_fleet_scale_events_total",
    "autoscale desired-count moves", ("direction", "reason"))
FLEET_DRAINS = _REG.counter(
    "ptpu_fleet_drains_total",
    "graceful replica drains started by the control loop")
FLEET_ROLLS = _REG.counter(
    "ptpu_fleet_rolls_total",
    "rolling weight updates completed (aborted rolls excluded)")
FLEET_VERSION_CONVERGENCE = _REG.histogram(
    "ptpu_fleet_version_convergence_seconds",
    "rolling update start -> 100% of the fleet serving the new "
    "artifact version")
# canary analysis plane (serving.fleet mirroring + serving.rollout,
# ISSUE 19): shadow decode volume is counted HERE, never on the
# incumbent serving counters (the PR-6 failed-request exclusion
# discipline applied to mirrored traffic); joined pairs and verdicts
# are the delta-SLO evidence a rollout is gated on
MIRROR_TOKENS = _REG.counter(
    "ptpu_mirror_tokens_total",
    "tokens decoded by SHADOW candidate engines (scored, never "
    "served; deliberately excluded from ptpu_serving_tokens_total)",
    ("engine",))
MIRROR_PAIRS = _REG.counter(
    "ptpu_mirror_pairs_total",
    "joined candidate/incumbent result pairs scored by the router",
    ("router",))
ROLLOUT_VERDICTS = _REG.counter(
    "ptpu_rollout_verdicts_total",
    "exactly-once delta-SLO verdicts emitted by rollout phases",
    ("phase", "verdict"))
ROLLOUT_PHASE = _REG.gauge(
    "ptpu_rollout_phase",
    "rollout controller phase (0 idle, 1 boot, 2 shadow, 3 canary, "
    "4 rolling, 5 promoted, -1 rolled-back)")


# bound on remembered per-compile cost entries: each key tuple pins its
# Program, so an unbounded map would leak graphs in a serving loop that
# compiles per-request programs (LRU eviction keeps the hot steps priced)
_COSTS_CAP = 512


class _State:
    on = False
    rec = None            # FlightRecorder | None
    dog = None            # Watchdog | None
    reporter = None       # (thread, stop_event) | None
    peak_flops = None     # float | None (None = auto-detect)
    listener_registered = False
    lock = threading.Lock()
    # per-program compile history {"versions", "sigs", "count"} — WEAK
    # keys: a discarded Program must not stay pinned (and a reused id
    # must not inherit a dead program's history)
    programs = weakref.WeakKeyDictionary()
    # cache key (by value) -> {"flops", "bytes", "tokens", "devices"}
    costs = collections.OrderedDict()
    tokens_override = None
    devices_recorded = False
    platform = None       # cached backend platform (cannot change)
    t_enable = None
    step_serial = 0


_S = _State()


def enabled():
    return _S.on


def recorder():
    return _S.rec


def set_peak_flops(value):
    """Override the device peak FLOP/s used for the MFU gauge (e.g.
    197e12 for a v5e chip in bf16)."""
    _S.peak_flops = float(value) if value else None


def set_tokens_per_step(n):
    """Pin tokens-per-step for the tokens/s gauge, overriding the
    integer-feed-size heuristic (call with None to restore it)."""
    _S.tokens_override = int(n) if n else None


def _auto_peak_flops():
    from .. import flags
    try:
        v = float(flags.get_flag("monitor_peak_flops"))
    except KeyError:
        v = 0.0
    if v > 0:
        return v
    try:
        import jax
        dev = jax.local_devices()[0]
        if dev.platform == "tpu":
            # single-chip bf16 peak by generation (dense); unknown kinds
            # fall back to the v5e figure BASELINE.json benches against
            kind = getattr(dev, "device_kind", "").lower()
            table = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
                     "v5p": 459e12, "v6": 918e12}
            for k, f in table.items():
                if k in kind:
                    return f
            return 197e12
    except Exception:
        pass
    return None


def enable(log_path=None, stall_timeout=None, report_interval=None,
           peak_flops=None, max_log_bytes=None):
    """Arm the monitor. Idempotent-ish: calling again replaces the
    flight recorder / watchdog configuration.

    log_path:        flight-recorder JSONL path (None = no recorder)
    stall_timeout:   seconds without a completed step/compile before the
                     watchdog dumps stacks (None/0 = no watchdog)
    report_interval: seconds between one-line console reports (None/0 =
                     no reporter thread)
    peak_flops:      device peak FLOP/s for MFU (None = auto-detect)
    """
    disable()
    with _S.lock:
        if log_path:
            _S.rec = FlightRecorder(
                log_path, max_bytes=max_log_bytes or (64 << 20))
            _S.rec.record("run_meta", **_run_meta())
        if peak_flops:
            _S.peak_flops = float(peak_flops)
        _S.devices_recorded = False
        _S.t_enable = time.monotonic()
        _S.on = True
        if stall_timeout:
            _S.dog = Watchdog(stall_timeout, _on_stall).start()
        if report_interval:
            stop = threading.Event()
            t = threading.Thread(target=_report_loop,
                                 args=(stop, float(report_interval)),
                                 daemon=True, name="ptpu-monitor-report")
            t.start()
            _S.reporter = (t, stop)
    _register_jax_listener()


def disable():
    with _S.lock:
        _S.on = False
        if _S.dog is not None:
            _S.dog.stop()
            _S.dog = None
        if _S.reporter is not None:
            t, stop = _S.reporter
            stop.set()
            _S.reporter = None
        if _S.rec is not None:
            _S.rec.close()
            _S.rec = None


def maybe_enable_from_flags():
    """Flag-driven arming (called from package import): PADDLE_TPU_MONITOR=1
    turns the monitor on, PADDLE_TPU_MONITOR_LOG names the JSONL,
    PADDLE_TPU_MONITOR_STALL_TIMEOUT arms the watchdog."""
    from .. import flags
    try:
        if not flags.get_flag("monitor"):
            return
    except KeyError:
        return
    stall = flags.get_flag("monitor_stall_timeout") or None
    report = flags.get_flag("monitor_report_interval") or None
    try:
        enable(log_path=flags.get_flag("monitor_log") or None,
               stall_timeout=stall, report_interval=report)
    except OSError as e:
        # telemetry must never take the process down: an unwritable log
        # path degrades to metrics-only instead of failing the import
        print("paddle_tpu.monitor: flight recorder disabled (%s); "
              "continuing with metrics only" % e, file=sys.stderr)
        enable(log_path=None, stall_timeout=stall,
               report_interval=report)


def _run_meta():
    """Process metadata only — deliberately NO jax device queries:
    enable() may run at 'import paddle_tpu' time (env-armed), and
    touching jax.local_devices() there would initialize the backend
    before jax.distributed.initialize() / jax_num_cpu_devices updates
    in launcher code. Device info lands in a later `devices` event
    (_maybe_record_devices) once the program is actually running."""
    meta = {"pid": os.getpid(), "argv": sys.argv[:8],
            "python": sys.version.split()[0]}
    try:
        import jax
        meta["jax"] = jax.__version__
    except Exception:
        pass
    return meta


def _maybe_record_devices():
    """Emit the one-shot `devices` event on the first step/compile —
    by then jax is in real use, so the backend query is safe."""
    if _S.devices_recorded or _S.rec is None:
        return
    _S.devices_recorded = True
    try:
        import jax
        devs = jax.local_devices()
        _S.rec.record("devices", platform=devs[0].platform,
                      device_kind=getattr(devs[0], "device_kind", ""),
                      device_count=jax.device_count())
    except Exception:
        pass


# -- executor hooks --------------------------------------------------------

def feed_nbytes(feed_arrays):
    """Host bytes that will cross to the device this step (jax.Arrays
    are already resident and cost nothing)."""
    import numpy as np
    total = 0
    for v in feed_arrays.values():
        if isinstance(v, (np.ndarray, np.generic)):
            total += v.nbytes
    return total


def tokens_in_feeds(feed_arrays):
    """Heuristic tokens-per-step: the largest integer-dtype feed is the
    token ids (LM src [B, T], classifier labels [B, 1], ...). Dense-only
    programs report their largest leading dim (samples/step)."""
    if _S.tokens_override:
        return _S.tokens_override
    import numpy as np
    best = 0
    lead = 0
    for k, v in feed_arrays.items():
        if k.endswith("@LOD") or k.endswith("@ACCUM_TOKENS"):
            continue
        dt = getattr(v, "dtype", None)
        shape = getattr(v, "shape", ())
        if dt is not None and np.issubdtype(dt, np.integer) and shape:
            best = max(best, int(np.prod(shape)))
        if shape:
            lead = max(lead, int(shape[0]))
    return best or lead


def on_compile(program, key, feed_sig, cost_fn=None, executor="exe",
               tokens=0, devices=1):
    """Cache-miss hook: classify the compile, price the step with the
    static cost model, flight-record the event. `key` is the executor's
    cache key; `devices` is how many chips run the step (scales the
    MFU denominator — the cost model priced the GLOBAL batch)."""
    if not _S.on:
        return
    # snapshot: a concurrent disable() may null these mid-hook, and
    # telemetry must never throw into the hot path
    rec, dog = _S.rec, _S.dog
    _maybe_record_devices()
    version = getattr(program, "_version", None)
    # a PassManager-transformed clone announces itself (parent version
    # + new program_version in _transform_meta); on the ARMED executor
    # path the caller's program carries the mirrored _transform_applied
    # (the compiled body was the transformed clone even though the
    # cache key — and this hook — see the original). Either way the
    # compile is attributed to the transform instead of counting as a
    # mystery new_program, so a post-transform recompile is classified
    transform_meta = getattr(program, "_transform_meta", None) \
        or getattr(program, "_transform_applied", None)
    # classify under the lock: two threads compiling the same program
    # concurrently (a supported Executor pattern) must not both read
    # count==0 and report new_program, hiding a real recompile
    with _S.lock:
        ent = _S.programs.setdefault(
            program, {"versions": set(), "sigs": set(), "pairs": set(),
                      "count": 0})
        if ent["count"] == 0:
            reason = ("transformed_program" if transform_meta
                      else "new_program")
        elif version not in ent["versions"]:
            reason = "program_version"
        elif feed_sig not in ent["sigs"]:
            reason = "feed_signature"
        elif (version, feed_sig) not in ent["pairs"]:
            # both components seen before, just never together — the
            # key churned on their combination, not on an option flag
            reason = "key_combination"
        else:
            # same (version, sig) compiled again: an option in the key
            # (amp/check_nan/fuse flags, fetch list, state keys) moved
            reason = "options"
        recompile = ent["count"] > 0
        ent["count"] += 1
        ent["versions"].add(version)
        ent["sigs"].add(feed_sig)
        ent["pairs"].add((version, feed_sig))

    CACHE_MISSES.inc()
    COMPILES.inc(reason=reason)
    if recompile:
        RECOMPILES.inc()

    flops = nbytes = None
    if cost_fn is not None and _flag("monitor_cost_model"):
        try:
            flops, nbytes = cost_fn()   # traces — NOT under the lock
            # keyed by VALUE: each run() builds a fresh (equal) key tuple
            with _S.lock:
                _S.costs[key] = {"flops": flops, "bytes": nbytes,
                                 "tokens": tokens,
                                 "devices": max(1, devices)}
                _S.costs.move_to_end(key)
                while len(_S.costs) > _COSTS_CAP:
                    _S.costs.popitem(last=False)
            STEP_FLOPS.set(flops)
        except Exception:
            pass  # cost model is advisory; never fail a compile over it
    if dog is not None:
        dog.touch()
    if rec is not None:
        extra = {}
        if transform_meta is not None:
            extra["transform_of"] = transform_meta.get("parent_version")
        rec.record("compile", executor=executor, reason=reason,
                   recompile=recompile, program=id(program),
                   version=version, flops=flops, bytes=nbytes,
                   tokens=tokens, **extra)
    _sample_device_memory()


def on_cache_hit():
    if _S.on:
        CACHE_HITS.inc()


def sync_every():
    """The monitor_sync_every flag (>= 1), read per step (cheap)."""
    from .. import flags
    try:
        return max(1, int(flags.get_flag("monitor_sync_every")))
    except KeyError:
        return 1


class StepTimer:
    """Per-executor window state for the monitor_sync_every
    amortization, shared by Executor and ParallelExecutor (one code
    path for the windowing logic). Thread-safe: a shared executor
    driven from two threads must never crash or corrupt the window."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._t0 = None

    def begin(self, now):
        """Count this step into the window; True when the caller should
        sync (end of window)."""
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self._count += 1
            return self._count >= sync_every()

    def end_synced(self, now, step_t0):
        """Window-average per-step seconds; resets the window. step_t0
        is the fallback base when a concurrent thread already closed
        the window (never throws into the hot path)."""
        with self._lock:
            base = self._t0 if self._t0 is not None else step_t0
            n = max(1, self._count)
            self._count = 0
            self._t0 = None
            return max(0.0, (now - base) / n)


def step_timer(obj):
    """The per-executor StepTimer, lazily attached to the instance."""
    t = obj.__dict__.get("_mon_sync")
    if t is None:
        t = obj.__dict__.setdefault("_mon_sync", StepTimer())
    return t


def on_step(key, dt, feed_bytes=0, tokens=0, executor="exe",
            synced=True):
    """Step-completion hook. synced=True: dt is real (blocked) wall
    time — feeds the latency histogram and the MFU/tokens-s gauges.
    synced=False (monitor_sync_every amortization on async pipelines):
    dt is dispatch time only — the step still counts and logs, but is
    excluded from latency/throughput derivations."""
    if not _S.on:
        return
    rec, dog = _S.rec, _S.dog    # see on_compile: disable() race
    _maybe_record_devices()
    STEPS.inc(executor=executor)
    if synced:
        STEP_SECONDS.observe(dt, executor=executor)
    if feed_bytes:
        FEED_BYTES.inc(feed_bytes)
    mfu = None
    with _S.lock:
        cost = _S.costs.get(key) if key is not None else None
        if cost is not None:
            _S.costs.move_to_end(key)   # keep hot step keys resident
    if synced and cost is not None and dt > 0:
        if _S.peak_flops is None:
            _S.peak_flops = _auto_peak_flops() or 0.0
        if _S.peak_flops:
            # whole-program FLOPs over ALL participating chips' peak
            mfu = cost["flops"] / dt \
                / (_S.peak_flops * cost.get("devices", 1))
            MFU.set(mfu)
    tps = None
    if synced and tokens and dt > 0:
        tps = tokens / dt
        TOKENS_PER_SEC.set(tps)
    if dog is not None:
        dog.touch()
    with _S.lock:
        _S.step_serial += 1
        serial = _S.step_serial
    if rec is not None:
        extra = {}
        tr = _active_trace_id()
        if tr is not None:
            # join this process's step telemetry to the fleet timeline
            extra["trace"] = tr
        rec.record("step", executor=executor, n=serial,
                   dt=dt, feed_bytes=feed_bytes, tokens=tokens,
                   mfu=mfu, tokens_per_sec=tps, synced=synced, **extra)
    # route the step span into the host profiler timeline when tracing
    from .. import profiler as _prof
    if _prof._enabled:
        _prof.add_span("monitor.step", time.perf_counter() - dt, dt)
    _sample_device_memory()


def on_megastep(key, dt, k, feed_bytes=0, tokens=0, executor="exe",
                synced=True):
    """One fused K-step dispatch (Executor.run_steps /
    ParallelExecutor.run_steps) completed in ``dt`` seconds of wall
    time. Latency, MFU and tokens/s all derive PER LOGICAL STEP — the
    megastep wall time divided by K — so dashboards, the monitor CLI
    and SLO step_latency gates read the same quantity at any K. The
    compile-time cost entry for ``key`` priced the WHOLE megastep (K
    scanned steps), so MFU uses the full dt. ``tokens`` is the total
    across all K logical steps."""
    if not _S.on:
        return
    rec, dog = _S.rec, _S.dog    # see on_compile: disable() race
    _maybe_record_devices()
    k = max(1, int(k))
    per = dt / k
    STEPS.inc(k, executor=executor)
    MEGASTEP_DISPATCHES.inc(executor=executor)
    MEGASTEP_STEPS.inc(k, executor=executor)
    if synced:
        for _ in range(k):
            STEP_SECONDS.observe(per, executor=executor)
    if feed_bytes:
        FEED_BYTES.inc(feed_bytes)
    mfu = None
    with _S.lock:
        cost = _S.costs.get(key) if key is not None else None
        if cost is not None:
            _S.costs.move_to_end(key)
    if synced and cost is not None and dt > 0:
        if _S.peak_flops is None:
            _S.peak_flops = _auto_peak_flops() or 0.0
        if _S.peak_flops:
            mfu = cost["flops"] / dt \
                / (_S.peak_flops * cost.get("devices", 1))
            MFU.set(mfu)
    tps = None
    if synced and tokens and dt > 0:
        tps = tokens / dt
        TOKENS_PER_SEC.set(tps)
    if dog is not None:
        dog.touch()
    with _S.lock:
        _S.step_serial += k
        serial = _S.step_serial
    if rec is not None:
        extra = {}
        tr = _active_trace_id()
        if tr is not None:
            extra["trace"] = tr
        # ONE row per dispatch; dt is the PER-LOGICAL-STEP figure the
        # CLI/SLO surfaces gate, megastep_dt the raw dispatch wall time
        rec.record("step", executor=executor, n=serial, dt=per, k=k,
                   megastep_dt=dt, feed_bytes=feed_bytes, tokens=tokens,
                   mfu=mfu, tokens_per_sec=tps, synced=synced, **extra)
    _sample_device_memory()


def on_nan_trip(where, detail=""):
    if not _S.on:
        return
    rec = _S.rec
    NAN_TRIPS.inc(where=where)
    if rec is not None:
        rec.record("nan_guard", where=where, detail=detail)


# -- resilience hooks (paddle_tpu.resilience: retry/faults/driver) ---------
# Counters always tick (sub-microsecond next to a socket error or an
# fsync); flight-recorder events land only when a recorder is armed.

def _active_trace_id():
    """Sampled ambient paddle_tpu.trace id (None when disarmed) —
    stamped on flight-recorder rows so per-process telemetry joins the
    merged fleet timeline. Inline import: trace imports monitor."""
    from ..trace import runtime as _trace
    return _trace.active_trace_id()


def _trace_extra():
    tr = _active_trace_id()
    return {} if tr is None else {"trace": tr}


def on_retry(what, attempt, error=None):
    RETRIES.inc(what=what)
    rec = _S.rec
    if rec is not None:
        rec.record("retry", what=what, attempt=attempt,
                   error=repr(error), **_trace_extra())


def on_reconnect(what):
    RECONNECTS.inc(what=what)
    rec = _S.rec
    if rec is not None:
        rec.record("reconnect", what=what, **_trace_extra())


def on_fault(kind, site=""):
    FAULTS.inc(kind=kind)
    rec = _S.rec
    if rec is not None:
        rec.record("fault", kind=kind, site=site, **_trace_extra())


def on_rollback(step, reason):
    ROLLBACKS.inc(reason=reason)
    rec = _S.rec
    if rec is not None:
        rec.record("rollback", step=step, reason=reason)
        rec.flush()


def on_resume(step):
    RESUMES.inc()
    rec = _S.rec
    if rec is not None:
        rec.record("resume", step=step)


def on_checkpoint(step, path, mode):
    CHECKPOINTS.inc(mode=mode)
    rec = _S.rec
    if rec is not None:
        rec.record("checkpoint", step=step, path=path, mode=mode)


# -- serving hooks (paddle_tpu.serving continuous-batching engine) ---------

def on_serving_step(active, slots, queue_depth, emitted=0, admitted=0,
                    retired=0, engine="engine", dt=None, k=1,
                    dispatched=None, kv_used=None, kv_total=None,
                    kv_bytes_used=None, kv_bytes_total=None,
                    prefix_hits=None, prefix_misses=None, preempted=0,
                    cache_hits=None, cache_misses=None,
                    cache_stale=None, cache_evictions=None,
                    spec_drafted=None, spec_accepted=None,
                    spec_emitted=None, spec_dispatches=None,
                    shadow=False, version=None):
    """One engine iteration completed: gauges reflect the step, counters
    accumulate, and (recorder armed) a ``serving_step`` row lands with
    the step wall time and the active trace id so the fleet timeline
    can join engine steps. Fused megastep iterations: ``dt`` is the
    whole dispatch, ``dispatched`` the scan trips the device ran
    (defaults to ``k``), ``k`` the decode steps actually consumed — a
    drain-tail megastep consumes fewer than it dispatched when every
    live slot retires early. The histogram observes (and the row
    reports) the PER-LOGICAL-STEP figure dt/dispatched, once per
    consumed step, so SLO step_latency gates stay comparable across
    K and a drain-tail dispatch cannot overstate per-step latency.
    Paged engines additionally report pool pressure (``kv_used`` /
    ``kv_total`` into the kv gauges and a ``kv_used_blocks`` row field
    the SLO engine and ``monitor watch`` gate on), cumulative prefix
    hit/miss counts, and ``preempted`` (requests pushed back to the
    queue this iteration)."""
    k = max(1, int(k))
    d = max(k, int(dispatched or k))
    per = None if dt is None else dt / d
    if shadow:
        # SHADOW engine step (canary analysis plane): scored, never
        # served — nothing here may tick the serving counters/gauges
        # the SLO engine, bench and autoscaler scale_hint read. The
        # decode volume lands on the mirror counter; the row below is
        # marked so slo/signals readers skip it too.
        if emitted:
            MIRROR_TOKENS.inc(emitted, engine=engine)
    else:
        SERVING_QUEUE_DEPTH.set(queue_depth)
        SERVING_SLOT_OCCUPANCY.set(active / slots if slots else 0.0)
        if kv_total is not None:
            KV_BLOCKS_TOTAL.set(kv_total)
        if kv_used is not None:
            KV_BLOCKS_USED.set(kv_used)
        if kv_bytes_total is not None:
            KV_BYTES_TOTAL.set(kv_bytes_total)
        if kv_bytes_used is not None:
            KV_BYTES_USED.set(kv_bytes_used)
        if preempted:
            SERVING_PREEMPTIONS.inc(preempted)
        if emitted:
            SERVING_TOKENS.inc(emitted)
        if admitted:
            SERVING_ADMISSIONS.inc(admitted)
        if retired:
            SERVING_RETIREMENTS.inc(retired)
        if dt is not None:
            for _ in range(k):
                SERVING_STEP_SECONDS.observe(per, engine=engine)
        if d > 1:
            MEGASTEP_DISPATCHES.inc(executor=engine)
            MEGASTEP_STEPS.inc(k, executor=engine)
    rec = _S.rec
    if rec is not None:
        extra = {} if d == 1 else {"k": k, "megastep_dt": dt,
                                   "dispatched": d}
        if shadow:
            extra["shadow"] = True
        if version is not None:
            extra["version"] = str(version)
        if kv_used is not None:
            # pool-pressure fields (paged engines only — dense rows
            # keep their PR-6 shape): kv_used_blocks is what slo/watch
            # windows gate on; the prefix counters are CUMULATIVE so a
            # window's hit rate is last-row arithmetic, not a sum
            extra["kv_used_blocks"] = kv_used
            extra["kv_total_blocks"] = kv_total
            if kv_bytes_total is not None:
                extra["kv_bytes_used"] = kv_bytes_used
                extra["kv_bytes_total"] = kv_bytes_total
            extra["prefix_hits"] = prefix_hits
            extra["prefix_misses"] = prefix_misses
            if preempted:
                extra["preempted"] = preempted
        if cache_hits is not None:
            # sparse scoring engines (serving.sparse): CUMULATIVE
            # hot-ID cache counters on every row, same discipline as
            # the prefix counters — a window's hit rate is last-row
            # arithmetic, never a sum
            extra["cache_hits"] = cache_hits
            extra["cache_misses"] = cache_misses
            extra["cache_stale"] = cache_stale
            extra["cache_evictions"] = cache_evictions
        if spec_dispatches is not None:
            # speculative engines (ISSUE 13): CUMULATIVE drafted/
            # accepted/emitted token counts + scoring dispatches, same
            # last-row-arithmetic discipline — acceptance rate and
            # accepted-tokens-per-dispatch fall out of any window's
            # last row
            extra["spec_drafted"] = spec_drafted
            extra["spec_accepted"] = spec_accepted
            extra["spec_emitted"] = spec_emitted
            extra["spec_dispatches"] = spec_dispatches
        rec.record("serving_step", engine=engine, active=active,
                   slots=slots, queue_depth=queue_depth,
                   emitted=emitted, admitted=admitted, retired=retired,
                   dt=per, **extra, **_trace_extra())


def on_prefix_lookup(hit):
    """One prefix-cache lookup at admission (paged engines)."""
    (PREFIX_HITS if hit else PREFIX_MISSES).inc()


def on_spec(drafted=0, accepted=0):
    """One speculative scoring dispatch completed (ISSUE 13):
    ``drafted`` tokens were proposed across the live slots, ``accepted``
    of them matched the model's own tokens and were committed (the
    per-slot bonus token is counted by ptpu_serving_tokens_total like
    every emitted token, not here)."""
    SPEC_DISPATCHES.inc()
    if drafted:
        SPEC_DRAFTED.inc(drafted)
    if accepted:
        SPEC_ACCEPTED.inc(accepted)


def on_prefix_evictions(n=1):
    """Prefix-cache blocks LRU-freed under pool pressure."""
    if n:
        PREFIX_EVICTIONS.inc(n)


# -- sparse serving hooks (paddle_tpu.serving.sparse, ISSUE 12) ------------

def on_sparse_lookup(hits=0, misses=0, stale=0):
    """One batched hot-ID cache lookup resolved: ``hits`` rows served
    cacheside, ``misses`` fetched cold, ``stale`` re-fetched past the
    staleness bound / version bump (stale rows also count as misses on
    the wire — the counters answer different questions and are not
    meant to sum to the row count)."""
    if hits:
        SPARSE_CACHE_HITS.inc(hits)
    if misses:
        SPARSE_CACHE_MISSES.inc(misses)
    if stale:
        SPARSE_CACHE_STALE.inc(stale)


def on_sparse_evictions(n=1):
    if n:
        SPARSE_CACHE_EVICTIONS.inc(n)


def on_sparse_prefetch(rows, nbytes):
    """One batched PRFT pull against a pserver shard completed."""
    if rows:
        SPARSE_PREFETCH_ROWS.inc(rows)
    if nbytes:
        SPARSE_PREFETCH_BYTES.inc(nbytes)


def on_sparse_staleness(seconds, table="table"):
    """One measured read-your-writes staleness sample (online update
    landed -> first serve reflecting it). Observes the histogram and —
    recorder armed — lands a ``sparse_staleness`` row, the sample the
    SLO ``staleness_s`` objective gates on the --log surface."""
    SPARSE_STALENESS.observe(float(seconds), table=table)
    rec = _S.rec
    if rec is not None:
        rec.record("sparse_staleness", value=float(seconds),
                   table=table, **_trace_extra())


def on_serving_request(engine, queue_wait=None, ttft=None, tpot=None,
                       tokens=0, prefill_chunks=0, prompt_len=0,
                       trace_id=None, shadow=False, version=None,
                       error=None):
    """One request retired (or failed) — the request-level latency
    attribution tier. Histograms observe unconditionally (requests are
    rare next to decode steps, same discipline as the serving
    counters); a ``serving_request`` recorder row lands when the flight
    recorder is armed, carrying the REQUEST's trace id (not the ambient
    step's) so the fleet timeline can join request lanes."""
    if shadow:
        # mirrored request (canary analysis plane): like the
        # failed-request exclusion below but total — neither the
        # error counter nor the latency histograms may see shadow
        # traffic; the marked row is the delta evaluator's input.
        pass
    elif error is not None:
        # failed requests are the ERROR BUDGET's business only: their
        # retire stamp is the failure time (a kill/wedge gap, not
        # decode pace), so observing them would fail latency
        # objectives with shutdown artifacts. The recorder row below
        # still carries the raw values for forensics.
        SERVING_FAILURES.inc()
    else:
        if queue_wait is not None:
            SERVING_QUEUE_WAIT.observe(queue_wait, engine=engine)
        if ttft is not None:
            SERVING_TTFT.observe(ttft, engine=engine)
        if tpot is not None:
            SERVING_TPOT.observe(tpot, engine=engine)
    rec = _S.rec
    if rec is not None:
        row = {"engine": engine, "queue_wait": queue_wait, "ttft": ttft,
               "tpot": tpot, "tokens": tokens,
               "prefill_chunks": prefill_chunks,
               "prompt_len": prompt_len}
        if trace_id is not None:
            row["trace"] = trace_id
        if shadow:
            row["shadow"] = True
        if version is not None:
            row["version"] = str(version)
        if error is not None:
            row["error"] = error
        rec.record("serving_request", **row)


def on_alert(rule, severity, state, value=None, figures=None,
             offenders=None, active=None, at=None):
    """One alert transition from the monitor.signals rule engine
    (exactly-once FIRING/RESOLVED edge). Counter ticks
    unconditionally; the armed recorder lands an ``alert`` row
    stamped with the triggering windows' figures and the worst
    offenders in-window — the row the ``monitor alerts --incident``
    timeline splices with the goodput ledger. The row's ``trace``
    field carries the FIRST offender's trace id so an alert joins
    the merged fleet timeline like every other row kind."""
    ALERT_TRANSITIONS.inc(rule=rule, severity=severity, state=state)
    if active is not None:
        ALERTS_ACTIVE.set(active)
    rec = _S.rec
    if rec is not None:
        row = {"rule": rule, "severity": severity, "state": state,
               "value": value, "figures": figures or {},
               "offenders": list(offenders or ())}
        if at is not None:
            # the transition's LOGICAL time (the evaluation round's
            # clock) — the recorder stamps its own write-time ts, and
            # an offline replay's write time is not when the alert
            # condition held
            row["at"] = at
        tr = next((o.get("trace") for o in row["offenders"]
                   if o.get("trace")), None)
        if tr is not None:
            row["trace"] = tr
        rec.record("alert", **row)
        rec.flush()


def on_scale_event(direction, desired, live, reason, detail=None,
                   version_mix=None):
    """One autoscale desired-count move (serving.autoscale control
    loop). ``reason`` is a SHORT category tag ("pressure", "idle",
    "roll", "manual") — it labels the counter, so cardinality must
    stay bounded; the free-text hint rationale travels in ``detail``
    on the recorder row only. ``version_mix`` ({version: replicas})
    refreshes the per-version gauge, the fleet's version-mix story
    `monitor watch` renders."""
    FLEET_SCALE_EVENTS.inc(direction=direction, reason=reason)
    FLEET_DESIRED.set(int(desired))
    if version_mix:
        for ver, n in version_mix.items():
            FLEET_VERSION_REPLICAS.set(int(n), version=str(ver))
    rec = _S.rec
    if rec is not None:
        row = {"direction": direction, "desired": int(desired),
               "live": int(live), "reason": reason}
        if detail is not None:
            row["detail"] = detail
        if version_mix:
            row["version_mix"] = {str(k): int(v)
                                  for k, v in version_mix.items()}
        rec.record("scale_event", **row)
        rec.flush()


def on_drain(slot, endpoint, version=None):
    """One graceful replica drain started (admissions closed; the
    cell retires once its in-flight work delivers and acks)."""
    FLEET_DRAINS.inc()
    rec = _S.rec
    if rec is not None:
        rec.record("drain", slot=slot, endpoint=endpoint,
                   version=version)


def on_roll(from_version, to_version, convergence_s=None, replaced=0,
            shed_during=0, aborted=False, reason=None):
    """One rolling weight update finished — completed (the fleet
    reached 100% ``to_version``; ``convergence_s`` observed into the
    histogram the SLO's ``version_convergence_s`` objective reads) or
    ABORTED (roll halted, surviving fleet intact; no convergence
    observation — a half-roll's wall time is not a convergence).
    ``shed_during`` is the router's shed delta across the roll — the
    shed-during-roll error budget's sample."""
    if not aborted:
        FLEET_ROLLS.inc()
        if convergence_s is not None:
            FLEET_VERSION_CONVERGENCE.observe(float(convergence_s))
    rec = _S.rec
    if rec is not None:
        row = {"from_version": from_version, "to_version": to_version,
               "replaced": int(replaced),
               "shed_during": int(shed_during),
               "aborted": bool(aborted)}
        if convergence_s is not None:
            row["convergence_s"] = float(convergence_s)
        if reason is not None:
            row["reason"] = reason
        rec.record("roll", **row)
        rec.flush()


def on_mirror_pair(version, rid, agree, match, router="router",
                   candidate_error=None):
    """One joined shadow pair scored by the router: the candidate's
    result for a mirrored request matched against the incumbent's
    SERVED result for the same durable rid. ``agree`` is exact token
    equality, ``match`` the common-prefix fraction — the
    token-agreement delta objective's samples. The row keeps
    ``{version, rid}`` so either side's serving_request row is
    joinable by rid."""
    MIRROR_PAIRS.inc(router=router)
    rec = _S.rec
    if rec is not None:
        row = {"version": str(version), "rid": rid,
               "agree": bool(agree), "match": float(match),
               "router": router}
        if candidate_error is not None:
            row["candidate_error"] = candidate_error
        rec.record("mirror_pair", **row)


def on_verdict(phase, version, verdict, figures=None, pairs=None,
               requests=None, reason=None, rule=None):
    """One EXACTLY-ONCE delta-SLO verdict (monitor.signals DeltaRule /
    serving.rollout): a rollout phase's candidate either PASSed or
    FAILed its delta objectives. Ticks the verdict counter and — armed
    — lands a flushed ``verdict`` row (the gate record `monitor watch`
    and the rollout controller read)."""
    ROLLOUT_VERDICTS.inc(phase=phase, verdict=verdict)
    rec = _S.rec
    if rec is not None:
        row = {"phase": phase, "version": str(version),
               "verdict": verdict, "figures": figures or {}}
        if pairs is not None:
            row["pairs"] = int(pairs)
        if requests is not None:
            row["requests"] = int(requests)
        if reason is not None:
            row["reason"] = reason
        if rule is not None:
            row["rule"] = rule
        rec.record("verdict", **row)
        rec.flush()


_ROLLOUT_PHASES = {"idle": 0, "boot": 1, "shadow": 2, "canary": 3,
                   "rolling": 4, "promoted": 5, "rolled-back": -1}


def on_rollout(phase, version, detail=None, version_mix=None,
               convergence_s=None):
    """Rollout controller phase transition (serving.rollout). The
    gauge carries the live phase for scrape; the flushed ``rollout``
    row is what feeds the `monitor watch` status line — no parallel
    machinery, the collector already ships recorder rows."""
    ROLLOUT_PHASE.set(_ROLLOUT_PHASES.get(phase, 0))
    if version_mix:
        for ver, n in version_mix.items():
            FLEET_VERSION_REPLICAS.set(int(n), version=str(ver))
    rec = _S.rec
    if rec is not None:
        row = {"phase": phase, "version": str(version)}
        if detail is not None:
            row["detail"] = detail
        if version_mix:
            row["version_mix"] = {str(k): int(v)
                                  for k, v in version_mix.items()}
        if convergence_s is not None:
            row["convergence_s"] = float(convergence_s)
        rec.record("rollout", **row)
        rec.flush()


def on_feed_plan(hit):
    """core/executor feed-plan cache outcome for one run() call."""
    (FEED_PLAN_HITS if hit else FEED_NORMALIZATIONS).inc()


def on_transform(program, pass_name, ops_before, ops_after, dt,
                 changes=None, patterns=None):
    """One optimizing-pass rewrite phase over a Program completed
    (paddle_tpu.transform.PassManager). ``changes`` is the pass's own
    removed-or-rewritten count — constant folding REPLACES ops in
    place, so the op-count delta alone would hide its work.
    ``patterns`` (the fusion pass) maps pattern name -> hits for this
    phase. Counters tick unconditionally (transforms run per compile,
    not per step); the armed recorder additionally lands a
    ``transform`` row — program id, pass, ops before/after, wall time
    — following the PR-2 row conventions."""
    removed = int(changes) if changes is not None \
        else max(0, int(ops_before) - int(ops_after))
    TRANSFORM_PASSES.inc(**{"pass": pass_name})
    if removed:
        TRANSFORM_OPS_REMOVED.inc(removed, **{"pass": pass_name})
    if patterns:
        for pat, n in patterns.items():
            if n:
                TRANSFORM_PATTERNS.inc(int(n), pattern=pat)
    if not _S.on:
        return
    rec = _S.rec
    if rec is not None:
        row = {"pass": pass_name, "ops_before": int(ops_before),
               "ops_after": int(ops_after), "removed": removed,
               "dt": dt}
        if patterns:
            row["patterns"] = {k: v for k, v in patterns.items() if v}
        rec.record("transform", program=id(program),
                   version=getattr(program, "_version", None), **row)


_mem_sample_counter = [0]


def _sample_device_memory():
    """Live/peak device bytes. On TPU allocator stats are one cheap
    call — sample every time; the CPU fallback walks jax.live_arrays()
    (O(arrays)), so it samples only when profile_memory is on. The
    platform is queried once and cached — it cannot change."""
    if _S.platform is None:
        try:
            import jax
            _S.platform = jax.local_devices()[0].platform
        except Exception:
            return
    from .. import profiler as _prof
    if _S.platform != "tpu" and not _prof.memory_enabled():
        return
    try:
        live, peak = _prof.device_memory()
        HBM_LIVE.set(live)
        HBM_PEAK.set(peak)
    except Exception:
        pass


def _flag(name):
    from .. import flags
    try:
        return flags.get_flag(name)
    except KeyError:
        return True


# -- jax compile-time listener ---------------------------------------------

def _register_jax_listener():
    with _S.lock:
        if _S.listener_registered:
            return
        _S.listener_registered = True
    try:
        import jax.monitoring as jm

        def _listener(event, duration, **kw):
            if not _S.on or "compile" not in event:
                return
            rec, dog = _S.rec, _S.dog
            what = event.rsplit("/", 1)[-1]
            XLA_COMPILE_SECONDS.observe(duration, what=what)
            if dog is not None:
                # compile phases count as liveness: a long first compile
                # (tracing, lowering, backend_compile each emit duration
                # events) must not read as a stall. A single compile
                # PHASE longer than the deadline can still fire — size
                # stall_timeout above the worst expected compile phase.
                dog.touch()
            if rec is not None and duration >= 0.01:
                rec.record("xla_compile", what=what, seconds=duration)

        jm.register_event_duration_secs_listener(_listener)
    except Exception:
        pass


# -- stall + reporter ------------------------------------------------------

def _on_stall(idle, stacks):
    rec = _S.rec
    STALLS.inc()
    snap = _REG.snapshot()
    msg = ("paddle_tpu.monitor WATCHDOG: no step/compile completed for "
           "%.1fs — dumping %d thread stacks" % (idle, len(stacks)))
    print(msg, file=sys.stderr)
    for label, stack in stacks.items():
        print("--- thread %s ---" % label, file=sys.stderr)
        print("\n".join(stack[-12:]), file=sys.stderr)
    if rec is not None:
        rec.record("stall", idle_seconds=idle, stacks=stacks,
                   metrics=snap)
        rec.flush()


def _report_loop(stop, interval):
    last_steps = 0
    while not stop.wait(interval):
        if not _S.on:
            continue
        s = summary()
        d = s["steps"] - last_steps
        last_steps = s["steps"]
        line = ("monitor: steps=%d (+%d) p50=%s p95=%s recompiles=%d"
                % (s["steps"], d, _fmt_s(s["p50_s"]), _fmt_s(s["p95_s"]),
                   s["recompiles"]))
        if s.get("mfu") is not None:
            line += " mfu=%.1f%%" % (100 * s["mfu"])
        if s.get("tokens_per_sec"):
            line += " tok/s=%.0f" % s["tokens_per_sec"]
        print(line, file=sys.stderr)


def _fmt_s(v):
    return "n/a" if v is None else "%.1fms" % (1000 * v)


# -- snapshots -------------------------------------------------------------

def summary():
    """One-look health dict (reporter line / bench.py stamp)."""
    steps = sum(STEPS.snapshot().values())
    out = {
        "steps": steps,
        "p50_s": _best_percentile(0.50),
        "p95_s": _best_percentile(0.95),
        "compiles": sum(COMPILES.snapshot().values()),
        "recompiles": RECOMPILES.value(),
        "cache_hits": CACHE_HITS.value(),
        "feed_bytes": FEED_BYTES.value(),
        "mfu": MFU.value(),
        "tokens_per_sec": TOKENS_PER_SEC.value(),
        "stalls": STALLS.value(),
    }
    return out


def _best_percentile(q):
    """Percentile over the busiest executor label (the headline series)."""
    snap = STEP_SECONDS.snapshot()
    if not snap:
        return None
    key = max(snap, key=lambda k: snap[k]["count"])
    return STEP_SECONDS.percentile(q, executor=key[0])


def prometheus_text():
    return _REG.render_prometheus()


def dump_metrics(path):
    """Write the registry as Prometheus text (.prom) or JSON."""
    if path.endswith(".json"):
        _REG.dump_json(path)
    else:
        with open(path, "w") as f:
            f.write(prometheus_text())


class MonitorSession:
    """Handle yielded by session(): .summary() returns the standard
    summary dict with the COUNT fields (steps/compiles/recompiles/
    cache_hits/feed_bytes/stalls) as deltas for the session's span;
    percentiles and gauges are ambient last-values."""

    _DELTA_KEYS = ("steps", "compiles", "recompiles", "cache_hits",
                   "feed_bytes", "stalls")

    def __init__(self, before):
        self._before = before
        self._after = None

    def _freeze(self):
        self._after = summary()

    def summary(self):
        cur = self._after if self._after is not None else summary()
        out = dict(cur)
        for k in self._DELTA_KEYS:
            out[k] = cur[k] - self._before[k]
        return out


class _SessionCM:
    def __init__(self, enable_kwargs):
        self._kw = enable_kwargs
        self._own = False
        self._sess = None

    def __enter__(self):
        # reuse an ambient session untouched (its recorder/watchdog
        # config wins); arm a fresh one only when the monitor is off
        self._own = not _S.on
        if self._own:
            enable(**self._kw)
        self._sess = MonitorSession(summary())
        return self._sess

    def __exit__(self, *exc):
        self._sess._freeze()
        if self._own:
            disable()
        return False


def session(log_path=None, **enable_kwargs):
    """``with monitor.session(log_path=...) as s:`` — the one shared
    arm-unless-ambient pattern (harness.monitored_run, benchmarks).
    Never resets the registry (counters are monotonic by contract);
    ``s.summary()`` reports the block's own counts as deltas."""
    return _SessionCM(dict(log_path=log_path, **enable_kwargs))


def reset_for_tests():
    """Clear metric series and compile history (test isolation)."""
    disable()
    _REG.reset()
    _S.programs.clear()
    _S.costs.clear()
    _S.tokens_override = None
    _S.peak_flops = None       # an explicit/auto peak must not leak
    _S.devices_recorded = False
    _S.platform = None
    _S.step_serial = 0
